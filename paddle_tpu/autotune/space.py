"""The serving config space: every tunable knob, typed and constrained.

One :class:`ConfigSpace` declares the full knob surface the serving
stack has grown — paged-cache geometry, tick batching, speculative
decoding, KV quantization, pool sizing, scheduler policy, and the fleet
tier (replica count, routing weights, probe cadence). A *config* is a
plain ``{knob: value}`` dict over exactly these knobs, so it JSON
round-trips into tuned profiles unchanged.

Knobs interact, so validity is first-class:

- ``spec_gate_low`` is dead weight when ``draft_k == 0``; canonicalize
  rather than reject, so fingerprints never differ on a knob that
  cannot matter.
- ``pool_frac < 1`` (pool sized below demand) REQUIRES a host pool to
  swap victims into (``host_pool_mb != 0``); with swapping disabled the
  starved pool degrades to stall livelock, which no search should ever
  measure as a candidate.
- speculation caps the tick window (``draft_k > 0`` requires
  ``tick_window <= 8``): the fused verify scan compiles one program
  spanning ``tick_window`` windows of width ``k+1``, so wide windows
  blow up both program size (multi-minute XLA compiles) and the
  surplus verify work past finished requests — the same reason the
  benchmark drops its tick-window default to 4 under ``--spec``.
- the fleet knobs (``prefix_weight``/``load_weight``/``probe_every``/
  ``degrade_cooldown_s``) are dead at ``fleet_replicas == 1`` and
  canonicalize to their defaults.
- the kernel tier (``mk_ffn_tile``/``mk_prefetch_depth``/``mk_dequant``
  — the megakernel's :class:`~..ops.decode_megakernel.MegakernelGeometry`
  as knobs) is dead weight when ``kernels != "megakernel"`` and
  canonicalizes to defaults; under ``kernels == "megakernel"`` with a
  ``model_cfg`` bound to the space, validity runs the geometry's
  VMEM-residency arithmetic against the per-core budget (~16 MiB on
  current TPUs) and the ffn-tile divisibility check — a geometry that
  cannot fit VMEM is invalid, not an OOM mid-search.
- ``cp > 1`` (context-parallel prefill) requires a mesh the host can
  actually build (the space's ``devices`` bound) and must divide
  ``prefill_chunk`` — the chunk shards evenly by construction.
- the tier watermarks are one ladder: ``tier_demote_low`` without
  ``tier_demote_high`` (or an unordered pair) is invalid, and the high
  watermark canonicalizes to None when the low trigger is off.

Sampling and mutation take an explicit ``numpy.random.RandomState`` and
are fully deterministic per seed — the search's trial sequence replays
bit-for-bit (see tests/test_autotune.py).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class Knob:
    """One tunable: a finite choice set plus the untuned default."""

    name: str
    choices: Tuple[Any, ...]
    default: Any
    help: str = ""

    def __post_init__(self):
        if self.default not in self.choices:
            raise ValueError(
                f"knob {self.name!r}: default {self.default!r} not in "
                f"choices {self.choices!r}")


#: the serving knob surface (engine tier first, fleet tier after).
#: Choice sets are small on purpose: the space is combinatorial anyway
#: (~1e5 engine-tier configs) and every value here is one the suite has
#: actually exercised.
ENGINE_KNOBS: Tuple[Knob, ...] = (
    Knob("block_size", (8, 16, 32), 16,
         "tokens per KV block (pool geometry + attention table width)"),
    Knob("tick_window", (1, 2, 4, 8, 16, 32), 16,
         "decode ticks fused per host round trip"),
    Knob("prefill_chunk", (32, 64, 128), 64,
         "tokens per chunked-prefill program"),
    Knob("draft_k", (0, 2, 4, 8), 0,
         "speculative drafts per verify window; 0 = speculation off"),
    Knob("spec_gate_low", (0.5, 1.0, 2.0, 4.0), 2.0,
         "dynamic-gate acceptance floor (accepted drafts/window)"),
    Knob("kv_quant", ("none", "int8"), "none",
         "KV pool storage: fp blocks or int8 codes + f32 scales"),
    Knob("pool_frac", (0.5, 0.75, 1.0), 1.0,
         "KV pool byte budget as a fraction of fp dense parity"),
    Knob("host_pool_mb", (None, 16, 64), None,
         "host swap-pool cap in MB; None = unbounded, 0 = no swapping"),
    Knob("policy", ("fifo", "priority", "wfq"), "fifo",
         "request scheduler (inference/scheduler.py)"),
    Knob("cp", (1, 2, 4), 1,
         "context-parallel mesh axis sharding the chunked prefill's "
         "sequence dimension (long-context prefill scaling); 1 = off"),
    Knob("tier_demote_low", (None, 0.1, 0.2), None,
         "free-block fraction that TRIGGERS hot->warm KV demotion; "
         "None = watermark-driven demotion off"),
    Knob("tier_demote_high", (None, 0.3, 0.5), None,
         "free-block fraction demotion restores before it stops; dead "
         "(canonicalized to None) when tier_demote_low is None"),
)

#: the kernel tier: dispatch mode plus the whole-tick megakernel's
#: geometry (ops/decode_megakernel.MegakernelGeometry) expressed as
#: knobs — dead (canonicalized to defaults) unless kernels="megakernel".
KERNEL_KNOBS: Tuple[Knob, ...] = (
    Knob("kernels", ("auto", "pallas", "megakernel", "reference"), "auto",
         "kernel dispatch rung for the compiled serving programs "
         "(ops.set_kernel_mode)"),
    Knob("mk_ffn_tile", (0, 512, 1024, 2048), 0,
         "megakernel FFN intermediate-dim tile width; 0 streams each "
         "layer's full gate/up/down weights (reference-exact contraction "
         "order)"),
    Knob("mk_prefetch_depth", (1, 2, 4), 2,
         "megakernel weight-stream lookahead in chunks (VMEM buffers per "
         "stream); 2 = classic double buffering"),
    Knob("mk_dequant", ("scores", "tile"), "scores",
         "megakernel int8 KV dequant placement: 'scores' folds scales "
         "into the softmax accumulators (token-exact vs reference), "
         "'tile' dequantizes the whole VMEM tile up front"),
)

FLEET_KNOBS: Tuple[Knob, ...] = (
    Knob("fleet_replicas", (1, 2, 4), 1,
         "FleetRouter replica count; 1 = single engine"),
    Knob("prefix_weight", (0.5, 1.0, 2.0), 1.0,
         "routing score weight on matched prefix blocks"),
    Knob("load_weight", (0.5, 1.0, 2.0), 1.0,
         "routing score weight on queue depth + occupancy"),
    Knob("probe_every", (8, 16, 32), 16,
         "router ticks between watchdog deep probes"),
    Knob("degrade_cooldown_s", (0.0, 2.0), 0.0,
         "seconds a degraded replica sits out before re-probe"),
)

ALL_KNOBS: Tuple[Knob, ...] = ENGINE_KNOBS + KERNEL_KNOBS + FLEET_KNOBS

#: per-core VMEM the megakernel's residency estimate is checked against
#: (~16 MiB on current TPU generations; override per space if yours
#: differs)
MK_VMEM_LIMIT_BYTES = 16 << 20


class ConfigSpace:
    """Typed knob space with validity, canonicalization, and seeded
    sampling/mutation.

    ``pins`` freezes knobs to a single value (the engine-tier search
    pins the fleet knobs to their defaults); ``max_len`` bounds
    ``block_size`` choices so one block never exceeds the serving
    horizon; ``devices`` bounds the ``cp`` mesh axis — a cp degree the
    host cannot build a mesh for is invalid, not a runtime crash.

    ``model_cfg`` binds a model geometry to the space and arms the
    kernel tier's validity arithmetic: under ``kernels="megakernel"``
    the candidate :class:`~..ops.decode_megakernel.MegakernelGeometry`'s
    worst-case VMEM residency (``vmem_bytes``, at ``max_batch`` rows ×
    the config's verify window) must fit ``vmem_limit_bytes``
    (default :data:`MK_VMEM_LIMIT_BYTES`), and ``mk_ffn_tile`` must
    divide the model's intermediate size. Without a bound model the
    kernel knobs only get the geometry's own range checks.
    """

    def __init__(self, knobs: Sequence[Knob] = ALL_KNOBS, *,
                 pins: Optional[Dict[str, Any]] = None,
                 max_len: Optional[int] = None,
                 devices: Optional[int] = None,
                 model_cfg=None, max_batch: int = 8,
                 vmem_limit_bytes: int = MK_VMEM_LIMIT_BYTES):
        self.devices = devices
        self.model_cfg = model_cfg
        self.max_batch = int(max_batch)
        self.vmem_limit_bytes = int(vmem_limit_bytes)
        self.knobs: Tuple[Knob, ...] = tuple(knobs)
        names = [k.name for k in self.knobs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate knob names in {names}")
        self._by_name = {k.name: k for k in self.knobs}
        self.pins: Dict[str, Any] = dict(pins or {})
        for name, val in self.pins.items():
            k = self._by_name.get(name)
            if k is None:
                raise ValueError(f"pin for unknown knob {name!r}")
            if val not in k.choices:
                raise ValueError(
                    f"pin {name}={val!r} not in choices {k.choices!r}")
        if max_len is not None:
            bs = self._by_name.get("block_size")
            if bs is not None:
                fit = tuple(c for c in bs.choices if c <= max_len)
                if not fit:
                    raise ValueError(
                        f"no block_size choice fits max_len={max_len}")
                self._by_name["block_size"] = dataclasses.replace(
                    bs, choices=fit, default=fit[-1]
                    if bs.default not in fit else bs.default)
                self.knobs = tuple(self._by_name[k.name]
                                   for k in self.knobs)

    # ------------------------------------------------------------- basics
    def knob(self, name: str) -> Knob:
        return self._by_name[name]

    def default(self) -> Dict[str, Any]:
        cfg = {k.name: k.default for k in self.knobs}
        cfg.update(self.pins)
        return self.canonicalize(cfg)

    def size(self) -> int:
        """Raw cartesian size (pre-constraint, pins collapse to 1)."""
        n = 1
        for k in self.knobs:
            n *= 1 if k.name in self.pins else len(k.choices)
        return n

    # -------------------------------------------------------- constraints
    def errors(self, config: Dict[str, Any]) -> List[str]:
        """Why this config is invalid; empty list = valid. Unknown or
        missing knobs and off-menu values are errors too — a profile
        edited by hand fails loudly, not at serving time."""
        errs: List[str] = []
        for name in config:
            if name not in self._by_name:
                errs.append(f"unknown knob {name!r}")
        for k in self.knobs:
            if k.name not in config:
                errs.append(f"missing knob {k.name!r}")
            elif config[k.name] not in k.choices:
                errs.append(f"{k.name}={config[k.name]!r} not in "
                            f"{k.choices!r}")
        for name, val in self.pins.items():
            if name in config and config[name] != val:
                errs.append(f"{name}={config[name]!r} violates pin "
                            f"{name}={val!r}")
        if errs:
            return errs
        # cross-knob feasibility
        if config.get("pool_frac", 1.0) < 1.0 \
                and config.get("host_pool_mb", None) == 0:
            errs.append(
                "pool_frac < 1.0 starves the KV pool below demand but "
                "host_pool_mb=0 disables swapping — victims would stall "
                "forever; give the overloaded pool a host pool")
        if config.get("draft_k", 0) > 0 and config.get("tick_window", 1) > 8:
            errs.append(
                "draft_k > 0 with tick_window > 8: the fused verify scan "
                "spans tick_window windows of width k+1, so wide windows "
                "explode program size (multi-minute compiles) and surplus "
                "verify work — cap the window at 8 when speculating")
        cp = int(config.get("cp", 1))
        if cp > 1:
            if self.devices is not None and cp > self.devices:
                errs.append(
                    f"cp={cp} needs a {cp}-device mesh but the space was "
                    f"built for {self.devices} device(s)")
            pc = int(config.get("prefill_chunk", 64))
            if pc % cp:
                errs.append(
                    f"cp={cp} must divide prefill_chunk={pc} — the chunk "
                    f"shards evenly over the cp axis by construction")
        lo = config.get("tier_demote_low", None)
        hi = config.get("tier_demote_high", None)
        if lo is not None:
            if hi is None:
                errs.append(
                    "tier_demote_low set without tier_demote_high — the "
                    "watermarks are one ladder, set both or neither")
            elif not (0.0 < lo < hi <= 1.0):
                errs.append(
                    f"tier watermarks must satisfy 0 < low < high <= 1, "
                    f"got low={lo} high={hi}")
        if config.get("kernels", "auto") == "megakernel":
            errs.extend(self._megakernel_errors(config))
        return errs

    def _megakernel_errors(self, config: Dict[str, Any]) -> List[str]:
        """Kernel-tier feasibility: the candidate geometry's own range
        checks, plus — with a model bound — ffn-tile divisibility and
        the worst-case VMEM-residency arithmetic against the per-core
        budget."""
        errs: List[str] = []
        from ..ops.decode_megakernel import MegakernelGeometry

        try:
            geom = MegakernelGeometry(
                ffn_tile=int(config.get("mk_ffn_tile", 0)),
                prefetch_depth=int(config.get("mk_prefetch_depth", 2)),
                dequant=str(config.get("mk_dequant", "scores")))
            geom.validate()
        except ValueError as e:
            return [f"megakernel geometry: {e}"]
        mc = self.model_cfg
        if mc is None:
            return errs
        I = int(mc.intermediate_size)
        if geom.ffn_tile and I % geom.ffn_tile:
            errs.append(
                f"mk_ffn_tile={geom.ffn_tile} does not divide the bound "
                f"model's intermediate_size={I}")
            return errs
        heads = int(mc.num_attention_heads)
        head_dim = int(mc.hidden_size) // heads
        need = geom.vmem_bytes(
            hidden=int(mc.hidden_size), heads=heads,
            kv_heads=int(mc.num_key_value_heads), head_dim=head_dim,
            intermediate=I, layers=int(mc.num_hidden_layers),
            batch=self.max_batch,
            window=int(config.get("draft_k", 0)) + 1,
            block_size=int(config.get("block_size", 16)),
            quantized=config.get("kv_quant", "none") == "int8")
        if need > self.vmem_limit_bytes:
            errs.append(
                f"megakernel geometry needs ~{need / (1 << 20):.1f} MiB "
                f"VMEM residency, over the "
                f"{self.vmem_limit_bytes / (1 << 20):.1f} MiB per-core "
                f"budget — shrink mk_ffn_tile/mk_prefetch_depth")
        return errs

    def is_valid(self, config: Dict[str, Any]) -> bool:
        return not self.errors(config)

    def canonicalize(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """Collapse dead knobs to their defaults so two configs that
        cannot behave differently share one fingerprint: the spec gate
        without speculation, the host pool without overload, the fleet
        routing knobs without a fleet."""
        cfg = dict(config)
        if cfg.get("draft_k", 0) == 0 and "spec_gate_low" in self._by_name:
            cfg["spec_gate_low"] = self._by_name["spec_gate_low"].default
        if cfg.get("tier_demote_low", None) is None \
                and "tier_demote_high" in self._by_name:
            # the high watermark is dead without the low trigger (cp=1
            # analogously needs no collapse: the cp axis carries no
            # satellite knobs, 1 IS its canonical off value)
            cfg["tier_demote_high"] = \
                self._by_name["tier_demote_high"].default
        if cfg.get("pool_frac", 1.0) >= 1.0 \
                and "host_pool_mb" in self._by_name:
            cfg["host_pool_mb"] = self._by_name["host_pool_mb"].default
        if cfg.get("fleet_replicas", 1) == 1:
            for name in ("prefix_weight", "load_weight", "probe_every",
                         "degrade_cooldown_s"):
                if name in self._by_name:
                    cfg[name] = self._by_name[name].default
        if cfg.get("kernels", "auto") != "megakernel":
            # the megakernel geometry is dead weight on every other
            # dispatch rung — two configs that cannot behave differently
            # must share one fingerprint
            for name in ("mk_ffn_tile", "mk_prefetch_depth", "mk_dequant"):
                if name in self._by_name:
                    cfg[name] = self._by_name[name].default
        return cfg

    def validate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """Canonicalize then raise on any remaining invalidity."""
        cfg = self.canonicalize(config)
        errs = self.errors(cfg)
        if errs:
            raise ValueError("invalid serving config: " + "; ".join(errs))
        return cfg

    # ----------------------------------------------------------- sampling
    def sample(self, rng: np.random.RandomState,  # graftlint: noqa[np-random]
               max_tries: int = 64) -> Dict[str, Any]:
        """One valid config, drawn knob-by-knob in declaration order
        (rejection-sampled against the cross-knob constraints). Same rng
        state -> same config, always."""
        for _ in range(max_tries):
            cfg = {}
            for k in self.knobs:
                if k.name in self.pins:
                    cfg[k.name] = self.pins[k.name]
                else:
                    cfg[k.name] = k.choices[int(rng.randint(len(k.choices)))]
            cfg = self.canonicalize(cfg)
            if self.is_valid(cfg):
                return cfg
        raise RuntimeError(
            f"could not sample a valid config in {max_tries} tries — "
            f"the pins/constraints have emptied the space")

    def mutate(self, config: Dict[str, Any], rng: np.random.RandomState,  # graftlint: noqa[np-random]
               mutations: int = 1, max_tries: int = 64) -> Dict[str, Any]:
        """Evolutionary neighbor: flip ``mutations`` unpinned knobs to a
        different choice, keeping the result valid. Deterministic per
        rng state."""
        base = self.validate(config)
        free = [k for k in self.knobs
                if k.name not in self.pins and len(k.choices) > 1]
        if not free:
            return dict(base)
        for _ in range(max_tries):
            cfg = dict(base)
            idx = rng.choice(len(free), size=min(mutations, len(free)),
                             replace=False)
            for i in sorted(int(j) for j in idx):
                k = free[i]
                alts = [c for c in k.choices if c != base[k.name]]
                cfg[k.name] = alts[int(rng.randint(len(alts)))]
            cfg = self.canonicalize(cfg)
            if self.is_valid(cfg) and cfg != base:
                return cfg
        return dict(base)

    # -------------------------------------------------------- fingerprint
    def fingerprint(self, config: Dict[str, Any]) -> str:
        """Stable id of the canonical config — the key trials, profiles
        and dedup all share."""
        cfg = self.validate(config)
        return hashlib.sha256(
            json.dumps(cfg, sort_keys=True, default=str).encode()
        ).hexdigest()[:12]


def engine_space(max_len: Optional[int] = None,
                 pins: Optional[Dict[str, Any]] = None,
                 devices: Optional[int] = None,
                 model_cfg=None, max_batch: int = 8,
                 vmem_limit_bytes: int = MK_VMEM_LIMIT_BYTES
                 ) -> ConfigSpace:
    """The single-engine search space: full knob surface declared, fleet
    tier pinned to its defaults (fleet_replicas=1 collapses the routing
    knobs too). ``devices`` bounds the cp axis to meshes the host can
    build; ``model_cfg``/``max_batch``/``vmem_limit_bytes`` arm the
    kernel tier's VMEM-validity arithmetic (see :class:`ConfigSpace`).
    This is what ``tools/autotune.py`` searches."""
    p = {k.name: k.default for k in FLEET_KNOBS}
    p.update(pins or {})
    return ConfigSpace(ALL_KNOBS, pins=p, max_len=max_len, devices=devices,
                       model_cfg=model_cfg, max_batch=max_batch,
                       vmem_limit_bytes=vmem_limit_bytes)
