"""Config + workload -> predicted throughput, calibrated from trials.

:class:`ServingCostModel` is the search's pruning oracle. It maps a
candidate serving config (``space.py`` dict) and a workload
(``workload.WorkloadSpec``) onto the analytic
:class:`~paddle_tpu.cost_model.PagedTickCostModel` features — how many
host trips, fused ticks, FLOPs and HBM bytes the run will take — and
predicts end-to-end seconds and tok/s. Measured trials feed
:meth:`observe`; :meth:`recalibrate` ridge-fits the four tick
coefficients to them, so ranking sharpens as the search spends budget.

The prediction is a *ranking* device, not a stopwatch: every term is
chosen to move in the right direction under each knob (bigger pools
fewer swaps, wider tick windows fewer trips, speculation paying only
above break-even acceptance) rather than to be absolutely accurate.
Hard accept/reject decisions always come from measurement
(``search.py``), never from here.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Mapping, Optional

from ..cost_model import PagedTickCostModel, REF_BLOCK_BYTES, TickShape
from .workload import WorkloadSpec

#: prior per-draft match probability for the n-gram drafter — repeated
#: suffixes lock the drafter on (PR 3 showcase); random-token prompts
#: rarely match. Calibration via measured acceptance replaces this.
ACCEPT_P_REPEAT = 0.85
ACCEPT_P_RANDOM = 0.25


def expected_acceptance(k: int, p: float) -> float:
    """E[accepted drafts per verify window] under a geometric match
    model: draft i lands only if all i drafts before it did."""
    return sum(p ** i for i in range(1, k + 1))


def count_params(cfg) -> int:
    """Parameter count of a Llama-shaped config (embeddings + untied
    head + per-layer attention/MLP/norms) — the flop feature's scale."""
    h = cfg.hidden_size
    d = h // cfg.num_attention_heads
    kv = cfg.num_key_value_heads
    attn = h * h + 2 * h * kv * d + h * h        # q, k, v, o projections
    mlp = 3 * h * cfg.intermediate_size          # gate, up, down
    per_layer = attn + mlp + 2 * h               # + the two norms
    return (2 * cfg.vocab_size * h               # embed + lm head
            + cfg.num_hidden_layers * per_layer + h)


def _block_bytes(cfg, block_size: int, kv_quant: str) -> int:
    if cfg is not None:
        from ..inference.serving import kv_block_bytes
        return kv_block_bytes(cfg, block_size, kv_quant)
    scale = 0.25 if kv_quant == "int8" else 1.0
    return int(REF_BLOCK_BYTES * (block_size / 16.0) * scale)


class ServingCostModel:
    """Analytic throughput predictor over (config, workload), online-
    calibrated from measured trials."""

    def __init__(self, model_cfg=None, *, max_batch: int = 8,
                 n_params: Optional[int] = None,
                 tick_model: Optional[PagedTickCostModel] = None):
        self.model_cfg = model_cfg
        self.max_batch = int(max_batch)
        self.n_params = int(n_params) if n_params is not None else (
            count_params(model_cfg) if model_cfg is not None
            else TickShape.__dataclass_fields__["n_params"].default)
        self.tick_model = tick_model or PagedTickCostModel()
        self._trials: List[Dict[str, float]] = []
        #: measured acceptance per window, once any spec trial ran —
        #: replaces the ACCEPT_P_* prior for subsequent predictions
        self.measured_acceptance: Optional[float] = None

    # ------------------------------------------------------------ features
    def aggregates(self, config: Mapping[str, Any],
                   workload: WorkloadSpec) -> Dict[str, float]:
        """Trial totals (trips, ticks, flops, bytes) for one full run of
        ``workload`` under ``config`` — the calibration feature row."""
        bs = int(config.get("block_size", 16))
        tw = int(config.get("tick_window", 16))
        k = int(config.get("draft_k", 0))
        pool_frac = float(config.get("pool_frac", 1.0))
        block_bytes = _block_bytes(self.model_cfg, bs,
                                   str(config.get("kv_quant", "none")))
        decoding = float(min(self.max_batch, workload.requests))
        mean_prompt = (sum(workload.prompt_ladder)
                       / len(workload.prompt_ladder))
        # mean resident context midway through a request's decode
        ctx_tokens = mean_prompt + workload.max_new / 2.0
        ctx_blocks = max(1.0, ctx_tokens / bs)

        total_new = float(workload.requests * workload.max_new)
        if k > 0:
            p = (self.measured_acceptance / k
                 if self.measured_acceptance is not None
                 else (ACCEPT_P_REPEAT if workload.repeat_suffix
                       else ACCEPT_P_RANDOM))
            p = min(max(p, 0.0), 0.99)
            gain = 1.0 + expected_acceptance(k, p)   # tokens per window
            width = k + 1
        else:
            gain, width = 1.0, 1
        ticks = max(1.0, total_new / (decoding * gain))

        shape = TickShape(decoding=int(decoding), width=width,
                          n_params=self.n_params, ctx_blocks=ctx_blocks,
                          block_bytes=block_bytes)
        tick_flops = shape.flops()
        tick_bytes = shape.hbm_bytes()
        if pool_frac < 1.0:
            # overflow fraction of the working set swaps through the
            # host pool every tick-ish — a deliberate overestimate that
            # ranks starved pools below parity ones
            tick_bytes += (1.0 - pool_frac) * decoding \
                * ctx_blocks * block_bytes

        # chunked prefill: one program dispatch per chunk, batched into
        # the same trips as decode
        chunk = int(config.get("prefill_chunk", 64))
        total_prompt = float(workload.requests) * mean_prompt
        pf_ticks = max(1.0, total_prompt / chunk)
        pf_flops = 2.0 * self.n_params * total_prompt
        pf_bytes = pf_ticks * 4.0 * self.n_params

        trips = max(1.0, ticks / tw) + pf_ticks
        return {
            "trips": trips,
            "ticks": ticks + pf_ticks,
            "flops": ticks * tick_flops + pf_flops,
            "bytes": ticks * tick_bytes + pf_bytes,
        }

    # ------------------------------------------------------------- predict
    def predict_seconds(self, config: Mapping[str, Any],
                        workload: WorkloadSpec) -> float:
        a = self.aggregates(config, workload)
        return self.tick_model.predict(a["trips"], a["ticks"],
                                       a["flops"], a["bytes"])

    def predict_tok_s(self, config: Mapping[str, Any],
                      workload: WorkloadSpec) -> float:
        total_new = workload.requests * workload.max_new
        sec = self.predict_seconds(config, workload)
        return total_new / sec if sec > 0 else 0.0

    # ----------------------------------------------------------- calibrate
    def observe(self, config: Mapping[str, Any], workload: WorkloadSpec,
                seconds: float,
                acceptance: Optional[float] = None) -> None:
        """Record one measured trial (analytic features, measured
        seconds). ``acceptance`` is the trial's measured accepted-drafts
        per verify window, if it ran speculation."""
        row = dict(self.aggregates(config, workload))
        row["seconds"] = float(seconds)
        self._trials.append(row)
        if acceptance is not None:
            self.measured_acceptance = float(acceptance)

    def recalibrate(self, ridge: float = 1e-3) -> None:
        """Refit the tick coefficients to every observed trial."""
        if self._trials:
            self.tick_model = self.tick_model.calibrate(self._trials,
                                                        ridge=ridge)

    # ------------------------------------------------------------ capacity
    def capacity_tok_s(self, config: Mapping[str, Any],
                       workload: WorkloadSpec) -> float:
        """Predicted steady-state serving capacity of ONE replica under
        this (config, workload) — new tokens per second, end to end.
        The fleet autoscaler's sizing oracle: like every prediction
        here it is a *ranking/sizing* device that sharpens as measured
        trials feed :meth:`observe`, not a stopwatch."""
        return self.predict_tok_s(config, workload)

    def replicas_for(self, demand_tok_s: float,
                     config: Mapping[str, Any],
                     workload: WorkloadSpec, *,
                     utilization: float = 1.0) -> int:
        """Replicas needed to serve ``demand_tok_s`` with each replica
        loaded to at most ``utilization`` of its predicted capacity —
        the capacity-planning half of elastic autoscaling (the burn-rate
        gauges are the reactive half). Always at least 1: a fleet with
        zero replicas can serve nothing and drain nothing."""
        if not 0.0 < utilization <= 1.0:
            raise ValueError(
                f"utilization must be in (0, 1], got {utilization!r}")
        cap = self.capacity_tok_s(config, workload) * utilization
        if cap <= 0.0 or demand_tok_s <= 0.0:
            return 1
        return max(1, int(math.ceil(demand_tok_s / cap)))

    def spec_break_even(self, k: int,
                        workload: WorkloadSpec,
                        config: Optional[Mapping[str, Any]] = None) -> float:
        """Accepted drafts per window where draft_k=k starts paying, at
        this workload's shapes (compare to SpecConfig.gate_low)."""
        cfg = dict(config or {})
        bs = int(cfg.get("block_size", 16))
        mean_prompt = (sum(workload.prompt_ladder)
                       / len(workload.prompt_ladder))
        shape = TickShape(
            decoding=int(min(self.max_batch, workload.requests)),
            n_params=self.n_params,
            ctx_blocks=max(1.0, (mean_prompt + workload.max_new / 2.0) / bs),
            block_bytes=_block_bytes(self.model_cfg, bs,
                                     str(cfg.get("kv_quant", "none"))))
        return self.tick_model.spec_break_even(k, shape)


def geometry_cost_proxy(op: str, geometry, **shape) -> float:
    """Analytic rank proxy for one kernel-geometry candidate — the
    per-op analogue of the tick model, used only to ORDER sweep rungs
    deterministically (measure promising schedules first so a truncated
    sweep still lands near the winner); the measured clock always
    decides. Lower is better. The terms are the obvious first-order
    costs: grid-step count (launch/bookkeeping overhead amortized by
    deeper streaming / larger tiles) plus a VMEM-pressure penalty once
    the occupancy model nears the per-core budget."""
    from .kernel_geometry import (CEGeometry, FlashAttentionGeometry,
                                  LoRAGeometry, NormGeometry,
                                  PagedAttentionGeometry)
    from .space import MK_VMEM_LIMIT_BYTES

    if isinstance(geometry, PagedAttentionGeometry):
        blocks = float(shape.get("blocks", 64))
        steps = blocks / geometry.kv_block_depth
        vmem = geometry.vmem_bytes(
            head_dim=shape.get("head_dim", 128),
            block_size=shape.get("block_size", 16),
            window=shape.get("window", 4), rep=shape.get("rep", 4),
            quantized=shape.get("quantized", False))
    elif isinstance(geometry, LoRAGeometry):
        rank = int(shape.get("rank", 8))
        rp = geometry.padded_rank(rank)
        # padding trades wasted MACs for MXU alignment; charge the waste
        steps = 1.0 + 0.1 * (rp - rank) / max(rank, 1)
        vmem = geometry.vmem_bytes(
            seq=shape.get("seq", 1), in_dim=shape.get("in_dim", 1024),
            out_dim=shape.get("out_dim", 1024), rank=rank)
    elif isinstance(geometry, FlashAttentionGeometry):
        seq = float(shape.get("seq_q", 2048))
        steps = seq / float(geometry.block_q or 512)
        vmem = geometry.vmem_bytes(head_dim=shape.get("head_dim", 128),
                                   seq_k=shape.get("seq_k", 2048))
    elif isinstance(geometry, (NormGeometry, CEGeometry)):
        rows_total = float(shape.get("rows_total", 2048))
        tile = float(geometry.rows or min(512, rows_total))
        steps = rows_total / max(tile, 1.0)
        width = shape.get("vocab" if isinstance(geometry, CEGeometry)
                          else "width", 4096)
        vmem = geometry.vmem_bytes(**(
            {"hidden": shape.get("hidden", 1024), "vocab": width}
            if isinstance(geometry, CEGeometry) else {"width": width}))
    else:
        raise ValueError(f"no cost proxy for {type(geometry).__name__}")
    pressure = max(0.0, vmem / MK_VMEM_LIMIT_BYTES - 0.5)
    return float(steps * (1.0 + 4.0 * pressure * pressure))
