"""Cost-model-driven serving autotuner (ROADMAP item 2, TVM mold).

Layers::

    workload.py   seeded traffic, decoupled from the serving config
    space.py      typed ConfigSpace over every serving knob
    features.py   telemetry snapshot -> flat FeatureVector per trial
    cost.py       analytic paged-tick predictor, calibrated online
    search.py     seeded search: warmup -> prune -> halving -> gates
    profile.py    tuned-profile JSON; GenerationServer(profile=...)

Entry points: ``tools/autotune.py`` (CLI), ``serving_benchmark --tune /
--profile``, and :func:`search.autotune` for library use. Everything
here is host-side and deterministic per seed; jax is only touched
through ``GenerationServer`` inside a trial.
"""
from .cost import ServingCostModel
from .features import FeatureVector, extract
from .profile import (PROFILE_SCHEMA_VERSION, TunedProfile,
                      config_server_kwargs, resolve_profile)
from .search import TrialResult, TrialRunner, autotune, tokens_fingerprint
from .space import (ALL_KNOBS, ConfigSpace, ENGINE_KNOBS, FLEET_KNOBS,
                    Knob, engine_space)
from .workload import (Traffic, TrafficRequest, WorkloadSpec, draw_traffic,
                       submit_traffic, warmup_traffic)

__all__ = [
    "ALL_KNOBS", "ConfigSpace", "ENGINE_KNOBS", "FLEET_KNOBS",
    "FeatureVector", "Knob", "PROFILE_SCHEMA_VERSION", "ServingCostModel",
    "Traffic", "TrafficRequest", "TrialResult", "TrialRunner",
    "TunedProfile", "WorkloadSpec", "autotune", "config_server_kwargs",
    "draw_traffic", "engine_space", "extract", "resolve_profile",
    "submit_traffic", "tokens_fingerprint", "warmup_traffic",
]
