"""Cost-model-driven serving autotuner (ROADMAP item 2, TVM mold).

Layers::

    workload.py          seeded traffic, decoupled from the serving config
    space.py             typed ConfigSpace over every serving knob
    features.py          telemetry snapshot -> flat FeatureVector per trial
    cost.py              analytic paged-tick predictor, calibrated online
    search.py            seeded search: warmup -> prune -> halving -> gates
    profile.py           tuned-profile JSON; GenerationServer(profile=...)
    kernel_geometry.py   per-layer kernel schedules + the per-(op, dtype,
                         shape, chip) winner cache (the per-op tier)

Entry points: ``tools/autotune.py`` (CLI), ``serving_benchmark --tune /
--profile``, ``kernel_bench.py --sweep-geometry`` (per-op tier), and
:func:`search.autotune` for library use. Everything here is host-side
and deterministic per seed; jax is only touched through
``GenerationServer`` inside a trial.
"""
from .cost import ServingCostModel, geometry_cost_proxy
from .features import FeatureVector, extract
from .kernel_geometry import (CEGeometry, FlashAttentionGeometry,
                              GeometryCache, LoRAGeometry, NormGeometry,
                              OP_FAMILIES, PagedAttentionGeometry,
                              default_geometry, geometry_candidates,
                              geometry_from_dict, install_geometry_cache,
                              local_device_kind, resolve_geometry,
                              resolve_server_geometries)
from .profile import (PROFILE_SCHEMA_VERSION, TunedProfile,
                      config_server_kwargs, resolve_profile)
from .search import (GeometrySweepResult, GeometryTrial, TrialResult,
                     TrialRunner, autotune, sweep_kernel_geometry,
                     tokens_fingerprint)
from .space import (ALL_KNOBS, ConfigSpace, ENGINE_KNOBS, FLEET_KNOBS,
                    Knob, engine_space)
from .workload import (Traffic, TrafficRequest, WorkloadSpec, draw_traffic,
                       submit_traffic, warmup_traffic)

__all__ = [
    "ALL_KNOBS", "CEGeometry", "ConfigSpace", "ENGINE_KNOBS",
    "FLEET_KNOBS", "FeatureVector", "FlashAttentionGeometry",
    "GeometryCache", "GeometrySweepResult", "GeometryTrial", "Knob",
    "LoRAGeometry", "NormGeometry", "OP_FAMILIES",
    "PROFILE_SCHEMA_VERSION", "PagedAttentionGeometry", "ServingCostModel",
    "Traffic", "TrafficRequest", "TrialResult", "TrialRunner",
    "TunedProfile", "WorkloadSpec", "autotune", "config_server_kwargs",
    "default_geometry", "draw_traffic", "engine_space", "extract",
    "geometry_candidates", "geometry_cost_proxy", "geometry_from_dict",
    "install_geometry_cache", "local_device_kind", "resolve_geometry",
    "resolve_profile", "resolve_server_geometries", "submit_traffic",
    "sweep_kernel_geometry", "tokens_fingerprint", "warmup_traffic",
]
