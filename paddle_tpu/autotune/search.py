"""Seeded, measurement-gated search over the serving config space.

The loop (``autotune()``):

1. **Reference trial** — the space's default config runs the full
   workload first. Its token fingerprint becomes the correctness
   reference (greedy serving is token-exact across every valid config —
   the invariant PRs 3–12 established), and its throughput is the
   baseline a winner must beat.
2. **Random warmup** — a few seeded samples run the full workload;
   every measurement feeds the analytic cost model's online calibration
   (``ServingCostModel.observe``/``recalibrate``).
3. **Cost-model pruning** — a larger seeded candidate pool (fresh
   samples + evolutionary mutations of the incumbent) is ranked by
   *predicted* tok/s; only the top slice is measured at all.
4. **Successive halving** — the top slice runs a truncated short rung
   first; short-rung survivors are promoted to full-workload trials.
5. **Hard gates** — any measured trial with a watchdog finding
   (preemption storm, pool-pressure stall, steady-state recompile) is
   rejected outright; full-rung trials must also match the reference
   token fingerprint bit-for-bit. A config that is fast but wrong, or
   fast but pathological, never becomes a profile.

Determinism: candidates come from one ``RandomState(seed)``; traffic is
pre-drawn per workload (``workload.py``); with an injected counting
clock the measurements themselves are reproducible, so the same seed
yields byte-identical trial sequences and winning profiles (the suite
asserts this).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .cost import ServingCostModel
from .features import FeatureVector, extract
from .profile import TunedProfile, config_server_kwargs
from .space import ConfigSpace, engine_space
from .workload import (Traffic, WorkloadSpec, draw_traffic, submit_traffic,
                       warmup_traffic)


def tokens_fingerprint(results_in_order: List[List[int]]) -> str:
    """Hash of the measured token streams, in submission order — the
    cross-config correctness gate."""
    return hashlib.sha256(
        json.dumps(results_in_order).encode()).hexdigest()[:16]


@dataclasses.dataclass
class TrialResult:
    index: int
    rung: str                       # "full" | "short"
    config: Dict[str, Any]
    fingerprint: str
    features: FeatureVector
    tokens_fp: str
    accepted: bool
    reject_reason: Optional[str] = None
    predicted_tok_s: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["features"] = self.features.to_dict()
        d["kind"] = "autotune_trial"
        return d


class TrialRunner:
    """Runs one candidate config against pre-drawn seeded traffic and
    returns (features, token fingerprint, watchdog findings).

    ``clock`` is injectable (GL012 discipline): tests pass a counting
    clock and every measured duration — hence the whole search — becomes
    deterministic. The default is the wall clock."""

    def __init__(self, model, workload: WorkloadSpec, *,
                 max_batch: int = 8, max_len: Optional[int] = None,
                 clock: Optional[Callable[[], float]] = None,
                 warmup_requests: int = 2):
        self.model = model
        self.workload = workload
        self.max_batch = int(max_batch)
        need = max(workload.prompt_ladder) + workload.max_new + 1
        self.max_len = int(max_len) if max_len is not None else need
        if self.max_len < need:
            raise ValueError(
                f"max_len={self.max_len} cannot hold the workload "
                f"(needs {need})")
        self.clock = clock if clock is not None else time.perf_counter
        self.warmup_requests = int(warmup_requests)
        self._traffic_cache: Dict[str, Traffic] = {}

    def traffic_for(self, spec: WorkloadSpec) -> Traffic:
        key = json.dumps(spec.to_dict(), sort_keys=True)
        if key not in self._traffic_cache:
            self._traffic_cache[key] = draw_traffic(spec)
        return self._traffic_cache[key]

    def run(self, config: Dict[str, Any],
            workload: Optional[WorkloadSpec] = None) \
            -> Tuple[FeatureVector, str, List[Dict[str, Any]]]:
        from ..inference.serving import GenerationServer
        from ..telemetry import ServingTelemetry

        spec = workload if workload is not None else self.workload
        traffic = self.traffic_for(spec)
        tel = ServingTelemetry(enabled=True, clock=self.clock)
        srv = GenerationServer(
            self.model, max_batch=self.max_batch, max_len=self.max_len,
            telemetry=tel, clock=self.clock,
            **config_server_kwargs(config, self.model.cfg,
                                   max_batch=self.max_batch,
                                   max_len=self.max_len))
        # warmup from the DISJOINT rng stream: compiles the programs this
        # config uses, then the telemetry reset folds their keys into
        # warm_progs so the watchdog charges any measured-phase recompile
        if self.warmup_requests:
            submit_traffic(srv, warmup_traffic(spec, self.warmup_requests))
            srv.run()
        tel.reset()

        t0 = self.clock()
        if traffic.schedule:
            # open loop: release bursts at their pre-drawn instants,
            # ticking the server while waiting
            base = self.clock()
            handed: Dict[int, Any] = {}
            i = 0
            for t_at, n in traffic.schedule:
                while self.clock() - base < t_at:
                    srv.step()
                handed.update(submit_traffic(
                    srv, traffic.requests[i:i + n]))
                i += n
            results = srv.run()
        else:
            handed = submit_traffic(srv, traffic.requests)
            results = srv.run()
        seconds = self.clock() - t0

        in_order = []
        new_tokens = 0
        for rid, req in handed.items():
            toks = results.get(rid, [])
            gen = toks[len(req.prompt):]
            new_tokens += len(gen)
            in_order.append(list(toks))
        fp = tokens_fingerprint(in_order)
        records = tel.flight.dump()
        findings = tel.watchdog()
        fv = extract(tel, tokens=new_tokens, seconds=seconds,
                     records=records, findings=findings)
        return fv, fp, findings


@dataclasses.dataclass
class GeometryTrial:
    index: int
    geometry: Dict[str, Any]
    seconds: float
    exact: bool                     # bitwise-equal to the default's output
    accepted: bool
    reject_reason: Optional[str] = None
    proxy_cost: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["kind"] = "geometry_trial"
        return d


@dataclasses.dataclass
class GeometrySweepResult:
    op: str
    dtype: str
    key: int
    device_kind: str
    trials: List[GeometryTrial]
    winner: Dict[str, Any]
    winner_index: int
    speedup: float                  # default seconds / winner seconds

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["trials"] = [t.to_dict() for t in self.trials]
        return d


def sweep_kernel_geometry(measure: Callable[[Any], Tuple[Any, float]],
                          op: str, *, dtype: str, key: int,
                          device_kind: Optional[str] = None,
                          candidates: Optional[List[Any]] = None,
                          quantized: bool = False,
                          shape: Optional[Dict[str, Any]] = None,
                          max_candidates: Optional[int] = None,
                          cache=None,
                          log: Optional[Callable[[str], None]] = None) \
        -> GeometrySweepResult:
    """The per-op kernel-geometry tier: measure every candidate schedule
    for one ``(op, dtype, key, chip)`` cell and cache the winner.

    ``measure(geometry) -> (output, seconds)`` runs the kernel under one
    candidate — kernel_bench supplies it with a fresh-jitted closure and
    the injectable clock, so with a counting clock the whole sweep is
    deterministic. Candidate index 0 is ALWAYS the default geometry; its
    output is the parity reference and every other candidate is
    HARD-REJECTED unless bitwise equal (np.array_equal — a schedule that
    regroups floating-point math can never become a cached winner). Ties
    on the clock resolve toward the earlier index, i.e. toward the
    default. ``max_candidates`` truncates the rung by the analytic
    ``geometry_cost_proxy`` rank (default always kept) so a short sweep
    still measures the promising schedules first."""
    from .cost import geometry_cost_proxy
    from .kernel_geometry import geometry_candidates, local_device_kind

    emit = log or (lambda s: None)
    if device_kind is None:
        device_kind = local_device_kind()
    shape = dict(shape or {})
    if candidates is None:
        candidates = geometry_candidates(op, quantized=quantized,
                                         **{k: v for k, v in shape.items()
                                            if k != "quantized"})
    proxies = []
    for g in candidates:
        try:
            proxies.append(geometry_cost_proxy(op, g, quantized=quantized,
                                               **shape))
        except Exception:
            proxies.append(None)
    if max_candidates is not None and len(candidates) > max_candidates:
        ranked = sorted(range(1, len(candidates)),
                        key=lambda i: (proxies[i] if proxies[i] is not None
                                       else float("inf"), i))
        keep = [0] + sorted(ranked[:max(0, max_candidates - 1)])
        emit(f"{op}: proxy rank truncated "
             f"{len(candidates) - len(keep)}/{len(candidates)} candidates")
        candidates = [candidates[i] for i in keep]
        proxies = [proxies[i] for i in keep]

    ref_out = None
    trials: List[GeometryTrial] = []
    best: Optional[Tuple[float, int]] = None
    for i, geom in enumerate(candidates):
        out, secs = measure(geom)
        out = np.asarray(out)
        if i == 0:
            ref_out = out
            exact = True
        else:
            exact = (out.shape == ref_out.shape
                     and out.dtype == ref_out.dtype
                     and bool(np.array_equal(out, ref_out)))
        reason = None if exact else "parity_mismatch_vs_default"
        trials.append(GeometryTrial(
            index=i, geometry=geom.asdict(), seconds=float(secs),
            exact=exact, accepted=exact, reject_reason=reason,
            proxy_cost=proxies[i]))
        emit(f"{op} geom {i:2d} {json.dumps(geom.asdict(), sort_keys=True)} "
             f"{secs * 1e3:8.3f} ms "
             f"{'ok' if exact else 'REJECT parity'}")
        if exact and (best is None or secs < best[0]):
            best = (secs, i)
    wi = best[1]
    winner = candidates[wi]
    speedup = trials[0].seconds / max(trials[wi].seconds, 1e-30)
    if cache is not None:
        cache.put(op, str(dtype), int(key), device_kind, winner)
    emit(f"{op} winner: geom {wi} "
         f"{json.dumps(winner.asdict(), sort_keys=True)} "
         f"speedup x{speedup:.2f} vs default")
    return GeometrySweepResult(op=op, dtype=str(dtype), key=int(key),
                               device_kind=device_kind, trials=trials,
                               winner=winner.asdict(), winner_index=wi,
                               speedup=float(speedup))


def _plan(budget: int) -> Tuple[int, int, int]:
    """Split a trial budget into (warmup, short-rung, full-rung)."""
    budget = max(1, int(budget))
    if budget <= 2:
        return budget, 0, 0
    n_warm = max(1, budget // 4)
    n_short = max(1, (budget - n_warm) * 2 // 3)
    n_full = max(0, budget - n_warm - n_short)
    return n_warm, n_short, n_full


def autotune(runner: TrialRunner, *, budget: int = 8, seed: int = 0,
             space: Optional[ConfigSpace] = None,
             cost: Optional[ServingCostModel] = None,
             geometry_cache=None,
             log: Optional[Callable[[str], None]] = None) \
        -> Tuple[TunedProfile, List[TrialResult]]:
    """Search ``space`` with ``budget`` measured candidate trials (the
    default-config reference trial is extra) and return the tuned
    profile plus every trial record (accepted and rejected).

    ``geometry_cache`` (a :class:`~paddle_tpu.autotune.kernel_geometry
    .GeometryCache` from ``sweep_kernel_geometry`` /
    ``kernel_bench.py --sweep-geometry``) is stamped into the profile's
    per-op tier so ``GenerationServer(profile=)`` resolves per-layer
    kernel geometry the same way it resolves ``mk_geometry``."""
    emit = log or (lambda s: None)
    if space is None:
        import jax

        # bound the cp axis to meshes THIS host can build — a sampled
        # cp=4 on a 1-device box must be invalid, not a trial crash
        space = engine_space(max_len=runner.max_len,
                             devices=len(jax.devices()))
    cost = cost or ServingCostModel(runner.model.cfg,
                                    max_batch=runner.max_batch)
    rng = np.random.RandomState(seed)  # graftlint: noqa[np-random]
    workload = runner.workload
    trials: List[TrialResult] = []
    seen: set = set()

    def measure(config: Dict[str, Any], rung: str,
                reference_fp: Optional[str],
                predicted: Optional[float] = None) -> TrialResult:
        cfg = space.validate(config)
        fp_cfg = space.fingerprint(cfg)
        spec = workload if rung == "full" else short_workload
        fv, tok_fp, findings = runner.run(cfg, workload=spec)
        reason = None
        if findings:
            kinds = ",".join(f["kind"] for f in findings)
            reason = f"watchdog:{kinds}"
        elif reference_fp is not None and tok_fp != reference_fp:
            reason = (f"token_fingerprint_mismatch:{tok_fp}"
                      f"!={reference_fp}")
        tr = TrialResult(index=len(trials), rung=rung, config=cfg,
                         fingerprint=fp_cfg, features=fv,
                         tokens_fp=tok_fp, accepted=reason is None,
                         reject_reason=reason, predicted_tok_s=predicted)
        trials.append(tr)
        cost.observe(cfg, spec, fv.seconds, acceptance=fv.acceptance)
        emit(f"trial {tr.index:2d} [{rung:5s}] cfg={fp_cfg} "
             f"tok/s={fv.tok_s:8.1f} "
             f"{'ok' if tr.accepted else 'REJECT ' + (reason or '')}")
        return tr

    def sample_new(n: int, mutate_from: Optional[Dict[str, Any]] = None) \
            -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        tries = 0
        while len(out) < n and tries < 64 * n:
            tries += 1
            cfg = (space.mutate(mutate_from, rng) if mutate_from is not None
                   else space.sample(rng))
            fp = space.fingerprint(cfg)
            if fp not in seen:
                seen.add(fp)
                out.append(cfg)
        return out

    n_warm, n_short, n_full = _plan(budget)
    short_workload = workload.truncated(max(2, workload.requests // 4))

    # 1. reference trial: default config, full workload
    default_cfg = space.default()
    seen.add(space.fingerprint(default_cfg))
    ref = measure(default_cfg, "full", None)
    reference_fp = ref.tokens_fp
    baseline = ref.features

    # 2. random warmup (full rung — these calibrate the cost model)
    for cfg in sample_new(n_warm):
        measure(cfg, "full", reference_fp)
    cost.recalibrate()

    def incumbent() -> TrialResult:
        best = ref
        for t in trials:
            if t.rung == "full" and t.accepted \
                    and t.features.tok_s > best.features.tok_s:
                best = t
        return best

    # 3. candidate pool: fresh samples + mutations of the incumbent,
    #    ranked by the calibrated model's predicted throughput
    if n_short:
        pool = sample_new(4 * n_short)
        pool += sample_new(max(1, n_short // 2),
                           mutate_from=incumbent().config)
        ranked = sorted(
            ((cost.predict_tok_s(c, workload), i, c)
             for i, c in enumerate(pool)),
            key=lambda t: (-t[0], t[1]))
        pruned = len(ranked) - n_short
        if pruned > 0:
            emit(f"cost model pruned {pruned}/{len(ranked)} candidates "
                 f"without measuring them")

        # 4. short rung, then promote the best survivors to full trials
        short_done: List[Tuple[float, int, TrialResult]] = []
        for pred, _, cfg in ranked[:n_short]:
            tr = measure(cfg, "short", None, predicted=pred)
            if tr.accepted:
                short_done.append((tr.features.tok_s, tr.index, tr))
        short_done.sort(key=lambda t: (-t[0], t[1]))
        for _, _, tr in short_done[:n_full]:
            measure(tr.config, "full", reference_fp,
                    predicted=cost.predict_tok_s(tr.config, workload))
        cost.recalibrate()

    # 5. winner: best ACCEPTED full trial (the reference trial makes the
    #    set non-empty unless even the default misbehaved)
    win = incumbent()
    emit(f"winner: trial {win.index} cfg={win.fingerprint} "
         f"tok/s={win.features.tok_s:.1f} "
         f"(default {baseline.tok_s:.1f})")

    traffic_sig = runner.traffic_for(workload).signature()
    profile = TunedProfile(
        config=win.config,
        config_fingerprint=win.fingerprint,
        workload=workload.to_dict(),
        workload_signature=traffic_sig,
        metrics=win.features.to_dict(),
        baseline=baseline.to_dict(),
        search={
            "budget": int(budget),
            "seed": int(seed),
            "objective": "tok_s",
            "trials": len(trials),
            "plan": {"warmup": n_warm, "short": n_short, "full": n_full},
            "winner_trial": win.index,
            "rejected": [
                {"index": t.index, "fingerprint": t.fingerprint,
                 "reason": t.reject_reason}
                for t in trials if not t.accepted],
        },
        cost_model=cost.tick_model.to_dict(),
        kernel_geometry=(None if geometry_cache is None
                         else geometry_cache.to_dict()),
    )
    return profile, trials
