"""Seeded serving workloads, decoupled from the serving configuration.

The autotuner's whole premise is that two candidate configs are compared
on *identical* traffic — same prompts, same arrival instants, same
priorities, byte for byte. Before this module, ``serving_benchmark``
drew its traffic lazily from one ``RandomState`` that warmup bursts
also consumed, so the number of warmup requests (a function of
``--slots`` / ``--pool-frac`` — i.e. of the CONFIG) shifted the rng
state under the measured trace: two candidates at the same ``--seed``
saw different workloads and their tok/s were not comparable.

:class:`WorkloadSpec` fixes that by construction. It names only
*workload* knobs (request count, prompt-length ladder, arrival process,
priority mix, adapter fan-out, seed) — no serving knob appears — and
:func:`draw_traffic` derives the complete trace up front from a rng
seeded by the spec alone. Serving-config knobs cannot reach the draw.
``signature()`` hashes the drawn trace, so a tuned profile can record
exactly which workload it was tuned against and a replay can verify it
is measuring the same thing.

Everything here is host-side numpy + stdlib; nothing touches jax.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

#: prompt-length ladders (tokens) — mirror serving_benchmark's buckets
SHORT_PROMPT_LADDER: Tuple[int, ...] = (16, 30, 64, 100, 128)
LONG_PROMPT_LADDER: Tuple[int, ...] = (64, 128, 256, 400, 512)
#: log-spaced long-context rungs (serving_benchmark --long-context);
#: CPU-scale workloads pass an explicit smaller ladder instead
LONG_CONTEXT_LADDER: Tuple[int, ...] = (8192, 16384, 32768, 65536, 131072)


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    """Declarative serving workload. Only workload knobs live here —
    adding a serving-config field to this class is a bug (it would
    re-couple traffic to the thing being tuned)."""

    requests: int = 16
    max_new: int = 32
    prompt_ladder: Tuple[int, ...] = SHORT_PROMPT_LADDER
    vocab_size: int = 256
    repeat_suffix: bool = False
    mixed_priority: bool = False
    lora_adapters: int = 0
    #: open-loop arrivals at this rate (req/s) in ``burst``-sized clumps;
    #: None = closed-loop (everything submitted up front)
    arrival_rate: Optional[float] = None
    burst: int = 4
    #: long-context axis: with the default ladder, swaps in the
    #: log-spaced 8k-128k LONG_CONTEXT_LADDER (an explicit ladder — a
    #: CPU-scaled one — always wins)
    long_context: bool = False
    #: fraction [0,1] of every prompt replaced by ONE shared per-seed
    #: token prefix — the cross-request prefix-cache / warm-tier workload
    shared_prefix_frac: float = 0.0
    seed: int = 0

    def __post_init__(self):
        if self.long_context \
                and tuple(self.prompt_ladder) == SHORT_PROMPT_LADDER:
            object.__setattr__(self, "prompt_ladder", LONG_CONTEXT_LADDER)
        if not (0.0 <= self.shared_prefix_frac <= 1.0):
            raise ValueError(
                f"shared_prefix_frac must be in [0, 1], got "
                f"{self.shared_prefix_frac}")
        if self.requests < 1:
            raise ValueError(f"requests must be >= 1, got {self.requests}")
        if self.max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {self.max_new}")
        if self.burst < 1:
            raise ValueError(f"burst must be >= 1, got {self.burst}")
        if self.vocab_size < 2:
            raise ValueError(
                f"vocab_size must be >= 2, got {self.vocab_size}")
        if not self.prompt_ladder:
            raise ValueError("prompt_ladder must be non-empty")

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["prompt_ladder"] = list(self.prompt_ladder)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "WorkloadSpec":
        kw = dict(d)
        kw["prompt_ladder"] = tuple(kw.get("prompt_ladder",
                                           SHORT_PROMPT_LADDER))
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in kw.items() if k in known})

    def truncated(self, requests: int) -> "WorkloadSpec":
        """Same spec, fewer requests — the successive-halving short rung.
        The drawn trace is a strict prefix of the full trace (the draws
        are per-request and order-stable), so short-rung measurements
        see the same opening traffic the full rung does."""
        return dataclasses.replace(self, requests=min(requests,
                                                      self.requests))


@dataclasses.dataclass(frozen=True)
class TrafficRequest:
    """One pre-drawn request: everything ``submit()`` needs."""

    prompt: Tuple[int, ...]
    max_new: int
    priority: int
    tenant: str
    adapter: Optional[str]


@dataclasses.dataclass(frozen=True)
class Traffic:
    """A fully-drawn trace: requests in submit order plus the open-loop
    arrival schedule ``[(t_seconds, n_requests), ...]`` (empty =
    closed-loop burst)."""

    requests: Tuple[TrafficRequest, ...]
    schedule: Tuple[Tuple[float, int], ...]
    motif: Tuple[int, ...]

    def signature(self) -> str:
        """Stable hash of the trace — two configs replaying the same
        signature measured the same workload."""
        blob = json.dumps(
            [[list(r.prompt), r.max_new, r.priority, r.tenant, r.adapter]
             for r in self.requests]
            + [[round(t, 9), n] for t, n in self.schedule],
            sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


def _draw_request(rng: np.random.RandomState, spec: WorkloadSpec,  # graftlint: noqa[np-random]
                  index: int, motif: Sequence[int],
                  shared: Sequence[int] = ()) -> TrafficRequest:
    ln = int(rng.choice(spec.prompt_ladder))
    if spec.repeat_suffix:
        # tile one shared motif: greedy decoding locks onto the
        # repetition (the speculative showcase) and the shared prefix
        # exercises the prefix cache
        prompt = tuple((list(motif) * (ln // len(motif) + 1))[:ln])
    else:
        prompt = tuple(int(t) for t in
                       rng.randint(1, spec.vocab_size, ln))
    if spec.shared_prefix_frac > 0.0:
        # overlay the per-seed shared prefix (prompt lengths still come
        # from the ladder draw above, so the stream stays order-stable)
        k = int(ln * spec.shared_prefix_frac)
        prompt = tuple(shared[:k]) + prompt[k:]
    prio, tenant, adapter = 1, "default", None
    if spec.mixed_priority:
        prio = (0, 1, 2)[index % 3]
        tenant = ("a", "b")[index % 2]
    if spec.lora_adapters:
        adapter = f"a{index % spec.lora_adapters}"
        tenant = f"t{index % spec.lora_adapters}"
    return TrafficRequest(prompt=prompt, max_new=spec.max_new,
                          priority=prio, tenant=tenant, adapter=adapter)


def draw_traffic(spec: WorkloadSpec) -> Traffic:
    """Derive the complete trace from the spec — deterministically, up
    front, from a rng only the spec seeds. The serving config is not an
    input; it *cannot* perturb the draw."""
    rng = np.random.RandomState(spec.seed)  # graftlint: noqa[np-random]
    motif = tuple(int(t) for t in
                  rng.randint(1, spec.vocab_size, 8))
    shared: Tuple[int, ...] = ()
    if spec.shared_prefix_frac > 0.0:
        # drawn only when the knob is on, from its own xor-seeded
        # stream — enabling it must not shift the per-request draws,
        # and specs without it keep their historical signatures
        srng = np.random.RandomState((spec.seed + 0x5AFE) & 0x7FFFFFFF)  # graftlint: noqa[np-random]
        shared = tuple(int(t) for t in srng.randint(
            1, spec.vocab_size, max(spec.prompt_ladder)))
    reqs = tuple(_draw_request(rng, spec, i, motif, shared)
                 for i in range(spec.requests))
    schedule: List[Tuple[float, int]] = []
    if spec.arrival_rate is not None:
        t, left = 0.0, spec.requests
        while left > 0:
            n = min(spec.burst, left)
            schedule.append((t, n))
            left -= n
            t += float(rng.exponential(spec.burst / spec.arrival_rate))
    return Traffic(requests=reqs, schedule=tuple(schedule), motif=motif)


def warmup_traffic(spec: WorkloadSpec, n: int) -> Tuple[TrafficRequest, ...]:
    """Warmup requests from a rng stream DISJOINT from the measured
    trace (seed xor'd) — however many a config's warmup consumes, the
    measured traffic above is already fully drawn and untouched."""
    rng = np.random.RandomState((spec.seed ^ 0x5EED) & 0x7FFFFFFF)  # graftlint: noqa[np-random]
    motif = tuple(int(t) for t in rng.randint(1, spec.vocab_size, 8))
    shared: Tuple[int, ...] = ()
    if spec.shared_prefix_frac > 0.0:
        # the SAME shared prefix as the measured trace — warmup re-hits
        # are the point of the knob (prefix cache + warm tier warm)
        srng = np.random.RandomState((spec.seed + 0x5AFE) & 0x7FFFFFFF)  # graftlint: noqa[np-random]
        shared = tuple(int(t) for t in srng.randint(
            1, spec.vocab_size, max(spec.prompt_ladder)))
    return tuple(_draw_request(rng, spec, i, motif, shared)
                 for i in range(n))


def submit_traffic(server, requests: Sequence[TrafficRequest]) \
        -> Dict[int, TrafficRequest]:
    """Submit pre-drawn requests in order; returns {rid: request}."""
    out: Dict[int, TrafficRequest] = {}
    for r in requests:
        rid = server.submit(list(r.prompt), max_new_tokens=r.max_new,
                            priority=r.priority, tenant=r.tenant,
                            adapter=r.adapter)
        out[rid] = r
    return out
