"""Feature extraction: telemetry snapshot -> one flat trial vector.

The PR 7/13 substrate already measures everything a serving cost model
wants — the registry holds TTFT/TPOT percentiles and pressure counters,
the flight ring holds per-tick occupancy/recompile/spec deltas, and the
watchdog classifies pathologies. :class:`FeatureVector` is the single
flattened view of all three that the autotuner stores per trial, feeds
to calibration (``cost.py``), and tabulates (``telemetry_dump``).

Throughput (tokens/seconds) is supplied by the trial runner — the
registry never sees the runner's measured wall window, only latencies.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence


def _mean(xs: Sequence[float]) -> float:
    return float(sum(xs) / len(xs)) if xs else 0.0


@dataclasses.dataclass(frozen=True)
class FeatureVector:
    """One measured trial, flattened. ``None`` means "not observed"
    (e.g. acceptance without speculation), never "zero"."""

    # throughput (runner-measured wall window)
    tokens: int = 0
    seconds: float = 0.0
    tok_s: float = 0.0
    # latency percentiles (registry histograms, post-warmup)
    ttft_p50_s: Optional[float] = None
    ttft_p95_s: Optional[float] = None
    tpot_p50_ms: Optional[float] = None
    tpot_p95_ms: Optional[float] = None
    # per-tick flight aggregates
    ticks: int = 0
    mean_decoding: float = 0.0
    occupancy: float = 0.0          # mean decoding / slots_total (if known)
    mean_blocks_in_use: float = 0.0
    mean_queue_depth: float = 0.0
    # pressure + stability totals over the flight window
    preemptions: int = 0
    stalls: int = 0
    swap_out_blocks: int = 0
    swap_in_blocks: int = 0
    recompiles: int = 0
    # speculation over the flight window
    spec_proposed: int = 0
    spec_accepted: int = 0
    acceptance: Optional[float] = None   # accepted / proposed per window
    # watchdog verdicts ("preemption_storm", "steady_state_recompile", ...)
    watchdog_kinds: tuple = ()

    @property
    def clean(self) -> bool:
        """No watchdog finding — the trial is admissible as a winner."""
        return not self.watchdog_kinds

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["watchdog_kinds"] = list(self.watchdog_kinds)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FeatureVector":
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in known}
        kw["watchdog_kinds"] = tuple(kw.get("watchdog_kinds", ()))
        return cls(**kw)


def extract(telemetry, *, tokens: int, seconds: float,
            records: Optional[List[Dict[str, Any]]] = None,
            findings: Optional[List[Dict[str, Any]]] = None) \
        -> FeatureVector:
    """Flatten a post-run ``ServingTelemetry`` into a
    :class:`FeatureVector`.

    ``records``/``findings`` override the live flight dump / watchdog
    pass — the benchmark already ran both and the flight ring may have
    wrapped since. ``tokens``/``seconds`` are the runner's measured
    window (percentiles cover the same window because the runner resets
    histograms at the warmup boundary).
    """
    reg = telemetry.registry
    recs = telemetry.flight.dump() if records is None else records
    finds = telemetry.watchdog() if findings is None else findings

    decoding = [float(r.get("decoding", 0)) for r in recs]
    slots_total = None
    g = reg.get("serving_slots_total")
    if g is not None and g.total():
        slots_total = g.total()
    mean_dec = _mean(decoding)

    proposed = int(sum(r.get("spec_proposed", 0) for r in recs))
    accepted = int(sum(r.get("spec_accepted", 0) for r in recs))
    # acceptance per verify window, the gate_low unit — windows are the
    # ticks that actually proposed drafts
    windows = sum(1 for r in recs if r.get("spec_proposed", 0) > 0)
    acceptance = (accepted / windows) if windows else None

    return FeatureVector(
        tokens=int(tokens),
        seconds=float(seconds),
        tok_s=(tokens / seconds) if seconds > 0 else 0.0,
        ttft_p50_s=reg.percentile("serving_ttft_s", 50.0),
        ttft_p95_s=reg.percentile("serving_ttft_s", 95.0),
        tpot_p50_ms=reg.percentile("serving_tpot_ms", 50.0),
        tpot_p95_ms=reg.percentile("serving_tpot_ms", 95.0),
        ticks=len(recs),
        mean_decoding=mean_dec,
        occupancy=(mean_dec / slots_total) if slots_total else mean_dec,
        mean_blocks_in_use=_mean([float(r.get("blocks_in_use", 0))
                                  for r in recs]),
        mean_queue_depth=_mean([float(r.get("queue_depth", 0))
                                for r in recs]),
        preemptions=int(sum(r.get("preemptions", 0) for r in recs)),
        stalls=int(sum(r.get("stalls", 0) for r in recs)),
        swap_out_blocks=int(sum(r.get("swap_out_blocks", 0) for r in recs)),
        swap_in_blocks=int(sum(r.get("swap_in_blocks", 0) for r in recs)),
        recompiles=int(sum(r.get("recompiles", 0) for r in recs)),
        spec_proposed=proposed,
        spec_accepted=accepted,
        acceptance=acceptance,
        watchdog_kinds=tuple(sorted({f.get("kind", "?") for f in finds})),
    )
