"""Tuned-profile JSON: the autotuner's durable artifact.

A profile records the winning config, the exact workload it was tuned
against (spec + drawn-trace signature), the measured metrics that won,
the baseline they beat, and the calibrated cost coefficients — enough
to (a) apply the config (``GenerationServer(profile=...)``), (b) audit
the decision (``telemetry_dump`` trials mode), and (c) detect drift
(replay the recorded workload, compare signatures).

``config_fingerprint`` is recomputed on load; a hand-edited config
fails loudly at load time, not as a mystery regression in production.
``created_unix`` is the only non-deterministic field — byte-equality
tests compare :meth:`TunedProfile.canonical_json`, which strips it.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Mapping, Optional

from .space import ALL_KNOBS, ConfigSpace
from .workload import WorkloadSpec

# v2: the config gained the kernel tier (kernels + mk_* megakernel
# geometry knobs) — v1 profiles are missing knobs under the new space
# and must retune rather than guess
# v3: profiles carry the per-layer kernel-geometry winner cache
# (``kernel_geometry``, a GeometryCache dict keyed by (op, dtype,
# shape, chip)) — v2 profiles lack the per-op tier entirely, and a
# default-geometry guess would silently discard the sweep, so they
# must retune rather than guess, same rule as v1->v2
PROFILE_SCHEMA_VERSION = 3


@dataclasses.dataclass
class TunedProfile:
    config: Dict[str, Any]
    config_fingerprint: str
    workload: Dict[str, Any]
    workload_signature: str
    metrics: Dict[str, Any]                 # winner's FeatureVector dict
    baseline: Dict[str, Any]                # default config's, same traffic
    search: Dict[str, Any]                  # budget/seed/trials/rejects
    cost_model: Dict[str, float]            # calibrated tick coefficients
    schema: int = PROFILE_SCHEMA_VERSION
    created_unix: Optional[float] = None
    # per-layer kernel-geometry winner cache (GeometryCache.to_dict();
    # None = no per-op sweep ran — servers keep default geometry)
    kernel_geometry: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------- (de)ser
    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any],
                  verify: bool = True) -> "TunedProfile":
        if d.get("schema") != PROFILE_SCHEMA_VERSION:
            raise ValueError(
                f"tuned profile schema {d.get('schema')!r} != "
                f"{PROFILE_SCHEMA_VERSION} — retune rather than guess")
        known = {f.name for f in dataclasses.fields(cls)}
        prof = cls(**{k: v for k, v in d.items() if k in known})
        if verify:
            space = ConfigSpace(ALL_KNOBS)
            fp = space.fingerprint(prof.config)   # validates the config too
            if fp != prof.config_fingerprint:
                raise ValueError(
                    f"profile config fingerprint mismatch: recorded "
                    f"{prof.config_fingerprint!r}, recomputed {fp!r} — "
                    f"the config was edited after tuning")
            if prof.kernel_geometry is not None:
                from .kernel_geometry import GeometryCache

                # recomputes the cache's own fingerprint — a tampered
                # geometry entry fails here, same contract as the config
                GeometryCache.from_dict(prof.kernel_geometry)
        return prof

    def geometry_cache(self):
        """The per-layer winner cache this profile carries, parsed
        (verified on access), or None when no per-op sweep ran."""
        if self.kernel_geometry is None:
            return None
        from .kernel_geometry import GeometryCache

        return GeometryCache.from_dict(self.kernel_geometry)

    def canonical_json(self) -> str:
        """Deterministic serialization (timestamp stripped) — what the
        determinism tests byte-compare."""
        d = self.to_dict()
        d.pop("created_unix", None)
        return json.dumps(d, sort_keys=True, indent=2, default=str) + "\n"

    def save(self, path: str, now: Optional[float] = None) -> str:
        """``now`` stamps ``created_unix`` (callers outside the
        deterministic search — the CLI — pass ``time.time()``; the
        search itself leaves it None so replays stay byte-equal)."""
        d = self.to_dict()
        if d.get("created_unix") is None and now is not None:
            d["created_unix"] = float(now)
        with open(path, "w") as f:
            json.dump(d, f, sort_keys=True, indent=2, default=str)
            f.write("\n")
        return path

    @classmethod
    def load(cls, path: str, verify: bool = True) -> "TunedProfile":
        with open(path) as f:
            return cls.from_dict(json.load(f), verify=verify)

    # ------------------------------------------------------------ apply
    def workload_spec(self) -> WorkloadSpec:
        return WorkloadSpec.from_dict(self.workload)

    def server_kwargs(self, model_cfg, *, max_batch: int,
                      max_len: int) -> Dict[str, Any]:
        """The ``GenerationServer`` ctor kwargs this profile pins. The
        caller still owns model/max_batch/max_len (they are workload
        inputs, not tuned knobs)."""
        return config_server_kwargs(self.config, model_cfg,
                                    max_batch=max_batch, max_len=max_len)

    def fleet_kwargs(self) -> Dict[str, Any]:
        """The fleet-tier knobs (``FleetRouter`` ctor args + replica
        count) for fleet deployments; single-engine users ignore this."""
        cfg = self.config
        return {
            "replicas": int(cfg.get("fleet_replicas", 1)),
            "prefix_weight": float(cfg.get("prefix_weight", 1.0)),
            "load_weight": float(cfg.get("load_weight", 1.0)),
            "probe_every": int(cfg.get("probe_every", 16)),
            "degrade_cooldown_s": float(cfg.get("degrade_cooldown_s", 0.0)),
        }


def config_server_kwargs(config: Mapping[str, Any], model_cfg, *,
                         max_batch: int, max_len: int) -> Dict[str, Any]:
    """Map a canonical space config onto ``GenerationServer`` ctor
    kwargs. ``pool_frac`` resolves against THIS geometry's fp-parity
    byte budget (``(max_batch*ceil(max_len/bs)+1) * fp block bytes``) so
    the fraction means the same thing at any batch shape or kv_quant —
    and the int8 pool keeps its capacity win at the same fraction."""
    from ..inference.serving import kv_block_bytes
    from ..inference.speculative import SpecConfig

    cfg = dict(config)
    bs = int(cfg["block_size"])
    kw: Dict[str, Any] = {
        "cache": "paged",
        "block_size": bs,
        "tick_window": int(cfg["tick_window"]),
        "prefill_chunk": int(cfg["prefill_chunk"]),
        "kv_quant": str(cfg["kv_quant"]),
        "policy": str(cfg["policy"]),
    }
    k = int(cfg.get("draft_k", 0))
    if k > 0:
        kw["spec"] = SpecConfig(k=k, gate_low=float(cfg["spec_gate_low"]))
    cp = int(cfg.get("cp", 1))
    if cp > 1:
        kw["mesh"] = f"cp={cp}"
    lo = cfg.get("tier_demote_low", None)
    if lo is not None:
        kw["tier_demote_low"] = float(lo)
        kw["tier_demote_high"] = float(cfg["tier_demote_high"])
    pool_frac = float(cfg.get("pool_frac", 1.0))
    if pool_frac < 1.0:
        entries = -(-max_len // bs)
        parity_bytes = (max_batch * entries + 1) \
            * kv_block_bytes(model_cfg, bs, "none")
        kw["pool_bytes"] = max(1, int(parity_bytes * pool_frac))
        mb = cfg.get("host_pool_mb", None)
        kw["host_pool_bytes"] = None if mb is None else int(mb) << 20
    kernels = str(cfg.get("kernels", "auto"))
    if kernels != "auto":
        kw["kernels"] = kernels
    if kernels == "megakernel":
        from ..ops.decode_megakernel import MegakernelGeometry

        kw["mk_geometry"] = MegakernelGeometry(
            ffn_tile=int(cfg.get("mk_ffn_tile", 0)),
            prefetch_depth=int(cfg.get("mk_prefetch_depth", 2)),
            dequant=str(cfg.get("mk_dequant", "scores")))
    return kw


def resolve_profile(profile) -> Optional[TunedProfile]:
    """Accept what ``GenerationServer(profile=)`` accepts: None, a path
    to a profile JSON, a parsed dict, or a :class:`TunedProfile`."""
    if profile is None or isinstance(profile, TunedProfile):
        return profile
    if isinstance(profile, str):
        return TunedProfile.load(profile)
    if isinstance(profile, Mapping):
        return TunedProfile.from_dict(profile)
    raise ValueError(
        f"profile must be None, a path, a dict, or a TunedProfile, "
        f"got {type(profile).__name__}")
