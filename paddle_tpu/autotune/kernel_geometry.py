"""Per-layer kernel geometry: tunable schedules for the per-op Pallas
kernels, plus the per-(op, dtype, shape, chip) winner cache.

PR 16 made the whole-tick megakernel's schedule tunable
(:class:`~paddle_tpu.ops.decode_megakernel.MegakernelGeometry`); this
module is the open half of ROADMAP item 3 — the *per-layer* kernels
(paged attention fp/int8, fused LoRA, flash attention, fused norm,
fused CE) get the same treatment. One frozen dataclass per op family
expresses the schedule as data with ``validate()`` + a VMEM-occupancy
model, mirroring ``MegakernelGeometry``.

The geometry contract is STRICTER than the megakernel's: every
supported geometry is a schedule change only — tile/block shapes, grid
iteration order, streaming depth, hoisted-but-exact casts — never a
math-order change, so any geometry's output is BIT-EXACT against the
default geometry's (the parity sweep in tests/test_kernel_geometry.py
pins this bitwise, fp and int8). The default geometry of every class
reproduces the pre-geometry kernels exactly: zero values mean "derive
today's hardcoded choice". Knobs that would regroup floating-point
accumulation (e.g. the flash kernel's kv block, which sets the online-
softmax update granularity) exist as declared axes but are excluded
from the sweep candidate space; the search additionally hard-rejects
any candidate whose output is not bitwise equal to the default's, so a
non-exact schedule can never become a cached winner.

Winners are cached per ``(op, dtype, head_dim_or_row, device_kind)`` in
a :class:`GeometryCache` — the schedule space is hardware-generation-
specific (TVM / the XLA fusion study, PAPERS.md), so a fleet on mixed
TPU generations resolves per-chip winners from one artifact. The cache
persists inside ``TunedProfile`` (schema v3) and carries its own
fingerprint; a hand-edited cache fails at load, same contract as the
profile's ``config_fingerprint``.

Resolution mirrors the kernel-mode contract (``ops.set_kernel_mode``):
``install_geometry_cache`` pins a process-wide cache that the op
dispatch seams read at TRACE time; ``GenerationServer`` installs the
profile's cache in its constructor (before the executor traces) and
records the resolved per-op geometry in its snapshot fingerprint.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, Mapping, Optional, Tuple

#: int8 dequant placements for the paged-attention kernel. Both apply
#: the k/v scales in the reference order (bit-exact); they differ only
#: in WHERE the exact int8->fp cast of the streamed KV tile sits:
#: "scores" casts inside the causal-skip branch (today's schedule,
#: skipped blocks never cast), "early" hoists the cast to the top of
#: the grid step (branchless stream — the tile is cast as soon as its
#: DMA lands, trading wasted casts on skipped blocks for a shorter
#: critical path into the QK matmul).
PA_DEQUANT_MODES = ("scores", "early")

#: paged-attention grid iteration orders over the two parallel axes:
#: "bgm" = (batch, kv_head, kv_block) — today's order; "gbm" swaps the
#: batch and kv-head axes (same cells, different walk — changes which
#: pool blocks are DMA-adjacent).
PA_GRID_ORDERS = ("bgm", "gbm")

#: fused-LoRA accumulation layouts: which matmul chain issues first.
#: The final combine is ``y + d * s`` either way (bit-exact);
#: "delta_first" starts the low-rank chain before the base projection
#: so the small matmuls hide under the big one's MXU occupancy.
LORA_ACCUM_LAYOUTS = ("base_first", "delta_first")


def _largest_divisor(n: int, want: int) -> int:
    """Largest divisor of ``n`` that is <= ``want`` (>= 1). Geometry
    values quantize onto real shapes through this — a requested tile
    that doesn't divide the axis degrades deterministically instead of
    erroring, same spirit as flash's ``_pick_block``."""
    want = max(1, min(int(want), int(n)))
    for c in range(want, 0, -1):
        if n % c == 0:
            return c
    return 1


@dataclasses.dataclass(frozen=True)
class PagedAttentionGeometry:
    """Schedule of the paged decode/verify/prefill attention kernel
    (ops/paged_attention_pallas.py), fp and int8.

    ``kv_block_depth``: KV-pool blocks streamed per grid step. 1 =
    today's one-block-per-step schedule; d > 1 fetches d table-routed
    blocks into VMEM per step (d block specs) and applies the online-
    softmax update to each IN ORDER inside the step — same math, same
    order, fewer grid steps, deeper DMA pipelining. Clamped to a
    divisor of the table width at trace time.

    ``q_rows``: q-row tile. 0 = the whole W*rep GQA row group per
    program (today); > 0 tiles the rows across an extra parallel grid
    axis (rows are independent in attention — bit-exact). Clamped to a
    divisor of W*rep.

    ``grid_order``: iteration order of the parallel axes, one of
    :data:`PA_GRID_ORDERS`.

    ``dequant``: int8 cast placement, one of :data:`PA_DEQUANT_MODES`;
    dead (canonicalized to "scores") for fp pools.
    """

    kv_block_depth: int = 1
    q_rows: int = 0
    grid_order: str = "bgm"
    dequant: str = "scores"

    def validate(self) -> None:
        if not 1 <= self.kv_block_depth <= 8:
            raise ValueError("kv_block_depth must be in [1, 8], got "
                             f"{self.kv_block_depth}")
        if self.q_rows < 0:
            raise ValueError(f"q_rows must be >= 0, got {self.q_rows}")
        if self.grid_order not in PA_GRID_ORDERS:
            raise ValueError(f"grid_order must be one of {PA_GRID_ORDERS}, "
                             f"got {self.grid_order!r}")
        if self.dequant not in PA_DEQUANT_MODES:
            raise ValueError(f"dequant must be one of {PA_DEQUANT_MODES}, "
                             f"got {self.dequant!r}")

    def vmem_bytes(self, *, head_dim: int, block_size: int, window: int,
                   rep: int, dtype_bytes: int = 4,
                   quantized: bool = False) -> int:
        """Worst-case VMEM residency of one grid step: the q tile, the
        streamed KV tiles (+ scales), and the online-softmax scratch."""
        rows = window * rep if self.q_rows == 0 \
            else min(self.q_rows, window * rep)
        d = self.kv_block_depth
        kv_item = 1 if quantized else dtype_bytes
        n = rows * head_dim * dtype_bytes                  # q tile
        n += d * 2 * block_size * head_dim * kv_item       # k/v tiles
        if quantized:
            n += d * 2 * 4                                 # per-block scales
            if self.dequant == "early":
                # hoisted casts keep fp twins of the tiles live
                n += d * 2 * block_size * head_dim * dtype_bytes
        n += rows * (2 * 128 + head_dim) * 4               # m/l/acc scratch
        return n

    def asdict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class LoRAGeometry:
    """Schedule of the fused base+LoRA projection
    (ops/paged_attention_pallas.fused_lora_matmul).

    ``rank_pad``: pad the adapter rank dim up to a multiple of this
    before the kernel (0 = no padding, today's layout). Zero columns
    of A / zero rows of B contribute exact zeros to the low-rank
    chain — bit-exact — while aligning the contraction to the MXU's
    native tiling.

    ``accum``: matmul issue order, one of :data:`LORA_ACCUM_LAYOUTS`.
    """

    rank_pad: int = 0
    accum: str = "base_first"

    def validate(self) -> None:
        if self.rank_pad < 0 or self.rank_pad > 1024:
            raise ValueError("rank_pad must be in [0, 1024], got "
                             f"{self.rank_pad}")
        if self.accum not in LORA_ACCUM_LAYOUTS:
            raise ValueError(f"accum must be one of {LORA_ACCUM_LAYOUTS}, "
                             f"got {self.accum!r}")

    def padded_rank(self, rank: int) -> int:
        if self.rank_pad <= 0 or rank % self.rank_pad == 0:
            return rank
        return -(-rank // self.rank_pad) * self.rank_pad

    def vmem_bytes(self, *, seq: int, in_dim: int, out_dim: int, rank: int,
                   dtype_bytes: int = 4) -> int:
        rp = self.padded_rank(rank)
        n = seq * in_dim * dtype_bytes          # x row
        n += in_dim * out_dim * dtype_bytes     # base weight
        n += (in_dim * rp + rp * out_dim) * 4   # A/B factors (f32)
        n += 2 * seq * out_dim * 4              # y + delta accumulators
        return n

    def asdict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class FlashAttentionGeometry:
    """Schedule of the flash attention kernels
    (ops/flash_attention.py). 0 = derive from the measured per-regime
    tables (``_block_defaults``) — today's behavior.

    ``block_q``: q-block rows. Rows are independent, so any block_q is
    mathematically identical — but bitwise equality additionally needs
    the backend's matmul to contract each row the same way at every
    tile shape (true of the MXU's fixed systolic order; host BLAS
    microkernels may regroup by tile). The sweep's bitwise gate decides
    empirically per chip: a block_q that regroups on this backend is
    parity-rejected and the default keeps the cell.

    ``block_kv``: kv-block width. CAUTION: this sets the online-softmax
    update granularity, so non-default values regroup the running
    max/sum accumulation — a schedule axis that is NOT parity-exact.
    It is declared here (and honored when set explicitly) but excluded
    from sweep candidates; the sweep's bitwise parity gate would reject
    any such candidate regardless.
    """

    block_q: int = 0
    block_kv: int = 0

    def validate(self) -> None:
        for name, v in (("block_q", self.block_q),
                        ("block_kv", self.block_kv)):
            if v < 0 or v > 4096:
                raise ValueError(f"{name} must be in [0, 4096], got {v}")
            if v and v % 8:
                raise ValueError(f"{name} must be sublane-aligned (8), "
                                 f"got {v}")

    def vmem_bytes(self, *, head_dim: int, seq_k: int,
                   dtype_bytes: int = 4) -> int:
        bq = self.block_q or 512
        bk = self.block_kv or 512
        n = bq * head_dim * dtype_bytes                 # q block
        n += 2 * min(bk, seq_k) * head_dim * dtype_bytes  # k/v blocks
        n += bq * (head_dim + 2) * 4                    # acc + m/l rows
        return n

    def asdict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class NormGeometry:
    """Row tile of the fused RMS/Layer norm kernels
    (ops/fused_norm.py). ``rows`` = 0 derives today's
    ``max(min(512, rows), 8)``; > 0 requests that tile, clamped to a
    divisor of the flattened row count (rows are independent —
    bit-exact)."""

    rows: int = 0

    def validate(self) -> None:
        if self.rows < 0 or self.rows > 4096:
            raise ValueError(f"rows must be in [0, 4096], got {self.rows}")

    def vmem_bytes(self, *, width: int, dtype_bytes: int = 4) -> int:
        r = self.rows or 512
        return r * width * (dtype_bytes + 4) + width * dtype_bytes

    def asdict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class CEGeometry:
    """Row sub-tile of the fused linear-cross-entropy forward
    (ops/fused_ce.py). ``rows`` = 0 keeps today's whole-chunk logits
    transient; > 0 computes the row-local quantities (logits row,
    logsumexp, label gather) in ``rows``-row sub-tiles of each scan
    chunk, shrinking the [chunk, V] f32 transient to [rows, V]. The
    loss reduction stays at whole-chunk granularity — per-row values
    are identical and the summation grouping is untouched, so any
    sub-tile is bit-exact vs the default. Clamped to a divisor of the
    effective chunk."""

    rows: int = 0

    def validate(self) -> None:
        if self.rows < 0 or self.rows > 16384:
            raise ValueError(f"rows must be in [0, 16384], got {self.rows}")

    def vmem_bytes(self, *, hidden: int, vocab: int,
                   dtype_bytes: int = 4) -> int:
        r = self.rows or 1024
        return r * vocab * 4 + r * hidden * dtype_bytes

    def asdict(self) -> dict:
        return dataclasses.asdict(self)


#: op family -> geometry class; the op names are the cache/telemetry
#: vocabulary (``serving_kernel_geometry{op=...}``)
OP_GEOMETRY = {
    "paged_attention": PagedAttentionGeometry,
    "fused_lora": LoRAGeometry,
    "flash_attention": FlashAttentionGeometry,
    "fused_norm": NormGeometry,
    "fused_ce": CEGeometry,
}

OP_FAMILIES = tuple(sorted(OP_GEOMETRY))


def default_geometry(op: str):
    return OP_GEOMETRY[op]()


def geometry_from_dict(op: str, d: Mapping[str, Any]):
    cls = OP_GEOMETRY.get(op)
    if cls is None:
        raise ValueError(f"unknown geometry op {op!r} — must be one of "
                         f"{OP_FAMILIES}")
    known = {f.name for f in dataclasses.fields(cls)}
    extra = set(d) - known
    if extra:
        raise ValueError(f"unknown {op} geometry fields {sorted(extra)}")
    geom = cls(**dict(d))
    geom.validate()
    return geom


# ---------------------------------------------------------------- the cache
def local_device_kind() -> str:
    """The chip the process is on (``jax.devices()[0].device_kind`` —
    e.g. "TPU v5e", "cpu"); cache keys carry it so one artifact serves
    a mixed-generation fleet."""
    import jax

    return str(jax.devices()[0].device_kind)


def _key_str(op: str, dtype: str, key: int, device_kind: str) -> str:
    for part in (op, dtype, device_kind):
        if "|" in part:
            raise ValueError(f"geometry cache key part {part!r} may not "
                             f"contain '|'")
    return f"{op}|{dtype}|{int(key)}|{device_kind}"


class GeometryCache:
    """Winner table keyed by ``(op, dtype, head_dim_or_row,
    device_kind)``. A miss — including an unknown chip — resolves to
    the op's default geometry at the caller, never to a guess from
    another key. Serialization carries a content fingerprint
    (sha256[:12] of the canonical entry JSON); :meth:`from_dict`
    recomputes it, so a tampered cache fails at load exactly like a
    tampered profile config."""

    def __init__(self, entries: Optional[Dict[str, Any]] = None):
        self._entries: Dict[str, Any] = {}
        if entries:
            for kstr, geom in entries.items():
                op = kstr.split("|", 1)[0]
                if not isinstance(geom, OP_GEOMETRY.get(op, ())):
                    raise ValueError(
                        f"entry {kstr!r} holds {type(geom).__name__}, "
                        f"expected {OP_GEOMETRY[op].__name__}")
                self._entries[kstr] = geom

    def __len__(self) -> int:
        return len(self._entries)

    def __eq__(self, other) -> bool:
        return (isinstance(other, GeometryCache)
                and self._entries == other._entries)

    def put(self, op: str, dtype: str, key: int, device_kind: str,
            geometry) -> None:
        if not isinstance(geometry, OP_GEOMETRY[op]):
            raise ValueError(
                f"{op} wants {OP_GEOMETRY[op].__name__}, got "
                f"{type(geometry).__name__}")
        geometry.validate()
        self._entries[_key_str(op, dtype, key, device_kind)] = geometry

    def lookup(self, op: str, dtype: str, key: int,
               device_kind: Optional[str] = None):
        """The cached winner, or None on any miss (op never swept,
        different dtype/shape, unknown chip) — the caller falls back to
        the op's default geometry."""
        if device_kind is None:
            device_kind = local_device_kind()
        return self._entries.get(_key_str(op, dtype, key, device_kind))

    def entries(self) -> Dict[str, Any]:
        return dict(self._entries)

    # ------------------------------------------------------------ (de)ser
    def _canonical_entries(self) -> Dict[str, dict]:
        return {k: self._entries[k].asdict() for k in sorted(self._entries)}

    def fingerprint(self) -> str:
        blob = json.dumps(self._canonical_entries(), sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:12]

    def to_dict(self) -> Dict[str, Any]:
        return {"entries": self._canonical_entries(),
                "fingerprint": self.fingerprint()}

    @classmethod
    def from_dict(cls, d: Mapping[str, Any],
                  verify: bool = True) -> "GeometryCache":
        entries = {}
        for kstr, gd in dict(d.get("entries", {})).items():
            parts = kstr.split("|")
            if len(parts) != 4:
                raise ValueError(f"malformed geometry cache key {kstr!r} "
                                 f"(want op|dtype|key|device_kind)")
            entries[kstr] = geometry_from_dict(parts[0], gd)
        cache = cls(entries)
        if verify:
            fp = cache.fingerprint()
            if fp != d.get("fingerprint"):
                raise ValueError(
                    f"geometry cache fingerprint mismatch: recorded "
                    f"{d.get('fingerprint')!r}, recomputed {fp!r} — the "
                    f"cache was edited after the sweep")
        return cache


# ------------------------------------------------- trace-time resolution
# Mirrors ops.set_kernel_mode: process-wide, read at TRACE time by the
# op dispatch seams, so it must be installed before the first trace
# (GenerationServer installs its profile's cache in the constructor).
GEOMETRY_SOURCES = ("default", "profile", "swept")

_ACTIVE_CACHE: Optional[GeometryCache] = None
_ACTIVE_SOURCE: str = "default"


def install_geometry_cache(cache: Optional[GeometryCache],
                           source: str = "swept") -> None:
    """Pin the process-wide winner cache (None resets to defaults).
    ``source`` labels telemetry: "profile" when a TunedProfile carried
    it, "swept" for a cache installed directly from a sweep artifact."""
    global _ACTIVE_CACHE, _ACTIVE_SOURCE
    if cache is not None and not isinstance(cache, GeometryCache):
        raise ValueError(f"expected a GeometryCache or None, got "
                         f"{type(cache).__name__}")
    if source not in GEOMETRY_SOURCES:
        raise ValueError(f"source must be one of {GEOMETRY_SOURCES}, "
                         f"got {source!r}")
    _ACTIVE_CACHE = cache
    _ACTIVE_SOURCE = "default" if cache is None else source


def active_geometry_cache() -> Optional[GeometryCache]:
    return _ACTIVE_CACHE


def active_geometry_source() -> str:
    return _ACTIVE_SOURCE


def resolve_geometry(op: str, dtype: str, key: int,
                     device_kind: Optional[str] = None) -> Tuple[Any, str]:
    """(geometry, source) for one op at trace time: the active cache's
    winner when present, else the op's default. Never raises on a miss
    — an unknown chip degrades to the default schedule."""
    if _ACTIVE_CACHE is not None:
        hit = _ACTIVE_CACHE.lookup(op, str(dtype), int(key), device_kind)
        if hit is not None:
            return hit, _ACTIVE_SOURCE
    return default_geometry(op), "default"


def resolve_server_geometries(*, head_dim: int, hidden: int, dtype: str,
                              kv_quant: str, lora_rank: Optional[int] = None,
                              device_kind: Optional[str] = None
                              ) -> Dict[str, Tuple[Any, str]]:
    """The per-op resolution a GenerationServer performs at
    construction — the per-layer twin of the megakernel's
    ``mk_geometry`` resolution. Keys follow the cache convention:
    head_dim for the attention ops, the adapter rank for fused LoRA,
    the hidden width for the row-tiled fused ops; the paged-attention
    dtype is "int8" under KV quantization (the int8 kernel is a
    different schedule space than the fp one)."""
    pa_dtype = "int8" if kv_quant == "int8" else dtype
    out = {
        "paged_attention": resolve_geometry(
            "paged_attention", pa_dtype, head_dim, device_kind),
        "flash_attention": resolve_geometry(
            "flash_attention", dtype, head_dim, device_kind),
        "fused_norm": resolve_geometry(
            "fused_norm", dtype, hidden, device_kind),
        "fused_ce": resolve_geometry(
            "fused_ce", dtype, hidden, device_kind),
    }
    if lora_rank is not None:
        out["fused_lora"] = resolve_geometry(
            "fused_lora", dtype, lora_rank, device_kind)
    return out


# ------------------------------------------------------ sweep candidates
def geometry_candidates(op: str, *, quantized: bool = False,
                        vmem_limit_bytes: Optional[int] = None,
                        **shape) -> list:
    """The deterministic candidate rung for one op family: a canonical
    enumeration of the bit-exact schedule axes, deduped after
    canonicalization (fp pins the dead dequant knob), filtered by the
    op's VMEM-occupancy model against the per-core budget. Ordered so
    index 0 is always the default geometry — ties in the sweep resolve
    toward it."""
    if vmem_limit_bytes is None:
        from .space import MK_VMEM_LIMIT_BYTES

        vmem_limit_bytes = MK_VMEM_LIMIT_BYTES
    cands: list = []
    if op == "paged_attention":
        for depth in (1, 2, 4):
            for q_rows in (0, 8, 16):
                for order in PA_GRID_ORDERS:
                    for deq in (PA_DEQUANT_MODES if quantized
                                else ("scores",)):
                        cands.append(PagedAttentionGeometry(
                            kv_block_depth=depth, q_rows=q_rows,
                            grid_order=order, dequant=deq))
        cands = [g for g in cands if g.vmem_bytes(
            head_dim=shape.get("head_dim", 128),
            block_size=shape.get("block_size", 16),
            window=shape.get("window", 4),
            rep=shape.get("rep", 4),
            quantized=quantized) <= vmem_limit_bytes]
    elif op == "fused_lora":
        for pad in (0, 8, 16, 128):
            for accum in LORA_ACCUM_LAYOUTS:
                cands.append(LoRAGeometry(rank_pad=pad, accum=accum))
        cands = [g for g in cands if g.vmem_bytes(
            seq=shape.get("seq", 1),
            in_dim=shape.get("in_dim", 1024),
            out_dim=shape.get("out_dim", 1024),
            rank=shape.get("rank", 8)) <= vmem_limit_bytes]
    elif op == "flash_attention":
        # block_kv stays at the regime default: it regroups the online
        # softmax (not parity-exact) — see FlashAttentionGeometry
        for bq in (0, 128, 256, 512):
            cands.append(FlashAttentionGeometry(block_q=bq))
        cands = [g for g in cands if g.vmem_bytes(
            head_dim=shape.get("head_dim", 128),
            seq_k=shape.get("seq_k", 2048)) <= vmem_limit_bytes]
    elif op == "fused_norm":
        for rows in (0, 8, 64, 256, 512):
            cands.append(NormGeometry(rows=rows))
    elif op == "fused_ce":
        for rows in (0, 64, 128, 256, 512):
            cands.append(CEGeometry(rows=rows))
    else:
        raise ValueError(f"unknown geometry op {op!r}")
    default = default_geometry(op)
    rest = sorted((g for g in cands if g != default),
                  key=lambda g: json.dumps(g.asdict(), sort_keys=True))
    return [default] + rest
