"""Linear algebra ops (ref: python/paddle/tensor/linalg.py).

All lower to XLA's native decompositions — on TPU these run on the MXU where
possible (matmul-rich algorithms) with fp32 accumulation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op
from .math import matmul, dot, bmm, mm  # re-exported by paddle.linalg


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def f(v):
        if p is None or p == "fro":
            if axis is None:
                return jnp.sqrt(jnp.sum(jnp.square(v)))
            return jnp.linalg.norm(v, ord=None, axis=_ax(axis), keepdims=keepdim)
        if p == "nuc":
            return jnp.sum(jnp.linalg.svd(v, compute_uv=False), axis=-1)
        if p == np.inf or p == float("inf"):
            return jnp.max(jnp.abs(v), axis=_ax(axis), keepdims=keepdim) if axis is not None \
                else jnp.max(jnp.abs(v))
        if p == -np.inf or p == float("-inf"):
            return jnp.min(jnp.abs(v), axis=_ax(axis), keepdims=keepdim) if axis is not None \
                else jnp.min(jnp.abs(v))
        if axis is None:
            return jnp.power(jnp.sum(jnp.power(jnp.abs(v), p)), 1.0 / p)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(v), p), axis=_ax(axis), keepdims=keepdim),
                         1.0 / p)

    def _ax(a):
        if isinstance(a, (list, tuple)):
            return tuple(int(i) for i in a)
        return int(a)

    return apply_op(f, x, op_name="norm")


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return apply_op(lambda v: jnp.linalg.norm(v, ord=None if p == "fro" else p,
                                              axis=tuple(axis), keepdims=keepdim), x)


def cond(x, p=None, name=None):
    return apply_op(lambda v: jnp.linalg.cond(v, p=p), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    t = to_array(tol) if isinstance(tol, Tensor) else tol
    return apply_op(lambda v: jnp.linalg.matrix_rank(v, rtol=None if t is None else t), x)


def matrix_power(x, n, name=None):
    return apply_op(lambda v: jnp.linalg.matrix_power(v, n), x)


def det(x, name=None):
    return apply_op(jnp.linalg.det, x)


def slogdet(x, name=None):
    def f(v):
        sign, logdet = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logdet])

    return apply_op(f, x)


def inv(x, name=None):
    return apply_op(jnp.linalg.inv, x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op(lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian), x)


def solve(x, y, name=None):
    return apply_op(jnp.linalg.solve, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular)

    return apply_op(f, x, y)


def cholesky(x, upper=False, name=None):
    def f(v):
        L = jnp.linalg.cholesky(v)
        return jnp.swapaxes(L, -1, -2) if upper else L

    return apply_op(f, x)


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)

    return apply_op(f, x, y)


def lu(x, pivot=True, get_infos=False, name=None):
    def f(v):
        lu_, piv = jax.scipy.linalg.lu_factor(v)
        return lu_, (piv + 1).astype(jnp.int32)

    outs = apply_op(f, x)
    if get_infos:
        return outs[0], outs[1], Tensor(jnp.zeros((), jnp.int32))
    return outs


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    lu_v = to_array(x)
    piv = np.asarray(to_array(y)) - 1
    n = lu_v.shape[-2]
    P = np.eye(n)
    perm = np.arange(n)
    for i, p in enumerate(piv.reshape(-1)[:n]):
        perm[[i, p]] = perm[[p, i]]
    P = P[perm]
    L = jnp.tril(lu_v, -1) + jnp.eye(lu_v.shape[-2], lu_v.shape[-1])
    U = jnp.triu(lu_v)
    return Tensor(jnp.asarray(P.T)), Tensor(L), Tensor(U)


def qr(x, mode="reduced", name=None):
    def f(v):
        q, r = jnp.linalg.qr(v, mode=mode)
        return q, r

    if mode == "r":
        return apply_op(lambda v: jnp.linalg.qr(v, mode="r"), x)
    return apply_op(f, x)


def svd(x, full_matrices=False, name=None):
    # returns (U, S, VH) with x == U @ diag(S) @ VH — ref
    # python/paddle/tensor/linalg.py:1871 ("VH is the conjugate transpose of V")
    def f(v):
        return jnp.linalg.svd(v, full_matrices=full_matrices)

    return apply_op(f, x)


def svdvals(x, name=None):
    return apply_op(lambda v: jnp.linalg.svd(v, compute_uv=False), x)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    u, s, vh = svd(x)
    v = apply_op(lambda m: jnp.swapaxes(m, -1, -2).conj(), vh)
    return u[..., :q], s[..., :q], v[..., :q]


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    qq = q if q is not None else min(6, *x.shape[-2:])

    def f(v):
        if center:
            v = v - jnp.mean(v, axis=-2, keepdims=True)
        u, s, vh = jnp.linalg.svd(v, full_matrices=False)
        return u[..., :qq], s[..., :qq], jnp.swapaxes(vh, -1, -2)[..., :qq]

    return apply_op(f, x)


def eig(x, name=None):
    v = np.asarray(to_array(x))
    w, vec = np.linalg.eig(v)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(vec))


def eigvals(x, name=None):
    v = np.asarray(to_array(x))
    return Tensor(jnp.asarray(np.linalg.eigvals(v)))


def eigh(x, UPLO="L", name=None):
    def f(v):
        w, vec = jnp.linalg.eigh(v, UPLO=UPLO)
        return w, vec

    return apply_op(f, x)


def eigvalsh(x, UPLO="L", name=None):
    return apply_op(lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO), x)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def f(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv

    sol, res, rank, sv = apply_op(f, x, y)
    return sol, res, rank, sv


def multi_dot(x, name=None):
    return apply_op(lambda *vs: jnp.linalg.multi_dot(vs), *x)


def matrix_exp(x, name=None):
    return apply_op(jax.scipy.linalg.expm, x)


def householder_product(x, tau, name=None):
    def f(v, t):
        m, n = v.shape[-2], v.shape[-1]
        eye = jnp.eye(m, dtype=v.dtype)
        Q = jnp.broadcast_to(eye, v.shape[:-2] + (m, m))

        def body(i, Q):
            w = jnp.where(jnp.arange(m)[:, None] > i, v[..., :, i:i + 1], 0.0)
            w = w.at[..., 0, 0].set(0.0)
            w = w + jnp.eye(m, 1, -int(0), dtype=v.dtype) * 0
            e = jax.nn.one_hot(i, m, dtype=v.dtype)[:, None]
            w = jnp.where(jnp.arange(m)[:, None] == i, 1.0, w)
            w = jnp.where(jnp.arange(m)[:, None] < i, 0.0, w)
            H = jnp.eye(m, dtype=v.dtype) - t[..., i] * (w @ jnp.swapaxes(w, -1, -2))
            return Q @ H

        for i in range(t.shape[-1]):
            Q = body(i, Q)
        return Q[..., :, :n]

    return apply_op(f, x, tau)


def corrcoef(x, rowvar=True, name=None):
    return apply_op(lambda v: jnp.corrcoef(v, rowvar=rowvar), x)


def cross(x, y, axis=9, name=None):
    from .math import cross as _cross

    return _cross(x, y, axis)


def dist(x, y, p=2, name=None):
    return apply_op(lambda a, b: jnp.linalg.norm((a - b).reshape(-1), ord=p), x, y)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary", name=None):
    def f(a, b):
        diff = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 1e-30)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(diff), p), axis=-1), 1.0 / p)

    return apply_op(f, x, y)


def histogram_bin_edges(x, bins=100, min=0, max=0, name=None):
    v = np.asarray(to_array(x))
    rng = None if (min == 0 and max == 0) else (min, max)
    return Tensor(jnp.asarray(np.histogram_bin_edges(v, bins=bins, range=rng)))


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    Q = householder_product(x, tau)
    Qv = Q.value if isinstance(Q, Tensor) else Q

    def f(q, o):
        qm = jnp.swapaxes(q, -1, -2) if transpose else q
        return jnp.matmul(qm, o) if left else jnp.matmul(o, qm)

    return apply_op(f, Q, other)
