"""Functional tensor op surface (ref: python/paddle/tensor/).

Also patches the ops onto Tensor as methods + operator overloads, the
analogue of the reference's math-op monkey patches
(ref: python/paddle/fluid/dygraph/math_op_patch.py)."""
from __future__ import annotations

from . import array, attribute, creation, einsum as _einsum_mod, linalg, logic, manipulation, \
    math, random, search, stat
from .array import array_length, array_read, array_write, create_array, create_tensor
from .attribute import imag, rank, real, shape
from .creation import (arange, assign, clone, complex, diag, diagflat, empty, empty_like, eye,
                       full, full_like, linspace, logspace, meshgrid, ones, ones_like, polar,
                       to_tensor, tril, tril_indices, triu, triu_indices, zeros, zeros_like)
from .einsum import einsum
from .linalg import (cdist, cholesky, cholesky_solve, cond, cross, det, dist, eig, eigh,
                     eigvals, eigvalsh, householder_product, inv, lstsq, lu, lu_unpack,
                     matrix_exp, matrix_norm, matrix_power, matrix_rank, multi_dot, norm, pinv,
                     qr, slogdet, solve, svd, svd_lowrank, svdvals, triangular_solve,
                     vector_norm)
from .logic import (allclose, bitwise_and, bitwise_left_shift, bitwise_not, bitwise_or,
                    bitwise_right_shift, bitwise_xor, equal, equal_all, greater_equal,
                    greater_than, is_empty, is_tensor, isclose, less_equal, less_than,
                    logical_and, logical_not, logical_or, logical_xor, not_equal)
from .manipulation import (as_complex, as_real, broadcast_tensors, broadcast_to, chunk, concat,
                           crop, expand, expand_as, flatten, flatten_, flip, gather, gather_nd,
                           index_add, index_put, index_sample, index_select, masked_fill,
                           masked_scatter, masked_select, moveaxis, pad, put_along_axis,
                           repeat_interleave, reshape, reshape_, roll, rot90, scatter, scatter_,
                           scatter_nd, scatter_nd_add, shard_index, slice, split, squeeze,
                           stack, strided_slice, swapaxes, t, take_along_axis, tensor_split,
                           tensordot, tile, transpose, unfold, unique, unique_consecutive,
                           unsqueeze, unstack, view, view_as)
from .math import (abs, acos, acosh, add, addmm, all, amax, amin, angle, any, asin, asinh, atan,
                   atan2, atanh, bmm, broadcast_shape, ceil, clip, conj, copysign, cos, cosh,
                   count_nonzero, cross, cummax, cummin, cumprod, cumsum, deg2rad, diff,
                   digamma, divide, dot, erf, erfinv, exp, expm1, floor, floor_divide,
                   floor_mod, fmax, fmin, frac, gcd, heaviside, hypot, i0, imag, increment,
                   inner, inverse, isfinite, isinf, isnan, kron, lcm, lerp, lgamma, log, log1p,
                   log2, log10, logaddexp, logit, logsumexp, matmul, max, maximum, mean,
                   min, minimum, mm, mod, multiplex, multiply, nan_to_num, nanmean,
                   nansum, neg, nextafter, outer, pow, prod, rad2deg, real, reciprocal,
                   remainder, renorm, round, rsqrt, scale, sigmoid, sign, sin, sinh, sqrt,
                   square, stanh, subtract, sum, take, tan, tanh, trace, trapezoid, trunc)
from .manipulation import put_along_axis_
from .math import (add_, ceil_, clip_, erfinv_, exp_, floor_, lerp_, reciprocal_, remainder_,
                   round_, rsqrt_, scale_, sqrt_, subtract_)
from .random import (bernoulli, bernoulli_, binomial, exponential_, gaussian, multinomial,
                     normal, normal_, poisson, rand, randint, randint_like, randn, randperm,
                     standard_gamma, standard_normal, uniform, uniform_)
from .search import (argmax, argmin, argsort, bucketize, index_fill, kthvalue, mode, nonzero,
                     searchsorted, sort, topk, where)
from .stat import (bincount, corrcoef, cov, histogram, histogramdd, median, nanmedian,
                   nanquantile, numel, quantile, std, var)

from ..framework.core import Tensor


def _patch_tensor_methods():
    import operator as _op

    from ..framework.dispatch import apply_op
    import jax.numpy as jnp

    T = Tensor

    # ---- arithmetic operators ----
    def _binop(fn, reverse=False):
        def method(self, other):
            if reverse:
                return fn(other if isinstance(other, Tensor) else to_tensor(other), self)
            return fn(self, other)

        return method

    T.__add__ = _binop(add)
    T.__radd__ = _binop(add, True)
    T.__sub__ = _binop(subtract)
    T.__rsub__ = _binop(subtract, True)
    T.__mul__ = _binop(multiply)
    T.__rmul__ = _binop(multiply, True)
    T.__truediv__ = _binop(divide)
    T.__rtruediv__ = _binop(divide, True)
    T.__floordiv__ = _binop(floor_divide)
    T.__rfloordiv__ = _binop(floor_divide, True)
    T.__mod__ = _binop(mod)
    T.__rmod__ = _binop(mod, True)
    T.__pow__ = _binop(pow)
    T.__rpow__ = _binop(pow, True)
    T.__matmul__ = _binop(matmul)
    T.__rmatmul__ = _binop(matmul, True)
    T.__neg__ = lambda self: neg(self)
    T.__abs__ = lambda self: abs(self)
    T.__invert__ = lambda self: apply_op(jnp.invert, self)
    T.__eq__ = lambda self, o: equal(self, o if isinstance(o, Tensor) else to_tensor(o))
    T.__ne__ = lambda self, o: not_equal(self, o if isinstance(o, Tensor) else to_tensor(o))
    T.__lt__ = _binop(less_than)
    T.__le__ = _binop(less_equal)
    T.__gt__ = _binop(greater_than)
    T.__ge__ = _binop(greater_equal)
    T.__and__ = _binop(logical_and)
    T.__or__ = _binop(logical_or)
    T.__xor__ = _binop(logical_xor)

    # ---- methods from functional modules ----
    import sys

    this = sys.modules[__name__]
    method_names = [
        "abs", "acos", "acosh", "add", "addmm", "all", "allclose", "amax", "amin", "angle",
        "any", "argmax", "argmin", "argsort", "asin", "asinh", "atan", "atan2", "atanh",
        "bincount", "bitwise_and", "bitwise_not", "bitwise_or", "bitwise_xor", "bmm",
        "broadcast_to", "ceil", "cholesky", "chunk", "clip", "concat", "conj", "cos", "cosh",
        "count_nonzero", "cross", "cumprod", "cumsum", "cummax", "cummin", "deg2rad", "det",
        "diagflat", "diff", "digamma", "dist", "divide", "dot", "equal", "equal_all", "erf",
        "erfinv", "exp", "expand", "expand_as", "expm1", "flatten", "flip", "floor",
        "floor_divide", "floor_mod", "fmax", "fmin", "frac", "gather", "gather_nd",
        "greater_equal", "greater_than", "histogram", "imag", "increment", "index_add",
        "index_fill", "index_put", "index_sample", "index_select", "inner", "inverse",
        "isclose", "isfinite", "isinf", "isnan", "kron", "kthvalue", "lcm", "lerp", "lgamma",
        "less_equal", "less_than", "log", "log1p", "log2", "log10", "logical_and",
        "logical_not", "logical_or", "logical_xor", "logit", "logsumexp", "masked_fill",
        "masked_select", "matmul", "matrix_power", "max", "maximum", "mean", "median", "min",
        "minimum", "mm", "mod", "moveaxis", "multiplex", "multiply", "nan_to_num", "nanmean",
        "nanmedian", "nansum", "neg", "nonzero", "norm", "not_equal", "numel", "outer", "pow",
        "prod", "put_along_axis", "quantile", "rad2deg", "rank", "real", "reciprocal",
        "remainder", "repeat_interleave", "reshape", "reshape_", "roll", "rot90", "round",
        "rsqrt", "scale", "scatter", "scatter_", "scatter_nd_add", "sigmoid", "sign", "sin",
        "sinh", "slice", "sort", "split", "sqrt", "square", "squeeze", "stanh", "std",
        "strided_slice", "subtract", "sum", "t", "take", "take_along_axis", "tanh",
        "tensor_split", "tile", "topk", "trace", "transpose", "tril", "triu", "trunc",
        "unbind" if hasattr(this, "unbind") else "unstack", "unfold", "unique",
        "unique_consecutive", "unsqueeze", "unstack", "var", "view", "view_as", "where",
        "bernoulli_", "exponential_", "normal_", "uniform_", "tan", "acos",
        "add_", "subtract_", "ceil_", "clip_", "erfinv_", "exp_", "floor_",
        "lerp_", "reciprocal_", "remainder_", "round_", "rsqrt_", "scale_",
        "sqrt_", "flatten_", "put_along_axis_",
    ]
    for nm in method_names:
        fn = getattr(this, nm, None)
        if fn is not None and not hasattr(T, nm):
            setattr(T, nm, fn)

    # Paddle 'T' property
    T.T = property(lambda self: transpose(self, list(range(self.ndim))[::-1]))
    T.mT = property(lambda self: swapaxes(self, -1, -2))


def unbind(x, axis=0):
    return unstack(x, axis=axis)


_patch_tensor_methods()
Tensor.unbind = unbind
