"""Tensor creation ops (ref: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op
from ..framework.dtype import convert_dtype, get_default_dtype, is_floating_point


def _resolve_dtype(dtype, data=None):
    if dtype is not None:
        return convert_dtype(dtype)
    return None


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor parity."""
    if isinstance(data, Tensor):
        val = data.value
    else:
        val = jnp.asarray(data)
    dtype = _resolve_dtype(dtype)
    if dtype is not None:
        val = val.astype(dtype)
    elif val.dtype == jnp.float64:
        # paddle defaults python floats to the default float dtype
        val = val.astype(get_default_dtype())
    return Tensor(val, stop_gradient=stop_gradient)


def _shape_list(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape]


def zeros(shape, dtype=None, name=None):
    dtype = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jnp.zeros(_shape_list(shape), dtype))


def ones(shape, dtype=None, name=None):
    dtype = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jnp.ones(_shape_list(shape), dtype))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = jnp.bool_
        elif isinstance(fill_value, int):
            dtype = jnp.int64
        else:
            dtype = get_default_dtype()
    else:
        dtype = convert_dtype(dtype)
    return Tensor(jnp.full(_shape_list(shape), fill_value, dtype))


def zeros_like(x, dtype=None, name=None):
    return apply_op(lambda v: jnp.zeros_like(v, dtype=convert_dtype(dtype)), x)


def ones_like(x, dtype=None, name=None):
    return apply_op(lambda v: jnp.ones_like(v, dtype=convert_dtype(dtype)), x)


def full_like(x, fill_value, dtype=None, name=None):
    return apply_op(lambda v: jnp.full_like(v, fill_value, dtype=convert_dtype(dtype)), x)


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    if end is None:
        start, end = 0, start
    for v in (start, end, step):
        if isinstance(v, Tensor):
            v = v.item()
    start = start.item() if isinstance(start, Tensor) else start
    end = end.item() if isinstance(end, Tensor) else end
    step = step.item() if isinstance(step, Tensor) else step
    if dtype is None:
        dtype = jnp.int64 if all(
            isinstance(v, (int, np.integer)) for v in (start, end, step)) else get_default_dtype()
    else:
        dtype = convert_dtype(dtype)
    return Tensor(jnp.arange(start, end, step, dtype=dtype))


def linspace(start, stop, num, dtype=None, name=None):
    start = start.item() if isinstance(start, Tensor) else start
    stop = stop.item() if isinstance(stop, Tensor) else stop
    num = num.item() if isinstance(num, Tensor) else num
    dtype = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jnp.linspace(start, stop, int(num), dtype=dtype))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    dtype = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jnp.logspace(float(start), float(stop), int(num), base=float(base), dtype=dtype))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    dtype = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jnp.eye(int(num_rows), num_columns and int(num_columns), dtype=dtype))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    outs = jnp.meshgrid(*[to_array(a) for a in args], indexing="ij")
    return [Tensor(o) for o in outs]


def diag(x, offset=0, padding_value=0, name=None):
    def f(v):
        if v.ndim == 1 and padding_value != 0:
            n = v.shape[0] + abs(offset)
            base = jnp.full((n, n), padding_value, v.dtype)
            return base + jnp.diag(v, k=offset) - jnp.diag(
                jnp.full((v.shape[0],), padding_value, v.dtype), k=offset)
        return jnp.diag(v, k=offset)

    return apply_op(f, x)


def diagflat(x, offset=0, name=None):
    return apply_op(lambda v: jnp.diagflat(v, k=offset), x)


def tril(x, diagonal=0, name=None):
    return apply_op(lambda v: jnp.tril(v, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    return apply_op(lambda v: jnp.triu(v, k=diagonal), x)


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), convert_dtype(dtype)))


def assign(x, output=None):
    if output is not None:
        output.set_value(to_array(x))
        return output
    if isinstance(x, Tensor):
        from ..framework.dispatch import apply_op

        return apply_op(lambda v: v, x)  # identity — keeps the tape
    return Tensor(jnp.asarray(to_array(x)))


def clone(x, name=None):
    return x.clone()


def complex(real, imag, name=None):
    return apply_op(lambda r, i: r + 1j * i.astype(jnp.result_type(i, jnp.complex64)), real, imag)


def polar(abs_t, angle, name=None):
    return apply_op(lambda a, t: a * jnp.exp(1j * t.astype(jnp.complex64)), abs_t, angle)
