"""TensorArray ops (ref: python/paddle/tensor/array.py — array_length:24,
array_read:73, array_write:141, create_array:222; creation.py create_tensor).

The reference's LoDTensorArray is a graph-variable holding a list of
tensors, indexed by scalar tensors inside control flow.  Eagerly (and under
``paddle_tpu.jit`` tracing, where Python lists are unrolled at trace time) a
plain Python list of Tensors carries the same semantics, so that is the
array representation here — writes grow the list, reads index it.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["array_length", "array_read", "array_write", "create_array",
           "create_tensor"]


def _idx(i) -> int:
    import numpy as np

    if isinstance(i, Tensor):
        return int(np.asarray(i.value))
    return int(i)


def create_array(dtype="float32", initialized_list=None):
    """New TensorArray; optionally seeded from ``initialized_list``
    (ref array.py:222).  ``dtype`` is advisory — elements keep their own."""
    out = []
    if initialized_list is not None:
        for v in initialized_list:
            out.append(v if isinstance(v, Tensor) else Tensor(jnp.asarray(v)))
    return out


def array_write(x, i, array=None):
    """Write ``x`` at position ``i``, growing the array as needed
    (ref array.py:141); returns the array."""
    if array is None:
        array = []
    i = _idx(i)
    if i < len(array):
        array[i] = x
    else:
        while len(array) < i:
            array.append(None)
        array.append(x)
    return array


def array_read(array, i):
    """Read position ``i`` (ref array.py:73)."""
    return array[_idx(i)]


def array_length(array):
    """Length as an int64 scalar Tensor (ref array.py:24)."""
    return Tensor(jnp.asarray(len(array), jnp.int64))


def create_tensor(dtype, name=None, persistable=False):
    """An (empty) tensor variable of ``dtype`` to be filled later, e.g. by
    ``paddle.assign`` (ref creation.py create_tensor)."""
    from ..framework.dtype import convert_dtype

    return Tensor(jnp.zeros((), convert_dtype(dtype)))
