"""Einsum (ref: python/paddle/tensor/einsum.py) — delegates to jnp.einsum,
which XLA maps onto MXU dot_generals."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.dispatch import apply_op


def einsum(equation, *operands):
    if len(operands) == 1 and isinstance(operands[0], (list, tuple)):
        operands = tuple(operands[0])
    return apply_op(lambda *vs: jnp.einsum(equation, *vs), *operands, op_name="einsum")
