"""Search/sort ops (ref: python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(v):
        out = jnp.argmax(v.reshape(-1) if axis is None else v,
                         axis=None if axis is None else int(axis))
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, int(axis))
        return out.astype(jnp.int64)

    return apply_op(f, x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(v):
        out = jnp.argmin(v.reshape(-1) if axis is None else v,
                         axis=None if axis is None else int(axis))
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, int(axis))
        return out.astype(jnp.int64)

    return apply_op(f, x)


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def f(v):
        idx = jnp.argsort(v, axis=axis, stable=True)
        if descending:
            idx = jnp.flip(idx, axis=axis)
        return idx.astype(jnp.int64)

    return apply_op(f, x)


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def f(v):
        out = jnp.sort(v, axis=axis)
        if descending:
            out = jnp.flip(out, axis=axis)
        return out

    return apply_op(f, x, op_name="sort")


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    k = int(k.item()) if isinstance(k, Tensor) else int(k)

    def f(v):
        ax = -1 if axis is None else int(axis)
        vm = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(vm, k)
        else:
            vals, idx = jax.lax.top_k(-vm, k)
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(jnp.int64), -1, ax)

    return apply_op(f, x, op_name="topk")


def nonzero(x, as_tuple=False):
    v = np.asarray(to_array(x))
    nz = np.nonzero(v)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(n.astype(np.int64))) for n in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply_op(lambda c, a, b: jnp.where(c, a, b), condition, x, y, op_name="where")


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def f(s, v):
        side = "right" if right else "left"
        if s.ndim == 1:
            out = jnp.searchsorted(s, v, side=side)
        else:
            out = jax.vmap(lambda ss, vv: jnp.searchsorted(ss, vv, side=side))(
                s.reshape(-1, s.shape[-1]), v.reshape(-1, v.shape[-1]))
            out = out.reshape(v.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)

    return apply_op(f, sorted_sequence, values)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    def f(v, s):
        out = jnp.searchsorted(s, v, side="right" if right else "left")
        return out.astype(jnp.int32 if out_int32 else jnp.int64)

    return apply_op(f, x, sorted_sequence)


def masked_select(x, mask, name=None):
    from .manipulation import masked_select as _ms

    return _ms(x, mask)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(v):
        vm = jnp.moveaxis(v, axis, -1)
        s = jnp.sort(vm, axis=-1)
        si = jnp.argsort(vm, axis=-1, stable=True)
        vals = s[..., k - 1]
        idx = si[..., k - 1].astype(jnp.int64)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx

    return apply_op(f, x)


def mode(x, axis=-1, keepdim=False, name=None):
    v = np.asarray(to_array(x))
    vm = np.moveaxis(v, axis, -1)
    flat = vm.reshape(-1, vm.shape[-1])
    vals = np.empty(flat.shape[0], v.dtype)
    idxs = np.empty(flat.shape[0], np.int64)
    for i, row in enumerate(flat):
        uniq, counts = np.unique(row, return_counts=True)
        best = uniq[np.argmax(counts[::-1][::-1])]
        cands = np.where(row == uniq[np.argmax(counts)])[0]
        vals[i] = uniq[np.argmax(counts)]
        idxs[i] = cands[-1]
    out_shape = vm.shape[:-1]
    vals = vals.reshape(out_shape)
    idxs = idxs.reshape(out_shape)
    if keepdim:
        vals = np.expand_dims(vals, axis)
        idxs = np.expand_dims(idxs, axis)
    return Tensor(jnp.asarray(vals)), Tensor(jnp.asarray(idxs))


def index_fill(x, index, axis, value, name=None):
    def f(v, i):
        vm = jnp.moveaxis(v, axis, 0)
        out = vm.at[i.astype(jnp.int32)].set(value)
        return jnp.moveaxis(out, 0, axis)

    return apply_op(f, x, index)
