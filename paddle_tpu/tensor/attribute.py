"""Tensor attribute ops (ref: python/paddle/tensor/attribute.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op
from ..framework.dtype import is_complex, is_floating_point, is_integer


def shape(x):
    return Tensor(jnp.asarray(x.shape, jnp.int64))


def rank(x):
    return Tensor(jnp.asarray(x.ndim, jnp.int64))


def is_floating_point_fn(x):
    return is_floating_point(x.dtype)


def is_integer_fn(x):
    return is_integer(x.dtype)


def is_complex_fn(x):
    return is_complex(x.dtype)


def real(x, name=None):
    return apply_op(jnp.real, x)


def imag(x, name=None):
    return apply_op(jnp.imag, x)
