"""Shape/layout manipulation ops (ref: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op


def _ints(x):
    if isinstance(x, Tensor):
        x = x.tolist()
    if isinstance(x, (int, np.integer)):
        return int(x)
    return [int(v.item()) if isinstance(v, Tensor) else int(v) for v in x]


def reshape(x, shape, name=None):
    shape = _ints(shape)
    return apply_op(lambda v: jnp.reshape(v, shape), x, op_name="reshape")


def reshape_(x, shape, name=None):
    x._value = jnp.reshape(x.value, _ints(shape))
    return x


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(v):
        nd = v.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = list(v.shape[:s]) + [-1] + list(v.shape[e + 1:])
        return jnp.reshape(v, new_shape)

    return apply_op(f, x, op_name="flatten")


def transpose(x, perm, name=None):
    perm = _ints(perm)
    return apply_op(lambda v: jnp.transpose(v, perm), x, op_name="transpose")


def moveaxis(x, source, destination, name=None):
    return apply_op(lambda v: jnp.moveaxis(v, source, destination), x)


def swapaxes(x, axis1, axis2, name=None):
    return apply_op(lambda v: jnp.swapaxes(v, axis1, axis2), x)


def t(x, name=None):
    return apply_op(lambda v: v.T if v.ndim >= 2 else v, x)


def squeeze(x, axis=None, name=None):
    def f(v):
        if axis is None:
            return jnp.squeeze(v)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(a % v.ndim for a in axes if v.shape[a % v.ndim] == 1)
        return jnp.squeeze(v, axis=axes) if axes else v

    return apply_op(f, x, op_name="squeeze")


def unsqueeze(x, axis, name=None):
    def f(v):
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        out = v
        for a in sorted(_ints(axes)):
            out = jnp.expand_dims(out, a)
        return out

    return apply_op(f, x, op_name="unsqueeze")


def concat(x, axis=0, name=None):
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply_op(lambda *vs: jnp.concatenate(vs, axis=axis), *x, op_name="concat")


def stack(x, axis=0, name=None):
    return apply_op(lambda *vs: jnp.stack(vs, axis=axis), *x, op_name="stack")


def unstack(x, axis=0, num=None, name=None):
    n = num if num is not None else x.shape[axis]
    outs = apply_op(
        lambda v: tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(v, n, axis=axis)), x)
    return list(outs)


def split(x, num_or_sections, axis=0, name=None):
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)

    def f(v):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(v, num_or_sections, axis=axis))
        secs = _ints(num_or_sections)
        total = v.shape[axis]
        known = [s for s in secs if s != -1]
        secs = [s if s != -1 else total - int(np.sum(known)) for s in secs]
        idxs = np.cumsum(secs)[:-1].tolist()
        return tuple(jnp.split(v, idxs, axis=axis))

    return list(apply_op(f, x, op_name="split"))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tensor_split(x, num_or_indices, axis=0, name=None):
    def f(v):
        return tuple(jnp.array_split(v, num_or_indices if isinstance(num_or_indices, int)
                                     else _ints(num_or_indices), axis=axis))

    return list(apply_op(f, x))


def slice(x, axes, starts, ends):
    import builtins

    axes, starts, ends = _ints(axes), _ints(starts), _ints(ends)

    def f(v):
        idx = [builtins.slice(None)] * v.ndim
        for a, s, e in zip(axes, starts, ends):
            idx[a] = builtins.slice(s, e)
        return v[tuple(idx)]

    return apply_op(f, x)


def strided_slice(x, axes, starts, ends, strides, name=None):
    import builtins

    axes, starts, ends, strides = _ints(axes), _ints(starts), _ints(ends), _ints(strides)

    def f(v):
        idx = [builtins.slice(None)] * v.ndim
        for a, s, e, st in zip(axes, starts, ends, strides):
            idx[a] = builtins.slice(s, e, st)
        return v[tuple(idx)]

    return apply_op(f, x)


def expand(x, shape, name=None):
    shape = _ints(shape)

    def f(v):
        tgt = list(shape)
        off = len(tgt) - v.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = v.shape[i - off]
        return jnp.broadcast_to(v, tgt)

    return apply_op(f, x, op_name="expand")


def expand_as(x, y, name=None):
    tgt = tuple(y.shape)
    return apply_op(lambda v: jnp.broadcast_to(v, tgt), x)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    outs = apply_op(lambda *vs: tuple(jnp.broadcast_arrays(*vs)), *inputs)
    return list(outs)


def tile(x, repeat_times, name=None):
    reps = _ints(repeat_times)
    return apply_op(lambda v: jnp.tile(v, reps), x)


def repeat_interleave(x, repeats, axis=None, name=None):
    r = to_array(repeats) if isinstance(repeats, Tensor) else repeats
    return apply_op(lambda v: jnp.repeat(v, r, axis=axis), x)


def flip(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply_op(lambda v: jnp.flip(v, axis=tuple(_ints(axes))), x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op(lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), x)


def roll(x, shifts, axis=None, name=None):
    sh = _ints(shifts) if not isinstance(shifts, int) else shifts
    ax = _ints(axis) if axis is not None and not isinstance(axis, int) else axis
    return apply_op(lambda v: jnp.roll(v, sh, axis=tuple(ax) if isinstance(ax, list) else ax), x)


def gather(x, index, axis=0, name=None):
    axis_i = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply_op(lambda v, i: jnp.take(v, i.astype(jnp.int32), axis=axis_i), x, index,
                    op_name="gather")


def gather_nd(x, index, name=None):
    def f(v, idx):
        idx = idx.astype(jnp.int32)
        return v[tuple(jnp.moveaxis(idx, -1, 0))]

    return apply_op(f, x, index)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply_op(
        lambda v, i: jnp.take_along_axis(v, i.astype(jnp.int32), axis=axis), arr, indices)


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def f(v, i, val):
        i = i.astype(jnp.int32)
        ax = axis % v.ndim
        # numpy put_along_axis broadcast rules: indices/values broadcast
        # against arr on the non-axis dims
        bshape = list(v.shape)
        bshape[ax] = i.shape[ax]
        i = jnp.broadcast_to(i, bshape)
        val = jnp.broadcast_to(val, bshape).astype(v.dtype)
        dims = [jnp.arange(s).reshape([-1 if d == k else 1 for d in range(v.ndim)])
                for k, s in enumerate(bshape)]
        idx = [jnp.broadcast_to(d, bshape) for d in dims]
        idx[ax] = i
        if reduce == "add":
            return v.at[tuple(idx)].add(val)
        if reduce in ("mul", "multiply"):
            return v.at[tuple(idx)].multiply(val)
        return v.at[tuple(idx)].set(val)

    return apply_op(f, arr, indices, values)


def scatter(x, index, updates, overwrite=True, name=None):
    def f(v, i, u):
        i = i.astype(jnp.int32).reshape(-1)
        if overwrite:
            return v.at[i].set(u)
        base = v.at[i].set(jnp.zeros_like(u))
        return base.at[i].add(u)

    return apply_op(f, x, index, updates, op_name="scatter")


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite)
    x._value = out.value
    return x


def scatter_nd(index, updates, shape, name=None):
    shape = _ints(shape)

    def f(i, u):
        z = jnp.zeros(shape, u.dtype)
        i = i.astype(jnp.int32)
        return z.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)

    return apply_op(f, index, updates)


def scatter_nd_add(x, index, updates, name=None):
    def f(v, i, u):
        i = i.astype(jnp.int32)
        return v.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)

    return apply_op(f, x, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply_op(lambda v, i: jnp.take(v, i.astype(jnp.int32), axis=axis), x, index)


def index_sample(x, index):
    def f(v, i):
        return jnp.take_along_axis(v, i.astype(jnp.int32), axis=1)

    return apply_op(f, x, index)


def index_add(x, index, axis, value, name=None):
    def f(v, i, val):
        i = i.astype(jnp.int32)
        vm = jnp.moveaxis(v, axis, 0)
        valm = jnp.moveaxis(val, axis, 0)
        out = vm.at[i].add(valm)
        return jnp.moveaxis(out, 0, axis)

    return apply_op(f, x, index, value)


def index_put(x, indices, value, accumulate=False, name=None):
    def f(v, val, *idx):
        idx = tuple(i.astype(jnp.int32) if jnp.issubdtype(i.dtype, jnp.integer) else i
                    for i in idx)
        if accumulate:
            return v.at[idx].add(val)
        return v.at[idx].set(val)

    return apply_op(lambda v, val, *idx: f(v, val, *idx), x, value, *indices)


def masked_select(x, mask, name=None):
    # Dynamic output shape: eager-only (not jittable) — same restriction XLA
    # has. The mask is concretized, so the gather is differentiable in x.
    m = np.asarray(to_array(mask)).astype(bool)
    idx = tuple(jnp.asarray(i) for i in np.nonzero(m))
    return apply_op(lambda v: v[idx], x)


def masked_fill(x, mask, value, name=None):
    val = to_array(value) if isinstance(value, Tensor) else value
    return apply_op(lambda v, m: jnp.where(m, jnp.asarray(val, v.dtype), v), x, mask)


def masked_scatter(x, mask, value, name=None):
    # concrete mask; differentiable in both x and value
    m = np.asarray(to_array(mask)).astype(bool)
    k = int(m.sum())
    idx = tuple(jnp.asarray(i) for i in np.nonzero(m))
    return apply_op(
        lambda v, val: v.at[idx].set(val.reshape(-1)[:k].astype(v.dtype)),
        x, value)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None,
           dtype="int64", name=None):
    v = np.asarray(to_array(x))
    res = np.unique(v, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64",
                       name=None):
    v = np.asarray(to_array(x))
    if axis is None:
        v = v.reshape(-1)
        ax = 0
    else:
        ax = axis
    n = v.shape[ax]
    import builtins

    if n == 0:
        outs = [Tensor(v)]
        if return_inverse:
            outs.append(Tensor(jnp.zeros((0,), jnp.int64)))
        if return_counts:
            outs.append(Tensor(jnp.zeros((0,), jnp.int64)))
    else:
        first = np.ones(n, dtype=bool)
        sl = [builtins.slice(None)] * v.ndim
        sl_prev = list(sl)
        sl[ax] = builtins.slice(1, None)
        sl_prev[ax] = builtins.slice(None, -1)
        neq = np.any(v[tuple(sl)] != v[tuple(sl_prev)],
                     axis=tuple(i for i in range(v.ndim) if i != ax)) if v.ndim > 1 else (
            v[1:] != v[:-1])
        first[1:] = neq
        idx = np.where(first)[0]
        taken = np.take(v, idx, axis=ax)
        outs = [Tensor(jnp.asarray(taken))]
        if return_inverse:
            inv = np.cumsum(first) - 1
            outs.append(Tensor(jnp.asarray(inv.astype(np.int64))))
        if return_counts:
            counts = np.diff(np.append(idx, n))
            outs.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def as_complex(x, name=None):
    return apply_op(lambda v: jax.lax.complex(v[..., 0], v[..., 1]), x)


def as_real(x, name=None):
    return apply_op(lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1), x)


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return x.astype(shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def unfold(x, axis, size, step, name=None):
    # windows along `axis` become a new trailing dim of length `size`
    # (Tensor.unfold semantics: out[..., w, ..., e] = x[..., w*step+e, ...])
    def f(v):
        ax = axis % v.ndim
        n = (v.shape[ax] - size) // step + 1
        idx = jnp.arange(n)[:, None] * step + jnp.arange(size)[None, :]
        out = jnp.take(v, idx.reshape(-1), axis=ax)
        out = out.reshape(v.shape[:ax] + (n, size) + v.shape[ax + 1:])
        return jnp.moveaxis(out, ax + 1, -1)

    return apply_op(f, x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ..nn.functional.common import pad as _pad

    return _pad(x, pad, mode=mode, value=value, data_format=data_format)


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(ax, Tensor):
        ax = ax.tolist()
    if isinstance(ax, (list, tuple)):
        ax = tuple(tuple(_ints(a)) if isinstance(a, (list, tuple, Tensor)) else a for a in ax)
    return apply_op(lambda a, b: jnp.tensordot(a, b, axes=ax), x, y)


def crop(x, shape=None, offsets=None, name=None):
    import builtins

    shape = _ints(shape)
    offsets = _ints(offsets) if offsets is not None else [0] * len(shape)

    def f(v):
        idx = tuple(builtins.slice(o, o + s if s != -1 else None)
                    for o, s in zip(offsets, shape))
        return v[idx]

    return apply_op(f, x)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def f(v):
        shard_size = (index_num + nshards - 1) // nshards
        lo = shard_id * shard_size
        in_shard = (v >= lo) & (v < lo + shard_size)
        return jnp.where(in_shard, v - lo, ignore_value)

    return apply_op(f, input)


def _inplace_pair():
    from .math import _make_inplace

    return _make_inplace(flatten), _make_inplace(put_along_axis)


flatten_, put_along_axis_ = _inplace_pair()
