"""Statistics ops (ref: python/paddle/tensor/stat.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op


def _axis(axis):
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return axis if axis is None else int(axis)


def mean(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda v: jnp.mean(v, axis=_axis(axis), keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(
        lambda v: jnp.var(v, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim), x)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(
        lambda v: jnp.std(v, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim), x)


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def f(v):
        if mode == "avg":
            return jnp.median(v, axis=_axis(axis), keepdims=keepdim)
        # 'min' mode: lower of the two middles
        ax = _axis(axis)
        if ax is None:
            flat = jnp.sort(v.reshape(-1))
            return flat[(flat.shape[0] - 1) // 2]
        s = jnp.sort(v, axis=ax)
        idx = (v.shape[ax] - 1) // 2
        out = jnp.take(s, idx, axis=ax)
        return jnp.expand_dims(out, ax) if keepdim else out

    return apply_op(f, x)


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    return apply_op(lambda v: jnp.nanmedian(v, axis=_axis(axis), keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qv = to_array(q) if isinstance(q, Tensor) else jnp.asarray(q)
    return apply_op(
        lambda v: jnp.quantile(v.astype(jnp.float32), qv, axis=_axis(axis), keepdims=keepdim,
                               method=interpolation), x)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qv = to_array(q) if isinstance(q, Tensor) else jnp.asarray(q)
    return apply_op(
        lambda v: jnp.nanquantile(v.astype(jnp.float32), qv, axis=_axis(axis), keepdims=keepdim,
                                  method=interpolation), x)


def numel(x, name=None):
    return Tensor(jnp.asarray(int(np.prod(x.shape)) if x.shape else 1, jnp.int64))


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):
    v = np.asarray(to_array(input))
    lo, hi = (float(min), float(max))
    if lo == 0 and hi == 0:
        lo, hi = (float(v.min()), float(v.max())) if v.size else (0.0, 1.0)
    w = np.asarray(to_array(weight)) if weight is not None else None
    h, _ = np.histogram(v, bins=bins, range=(lo, hi), weights=w, density=density)
    return Tensor(jnp.asarray(h if density or w is not None else h.astype(np.int64)))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    v = np.asarray(to_array(x))
    w = np.asarray(to_array(weights)) if weights is not None else None
    h, edges = np.histogramdd(v, bins=bins, range=ranges, density=density, weights=w)
    return Tensor(jnp.asarray(h)), [Tensor(jnp.asarray(e)) for e in edges]


def bincount(x, weights=None, minlength=0, name=None):
    if weights is None:
        return apply_op(lambda v: jnp.bincount(v.astype(jnp.int32), minlength=minlength,
                                               length=None).astype(jnp.int64), x)
    return apply_op(
        lambda v, w: jnp.bincount(v.astype(jnp.int32), weights=w, minlength=minlength), x, weights)


def corrcoef(x, rowvar=True, name=None):
    return apply_op(lambda v: jnp.corrcoef(v, rowvar=rowvar), x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = np.asarray(to_array(fweights)) if fweights is not None else None
    aw = np.asarray(to_array(aweights)) if aweights is not None else None
    return apply_op(
        lambda v: jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0, fweights=fw, aweights=aw), x)
