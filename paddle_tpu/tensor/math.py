"""Elementwise & reduction math ops (ref: python/paddle/tensor/math.py).

Each op is a thin eager wrapper over the jnp lowering; under jit these trace
straight into the jaxpr, and XLA fuses chains of them into single TPU loops
(replacing the reference's hand-fused CUDA kernels in phi/kernels/fusion/).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op, defop
from ..framework.dtype import convert_dtype


def _axis(axis):
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return axis if axis is None else int(axis)


# ---- binary elementwise ----------------------------------------------------

def add(x, y, name=None):
    return apply_op(jnp.add, x, y, op_name="add")


def subtract(x, y, name=None):
    return apply_op(jnp.subtract, x, y, op_name="subtract")


def multiply(x, y, name=None):
    return apply_op(jnp.multiply, x, y, op_name="multiply")


def divide(x, y, name=None):
    return apply_op(jnp.divide, x, y, op_name="divide")


def floor_divide(x, y, name=None):
    return apply_op(jnp.floor_divide, x, y)


def mod(x, y, name=None):
    return apply_op(jnp.mod, x, y)


remainder = mod
floor_mod = mod


def pow(x, y, name=None):
    return apply_op(jnp.power, x, y, op_name="pow")


def maximum(x, y, name=None):
    return apply_op(jnp.maximum, x, y)


def minimum(x, y, name=None):
    return apply_op(jnp.minimum, x, y)


def fmax(x, y, name=None):
    return apply_op(jnp.fmax, x, y)


def fmin(x, y, name=None):
    return apply_op(jnp.fmin, x, y)


def atan2(x, y, name=None):
    return apply_op(jnp.arctan2, x, y)


def hypot(x, y, name=None):
    return apply_op(jnp.hypot, x, y)


def copysign(x, y, name=None):
    return apply_op(jnp.copysign, x, y)


def nextafter(x, y, name=None):
    return apply_op(jnp.nextafter, x, y)


def heaviside(x, y, name=None):
    return apply_op(jnp.heaviside, x, y)


def gcd(x, y, name=None):
    return apply_op(jnp.gcd, x, y)


def lcm(x, y, name=None):
    return apply_op(jnp.lcm, x, y)


def logaddexp(x, y, name=None):
    return apply_op(jnp.logaddexp, x, y)


# ---- unary elementwise -----------------------------------------------------

exp = defop(jnp.exp, "exp")
expm1 = defop(jnp.expm1, "expm1")
log = defop(jnp.log, "log")
log2 = defop(jnp.log2, "log2")
log10 = defop(jnp.log10, "log10")
log1p = defop(jnp.log1p, "log1p")
sqrt = defop(jnp.sqrt, "sqrt")
rsqrt = defop(jax.lax.rsqrt, "rsqrt")
abs = defop(jnp.abs, "abs")
ceil = defop(jnp.ceil, "ceil")
floor = defop(jnp.floor, "floor")
round = defop(jnp.round, "round")
trunc = defop(jnp.trunc, "trunc")
frac = defop(lambda x: x - jnp.trunc(x), "frac")
sin = defop(jnp.sin, "sin")
cos = defop(jnp.cos, "cos")
tan = defop(jnp.tan, "tan")
asin = defop(jnp.arcsin, "asin")
acos = defop(jnp.arccos, "acos")
atan = defop(jnp.arctan, "atan")
sinh = defop(jnp.sinh, "sinh")
cosh = defop(jnp.cosh, "cosh")
tanh = defop(jnp.tanh, "tanh")
asinh = defop(jnp.arcsinh, "asinh")
acosh = defop(jnp.arccosh, "acosh")
atanh = defop(jnp.arctanh, "atanh")
square = defop(jnp.square, "square")
reciprocal = defop(lambda x: 1.0 / x, "reciprocal")
sign = defop(jnp.sign, "sign")
neg = defop(jnp.negative, "neg")
erf = defop(jax.scipy.special.erf, "erf")
erfinv = defop(jax.scipy.special.erfinv, "erfinv")
lgamma = defop(jax.scipy.special.gammaln, "lgamma")
digamma = defop(jax.scipy.special.digamma, "digamma")
i0 = defop(jnp.i0, "i0")
deg2rad = defop(jnp.deg2rad, "deg2rad")
rad2deg = defop(jnp.rad2deg, "rad2deg")
angle = defop(jnp.angle, "angle")
conj = defop(jnp.conj, "conj")
real = defop(jnp.real, "real")
imag = defop(jnp.imag, "imag")
sigmoid = defop(jax.nn.sigmoid, "sigmoid")
logit = defop(jax.scipy.special.logit, "logit")
exponent = defop(lambda x: jnp.frexp(x)[1], "exponent")


def clip(x, min=None, max=None, name=None):
    lo = to_array(min) if isinstance(min, Tensor) else min
    hi = to_array(max) if isinstance(max, Tensor) else max
    return apply_op(lambda v: jnp.clip(v, lo, hi), x, op_name="clip")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = to_array(scale) if isinstance(scale, Tensor) else scale

    def f(v):
        out = v * s + bias if bias_after_scale else (v + bias) * s
        return out

    return apply_op(f, x, op_name="scale")


def increment(x, value=1.0, name=None):
    x.set_value(x.value + value)
    return x


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op(lambda v: scale_b * jnp.tanh(scale_a * v), x)


def multiplex(inputs, index, name=None):
    arrs = [to_array(i) for i in inputs]

    def f(idx, *xs):
        stacked = jnp.stack(xs, axis=0)
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))).astype(jnp.int32), axis=0
        )[0]

    return apply_op(lambda idx, *xs: f(idx, *xs), index, *inputs)


# ---- reductions ------------------------------------------------------------

def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = convert_dtype(dtype)
    return apply_op(lambda v: jnp.sum(v, axis=_axis(axis), dtype=d, keepdims=keepdim), x,
                    op_name="sum")


def mean(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda v: jnp.mean(v, axis=_axis(axis), keepdims=keepdim), x, op_name="mean")


def max(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda v: jnp.max(v, axis=_axis(axis), keepdims=keepdim), x, op_name="max")


def min(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda v: jnp.min(v, axis=_axis(axis), keepdims=keepdim), x, op_name="min")


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    d = convert_dtype(dtype)
    return apply_op(lambda v: jnp.prod(v, axis=_axis(axis), dtype=d, keepdims=keepdim), x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply_op(
        lambda v: jax.scipy.special.logsumexp(v, axis=_axis(axis), keepdims=keepdim), x)


def all(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda v: jnp.all(v, axis=_axis(axis), keepdims=keepdim), x)


def any(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda v: jnp.any(v, axis=_axis(axis), keepdims=keepdim), x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply_op(
        lambda v: jnp.count_nonzero(v, axis=_axis(axis), keepdims=keepdim).astype(jnp.int64), x)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = convert_dtype(dtype)
    return apply_op(lambda v: jnp.nansum(v, axis=_axis(axis), dtype=d, keepdims=keepdim), x)


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda v: jnp.nanmean(v, axis=_axis(axis), keepdims=keepdim), x)


# ---- cumulative ------------------------------------------------------------

def cumsum(x, axis=None, dtype=None, name=None):
    d = convert_dtype(dtype)

    def f(v):
        if axis is None:
            return jnp.cumsum(v.reshape(-1), dtype=d)
        return jnp.cumsum(v, axis=int(axis), dtype=d)

    return apply_op(f, x)


def cumprod(x, dim=None, dtype=None, name=None):
    d = convert_dtype(dtype)
    return apply_op(lambda v: jnp.cumprod(v, axis=int(dim), dtype=d), x)


def _cum_extreme(x, axis, dtype, op):
    """(values, indices) running extreme — ref paddle.cummax/cummin return
    both; index is the position of the running extreme along the axis."""
    from ..framework.dtype import convert_dtype

    def f(v):
        flat = axis is None
        vv = v.reshape(-1) if flat else v
        ax = -1 if flat else int(axis)
        n = vv.shape[ax]
        pos_shape = [1] * vv.ndim
        pos_shape[ax] = n
        pos = jnp.broadcast_to(
            jnp.arange(n).reshape(pos_shape), vv.shape)

        def combine(a, b):
            av, ai = a
            bv, bi = b
            # NaN-propagating like np.maximum/minimum.accumulate: once a NaN
            # enters the running extreme it sticks
            take_b = (bv > av) if op is jnp.maximum else (bv < av)
            take_b = take_b | jnp.isnan(bv)
            return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

        vals, idx = jax.lax.associative_scan(combine, (vv, pos), axis=ax)
        return vals, idx.astype(convert_dtype(dtype))

    return apply_op(f, x)


def cummax(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, dtype, jnp.maximum)


def cummin(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, dtype, jnp.minimum)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre = to_array(prepend) if prepend is not None else None
    app = to_array(append) if append is not None else None
    return apply_op(lambda v: jnp.diff(v, n=n, axis=axis, prepend=pre, append=app), x)


# ---- matmul family ---------------------------------------------------------

def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)

    return apply_op(f, x, y, op_name="matmul")


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return apply_op(jnp.matmul, x, y)


def dot(x, y, name=None):
    return apply_op(lambda a, b: jnp.sum(a * b, axis=-1), x, y)


def inner(x, y, name=None):
    return apply_op(jnp.inner, x, y)


def outer(x, y, name=None):
    return apply_op(lambda a, b: jnp.outer(a, b), x, y)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op(lambda i, a, b: beta * i + alpha * jnp.matmul(a, b), input, x, y)


def kron(x, y, name=None):
    return apply_op(jnp.kron, x, y)


def cross(x, y, axis=9, name=None):
    def f(a, b):
        ax = axis
        if ax == 9:
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)

    return apply_op(f, x, y)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2), x)


def isfinite(x, name=None):
    return apply_op(jnp.isfinite, x)


def isinf(x, name=None):
    return apply_op(jnp.isinf, x)


def isnan(x, name=None):
    return apply_op(jnp.isnan, x)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op(lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf), x)


def lerp(x, y, weight, name=None):
    if isinstance(weight, (int, float)):
        return apply_op(lambda a, b: a + weight * (b - a), x, y)
    return apply_op(lambda a, b, w: a + w * (b - a), x, y, weight)


def inverse(x, name=None):
    return apply_op(jnp.linalg.inv, x)


def renorm(x, p, axis, max_norm, name=None):
    def f(v):
        dims = tuple(i for i in range(v.ndim) if i != axis)
        norms = jnp.power(jnp.sum(jnp.power(jnp.abs(v), p), axis=dims, keepdims=True), 1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return v * factor

    return apply_op(f, x)


def take(x, index, mode="raise", name=None):
    def f(v, idx):
        flat = v.reshape(-1)
        n = flat.shape[0]
        if mode == "wrap":
            idx = jnp.mod(idx, n)
        elif mode == "clip":
            idx = jnp.clip(idx, 0, n - 1)
        else:
            idx = jnp.where(idx < 0, idx + n, idx)
        return flat[idx]

    return apply_op(f, x, index)


def broadcast_shape(x_shape, y_shape):
    import numpy as np

    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    import numpy as _np

    if x is not None:
        return apply_op(lambda yy, xx: jax.scipy.integrate.trapezoid(yy, xx, axis=axis), y, x)
    return apply_op(
        lambda yy: jax.scipy.integrate.trapezoid(yy, dx=(1.0 if dx is None else dx), axis=axis), y)


def log_normalize(x, axis=-1):
    return apply_op(lambda v: v - jax.scipy.special.logsumexp(v, axis=axis, keepdims=True), x)


# ---------------------------------------------------------------------------
# in-place variants (ref: python/paddle/tensor/math.py *_ APIs /
# fluid/dygraph/math_op_patch.py): compute out-of-place (XLA arrays are
# immutable — "in-place" on TPU is a rebind, which XLA turns into buffer
# reuse via donation), then rebind the Tensor's value and return it.
# ---------------------------------------------------------------------------


def _make_inplace(fn):
    import functools

    @functools.wraps(fn)
    def method(x, *args, **kwargs):
        out = fn(x, *args, **kwargs)
        x._value = out.value if hasattr(out, "value") else out
        return x

    method.__name__ = fn.__name__ + "_"
    method.__qualname__ = fn.__qualname__ + "_"
    method.__doc__ = (f"In-place variant of :func:`{fn.__name__}` "
                      f"(rebinds ``x``'s value; ref tensor/math.py "
                      f"{fn.__name__}_).")
    return method


add_ = _make_inplace(add)
subtract_ = _make_inplace(subtract)
ceil_ = _make_inplace(ceil)
clip_ = _make_inplace(clip)
erfinv_ = _make_inplace(erfinv)
exp_ = _make_inplace(exp)
floor_ = _make_inplace(floor)
lerp_ = _make_inplace(lerp)
reciprocal_ = _make_inplace(reciprocal)
remainder_ = _make_inplace(remainder)
round_ = _make_inplace(round)
rsqrt_ = _make_inplace(rsqrt)
scale_ = _make_inplace(scale)
sqrt_ = _make_inplace(sqrt)
