"""Random sampling ops (ref: python/paddle/tensor/random.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op
from ..framework.dtype import convert_dtype, get_default_dtype
from ..framework.random import next_key


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def rand(shape, dtype=None, name=None):
    dtype = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jax.random.uniform(next_key(), _shape(shape), dtype))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    dtype = convert_dtype(dtype) or get_default_dtype()
    key = jax.random.key(seed) if seed else next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), dtype, minval=min, maxval=max))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x._value = jax.random.uniform(next_key(), tuple(x.shape), x.dtype, minval=min, maxval=max)
    return x


def randn(shape, dtype=None, name=None):
    dtype = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jax.random.normal(next_key(), _shape(shape), dtype))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = to_array(mean) if isinstance(mean, Tensor) else mean
        s = to_array(std) if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(
            m.shape if hasattr(m, "shape") else (), s.shape if hasattr(s, "shape") else ())
        return Tensor(jax.random.normal(next_key(), shp, get_default_dtype()) * s + m)
    shp = _shape(shape) if shape is not None else ()
    return Tensor(jax.random.normal(next_key(), shp, get_default_dtype()) * std + mean)


def normal_(x, mean=0.0, std=1.0, name=None):
    x._value = (jax.random.normal(next_key(), tuple(x.shape), x.dtype) * std + mean)
    return x


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    dtype = convert_dtype(dtype) or get_default_dtype()
    key = jax.random.key(seed) if seed else next_key()
    return Tensor(jax.random.normal(key, _shape(shape), dtype) * std + mean)


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def standard_gamma(alpha, name=None):
    return apply_op(lambda a: jax.random.gamma(next_key(), a), alpha)


def poisson(x, name=None):
    return apply_op(lambda lam: jax.random.poisson(next_key(), lam).astype(lam.dtype), x)


def bernoulli(x, name=None):
    return apply_op(lambda p: jax.random.bernoulli(next_key(), p).astype(p.dtype), x)


def bernoulli_(x, p=0.5, name=None):
    x._value = jax.random.bernoulli(next_key(), p, tuple(x.shape)).astype(x.dtype)
    return x


def binomial(count, prob, name=None):
    def f(n, p):
        return jax.random.binomial(next_key(), n.astype(jnp.float32), p).astype(jnp.int64)

    return apply_op(f, count, prob)


def multinomial(x, num_samples=1, replacement=False, name=None):
    def f(p):
        logits = jnp.log(jnp.clip(p, 1e-30, None))
        return jax.random.categorical(
            next_key(), logits, axis=-1,
            shape=(num_samples,) + p.shape[:-1]).T if p.ndim > 1 else jax.random.categorical(
            next_key(), logits, shape=(num_samples,))

    out = apply_op(lambda p: f(p).astype(jnp.int64), x)
    return out


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    dtype = convert_dtype(dtype)
    return Tensor(jax.random.randint(next_key(), _shape(shape), int(low), int(high), dtype))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    d = convert_dtype(dtype) or x.dtype
    return Tensor(jax.random.randint(next_key(), tuple(x.shape), int(low), int(high)).astype(d))


def randperm(n, dtype="int64", name=None):
    dtype = convert_dtype(dtype)
    return Tensor(jax.random.permutation(next_key(), int(n)).astype(dtype))


def rand_like(x, dtype=None, name=None):
    d = convert_dtype(dtype) or x.dtype
    return Tensor(jax.random.uniform(next_key(), tuple(x.shape), d))


def randn_like(x, dtype=None, name=None):
    d = convert_dtype(dtype) or x.dtype
    return Tensor(jax.random.normal(next_key(), tuple(x.shape), d))


def exponential_(x, lam=1.0, name=None):
    x._value = (jax.random.exponential(next_key(), tuple(x.shape), x.dtype) / lam)
    return x


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    shp = _shape(shape) if shape is not None else ()
    return Tensor(jnp.exp(jax.random.normal(next_key(), shp, get_default_dtype()) * std + mean))
