"""paddle.sparse (ref: python/paddle/sparse/ — COO/CSR tensors + ops).

TPU-native: XLA has no native sparse storage; we use the standard JAX
approach (jax.experimental.sparse BCOO) wrapped in paddle's API names.
Sparse compute lowers to gather/scatter + dense MXU matmuls, which is also
how TPUs execute sparsity best.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..framework.core import Tensor, to_array


class SparseCooTensor(Tensor):
    """COO tensor (ref paddle/phi/core/sparse_coo_tensor.h)."""

    __slots__ = ("_bcoo",)

    def __init__(self, bcoo, stop_gradient=True):
        self._bcoo = bcoo
        super().__init__(bcoo.todense(), stop_gradient=stop_gradient)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, -1, -2))

    def values(self):
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def nnz(self):
        return int(self._bcoo.nse)


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    idx = to_array(indices) if isinstance(indices, Tensor) else jnp.asarray(indices)
    vals = to_array(values) if isinstance(values, Tensor) else jnp.asarray(values)
    if dtype is not None:
        from ..framework.dtype import convert_dtype

        vals = vals.astype(convert_dtype(dtype))
    bcoo = jsparse.BCOO((vals, jnp.swapaxes(idx, 0, 1).astype(jnp.int32)),
                        shape=tuple(int(s) for s in shape))
    return SparseCooTensor(bcoo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    # convert CSR to COO rows
    crows_np = np.asarray(to_array(crows) if isinstance(crows, Tensor) else crows)
    cols_np = np.asarray(to_array(cols) if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    idx = np.stack([rows, cols_np])
    return sparse_coo_tensor(idx, values, shape, dtype, place, stop_gradient)


def matmul(x, y, name=None):
    from ..framework.dispatch import apply_op

    if isinstance(x, SparseCooTensor):
        bcoo = x._bcoo
        return apply_op(lambda yv: bcoo @ yv, y)
    return apply_op(jnp.matmul, x, y)


def add(x, y, name=None):
    from ..tensor.math import add as _add

    return _add(x.to_dense() if isinstance(x, SparseCooTensor) else x,
                y.to_dense() if isinstance(y, SparseCooTensor) else y)


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)
