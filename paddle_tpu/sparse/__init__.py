"""paddle.sparse (ref: python/paddle/sparse/ — COO/CSR tensors + full op surface).

TPU-native design: XLA has no native sparse storage; we keep the standard JAX
approach (jax.experimental.sparse BCOO) wrapped in paddle's API names.
Structure-preserving ops (unary math, relu, batch norm) operate on the nse
value vector directly; structure-changing ops (conv3d, pooling, reshape) go
through a dense roundtrip — on TPU, dense MXU compute over gathered blocks IS
the fast path for the voxel workloads these ops serve (no warp-level scatter
hardware to exploit, unlike the reference's cuSPARSE/submanifold CUDA kernels,
ref paddle/phi/kernels/sparse/).
"""
from __future__ import annotations

import weakref

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op

__all__ = [
    'sparse_coo_tensor', 'sparse_csr_tensor', 'sin', 'tan', 'asin', 'atan', 'sinh',
    'tanh', 'asinh', 'atanh', 'sqrt', 'square', 'log1p', 'abs', 'pow', 'cast', 'neg',
    'deg2rad', 'rad2deg', 'expm1', 'mv', 'matmul', 'masked_matmul', 'addmm', 'add',
    'subtract', 'transpose', 'multiply', 'divide', 'coalesce', 'is_same_shape',
    'reshape', 'nn', 'SparseCooTensor', 'SparseCsrTensor',
]


class SparseCooTensor(Tensor):
    """COO tensor (ref paddle/phi/core/sparse_coo_tensor.h)."""

    __slots__ = ("_bcoo",)

    def __init__(self, bcoo, stop_gradient=True):
        self._bcoo = bcoo
        super().__init__(bcoo.todense(), stop_gradient=stop_gradient)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, -1, -2))

    def values(self):
        return _tape_values(self)

    def to_dense(self):
        return apply_op(lambda a: a, self, op_name="sparse_to_dense")

    def to_sparse_csr(self):
        if len(self._bcoo.shape) != 2:
            raise ValueError("to_sparse_csr: only 2-D supported")
        return SparseCsrTensor._from_coo(self._bcoo)

    def to_sparse_coo(self, sparse_dim=None):
        return self

    def nnz(self):
        return int(self._bcoo.nse)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def coalesce(self):
        return SparseCooTensor(self._bcoo.sum_duplicates(), self.stop_gradient)

    def _replace_values(self, new_vals):
        return _with_values(self, new_vals)


class SparseCsrTensor(Tensor):
    """CSR tensor (ref paddle/phi/core/sparse_csr_tensor.h). Stored as a COO
    kept in row-major order plus the compressed row pointer."""

    __slots__ = ("_bcoo", "_crows")

    def __init__(self, bcoo, crows, stop_gradient=True):
        self._bcoo = bcoo
        crows = jnp.asarray(crows)
        if not jnp.issubdtype(crows.dtype, jnp.integer):
            crows = crows.astype(jnp.int64)
        self._crows = crows
        super().__init__(bcoo.todense(), stop_gradient=stop_gradient)

    @classmethod
    def _from_coo(cls, bcoo, stop_gradient=True):
        bcoo = bcoo.sum_duplicates()
        idx = np.asarray(bcoo.indices)
        order = np.lexsort((idx[:, 1], idx[:, 0]))
        idx = idx[order]
        data = jnp.asarray(np.asarray(bcoo.data)[order])
        crows = np.zeros(bcoo.shape[0] + 1, np.int64)
        np.add.at(crows, idx[:, 0] + 1, 1)
        crows = np.cumsum(crows)
        sorted_bcoo = jsparse.BCOO((data, jnp.asarray(idx)), shape=bcoo.shape)
        return cls(sorted_bcoo, crows, stop_gradient)

    def crows(self):
        return Tensor(self._crows)

    def cols(self):
        return Tensor(self._bcoo.indices[:, 1])

    def values(self):
        return _tape_values(self)

    def to_dense(self):
        return apply_op(lambda a: a, self, op_name="sparse_to_dense")

    def to_sparse_coo(self, sparse_dim=None):
        return SparseCooTensor(self._bcoo, self.stop_gradient)

    def nnz(self):
        return int(self._bcoo.nse)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def _replace_values(self, new_vals):
        return _with_values(self, new_vals)


def _is_sparse(x):
    return isinstance(x, (SparseCooTensor, SparseCsrTensor))


# ------------------------------------------------------- tape-aware plumbing
#
# Sparse tensors ARE Tensors (their base value is the densified array), so
# autograd flows through them as long as every op goes through apply_op.
# _adopt_tape clones a freshly-computed dense Tensor's tape node onto the
# sparse wrapper so `loss.backward()` reaches parameters of sparse layers.

def _adopt_tape(sparse_t, dense_t):
    sparse_t.stop_gradient = dense_t.stop_gradient
    sparse_t._node = dense_t._node
    sparse_t._idx = dense_t._idx
    if dense_t._node is not None:
        dense_t._node.out_tensors[dense_t._idx] = weakref.ref(sparse_t)
    return sparse_t


def _coo_from_dense_tensor(dense_t, n_dense=0, stop_gradient=None):
    """Wrap a tape-carrying dense Tensor as SparseCooTensor (pattern from its
    current value)."""
    bcoo = jsparse.BCOO.fromdense(dense_t.value, n_dense=n_dense)
    s = SparseCooTensor(bcoo, stop_gradient=dense_t.stop_gradient
                        if stop_gradient is None else stop_gradient)
    return _adopt_tape(s, dense_t)


def _tape_values(x):
    """Gather the nse values of sparse ``x`` as a tape-connected Tensor."""
    idx = np.asarray(x._bcoo.indices)
    gather_idx = tuple(jnp.asarray(idx[:, i]) for i in range(idx.shape[1]))
    return apply_op(lambda a: a[gather_idx], x, op_name="sparse_values")


def _with_values(x, vals, cls=None):
    """Scatter ``vals`` (Tensor or array) back into x's sparsity pattern,
    keeping the tape. Returns the same sparse class as ``x``."""
    idx = x._bcoo.indices
    shape = x._bcoo.shape
    if not isinstance(vals, Tensor):
        vals = Tensor(jnp.asarray(vals), stop_gradient=x.stop_gradient)

    def scat(v):
        return jsparse.BCOO((v, idx), shape=shape).todense()

    dense_t = apply_op(scat, vals, op_name="sparse_scatter")
    bcoo = jsparse.BCOO((vals.value, idx), shape=shape)
    cls = cls or type(x)
    if cls is SparseCsrTensor:
        s = SparseCsrTensor(bcoo, x._crows, dense_t.stop_gradient)
    else:
        s = SparseCooTensor(bcoo, dense_t.stop_gradient)
    return _adopt_tape(s, dense_t)


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    idx = to_array(indices) if isinstance(indices, Tensor) else jnp.asarray(indices)
    vals = to_array(values) if isinstance(values, Tensor) else jnp.asarray(values)
    if dtype is not None:
        from ..framework.dtype import convert_dtype

        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(idx).max(axis=1))
    bcoo = jsparse.BCOO((vals, jnp.swapaxes(idx, 0, 1).astype(jnp.int32)),
                        shape=tuple(int(s) for s in shape))
    return SparseCooTensor(bcoo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    crows_np = np.asarray(to_array(crows) if isinstance(crows, Tensor) else crows)
    cols_np = np.asarray(to_array(cols) if isinstance(cols, Tensor) else cols)
    vals = to_array(values) if isinstance(values, Tensor) else jnp.asarray(values)
    if dtype is not None:
        from ..framework.dtype import convert_dtype

        vals = vals.astype(convert_dtype(dtype))
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    idx = jnp.asarray(np.stack([rows, cols_np], axis=1).astype(np.int32))
    bcoo = jsparse.BCOO((vals, idx), shape=tuple(int(s) for s in shape))
    return SparseCsrTensor(bcoo, jnp.asarray(crows_np), stop_gradient)


def to_sparse_coo(x, sparse_dim=None):
    """Dense → COO (ref Tensor.to_sparse_coo)."""
    arr = to_array(x)
    return SparseCooTensor(jsparse.BCOO.fromdense(arr), getattr(x, "stop_gradient", True))


def to_sparse_csr(x):
    arr = to_array(x)
    return SparseCsrTensor._from_coo(jsparse.BCOO.fromdense(arr),
                                     getattr(x, "stop_gradient", True))


# ------------------------------------------------- unary (structure-preserving)

def _unary(fn):
    def op(x, name=None):
        if _is_sparse(x):
            return _with_values(x, apply_op(fn, _tape_values(x)))
        return apply_op(fn, x)
    return op


sin = _unary(jnp.sin)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
tanh = _unary(jnp.tanh)
asinh = _unary(jnp.arcsinh)
atanh = _unary(jnp.arctanh)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
log1p = _unary(jnp.log1p)
abs = _unary(jnp.abs)
neg = _unary(jnp.negative)
deg2rad = _unary(jnp.deg2rad)
rad2deg = _unary(jnp.rad2deg)
expm1 = _unary(jnp.expm1)


def pow(x, factor, name=None):
    if _is_sparse(x):
        return _with_values(x, apply_op(lambda v: jnp.power(v, factor), _tape_values(x)))
    return apply_op(jnp.power, x, factor)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..framework.dtype import convert_dtype

    if not _is_sparse(x):
        if value_dtype is None:
            return Tensor(to_array(x), getattr(x, "stop_gradient", True))
        return Tensor(to_array(x).astype(convert_dtype(value_dtype)))
    data = x._bcoo.data
    idx = x._bcoo.indices
    crows = getattr(x, "_crows", None)
    if value_dtype is not None:
        data = data.astype(convert_dtype(value_dtype))
    if index_dtype is not None:
        idx = idx.astype(convert_dtype(index_dtype))
        if crows is not None:
            crows = crows.astype(convert_dtype(index_dtype))
    bcoo = jsparse.BCOO((data, idx), shape=x._bcoo.shape)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(bcoo, crows, x.stop_gradient)
    return SparseCooTensor(bcoo, x.stop_gradient)


# ----------------------------------------------------------------- binary ops

def matmul(x, y, name=None):
    if _is_sparse(x) and _is_sparse(y):
        # sparse @ sparse → sparse (ref coo@coo / csr@csr contract)
        out = apply_op(jnp.matmul, x, y, op_name="sparse_matmul")
        if isinstance(x, SparseCsrTensor):
            return _adopt_tape(SparseCsrTensor._from_coo(
                jsparse.BCOO.fromdense(out.value)), out)
        return _coo_from_dense_tensor(out)
    if _is_sparse(x):
        # spmm: keep the BCOO dot_general (gather + MXU matmul) for the values
        bcoo = x._bcoo
        return apply_op(lambda yv: bcoo @ yv, y, op_name="spmm")
    return apply_op(jnp.matmul, x, y)


def mv(x, vec, name=None):
    return matmul(x, vec, name=name)


def masked_matmul(x, y, mask, name=None):
    """Dense@dense sampled at mask's sparsity pattern (SDDMM,
    ref phi sparse masked_matmul_kernel)."""
    idx = mask._bcoo.indices  # [nse, ndim] — trailing two dims are (row, col)
    lead = tuple(idx[:, i] for i in range(idx.shape[1] - 2))
    rows, cols = idx[:, -2], idx[:, -1]

    def f(xv, yv):
        xg = xv[(*lead, rows)]                        # [nse, K]
        yg = jnp.swapaxes(yv, -1, -2)[(*lead, cols)]  # [nse, K]
        return jnp.einsum("nk,nk->n", xg, yg).astype(xv.dtype)

    vals = apply_op(f, x, y, op_name="sddmm")
    cls = SparseCsrTensor if isinstance(mask, SparseCsrTensor) else SparseCooTensor
    return _with_values(mask, vals, cls=cls)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x@y) (ref phi sparse addmm_kernel)."""
    prod = matmul(x, y)
    inp = input.to_dense() if _is_sparse(input) else input
    return apply_op(lambda a, b: beta * a + alpha * b, inp, prod)


def _binary_elemwise(fn):
    def op(x, y, name=None):
        xs, ys = _is_sparse(x), _is_sparse(y)
        if xs and ys:
            # operate on the UNION pattern only: implicit zeros stay implicit
            # even for non-zero-preserving fns like divide (0/0 positions are
            # not materialized, matching the reference's merge kernels)
            def f(a, b):
                union = (a != 0) | (b != 0)
                # "where trick": feed safe operands at masked positions so
                # neither the forward nor the VJP sees 0/0 → nan
                one = jnp.ones((), a.dtype)
                safe = fn(jnp.where(union, a, one), jnp.where(union, b, one))
                return jnp.where(union, safe, jnp.zeros((), a.dtype))

            out = apply_op(f, x, y, op_name=fn.__name__)
            if isinstance(x, SparseCsrTensor):
                return _adopt_tape(SparseCsrTensor._from_coo(
                    jsparse.BCOO.fromdense(out.value)), out)
            return _coo_from_dense_tensor(out)
        a = x.to_dense() if xs else x
        b = y.to_dense() if ys else y
        return apply_op(fn, a, b)
    return op


add = _binary_elemwise(jnp.add)
subtract = _binary_elemwise(jnp.subtract)
multiply = _binary_elemwise(jnp.multiply)
divide = _binary_elemwise(jnp.divide)


def coalesce(x, name=None):
    return x.coalesce()


def _structure_op(x, fn, op_name):
    """Dense-roundtrip structural op, tape preserved."""
    out = apply_op(fn, x, op_name=op_name)
    if isinstance(x, SparseCsrTensor):
        return _adopt_tape(SparseCsrTensor._from_coo(
            jsparse.BCOO.fromdense(out.value)), out)
    return _coo_from_dense_tensor(out)


def transpose(x, perm, name=None):
    if not _is_sparse(x):
        from ..tensor.manipulation import transpose as _t

        return _t(x, perm)
    return _structure_op(x, lambda a: jnp.transpose(a, perm), "sparse_transpose")


def reshape(x, shape, name=None):
    if not _is_sparse(x):
        from ..tensor.manipulation import reshape as _r

        return _r(x, shape)
    return _structure_op(x, lambda a: jnp.reshape(a, [int(s) for s in shape]),
                         "sparse_reshape")


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


from . import nn  # noqa: E402,F401
