"""paddle.sparse.nn.functional (ref: python/paddle/sparse/nn/functional/).

Sparse conv/pool run as dense XLA ops over the densified voxel grid, then
re-sparsify (see package docstring for the TPU rationale). All compute goes
through apply_op so autograd reaches layer parameters. Activations are
structure-preserving and run on the nse value vector.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, to_array
from ...framework.dispatch import apply_op


def relu(x, name=None):
    from .. import _is_sparse, _tape_values, _with_values

    if _is_sparse(x):
        return _with_values(x, apply_op(jax.nn.relu, _tape_values(x)))
    from ...nn.functional import relu as _relu

    return _relu(x)


def relu6(x, name=None):
    from .. import _is_sparse, _tape_values, _with_values

    if _is_sparse(x):
        return _with_values(x, apply_op(lambda v: jnp.clip(v, 0, 6), _tape_values(x)))
    from ...nn.functional import relu6 as _relu6

    return _relu6(x)


def leaky_relu(x, negative_slope=0.01, name=None):
    from .. import _is_sparse, _tape_values, _with_values

    if _is_sparse(x):
        return _with_values(x, apply_op(
            lambda v: jax.nn.leaky_relu(v, negative_slope), _tape_values(x)))
    from ...nn.functional import leaky_relu as _lr

    return _lr(x, negative_slope)


def softmax(x, axis=-1, name=None):
    """Softmax over the stored entries of each row (ref phi sparse softmax:
    only non-zero entries participate). Rows are all-but-last sparse dims."""
    from .. import _is_sparse, _tape_values, _with_values

    if not _is_sparse(x):
        from ...nn.functional import softmax as _sm

        return _sm(x, axis)
    n_sparse = x._bcoo.indices.shape[1]
    assert axis in (-1, len(x._bcoo.shape) - 1), \
        "sparse softmax supports the last axis only (like the reference)"
    idx = np.asarray(x._bcoo.indices)
    if n_sparse == 1:
        seg = np.zeros(idx.shape[0], np.int32)
        n_seg = 1
    else:
        # composite row key over all sparse dims except the last
        row_dims = idx[:, :-1]
        shape = np.asarray(x._bcoo.shape[:n_sparse - 1], np.int64)
        seg = np.ravel_multi_index(tuple(row_dims.T), tuple(shape)).astype(np.int32)
        n_seg = int(np.prod(shape))
    seg_j = jnp.asarray(seg)

    def f(vals):
        row_max = jax.ops.segment_max(vals, seg_j, num_segments=n_seg)
        ex = jnp.exp(vals - row_max[seg_j])
        denom = jax.ops.segment_sum(ex, seg_j, num_segments=n_seg)
        return ex / denom[seg_j]

    return _with_values(x, apply_op(f, _tape_values(x)))


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """Sparse conv3d: dense XLA conv over the voxel grid, re-sparsified.
    x: SparseCooTensor [N, D, H, W, C]; weight [kd, kh, kw, Cin/g, Cout]."""
    from .. import _coo_from_dense_tensor

    s = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    d = (dilation,) * 3 if isinstance(dilation, int) else tuple(dilation)

    def f(dense, w, *b):
        out = jax.lax.conv_general_dilated(
            dense, w, window_strides=s, padding=[(pi, pi) for pi in p], rhs_dilation=d,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"), feature_group_count=groups)
        if b:
            out = out + b[0]
        return out

    args = [x, weight] + ([bias] if bias is not None else [])
    out = apply_op(f, *args, op_name="sparse_conv3d")
    return _coo_from_dense_tensor(out, n_dense=1)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
                data_format="NDHWC", key=None, name=None):
    """Submanifold conv3d (ref sparse subm_conv3d): conv with the given
    stride/padding, output restricted to the input's active sites (mapped
    through the same window when strided)."""
    from .. import SparseCooTensor, _adopt_tape

    s = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    dil = (dilation,) * 3 if isinstance(dilation, int) else tuple(dilation)
    ks = tuple(int(k) for k in to_array(weight).shape[:3])

    def f(dense, w, *b):
        out = jax.lax.conv_general_dilated(
            dense, w, window_strides=s, padding=[(pi, pi) for pi in p],
            rhs_dilation=dil, dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
            feature_group_count=groups)
        if b:
            out = out + b[0]
        # active-site mask, pushed through the same window geometry
        active = (dense != 0).any(axis=-1, keepdims=True).astype(out.dtype)
        act_out = jax.lax.reduce_window(
            active, jnp.zeros((), active.dtype), jax.lax.max,
            window_dimensions=(1, *ks, 1),
            window_strides=(1, *s, 1),
            padding=[(0, 0), *[(pi, pi) for pi in p], (0, 0)],
            window_dilation=(1, *dil, 1))
        if s == (1, 1, 1) and all(pi == (dil_ * (k - 1)) // 2
                                  for pi, k, dil_ in zip(p, ks, dil)):
            # true submanifold case: exactly the input's sites
            act_out = active
        return jnp.where(act_out > 0, out, jnp.zeros((), out.dtype))

    args = [x, weight] + ([bias] if bias is not None else [])
    out = apply_op(f, *args, op_name="sparse_subm_conv3d")
    from jax.experimental import sparse as jsparse

    return _adopt_tape(SparseCooTensor(jsparse.BCOO.fromdense(out.value, n_dense=1),
                                       out.stop_gradient), out)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    from .. import _coo_from_dense_tensor

    ks = (kernel_size,) * 3 if isinstance(kernel_size, int) else tuple(kernel_size)
    st = ks if stride is None else ((stride,) * 3 if isinstance(stride, int)
                                    else tuple(stride))
    p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)

    def f(dense):
        # pool only over ACTIVE sites: implicit zeros must not win the max
        # (an all-negative active window pools to its max, not 0)
        active = (dense != 0).any(axis=-1, keepdims=True)
        neg_inf = jnp.asarray(-jnp.inf, dense.dtype)
        masked = jnp.where(active, dense, neg_inf)
        pad = [(0, 0), *[(pi, pi) for pi in p], (0, 0)]
        out = jax.lax.reduce_window(
            masked, neg_inf, jax.lax.max, window_dimensions=(1, *ks, 1),
            window_strides=(1, *st, 1), padding=pad)
        act_out = jax.lax.reduce_window(
            active, False, jax.lax.bitwise_or, window_dimensions=(1, *ks, 1),
            window_strides=(1, *st, 1), padding=pad)
        return jnp.where(act_out, out, jnp.zeros((), dense.dtype))

    out = apply_op(f, x, op_name="sparse_max_pool3d")
    return _coo_from_dense_tensor(out, n_dense=1)


def attention(query, key, value, sparse_mask, key_padding_mask=None, attn_mask=None,
              name=None):
    """Sparse-masked scaled-dot-product attention (ref
    sparse/nn/functional/transformer.py). The sparse mask gives the attended
    pattern; key_padding_mask [B, S] and attn_mask [S, S] apply additively like
    the reference. Computed densely (flash-attention covers the dense path)."""
    def f(q, k, v, m, *extra):
        scale = 1.0 / np.sqrt(q.shape[-1])
        scores = jnp.einsum("...qd,...kd->...qk", q, k) * scale
        i = 0
        if key_padding_mask is not None:
            kp = extra[i]
            i += 1
            scores = scores + kp[:, None, None, :]
        if attn_mask is not None:
            scores = scores + extra[i][None, None, :, :]
        neg = jnp.asarray(-1e9, scores.dtype)
        scores = jnp.where(m != 0, scores, neg)
        probs = jax.nn.softmax(scores, axis=-1)
        probs = jnp.where(m != 0, probs, 0.0)
        return jnp.einsum("...qk,...kd->...qd", probs, v)

    mask_dense = sparse_mask.to_dense() if hasattr(sparse_mask, "to_dense") else sparse_mask
    args = [query, key, value, mask_dense]
    if key_padding_mask is not None:
        args.append(key_padding_mask)
    if attn_mask is not None:
        args.append(attn_mask)
    return apply_op(f, *args, op_name="sparse_attention")
