"""paddle.sparse.nn (ref: python/paddle/sparse/nn/ — sparse layers)."""
from __future__ import annotations

import numpy as np

from ...nn.layer_base import Layer
from ...nn.initializer import Uniform
from . import functional
from .functional import (relu, relu6, leaky_relu, softmax, conv3d, subm_conv3d,
                         max_pool3d, attention)


class ReLU(Layer):
    def forward(self, x):
        return relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._negative_slope = negative_slope

    def forward(self, x):
        return leaky_relu(x, self._negative_slope)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return softmax(x, self._axis)


class _Conv3DBase(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, subm=False, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NDHWC"):
        super().__init__()
        assert data_format == "NDHWC", "sparse conv3d is NDHWC (channels-last) only"
        ks = ((kernel_size,) * 3 if isinstance(kernel_size, int) else tuple(kernel_size))
        self._kernel_size = ks
        self._stride = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
        self._padding = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
        self._dilation = (dilation,) * 3 if isinstance(dilation, int) else tuple(dilation)
        self._groups = groups
        self._subm = subm
        fan_in = in_channels * int(np.prod(ks))
        k = float(np.sqrt(1.0 / fan_in))
        # kernel layout [kd, kh, kw, in, out] (ref sparse conv3d kernel layout)
        self.weight = self.create_parameter([*ks, in_channels // groups, out_channels],
                                            default_initializer=Uniform(-k, k))
        self.bias = (None if bias_attr is False else
                     self.create_parameter([out_channels], is_bias=True,
                                           default_initializer=Uniform(-k, k)))

    def forward(self, x):
        if self._subm:
            return subm_conv3d(x, self.weight, self.bias, self._stride, self._padding,
                               self._dilation, self._groups)
        return conv3d(x, self.weight, self.bias, self._stride, self._padding,
                      self._dilation, self._groups)


class Conv3D(_Conv3DBase):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, False, padding_mode, weight_attr, bias_attr,
                         data_format)


class SubmConv3D(_Conv3DBase):
    """Submanifold conv: output sites == input sites (ref sparse subm_conv3d)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", key=None, weight_attr=None,
                 bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, True, padding_mode, weight_attr, bias_attr,
                         data_format)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NDHWC"):
        super().__init__()
        self._kernel_size = kernel_size
        self._stride = stride if stride is not None else kernel_size
        self._padding = padding

    def forward(self, x):
        return max_pool3d(x, self._kernel_size, self._stride, self._padding)


class BatchNorm(Layer):
    """BatchNorm over the nse values' channel dim (ref sparse/nn/layer/norm.py:
    normalizes only active sites)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NDHWC", use_global_stats=None, name=None):
        super().__init__()
        from ...nn import BatchNorm1D

        self._bn = BatchNorm1D(num_features, momentum=momentum, epsilon=epsilon,
                               weight_attr=weight_attr, bias_attr=bias_attr)

    def forward(self, x):
        return x._replace_values(self._bn(x.values()))


class SyncBatchNorm(BatchNorm):
    """On TPU, batch norm inside pjit already reduces across the data mesh axis
    (GSPMD inserts the cross-replica psum) — identical semantics to the
    reference's SyncBatchNorm (ref sparse/nn/layer/norm.py SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer
