"""paddle.sysconfig parity (ref python/paddle/sysconfig.py:20 get_include,
:39 get_lib) — paths for compiling C extensions against the framework.

TPU-native: the native surface is the C-ABI custom-op SDK
(utils/cpp_extension.py) and csrc/ shared objects; there are no CUDA headers.
"""
import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    """Directory containing the framework's C headers (csrc/)."""
    return os.path.join(os.path.dirname(_ROOT), "csrc")


def get_lib() -> str:
    """Directory containing the framework's shared libraries."""
    return os.path.join(os.path.dirname(_ROOT), "csrc")
