"""paddle.incubate parity (ref: python/paddle/incubate/).

Currently: autograd (functional jacobian/hessian/vjp/jvp over jax transforms),
nn fused layers (incubate/nn/layer/fused_transformer.py analogues live in
paddle_tpu.incubate.nn), autotune config shim.
"""
from . import asp  # noqa: F401
from . import autograd  # noqa: F401
from . import autotune  # noqa: F401
from . import checkpoint  # noqa: F401
from . import distributed  # noqa: F401
from . import multiprocessing  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import DistributedFusedLamb, LBFGS, LookAhead, ModelAverage  # noqa: F401
from . import operators  # noqa: F401
from .operators import (graph_khop_sampler, graph_reindex,  # noqa: F401
                        graph_sample_neighbors, graph_send_recv,
                        identity_loss, softmax_mask_fuse,
                        softmax_mask_fuse_upper_triangle)
from ..geometric import (segment_max, segment_mean, segment_min,  # noqa: F401
                         segment_sum)
