"""incubate operators (ref: python/paddle/incubate/operators/ —
graph_send_recv.py:36, graph_khop_sampler.py:21, graph_reindex.py:28,
graph_sample_neighbors.py:28, softmax_mask_fuse.py:20,
softmax_mask_fuse_upper_triangle.py:20; incubate/nn/loss.py identity_loss).

The graph SAMPLING ops are host-side data-preparation (the reference runs
them as CPU/GPU kernels at dataloading time); numpy implementations are the
right tool — their outputs feed jitted compute.  The fused softmax ops are
XLA compositions (the fusion the reference hand-writes in CUDA falls out of
the compiler)."""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op

__all__ = ["graph_send_recv", "graph_khop_sampler", "graph_reindex",
           "graph_sample_neighbors", "identity_loss", "softmax_mask_fuse",
           "softmax_mask_fuse_upper_triangle"]


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None,
                    name=None):
    """Gather-by-src then segment-reduce-to-dst (ref graph_send_recv.py:36);
    alias of geometric.send_u_recv with the legacy arg name."""
    from ..geometric import send_u_recv

    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def _np1d(t):
    return np.asarray(to_array(t)).reshape(-1)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    """Uniform neighbor sampling on a CSC graph (ref
    graph_sample_neighbors.py:28): for each input node draw up to
    ``sample_size`` neighbors (all when -1).  Returns (out_neighbors,
    out_count[, out_eids])."""
    rown = _np1d(row)
    ptr = _np1d(colptr)
    nodes = _np1d(input_nodes)
    eidn = _np1d(eids) if eids is not None else None
    # entropy from the framework generator: fresh draw per call, but the
    # whole sequence replays after paddle.seed (reference ops honor the
    # global seed the same way)
    from ..framework.random import default_generator, derived_rng

    ent = np.asarray(jax.random.key_data(  # graftlint: noqa[host-sync]
        default_generator().next_key())).ravel().tolist()
    rng = derived_rng(*ent)
    neigh, counts, out_eids = [], [], []
    for n in nodes:
        lo, hi = int(ptr[n]), int(ptr[n + 1])
        idx = np.arange(lo, hi)
        if 0 <= sample_size < len(idx):
            idx = rng.choice(idx, size=sample_size, replace=False)
        neigh.append(rown[idx])
        counts.append(len(idx))
        if return_eids:
            out_eids.append(eidn[idx] if eidn is not None else idx)
    out_n = Tensor(jnp.asarray(np.concatenate(neigh)
                               if neigh else np.zeros(0, rown.dtype)))
    out_c = Tensor(jnp.asarray(np.asarray(counts, rown.dtype)))
    if return_eids:
        ee = Tensor(jnp.asarray(np.concatenate(out_eids)
                                if out_eids else np.zeros(0, rown.dtype)))
        return out_n, out_c, ee
    return out_n, out_c


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """Reindex node ids to a dense [0, n) range, inputs first (ref
    graph_reindex.py:28).  Returns (reindex_src, reindex_dst, out_nodes)."""
    xs = _np1d(x)
    nb = _np1d(neighbors)
    cnt = _np1d(count)
    # unique neighbor ids not already in x, in first-appearance order
    seen = {int(v): i for i, v in enumerate(xs)}
    order = list(xs)
    for v in nb:
        if int(v) not in seen:
            seen[int(v)] = len(order)
            order.append(v)
    remap = np.vectorize(lambda v: seen[int(v)])
    reindex_src = remap(nb) if len(nb) else np.zeros(0, np.int64)
    dst = np.repeat(np.arange(len(xs)), cnt)
    return (Tensor(jnp.asarray(reindex_src.astype(xs.dtype))),
            Tensor(jnp.asarray(dst.astype(xs.dtype))),
            Tensor(jnp.asarray(np.asarray(order, xs.dtype))))


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling + reindex (ref graph_khop_sampler.py:21).
    Returns (edge_src, edge_dst, sample_index, reindex_nodes[, edge_eids])."""
    nodes = _np1d(input_nodes)
    frontier = nodes
    all_src, all_dst, all_eids = [], [], []
    for size in sample_sizes:
        res = graph_sample_neighbors(row, colptr, frontier,
                                     eids=sorted_eids, sample_size=int(size),
                                     return_eids=return_eids)
        nb, cnt = _np1d(res[0]), _np1d(res[1])
        all_src.append(nb)
        all_dst.append(np.repeat(frontier, cnt))
        if return_eids:
            all_eids.append(_np1d(res[2]))
        frontier = np.unique(nb)
    src = np.concatenate(all_src) if all_src else np.zeros(0, nodes.dtype)
    dst = np.concatenate(all_dst) if all_dst else np.zeros(0, nodes.dtype)
    # dense reindex, inputs first
    seen = {int(v): i for i, v in enumerate(nodes)}
    order = list(nodes)
    for v in np.concatenate([src, dst]) if len(src) else []:
        if int(v) not in seen:
            seen[int(v)] = len(order)
            order.append(v)
    remap = np.vectorize(lambda v: seen[int(v)])
    e_src = remap(src) if len(src) else np.zeros(0, np.int64)
    e_dst = remap(dst) if len(dst) else np.zeros(0, np.int64)
    out = (Tensor(jnp.asarray(e_src.astype(nodes.dtype)).reshape(-1, 1)),
           Tensor(jnp.asarray(e_dst.astype(nodes.dtype)).reshape(-1, 1)),
           Tensor(jnp.asarray(np.asarray(order, nodes.dtype))),
           Tensor(jnp.asarray(remap(nodes).astype(nodes.dtype))))
    if return_eids:
        ee = (np.concatenate(all_eids) if all_eids
              else np.zeros(0, nodes.dtype))
        return out + (Tensor(jnp.asarray(ee)),)
    return out


def identity_loss(x, reduction="none"):
    """Mark a tensor as the loss head (ref incubate/nn/loss.py:21); the
    reference uses it to anchor IPU backprop — here it is the identity with
    the requested reduction."""
    red = {0: "sum", 1: "mean", 2: "none"}.get(reduction, reduction)
    if red == "mean":
        return apply_op(jnp.mean, x)
    if red == "sum":
        return apply_op(jnp.sum, x)
    if red == "none":
        return apply_op(lambda v: v, x)
    raise ValueError(f"unknown reduction {reduction!r}")


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) fused (ref softmax_mask_fuse.py:20 — a CUDA kernel
    there; one XLA fusion here)."""
    return apply_op(lambda a, m: jax.nn.softmax(a + m, axis=-1), x, mask)


def softmax_mask_fuse_upper_triangle(x):
    """softmax with the upper triangle masked out (causal; ref
    softmax_mask_fuse_upper_triangle.py:20)."""

    def f(a):
        s = a.shape[-1]
        mask = jnp.tril(jnp.ones((a.shape[-2], s), bool), k=s - a.shape[-2])
        return jax.nn.softmax(jnp.where(mask, a, -1e30), axis=-1)

    return apply_op(f, x)
