"""ASP — 2:4 structured sparsity (ref: python/paddle/incubate/asp/ —
calculate_density, create_mask, prune_model, decorate/ASPOptimizer).

TPU note: 2:4 sparsity is an Ampere tensor-core feature; on TPU the masks
give model-compression parity (pruned weights stay zero through training),
executed as dense-with-zeros on the MXU.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..framework.core import Parameter, Tensor, to_array

_MASKS: Dict[int, jnp.ndarray] = {}


def calculate_density(x) -> float:
    v = np.asarray(to_array(x) if isinstance(x, Tensor) else x)
    return float((v != 0).sum() / v.size)


def _mask_2to4_1d(row: np.ndarray) -> np.ndarray:
    out = np.zeros_like(row, dtype=bool)
    for i in range(0, len(row) - len(row) % 4, 4):
        blk = np.abs(row[i:i + 4])
        keep = np.argsort(-blk)[:2]
        out[i + keep] = True
    out[len(row) - len(row) % 4:] = True
    return out


def create_mask(tensor, func_name="mask_2d_best", n=2, m=4) -> np.ndarray:
    v = np.asarray(to_array(tensor) if isinstance(tensor, Tensor) else tensor)
    flat = v.reshape(-1, v.shape[-1]) if v.ndim > 1 else v.reshape(1, -1)
    mask = np.stack([_mask_2to4_1d(r) for r in flat])
    return mask.reshape(v.shape)


def check_sparsity(tensor, n=2, m=4, func_name=None) -> bool:
    v = np.asarray(to_array(tensor) if isinstance(tensor, Tensor) else tensor)
    flat = v.reshape(-1, v.shape[-1]) if v.ndim > 1 else v.reshape(1, -1)
    for row in flat:
        for i in range(0, len(row) - len(row) % m, m):
            if (row[i:i + m] != 0).sum() > n:
                return False
    return True


def _supported(p: Parameter) -> bool:
    return p.ndim == 2 and p.shape[0] % 4 == 0 or (p.ndim == 2 and p.shape[-1] % 4 == 0)


def prune_model(model, n=2, m=4, mask_algo="mask_2d_best", with_mask=True):
    """Apply 2:4 masks to all eligible weights; registers masks so
    ASP-decorated optimizers re-apply them after each step."""
    pruned = {}
    for name, p in model.named_parameters():
        if p.ndim != 2 or p.shape[-1] % 4 != 0:
            continue
        mask = create_mask(p, mask_algo, n, m)
        _MASKS[id(p)] = jnp.asarray(mask, p.dtype)
        p._value = p.value * _MASKS[id(p)]
        pruned[name] = mask
    return pruned


def decorate(optimizer):
    """ASPOptimizer parity: re-mask after every optimizer step."""
    orig_step = optimizer.step

    def step(*a, **k):
        out = orig_step(*a, **k)
        for p in optimizer._get_params():
            m = _MASKS.get(id(p))
            if m is not None:
                p._value = p.value * m
        return out

    optimizer.step = step
    return optimizer


def reset_excluded_layers(model=None):
    _MASKS.clear()


def set_excluded_layers(param_names, main_program=None):
    pass
