"""Shared-memory pickle reductions for Tensor (ref python/paddle/incubate/
multiprocessing/reductions.py:94 _reduce_tensor / :182 init_reductions).

The reference shares CUDA memory via cudaIpcGetMemHandle and CPU LoDTensors
via /dev/shm files.  Here a Tensor crossing a process boundary is staged to a
``multiprocessing.shared_memory`` block; the receiver maps it zero-copy and
wraps it back into a Tensor (device placement happens lazily on first use,
as with any host array entering jax).
"""
from __future__ import annotations

from multiprocessing import shared_memory

import numpy as np

from ...framework.core import Tensor, to_array

__all__ = ["init_reductions"]

# keep SharedMemory blocks alive on the producer side until gc
_PRODUCED = []


def _rebuild_tensor_from_shm(shm_name, shape, dtype_str, stop_gradient):
    shm = shared_memory.SharedMemory(name=shm_name)
    try:
        arr = np.ndarray(shape, dtype=np.dtype(dtype_str), buffer=shm.buf)
        t = Tensor(np.array(arr), stop_gradient=stop_gradient)  # own the data
    finally:
        shm.close()
        try:
            shm.unlink()  # receiver owns the lifetime: one-shot handoff
        except FileNotFoundError:
            pass
    return t


def _reduce_tensor(t: Tensor):
    arr = np.asarray(to_array(t))
    if arr.nbytes == 0:
        return (Tensor, (arr,))
    shm = shared_memory.SharedMemory(create=True, size=arr.nbytes)
    dst = np.ndarray(arr.shape, dtype=arr.dtype, buffer=shm.buf)
    dst[...] = arr
    _PRODUCED.append(shm)  # hold mapping until interpreter exit
    return (_rebuild_tensor_from_shm,
            (shm.name, arr.shape, arr.dtype.str, bool(t.stop_gradient)))


def init_reductions() -> None:
    """Register with ForkingPickler ONLY (ref reductions.py:182): the shm
    path must apply to multiprocessing transport, not to ordinary pickling
    (paddle.save must keep writing self-contained files)."""
    from multiprocessing.reduction import ForkingPickler

    ForkingPickler.register(Tensor, _reduce_tensor)
