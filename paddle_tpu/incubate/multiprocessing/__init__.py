"""paddle.incubate.multiprocessing (ref python/paddle/incubate/
multiprocessing/ — CUDA-IPC / shared-memory tensor passing between processes).

TPU-native: device memory is owned by the XLA runtime and is not IPC-shareable
the way CUDA allocations are; cross-process tensor transport goes through host
shared memory.  We register pickle reductions that move Tensor data via
``multiprocessing.shared_memory`` blocks (the analogue of the reference's
file_descriptor/file_system LoDTensor strategies in reductions.py), so
``mp.Queue``/``Pipe`` of Tensors avoids a serialize copy of the payload.
"""
from .reductions import init_reductions  # noqa: F401

init_reductions()

__all__ = []
