"""Incubate optimizers (ref: python/paddle/incubate/optimizer/ — LBFGS,
Lookahead, ModelAverage; distributed_fused_lamb).
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from ..framework.core import Parameter, Tensor
from ..optimizer.optimizer import Lamb, Optimizer


class LookAhead(Optimizer):
    """Ref incubate/optimizer/lookahead.py — k inner steps then interpolate
    toward slow weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._slow = {}
        self._step_count = 0

    def step(self):
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k == 0:
            for p in self.inner_optimizer._get_params():
                key = id(p)
                if key not in self._slow:
                    self._slow[key] = p.value
                slow = self._slow[key].astype(jnp.float32)
                fast = p.value.astype(jnp.float32)
                new_slow = slow + self.alpha * (fast - slow)
                self._slow[key] = new_slow.astype(p.dtype)
                p._value = self._slow[key]

    def clear_grad(self, *a, **k):
        self.inner_optimizer.clear_grad()

    clear_gradients = clear_grad

    def state_dict(self):
        return self.inner_optimizer.state_dict()

    def set_state_dict(self, sd):
        return self.inner_optimizer.set_state_dict(sd)

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()


class ModelAverage(Optimizer):
    """Ref incubate/optimizer/modelaverage.py — maintain running average of
    params; apply()/restore() swap them in/out for eval."""

    def __init__(self, average_window_rate=0.15, parameters=None, min_average_window=
                 10000, max_average_window=10000, name=None):
        super().__init__(0.0, parameters)
        self._sums = {}
        self._counts = {}
        self._backup = {}

    def step(self):
        for p in self._get_params():
            key = id(p)
            self._sums[key] = self._sums.get(key, jnp.zeros_like(
                p.value, jnp.float32)) + p.value.astype(jnp.float32)
            self._counts[key] = self._counts.get(key, 0) + 1

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._apply_now()
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return ctx()

    def _apply_now(self):
        for p in self._get_params():
            key = id(p)
            if key in self._sums and self._counts[key] > 0:
                self._backup[key] = p.value
                p._value = (self._sums[key] / self._counts[key]).astype(p.dtype)

    def restore(self, executor=None):
        for p in self._get_params():
            key = id(p)
            if key in self._backup:
                p._value = self._backup.pop(key)


class LBFGS(Optimizer):
    """Ref incubate/optimizer/lbfgs.py — full-batch L-BFGS with closure."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self.max_iter = max_iter
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        self._s: List[np.ndarray] = []
        self._y: List[np.ndarray] = []
        self._prev_flat_grad = None
        self._prev_flat_param = None

    def _flatten(self, vals):
        return np.concatenate([np.asarray(v, np.float64).reshape(-1) for v in vals])

    def _unflatten_to_params(self, flat):
        ofs = 0
        for p in self._get_params():
            n = int(np.prod(p.shape)) if p.shape else 1
            p._value = jnp.asarray(flat[ofs:ofs + n].reshape(p.shape), p.dtype)
            ofs += n

    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure returning the loss")
        loss = closure()
        params = self._get_params()
        g = self._flatten([p.grad.value for p in params])
        x = self._flatten([p.value for p in params])

        if self._prev_flat_grad is not None:
            s = x - self._prev_flat_param
            y = g - self._prev_flat_grad
            if float(y @ s) > 1e-10:
                self._s.append(s)
                self._y.append(y)
                if len(self._s) > self.history_size:
                    self._s.pop(0)
                    self._y.pop(0)

        # two-loop recursion
        q = g.copy()
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / (y @ s)
            a = rho * (s @ q)
            alphas.append((a, rho, s, y))
            q -= a * y
        if self._s:
            gamma = (self._s[-1] @ self._y[-1]) / (self._y[-1] @ self._y[-1])
            q *= gamma
        for a, rho, s, y in reversed(alphas):
            b = rho * (y @ q)
            q += (a - b) * s
        direction = -q

        lr = self.get_lr()
        self._prev_flat_grad = g
        self._prev_flat_param = x
        self._unflatten_to_params(x + lr * direction)
        for p in params:
            p.clear_grad()
        return loss


class DistributedFusedLamb(Lamb):
    """ref python/paddle/incubate/optimizer/distributed_fused_lamb.py — LAMB
    with optimizer state distributed across ranks. TPU-native: state sharding
    is a LAYOUT property (ParallelEngine(fsdp=True) places moments with the
    param shards via GSPMD), so the optimizer math is exactly Lamb and the
    reference's fused multi-tensor CUDA kernel is XLA fusion. Layout-only
    knobs (clip_after_allreduce, nproc_per_node, master-param flags) are
    accepted no-ops; gradient accumulation changes training math and is the
    engine's job (gradient-merge pass), so != 1 raises."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, clip_after_allreduce=True,
                 is_grad_scaled_by_nranks=True, use_master_param_norm=True,
                 gradient_accumulation_steps=1, use_master_acc_grad=True,
                 nproc_per_node=None, name=None):
        if gradient_accumulation_steps != 1:
            raise NotImplementedError(
                "gradient_accumulation_steps != 1: use the engine's "
                "gradient-merge pass (distributed/passes) instead — a "
                "silently ignored value would change the update schedule")
        super().__init__(learning_rate=learning_rate,
                         lamb_weight_decay=lamb_weight_decay, beta1=beta1,
                         beta2=beta2, epsilon=epsilon, parameters=parameters,
                         grad_clip=grad_clip,
                         exclude_from_weight_decay_fn=exclude_from_weight_decay_fn)
