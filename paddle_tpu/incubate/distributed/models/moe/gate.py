"""MoE gates (ref: python/paddle/incubate/distributed/models/moe/gate/
{naive,gshard,switch}_gate.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....framework.dispatch import apply_op
from .....nn.initializer import XavierUniform
from .....nn.layer_base import Layer


class BaseGate(Layer):
    def __init__(self, d_model, num_experts, top_k=2):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.top_k = top_k
        self.loss = None  # aux load-balance loss, read by the trainer

    def gate_logits(self, x_val, w_val):
        return jnp.matmul(x_val.astype(jnp.float32), w_val.astype(jnp.float32))


class NaiveGate(BaseGate):
    """Top-k softmax gate, no aux loss (ref naive_gate.py)."""

    def __init__(self, d_model, num_expert=None, world_size=1, topk=2, num_experts=None):
        n = num_experts if num_experts is not None else (num_expert or 1) * world_size
        super().__init__(d_model, n, topk)
        self.weight = self.create_parameter([d_model, n],
                                            default_initializer=XavierUniform())

    def routing(self, x_val, w_val):
        """Pure: returns (combine_weights, dispatch_mask_idx, aux_loss)."""
        logits = self.gate_logits(x_val, w_val)
        probs = jax.nn.softmax(logits, axis=-1)
        topv, topi = jax.lax.top_k(probs, self.top_k)
        topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
        return topv, topi, jnp.zeros((), jnp.float32)


class GShardGate(NaiveGate):
    """Top-2 gate with GShard load-balance aux loss (ref gshard_gate.py)."""

    def __init__(self, d_model, num_expert=None, world_size=1, topk=2, capacity=(1.2, 2.4),
                 group=None, num_experts=None):
        super().__init__(d_model, num_expert, world_size, topk, num_experts)
        self.capacity = capacity

    def routing(self, x_val, w_val):
        logits = self.gate_logits(x_val, w_val)
        probs = jax.nn.softmax(logits, axis=-1)
        topv, topi = jax.lax.top_k(probs, self.top_k)
        topv = topv / jnp.clip(jnp.sum(topv, axis=-1, keepdims=True), 1e-9)
        # aux: mean_prob_e * frac_tokens_e summed over experts
        me = jnp.mean(probs, axis=0)
        ce = jnp.mean(jax.nn.one_hot(topi[:, 0], self.num_experts), axis=0)
        aux = jnp.sum(me * ce) * self.num_experts
        return topv, topi, aux


class SwitchGate(NaiveGate):
    """Top-1 switch-transformer gate (ref switch_gate.py)."""

    def __init__(self, d_model, num_expert=None, world_size=1, topk=1, switch_eps=0.1,
                 capacity=(1.2, 2.4), group=None, num_experts=None):
        super().__init__(d_model, num_expert, world_size, 1, num_experts)
        self.switch_eps = switch_eps

    def routing(self, x_val, w_val):
        logits = self.gate_logits(x_val, w_val)
        if self.training:
            from .....framework.random import next_key

            noise = jax.random.uniform(next_key(), logits.shape, jnp.float32,
                                       1.0 - self.switch_eps, 1.0 + self.switch_eps)
            logits = logits * noise
        probs = jax.nn.softmax(logits, axis=-1)
        topv, topi = jax.lax.top_k(probs, 1)
        me = jnp.mean(probs, axis=0)
        ce = jnp.mean(jax.nn.one_hot(topi[:, 0], self.num_experts), axis=0)
        aux = jnp.sum(me * ce) * self.num_experts
        return topv, topi, aux
