"""MoE layer with expert parallelism.

Ref: python/paddle/incubate/distributed/models/moe/moe_layer.py (MoELayer:260
— alltoall dispatch via global_scatter/global_gather ops :116-187, backed by
paddle/fluid/operators/collective/global_scatter_op + moe_kernel.h).

TPU-native redesign: experts are ONE stacked parameter (E, d, d_ff) sharded
over the 'expert' mesh axis; dispatch/combine are capacity-bucketed einsums
(dense one-hot dispatch — the GShard/TPU formulation). Under pjit, GSPMD
turns the (tokens → expert-buckets) contraction into the same all_to_all the
reference issues manually; eagerly it's plain math. No scatter/gather custom
ops needed — the MXU eats the dispatch einsum.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .....framework.core import Tensor
from .....framework.dispatch import apply_op
from .....nn.initializer import XavierUniform
from .....nn.layer_base import Layer
from .....parallel.api import shard_constraint
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate


class ExpertMLP(Layer):
    """Stacked expert FFN weights: (E, d_model, d_hidden) + (E, d_hidden,
    d_model), expert dim sharded over the 'expert' axis.

    ``gated=True`` makes each expert a bias-free SwiGLU (gate/up/down —
    the Llama/Mixtral expert shape) instead of the two-matmul GELU MLP."""

    def __init__(self, num_experts, d_model, d_hidden, activation="gelu",
                 gated: bool = False):
        super().__init__()
        self.num_experts = num_experts
        self.gated = gated
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden],
                                        default_initializer=XavierUniform())
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model],
                                        default_initializer=XavierUniform())
        if gated:
            self.w3 = self.create_parameter(
                [num_experts, d_model, d_hidden],
                default_initializer=XavierUniform())
            self.w3.pspec = P("expert")
        else:
            self.b1 = self.create_parameter([num_experts, d_hidden],
                                            is_bias=True)
            self.b2 = self.create_parameter([num_experts, d_model],
                                            is_bias=True)
            self.b1.pspec = P("expert")
            self.b2.pspec = P("expert")
        self.w1.pspec = P("expert")
        self.w2.pspec = P("expert")
        self.activation = activation

    def expert_params(self):
        if self.gated:
            return (self.w1, self.w2, self.w3)
        return (self.w1, self.w2, self.b1, self.b2)

    def run_experts(self, buckets, w1, w2, *rest):
        """buckets: (E, C, d) — per-expert token buffers."""
        if self.gated:
            (w3,) = rest
            h = jax.nn.silu(jnp.einsum("ecd,edh->ech", buckets, w1)) * \
                jnp.einsum("ecd,edh->ech", buckets, w3)
            return jnp.einsum("ech,ehd->ecd", h, w2)
        b1, b2 = rest
        act = jax.nn.gelu if self.activation == "gelu" else jax.nn.relu
        h = jnp.einsum("ecd,edh->ech", buckets, w1) + b1[:, None, :]
        h = act(h)
        return jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]


def _dispatch_mode() -> str:
    """dense (one-hot einsums) | sparse (scatter index + gathers).

    The dense GShard dispatch is O(T·E·C·d) MXU work — measured 86% of the
    whole MoE forward at E=8/C=5120 on v5e (tools/bench_moe.py r5). The
    sparse path builds an (E, C) slot→token index with ONE int scatter and
    moves activations with two gathers, O(T·K·d) traffic — the same
    token→bucket contraction the reference does with assign_pos +
    global_scatter custom ops (assign_pos_op.cu), done with XLA
    scatter/gather instead."""
    import os

    return os.environ.get("PT_MOE_DISPATCH", "sparse")


def _sparse_dispatch(flat, topi, pos, keep, E, C):
    """Returns (buckets (E,C,d), take_back(out_buckets, topv) -> (T,d)).

    Slot grid has C+1 columns per expert; column C is the shared overflow
    trash (scatter collisions there are masked out). Gradients flow through
    the activation gathers; the index scatter is integer-valued."""
    T, d = flat.shape
    K = topi.shape[1]
    e_flat = topi.reshape(-1)
    p_flat = jnp.where(keep, pos, C).reshape(-1)
    slot = e_flat * (C + 1) + p_flat
    n_slots = E * (C + 1)
    tok_of_slot = jnp.zeros((n_slots,), jnp.int32).at[slot].set(
        jnp.arange(T * K, dtype=jnp.int32) // K)
    filled = jnp.zeros((n_slots,), flat.dtype).at[slot].max(
        jnp.ones((T * K,), flat.dtype))
    grid = tok_of_slot.reshape(E, C + 1)[:, :C]
    fill = filled.reshape(E, C + 1)[:, :C]
    buckets = flat[grid] * fill[..., None]

    def take_back(out_buckets, topv):
        y = out_buckets[e_flat, jnp.minimum(p_flat, C - 1)]  # (T*K, d)
        w = (topv.reshape(-1) * keep.reshape(-1).astype(topv.dtype))
        return (y * w[:, None]).reshape(T, K, d).sum(axis=1)

    return buckets, take_back


class MoELayer(Layer):
    """Ref moe_layer.py:260 — same constructor spirit; `experts` may be an
    ExpertMLP (fast stacked path) or a list of Layers (generic path)."""

    def __init__(self, d_model, experts=None, gate=None, moe_group=None, mp_group=None,
                 recompute_interval=0, capacity_factor: float = 1.25, top_k: int = 2,
                 num_experts: Optional[int] = None, d_hidden: Optional[int] = None,
                 gated_experts: bool = False, **kwargs):
        super().__init__()
        self.d_model = d_model
        if isinstance(gate, dict):
            gate_type = gate.get("type", "gshard")
            top_k = gate.get("top_k", top_k)
            gate = None
        else:
            gate_type = "gshard"
        if experts is None:
            assert num_experts and d_hidden, "need num_experts + d_hidden or experts"
            experts = ExpertMLP(num_experts, d_model, d_hidden,
                                gated=gated_experts)
        if isinstance(experts, (list, tuple)):
            from .....nn.layer.container import LayerList

            self.experts = LayerList(list(experts))
            self.num_experts = len(experts)
            self._stacked = False
        else:
            self.experts = experts
            self.num_experts = experts.num_experts
            self._stacked = True
        if gate is None:
            cls = {"naive": NaiveGate, "gshard": GShardGate, "switch": SwitchGate}[
                gate_type]
            gate = cls(d_model, num_experts=self.num_experts, topk=top_k)
        self.gate = gate
        self.top_k = self.gate.top_k
        self.capacity_factor = capacity_factor

    def forward(self, x):
        """x: (..., d_model). Returns same shape; sets self.gate.loss."""
        orig_shape = x.shape
        E = self.num_experts
        K = self.top_k
        cf = self.capacity_factor

        if not self._stacked:
            return self._forward_listed(x, orig_shape)

        gate_w = self.gate.weight
        gate_obj = self.gate

        def f(xv, gw, *ws):
            flat = xv.reshape(-1, xv.shape[-1])  # (T, d)
            T = flat.shape[0]
            C = max(int(cf * T * K / E), 1)
            topv, topi, aux = gate_obj.routing(flat, gw)  # (T,K)
            # position of each (token, k) within its expert bucket
            onehot = jax.nn.one_hot(topi, E, dtype=jnp.int32)  # (T,K,E)
            flat_oh = onehot.reshape(T * K, E)
            pos_in_e = jnp.cumsum(flat_oh, axis=0) - flat_oh  # (T*K, E)
            pos = jnp.sum(pos_in_e * flat_oh, axis=-1).reshape(T, K)
            keep = pos < C
            if _dispatch_mode() == "sparse":
                buckets, take_back = _sparse_dispatch(flat, topi, pos, keep,
                                                      E, C)
                out_buckets = self.experts.run_experts(buckets, *ws)
                out = take_back(out_buckets, topv.astype(xv.dtype))
                return out.reshape(xv.shape), aux
            # combine/dispatch one-hots (GShard formulation): overflow → 0 row
            oh_e = jax.nn.one_hot(topi, E, dtype=xv.dtype)          # (T,K,E)
            oh_c = jax.nn.one_hot(jnp.where(keep, pos, C), C,
                                  dtype=xv.dtype)                    # (T,K,C)
            dispatch = jnp.einsum("tke,tkc->tec", oh_e, oh_c)        # (T,E,C)
            buckets = jnp.einsum("tec,td->ecd", dispatch, flat)
            out_buckets = self.experts.run_experts(buckets, *ws)
            combine = jnp.einsum("tke,tkc,tk->tec", oh_e, oh_c,
                                 topv.astype(xv.dtype))
            out = jnp.einsum("tec,ecd->td", combine, out_buckets)
            return out.reshape(xv.shape), aux

        out, aux = apply_op(f, x, gate_w, *self.experts.expert_params(),
                            op_name="moe")
        self.gate.loss = aux
        return out

    def _forward_listed(self, x, orig_shape):
        """Generic per-expert loop (eager; arbitrary expert Layers)."""
        import numpy as np

        from .....tensor.manipulation import reshape

        flat = reshape(x, [-1, self.d_model])
        gate_w = self.gate.weight
        topv_t, topi_t = None, None

        def route(xv, gw):
            return self.gate.routing(xv, gw)

        topv, topi, aux = apply_op(route, flat, gate_w)
        self.gate.loss = aux
        idx = np.asarray(topi.value)
        weights = topv
        out = None
        from .....tensor.creation import zeros_like

        out = zeros_like(flat)
        for e in range(self.num_experts):
            mask_np = (idx == e)
            if not mask_np.any():
                continue
            tok_ids, k_ids = np.nonzero(mask_np)
            sel = flat[Tensor(jnp.asarray(tok_ids, jnp.int32))]
            y = self.experts[e](sel)
            w = weights[Tensor(jnp.asarray(tok_ids, jnp.int32)),
                        Tensor(jnp.asarray(k_ids, jnp.int32))]
            from .....tensor.manipulation import scatter_nd_add

            contrib = y * w.unsqueeze(-1)
            out = scatter_nd_add(out, Tensor(jnp.asarray(tok_ids[:, None], jnp.int32)),
                                 contrib)
        return reshape(out, list(orig_shape))
