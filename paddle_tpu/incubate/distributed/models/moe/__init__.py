from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate
from .moe_layer import ExpertMLP, MoELayer

__all__ = ["MoELayer", "ExpertMLP", "BaseGate", "NaiveGate", "GShardGate", "SwitchGate"]
