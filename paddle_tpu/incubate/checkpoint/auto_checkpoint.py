"""Auto-checkpoint: exactly-once epoch-range resume (ref
python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py —
AutoCheckpointChecker :72 env-driven config, train_epoch_range generator with
epoch bookkeeping, ExeTrainStatus :210 serialized status).

TPU-native: the reference snapshots executor state to HDFS inside the epoch
loop.  Here the loop generator persists an epoch-progress record plus (opt-in)
a state_dict snapshot to a local/NFS dir (checkpoint storage on TPU jobs is
typically GCS-fuse or NFS mounts — same file API), and on restart skips the
epochs already completed: the recovery story for elastic restarts
(SURVEY §5.3/§5.4).
"""
from __future__ import annotations

import json
import os
import time
from typing import Iterator, Optional

__all__ = []

_EPOCH_STATUS_FILE = "acp_epoch_status.json"


class AutoCheckpointChecker:
    """Env-driven enable/config (ref auto_checkpoint.py:72; env vars renamed
    from HDFS to a generic checkpoint dir)."""

    def __init__(self):
        self.run_env = os.environ.get("PADDLE_RUNNING_ENV", "")
        self.platform = os.environ.get("PADDLE_RUNNING_PLATFORM", "")
        self.job_id = os.environ.get("PADDLE_JOB_ID", "default_job")
        self.ckpt_home = os.environ.get(
            "PADDLE_CHECKPOINT_DIR",
            os.environ.get("PADDLE_EDL_HDFS_CHECKPOINT_PATH", ""))
        self.trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self.save_checkpoint_inter = int(
            os.environ.get("PADDLE_EDL_SAVE_CHECKPOINT_INTER", 900))

    def valid(self) -> bool:
        return bool(self.ckpt_home)

    def get_job_path(self) -> str:
        return os.path.join(self.ckpt_home, self.job_id)

    def get_range_checkpoint_path(self, name: str) -> str:
        return os.path.join(self.get_job_path(), "range", name)

    def __str__(self):
        return (f"AutoCheckpointChecker(job={self.job_id!r}, "
                f"home={self.ckpt_home!r}, trainer={self.trainer_id})")


g_checker: Optional[AutoCheckpointChecker] = None


def _get_checker() -> AutoCheckpointChecker:
    global g_checker
    if g_checker is None:
        g_checker = AutoCheckpointChecker()
    return g_checker


class TrainEpochRange:
    """Epoch bookkeeping for one named range (ref ExeTrainStatus/TrainEpochRange)."""

    def __init__(self, max_epoch_num: int, name: str,
                 checkpoint_inter: Optional[int] = None, save_fn=None,
                 restore_fn=None):
        self.name = name
        self.max_epoch_num = max_epoch_num
        self.checker = _get_checker()
        self.restored_from = None
        self.last_checkpoint_time = time.time()
        self.checkpoint_inter = (checkpoint_inter
                                 if checkpoint_inter is not None
                                 else self.checker.save_checkpoint_inter)
        self._save_fn = save_fn
        self._restore_fn = restore_fn
        self._completed = -1
        if self.checker.valid():
            self._path = self.checker.get_range_checkpoint_path(name)
            os.makedirs(self._path, exist_ok=True)
            status = os.path.join(self._path, _EPOCH_STATUS_FILE)
            if os.path.exists(status):
                with open(status) as f:
                    rec = json.load(f)
                self._completed = int(rec.get("epoch_no", -1))
                self.restored_from = status
                if self._restore_fn is not None and rec.get("has_state"):
                    self._restore_fn(os.path.join(self._path, "state"))
        else:
            self._path = None

    def _persist(self, epoch_no: int, force: bool = False):
        if self._path is None:
            return
        has_state = False
        now = time.time()
        if self._save_fn is not None and (
                force or now - self.last_checkpoint_time >= self.checkpoint_inter):
            self._save_fn(os.path.join(self._path, "state"))
            self.last_checkpoint_time = now
            has_state = True
        tmp = os.path.join(self._path, _EPOCH_STATUS_FILE + ".tmp")
        with open(tmp, "w") as f:
            json.dump({"name": self.name, "epoch_no": epoch_no,
                       "has_state": has_state or self._save_fn is not None,
                       "timestamp": now}, f)
        os.replace(tmp, os.path.join(self._path, _EPOCH_STATUS_FILE))

    def next(self) -> Iterator[int]:
        start = self._completed + 1
        for epoch in range(start, self.max_epoch_num):
            yield epoch
            self._completed = epoch
            self._persist(epoch, force=(epoch == self.max_epoch_num - 1))


g_train_epoch_range: Optional[TrainEpochRange] = None


def train_epoch_range(max_epoch_num: int, name: Optional[str] = None,
                      save_checkpoint_inter: Optional[int] = None,
                      save_fn=None, restore_fn=None) -> Iterator[int]:
    """Resumable epoch loop (ref auto_checkpoint.py train_epoch_range):

        for epoch in train_epoch_range(10, name="job0",
                                       save_fn=..., restore_fn=...):
            train_one_epoch()

    On restart with the same PADDLE_CHECKPOINT_DIR/PADDLE_JOB_ID, completed
    epochs are skipped exactly-once; save_fn(path)/restore_fn(path) snapshot
    and restore model+optimizer state (e.g. via paddle.save/state_dict).
    """
    global g_train_epoch_range
    g_train_epoch_range = TrainEpochRange(
        max_epoch_num, name or "default_range",
        checkpoint_inter=save_checkpoint_inter,
        save_fn=save_fn, restore_fn=restore_fn)
    try:
        yield from g_train_epoch_range.next()
    finally:
        g_train_epoch_range = None
