"""paddle.incubate.checkpoint (ref python/paddle/incubate/checkpoint/
re-exporting fluid/incubate/checkpoint/auto_checkpoint.py)."""
from . import auto_checkpoint  # noqa: F401
from .auto_checkpoint import train_epoch_range  # noqa: F401

__all__ = []
