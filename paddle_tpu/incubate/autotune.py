"""paddle.incubate.autotune (ref python/paddle/incubate/autotune.py:24
set_config — kernel/layout/dataloader autotuning switches).

TPU-native meaning of each knob:
  kernel  — XLA autotuning is always on at compile time; the toggle maps to
            jax's compilation-effort / Pallas dimension-semantics flags.
  layout  — the reference flips NCHW↔NHWC per-op (imperative/layout_autotune);
            our conv path already canonicalizes to NHWC for the MXU, so this
            records the preference used by nn.Conv2D's lowering.
  dataloader — tunes io.DataLoader prefetch depth / worker count.
State is queryable via get_config(); DataLoader and conv read it lazily.
"""
from __future__ import annotations

import json
from typing import Any, Dict, Optional

__all__ = ["set_config"]

_CONFIG: Dict[str, Dict[str, Any]] = {
    "kernel": {"enable": True, "tuning_range": [1, 10]},
    "layout": {"enable": True},
    "dataloader": {"enable": False, "tuning_steps": 500},
}


def set_config(config: Optional[object] = None) -> None:
    """Accepts a dict or a path to a json file (ref autotune.py:24)."""
    if config is None:
        for v in _CONFIG.values():
            v["enable"] = True
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise TypeError(f"config must be None|dict|json path, got {type(config)}")
    for key, val in config.items():
        if key not in _CONFIG:
            raise ValueError(f"unknown autotune domain {key!r}; valid: "
                             f"{sorted(_CONFIG)}")
        _CONFIG[key].update(val)


def get_config() -> Dict[str, Dict[str, Any]]:
    return {k: dict(v) for k, v in _CONFIG.items()}
