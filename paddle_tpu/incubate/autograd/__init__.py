"""Functional autograd (ref: python/paddle/incubate/autograd/functional.py —
jacobian/hessian/jvp/vjp; primapi.py forward_grad).

These are direct jax transforms over a pure function of Tensors.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, no_grad_ctx, to_array


def _pure(func):
    def fn(*vals):
        with no_grad_ctx():
            out = func(*[Tensor(v) for v in vals])
        if isinstance(out, (tuple, list)):
            return tuple(o.value if isinstance(o, Tensor) else o for o in out)
        return out.value if isinstance(out, Tensor) else out

    return fn


def _vals(xs):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    return [to_array(x) for x in xs]


def vjp(func, xs, v=None):
    vals = _vals(xs)
    out, vjp_fn = jax.vjp(_pure(func), *vals)
    if v is None:
        v_val = jnp.ones_like(out) if not isinstance(out, tuple) else tuple(
            jnp.ones_like(o) for o in out)
    else:
        v_val = to_array(v) if isinstance(v, Tensor) else jax.tree_util.tree_map(to_array, v)
    grads = vjp_fn(v_val)
    outs = Tensor(out) if not isinstance(out, tuple) else tuple(Tensor(o) for o in out)
    gs = [Tensor(g) for g in grads]
    return outs, gs if len(gs) > 1 else gs[0]


def jvp(func, xs, v=None):
    vals = _vals(xs)
    if v is None:
        v_vals = tuple(jnp.ones_like(x) for x in vals)
    else:
        v_list = v if isinstance(v, (list, tuple)) else [v]
        v_vals = tuple(to_array(t) for t in v_list)
    out, tangent = jax.jvp(_pure(func), tuple(vals), v_vals)
    outs = Tensor(out) if not isinstance(out, tuple) else tuple(Tensor(o) for o in out)
    tans = Tensor(tangent) if not isinstance(tangent, tuple) else tuple(
        Tensor(t) for t in tangent)
    return outs, tans


forward_grad = jvp


class Jacobian:
    """Ref autograd/functional.py Jacobian — lazy row/col evaluation skipped;
    computes the full jacobian via jax.jacrev."""

    def __init__(self, func, xs, is_batched=False):
        vals = _vals(xs)
        jac = jax.jacrev(_pure(func), argnums=tuple(range(len(vals))))(*vals)
        self._jac = jac if len(vals) > 1 else (jac,)
        self._single = len(vals) == 1

    def __getitem__(self, idx):
        j = self._jac[0] if self._single else self._jac
        return Tensor(j[idx] if not self._single else self._jac[0][idx])

    @property
    def shape(self):
        return list(self._jac[0].shape)

    def numpy(self):
        import numpy as np

        return np.asarray(self._jac[0])


class Hessian:
    def __init__(self, func, xs, is_batched=False):
        vals = _vals(xs)
        h = jax.hessian(_pure(func))(*vals)
        self._h = h

    def __getitem__(self, idx):
        return Tensor(self._h[idx])

    @property
    def shape(self):
        return list(self._h.shape)


def jacobian(func, xs, create_graph=False, allow_unused=False):
    vals = _vals(xs)
    jac = jax.jacrev(_pure(func), argnums=tuple(range(len(vals))))(*vals)
    if len(vals) == 1:
        return Tensor(jac[0] if isinstance(jac, tuple) else jac)
    return tuple(Tensor(j) for j in jac)


def hessian(func, xs, create_graph=False, allow_unused=False):
    vals = _vals(xs)
    h = jax.hessian(_pure(func))(*vals)
    return Tensor(h)


# --------------------------------------------------------------------------- #
# prim system (ref python/paddle/incubate/autograd/primapi.py)
# --------------------------------------------------------------------------- #

_PRIM_ENABLED = False


def enable_prim():
    """ref primapi enable_prim — turns on composite-primitive lowering of the
    static graph. TPU-native: jaxpr IS the primitive IR (every op we record is
    already a composition of jax primitives; XLA decomposes further), so this
    is a semantic no-op kept as a queryable switch."""
    global _PRIM_ENABLED
    _PRIM_ENABLED = True


def disable_prim():
    global _PRIM_ENABLED
    _PRIM_ENABLED = False


def prim_enabled() -> bool:
    return _PRIM_ENABLED


def prim2orig(*args, **kwargs):
    """ref primapi prim2orig — lower primitive ops back to original ops; the
    jaxpr never leaves primitive form, so there is nothing to lower."""
    return None


def forward_grad(outputs, inputs, grad_inputs=None):
    """Forward-mode AD (ref primapi.py:24 forward_grad): JVP of outputs w.r.t.
    inputs, seeded with grad_inputs (defaults to ones)."""
    outs, tangents = jvp(
        outputs if callable(outputs) else (lambda *xs: outputs),
        inputs, v=grad_inputs)
    return tangents


def grad(outputs, inputs, grad_outputs=None):
    """ref primapi grad / autograd.grad for pure functions: VJP of outputs
    w.r.t. inputs seeded with grad_outputs."""
    _, grads = vjp(
        outputs if callable(outputs) else (lambda *xs: outputs),
        inputs, v=grad_outputs)
    return grads if isinstance(grads, (list, tuple)) else [grads]
