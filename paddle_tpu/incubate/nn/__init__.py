"""Fused layers (ref: python/paddle/incubate/nn/layer/fused_transformer.py:
FusedMultiHeadAttention:192, FusedFeedForward:497, FusedMultiTransformer:1021).

On TPU "fused" means: written as one jnp composition that XLA fuses, with the
attention core on the Pallas flash kernel. The classes keep the reference's
constructor signatures so checkpoints/configs port over.
"""
from __future__ import annotations

import jax.numpy as jnp

from . import functional  # noqa: F401

from ...nn import functional as F
from ...nn.initializer import Constant
from ...nn.layer_base import Layer
from ...nn.layer.common import Dropout, Linear
from ...nn.layer.norm import LayerNorm
from ...tensor.manipulation import reshape


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5, attn_dropout_rate=0.5,
                 kdim=None, vdim=None, normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5, nranks=1, ring_id=-1,
                 name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        # fused qkv weight: [3, num_heads, head_dim, embed_dim] in ref; we keep
        # a single [embed_dim, 3*embed_dim] matmul (same math, MXU-friendlier)
        self.qkv_weight = self.create_parameter([embed_dim, 3 * embed_dim],
                                                attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter([3 * embed_dim], attr=qkv_bias_attr,
                                              is_bias=True)
        self.linear_weight = self.create_parameter([embed_dim, embed_dim],
                                                   attr=linear_weight_attr)
        self.linear_bias = self.create_parameter([embed_dim], attr=linear_bias_attr,
                                                 is_bias=True)
        self.pre_ln = LayerNorm(embed_dim, epsilon=epsilon)
        self.post_ln = LayerNorm(embed_dim, epsilon=epsilon)

    def forward(self, x, attn_mask=None, cache=None):
        residual = x
        if self.normalize_before:
            x = self.pre_ln(x)
        qkv = F.linear(x, self.qkv_weight, self.qkv_bias)
        B, S = qkv.shape[0], qkv.shape[1]
        qkv = reshape(qkv, [B, S, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0)
        out = reshape(out, [B, S, self.embed_dim])
        out = F.linear(out, self.linear_weight, self.linear_bias)
        out = F.dropout(out, self.dropout_rate, training=self.training)
        out = residual + out
        if not self.normalize_before:
            out = self.post_ln(out)
        return out


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-05,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None, ln1_bias_attr=None,
                 ln2_scale_attr=None, ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.linear1 = Linear(d_model, dim_feedforward, linear1_weight_attr,
                              linear1_bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, linear2_weight_attr,
                              linear2_bias_attr)
        self.norm = LayerNorm(d_model, epsilon=epsilon)
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = act_dropout_rate if act_dropout_rate is not None \
            else dropout_rate
        self.activation = getattr(F, activation)

    def forward(self, src):
        residual = src
        if self.normalize_before:
            src = self.norm(src)
        out = self.activation(self.linear1(src))
        out = F.dropout(out, self.act_dropout_rate, training=self.training)
        out = self.linear2(out)
        out = F.dropout(out, self.dropout_rate, training=self.training)
        out = residual + out
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate if attn_dropout_rate is not None
            else dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate, activation=activation,
            act_dropout_rate=act_dropout_rate, normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class FusedBiasDropoutResidualLayerNorm(Layer):
    def __init__(self, embed_dim, dropout_rate=0.5, bias_attr=None, epsilon=1e-5,
                 name=None):
        super().__init__()
        self.bias = self.create_parameter([embed_dim], attr=bias_attr, is_bias=True)
        self.norm = LayerNorm(embed_dim, epsilon=epsilon)
        self.dropout_rate = dropout_rate

    def forward(self, x, residual):
        out = F.dropout(x + self.bias, self.dropout_rate, training=self.training)
        return self.norm(residual + out)


class FusedLinear(Linear):
    """fused_matmul_bias analogue — XLA always fuses bias into the matmul."""
