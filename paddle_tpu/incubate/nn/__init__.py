"""Fused layers (ref: python/paddle/incubate/nn/layer/fused_transformer.py:
FusedMultiHeadAttention:192, FusedFeedForward:497, FusedMultiTransformer:1021).

On TPU "fused" means: written as one jnp composition that XLA fuses, with the
attention core on the Pallas flash kernel. The classes keep the reference's
constructor signatures so checkpoints/configs port over.
"""
from __future__ import annotations

import jax.numpy as jnp

from . import functional  # noqa: F401

from ...nn import functional as F
from ...nn.initializer import Constant
from ...nn.layer_base import Layer
from ...nn.layer.common import Dropout, Linear
from ...nn.layer.norm import LayerNorm
from ...tensor.manipulation import reshape


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5, attn_dropout_rate=0.5,
                 kdim=None, vdim=None, normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5, nranks=1, ring_id=-1,
                 name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        # fused qkv weight: [3, num_heads, head_dim, embed_dim] in ref; we keep
        # a single [embed_dim, 3*embed_dim] matmul (same math, MXU-friendlier)
        self.qkv_weight = self.create_parameter([embed_dim, 3 * embed_dim],
                                                attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter([3 * embed_dim], attr=qkv_bias_attr,
                                              is_bias=True)
        self.linear_weight = self.create_parameter([embed_dim, embed_dim],
                                                   attr=linear_weight_attr)
        self.linear_bias = self.create_parameter([embed_dim], attr=linear_bias_attr,
                                                 is_bias=True)
        self.pre_ln = LayerNorm(embed_dim, epsilon=epsilon)
        self.post_ln = LayerNorm(embed_dim, epsilon=epsilon)

    def forward(self, x, attn_mask=None, cache=None):
        residual = x
        if self.normalize_before:
            x = self.pre_ln(x)
        qkv = F.linear(x, self.qkv_weight, self.qkv_bias)
        B, S = qkv.shape[0], qkv.shape[1]
        qkv = reshape(qkv, [B, S, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0)
        out = reshape(out, [B, S, self.embed_dim])
        out = F.linear(out, self.linear_weight, self.linear_bias)
        out = F.dropout(out, self.dropout_rate, training=self.training)
        out = residual + out
        if not self.normalize_before:
            out = self.post_ln(out)
        return out


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-05,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None, ln1_bias_attr=None,
                 ln2_scale_attr=None, ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.linear1 = Linear(d_model, dim_feedforward, linear1_weight_attr,
                              linear1_bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, linear2_weight_attr,
                              linear2_bias_attr)
        self.norm = LayerNorm(d_model, epsilon=epsilon)
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = act_dropout_rate if act_dropout_rate is not None \
            else dropout_rate
        self.activation = getattr(F, activation)

    def forward(self, src):
        residual = src
        if self.normalize_before:
            src = self.norm(src)
        out = self.activation(self.linear1(src))
        out = F.dropout(out, self.act_dropout_rate, training=self.training)
        out = self.linear2(out)
        out = F.dropout(out, self.dropout_rate, training=self.training)
        out = residual + out
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate if attn_dropout_rate is not None
            else dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate, activation=activation,
            act_dropout_rate=act_dropout_rate, normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class FusedBiasDropoutResidualLayerNorm(Layer):
    def __init__(self, embed_dim, dropout_rate=0.5, bias_attr=None, epsilon=1e-5,
                 name=None):
        super().__init__()
        self.bias = self.create_parameter([embed_dim], attr=bias_attr, is_bias=True)
        self.norm = LayerNorm(embed_dim, epsilon=epsilon)
        self.dropout_rate = dropout_rate

    def forward(self, x, residual):
        out = F.dropout(x + self.bias, self.dropout_rate, training=self.training)
        return self.norm(residual + out)


def _fused_multi_transformer_run(x, mask, key_data, *rest, n_layers, heads,
                                 head_dim, eps, activation, time_step,
                                 has_caches, dropout_rate, train):
    """Closure-free N-layer pre-LN decoder stack so dispatch's vjp cache
    engages (dispatch.py _cached_fwd requires fn.__closure__ is None).
    ``key_data`` is dropout PRNG key data passed as an ARRAY so per-step keys
    don't blow the compile cache (a static seed kwarg would)."""
    import jax
    import jax.numpy as jnp

    P = 12
    params, flat_caches = rest[:P * n_layers], rest[P * n_layers:]
    B, S, d = x.shape
    base = 0 if time_step is None else time_step

    def layer_norm(h, scale, bias):
        mu = h.mean(-1, keepdims=True)
        var = ((h - mu) ** 2).mean(-1, keepdims=True)
        return (h - mu) / jnp.sqrt(var + eps) * scale + bias

    drop_key = jax.random.wrap_key_data(key_data) \
        if (train and dropout_rate > 0) else None
    new_caches = []
    for i in range(n_layers):
        (ln_s, ln_b, qkv_w, qkv_b, lin_w, lin_b, fln_s, fln_b,
         ffn1_w, ffn1_b, ffn2_w, ffn2_b) = params[P * i:P * (i + 1)]
        residual = x
        h = layer_norm(x.astype(jnp.float32), ln_s, ln_b).astype(x.dtype)
        qkv = h @ qkv_w + qkv_b
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, heads, head_dim).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, heads, head_dim).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, heads, head_dim).transpose(0, 2, 1, 3)
        if has_caches:
            ck, cv = flat_caches[2 * i], flat_caches[2 * i + 1]
            ck = jax.lax.dynamic_update_slice(ck, k, (0, 0, base, 0))
            cv = jax.lax.dynamic_update_slice(cv, v, (0, 0, base, 0))
            new_caches += [ck, cv]
            if time_step is not None:
                kv_len = base + S
                k_all, v_all = ck[:, :, :kv_len], cv[:, :, :kv_len]
            else:
                k_all, v_all = k, v
        else:
            k_all, v_all = k, v
        scores = (q @ k_all.transpose(0, 1, 3, 2)) / jnp.sqrt(
            jnp.asarray(head_dim, x.dtype))
        if mask is not None:
            scores = scores + mask
        elif S > 1:
            # queries sit at absolute positions base+i; keys at 0..kv_len-1
            kv = scores.shape[-1]
            allowed = (jnp.arange(kv)[None, :] <=
                       base + jnp.arange(S)[:, None])
            scores = jnp.where(allowed, scores, jnp.asarray(
                jnp.finfo(jnp.float32).min, scores.dtype))
        probs = jax.nn.softmax(scores.astype(jnp.float32),
                               axis=-1).astype(x.dtype)
        out = (probs @ v_all).transpose(0, 2, 1, 3).reshape(B, S, d)
        out = out @ lin_w + lin_b
        if drop_key is not None:
            drop_key, sub = jax.random.split(drop_key)
            keep = jax.random.bernoulli(sub, 1 - dropout_rate, out.shape)
            out = jnp.where(keep, out / (1 - dropout_rate), 0).astype(out.dtype)
        x = residual + out
        residual = x
        h = layer_norm(x.astype(jnp.float32), fln_s, fln_b).astype(x.dtype)
        h = h @ ffn1_w + ffn1_b
        h = jax.nn.gelu(h) if activation == "gelu" else jax.nn.relu(h)
        h = h @ ffn2_w + ffn2_b
        if drop_key is not None:
            drop_key, sub = jax.random.split(drop_key)
            keep = jax.random.bernoulli(sub, 1 - dropout_rate, h.shape)
            h = jnp.where(keep, h / (1 - dropout_rate), 0).astype(h.dtype)
        x = residual + h
    return (x, *new_caches) if new_caches else x


class FusedMultiTransformer(Layer):
    """Whole-decoder-stack fused transformer for generation
    (ref python/paddle/incubate/nn/layer/fused_transformer.py:1021
    FusedMultiTransformer / operators/fused/fused_multi_transformer_op.cu).

    The reference fuses N pre-LN decoder layers into one CUDA op with
    in-place KV caches indexed by ``time_step``.  Here the whole stack is one
    closure-free jnp function that dispatch jit-caches; caches are
    functional — forward returns the updated cache list — and decode writes
    at ``time_step`` via ``lax.dynamic_update_slice`` so the stack stays
    jittable.  Parameters are per-layer lists with the reference's names.
    RoPE (``rotary_embs``), ``pre_caches`` and ``seq_lens`` are not
    implemented and raise loudly rather than silently ignoring."""

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, ln_scale_attrs=None,
                 ln_bias_attrs=None, qkv_weight_attrs=None, qkv_bias_attrs=None,
                 linear_weight_attrs=None, linear_bias_attrs=None,
                 ffn_ln_scale_attrs=None, ffn_ln_bias_attrs=None,
                 ffn1_weight_attrs=None, ffn1_bias_attrs=None,
                 ffn2_weight_attrs=None, ffn2_bias_attrs=None, epsilon=1e-5,
                 num_layers=-1, nranks=1, trans_qkvw=True, ring_id=-1, name=None):
        super().__init__()
        if num_layers < 0:
            num_layers = len(qkv_weight_attrs) if isinstance(
                qkv_weight_attrs, (list, tuple)) else 1
        if not normalize_before:
            raise NotImplementedError(
                "FusedMultiTransformer is pre-LN only, matching the reference")
        if not trans_qkvw:
            raise NotImplementedError(
                "only the default trans_qkvw=True weight layout is supported; "
                "weights here are a single [d, 3d] matmul")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.num_layers = num_layers
        self.dropout_rate = dropout_rate
        self.activation = activation
        self.epsilon = epsilon

        def attr(attrs, i):
            return attrs[i] if isinstance(attrs, (list, tuple)) else attrs

        ones, d = Constant(1.0), embed_dim
        self.ln_scales, self.ln_biases = [], []
        self.qkv_weights, self.qkv_biases = [], []
        self.linear_weights, self.linear_biases = [], []
        self.ffn_ln_scales, self.ffn_ln_biases = [], []
        self.ffn1_weights, self.ffn1_biases = [], []
        self.ffn2_weights, self.ffn2_biases = [], []
        for i in range(num_layers):
            self.ln_scales.append(self.create_parameter(
                [d], attr=attr(ln_scale_attrs, i), default_initializer=ones))
            self.ln_biases.append(self.create_parameter(
                [d], attr=attr(ln_bias_attrs, i), is_bias=True))
            self.qkv_weights.append(self.create_parameter(
                [d, 3 * d], attr=attr(qkv_weight_attrs, i)))
            self.qkv_biases.append(self.create_parameter(
                [3 * d], attr=attr(qkv_bias_attrs, i), is_bias=True))
            self.linear_weights.append(self.create_parameter(
                [d, d], attr=attr(linear_weight_attrs, i)))
            self.linear_biases.append(self.create_parameter(
                [d], attr=attr(linear_bias_attrs, i), is_bias=True))
            self.ffn_ln_scales.append(self.create_parameter(
                [d], attr=attr(ffn_ln_scale_attrs, i), default_initializer=ones))
            self.ffn_ln_biases.append(self.create_parameter(
                [d], attr=attr(ffn_ln_bias_attrs, i), is_bias=True))
            self.ffn1_weights.append(self.create_parameter(
                [d, dim_feedforward], attr=attr(ffn1_weight_attrs, i)))
            self.ffn1_biases.append(self.create_parameter(
                [dim_feedforward], attr=attr(ffn1_bias_attrs, i), is_bias=True))
            self.ffn2_weights.append(self.create_parameter(
                [dim_feedforward, d], attr=attr(ffn2_weight_attrs, i)))
            self.ffn2_biases.append(self.create_parameter(
                [d], attr=attr(ffn2_bias_attrs, i), is_bias=True))
        for group in ("ln_scales", "ln_biases", "qkv_weights", "qkv_biases",
                      "linear_weights", "linear_biases", "ffn_ln_scales",
                      "ffn_ln_biases", "ffn1_weights", "ffn1_biases",
                      "ffn2_weights", "ffn2_biases"):
            for i, p in enumerate(getattr(self, group)):
                self.add_parameter(f"{group}_{i}", p)

    def forward(self, src, attn_mask=None, caches=None, pre_caches=None,
                time_step=None, seq_lens=None, rotary_embs=None,
                rotary_emb_dims=0, trans_to_fp16=False):
        import jax

        from ...framework.dispatch import apply_op
        from ...framework.random import default_generator

        if rotary_embs is not None or rotary_emb_dims:
            raise NotImplementedError(
                "rotary embeddings are not implemented in "
                "FusedMultiTransformer; apply RoPE upstream or use "
                "paddle_tpu.models.llama for a RoPE decoder")
        if pre_caches is not None or seq_lens is not None:
            raise NotImplementedError(
                "pre_caches / seq_lens are not implemented in "
                "FusedMultiTransformer")
        n_layers = self.num_layers
        S = src.shape[1]
        ts = None if time_step is None else int(time_step)
        if caches is not None:
            cache_len = caches[0][0].shape[2]
            if (ts or 0) + S > cache_len:
                raise ValueError(
                    f"cache overflow: writing {S} token(s) at time_step="
                    f"{ts or 0} exceeds cache length {cache_len}")
        train = self.training and self.dropout_rate > 0
        flat = []
        for i in range(n_layers):
            flat += [self.ln_scales[i], self.ln_biases[i],
                     self.qkv_weights[i], self.qkv_biases[i],
                     self.linear_weights[i], self.linear_biases[i],
                     self.ffn_ln_scales[i], self.ffn_ln_biases[i],
                     self.ffn1_weights[i], self.ffn1_biases[i],
                     self.ffn2_weights[i], self.ffn2_biases[i]]
        if caches is not None:
            for ck, cv in caches:
                flat += [ck, cv]
        key_data = jax.random.key_data(default_generator().next_key()) \
            if train else jax.numpy.zeros((2,), "uint32")
        res = apply_op(_fused_multi_transformer_run, src, attn_mask, key_data,
                       *flat,
                       op_name="fused_multi_transformer", n_layers=n_layers,
                       heads=self.num_heads, head_dim=self.head_dim,
                       eps=self.epsilon, activation=self.activation,
                       time_step=ts, has_caches=caches is not None,
                       dropout_rate=self.dropout_rate, train=train)
        if caches is not None:
            out, rest = res[0], res[1:]
            return out, [(rest[2 * i], rest[2 * i + 1])
                         for i in range(n_layers)]
        return res


class FusedLinear(Linear):
    """fused_matmul_bias analogue — XLA always fuses bias into the matmul."""
