"""incubate.nn.functional fused ops (ref: python/paddle/incubate/nn/functional/
— fused_multi_head_attention, fused_feedforward, fused_matmul_bias,
fused_linear, fused_multi_transformer).

On TPU "fused" = one jnp composition XLA fuses + the Pallas attention core.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.dispatch import apply_op
from ...nn import functional as F


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False, name=None):
    def f(a, b, *bb):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = jnp.matmul(a, b)
        if bb:
            out = out + bb[0]
        return out

    args = [x, y] + ([bias] if bias is not None else [])
    return apply_op(f, *args, op_name="matmul")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_linear_activation(x, weight, bias=None, trans_x=False, trans_y=False,
                            activation="gelu"):
    out = fused_matmul_bias(x, weight, bias, trans_x, trans_y)
    return getattr(F, activation)(out)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None, ln2_scale=None,
                      ln2_bias=None, dropout1_rate=0.5, dropout2_rate=0.5,
                      activation="relu", ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode="upscale_in_train",
                      ring_id=-1, name=None):
    residual = x
    if pre_layer_norm and ln1_scale is not None:
        x = F.layer_norm(x, [x.shape[-1]], ln1_scale, ln1_bias, ln1_epsilon)
    out = fused_matmul_bias(x, linear1_weight, linear1_bias)
    out = getattr(F, activation)(out)
    out = F.dropout(out, dropout1_rate, training=training, mode=mode)
    out = fused_matmul_bias(out, linear2_weight, linear2_bias)
    out = F.dropout(out, dropout2_rate, training=training, mode=mode)
    out = residual + out
    if not pre_layer_norm and ln2_scale is not None:
        out = F.layer_norm(out, [out.shape[-1]], ln2_scale, ln2_bias, ln2_epsilon)
    return out


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5,
                               ln_epsilon=1e-5, training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=None,
                               name=None):
    """Ref fused_attention_op.cu capability as one composition."""
    from ...tensor.manipulation import reshape

    residual = x
    if pre_layer_norm and pre_ln_scale is not None:
        x = F.layer_norm(x, [x.shape[-1]], pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    B, S, H = x.shape[0], x.shape[1], x.shape[2]
    # qkv_weight: ref layout (3, num_heads, head_dim, embed) or (embed, 3*embed)
    if len(qkv_weight.shape) == 4:
        nh = qkv_weight.shape[1]
        hd = qkv_weight.shape[2]

        def qkv_f(v, w, *b):
            out = jnp.einsum("bse,khde->bskhd", v, w)
            if b:
                out = out + b[0].reshape(3, nh, hd)
            return out

        args = [x, qkv_weight] + ([qkv_bias] if qkv_bias is not None else [])
        qkv = apply_op(qkv_f, *args)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    else:
        if num_heads is None:
            raise ValueError(
                "fused_multi_head_attention: num_heads is required when "
                "qkv_weight is 2-D (the head split cannot be inferred)")
        nh = num_heads
        if H % nh:
            raise ValueError(f"embed dim {H} not divisible by num_heads {nh}")
        hd = H // nh
        qkv = fused_matmul_bias(x, qkv_weight, qkv_bias)
        qkv = reshape(qkv, [B, S, 3, nh, hd])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                         dropout_p=attn_dropout_rate if training
                                         else 0.0, training=training)
    out = reshape(out, [B, S, -1])
    out = fused_matmul_bias(out, linear_weight, linear_bias)
    out = F.dropout(out, dropout_rate, training=training, mode=mode)
    if add_residual:
        out = residual + out
    if not pre_layer_norm and ln_scale is not None:
        out = F.layer_norm(out, [out.shape[-1]], ln_scale, ln_bias, ln_epsilon)
    return out


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None, ln_scale=None,
                                           ln_bias=None, dropout_rate=0.5,
                                           ln_epsilon=1e-5, training=True,
                                           mode="upscale_in_train", name=None):
    out = x if bias is None else x + bias
    out = F.dropout(out, dropout_rate, training=training, mode=mode)
    out = residual + out
    if ln_scale is not None:
        out = F.layer_norm(out, [out.shape[-1]], ln_scale, ln_bias, ln_epsilon)
    return out


def fused_rms_norm(x, weight, epsilon=1e-6):
    from ...ops.fused_norm import fused_rms_norm as _k

    return apply_op(lambda v, w: _k(v, w, epsilon), x, weight)


def fused_layer_norm(x, weight, bias, epsilon=1e-5):
    from ...ops.fused_norm import fused_layer_norm as _k

    return apply_op(lambda v, w, b: _k(v, w, b, epsilon), x, weight, bias)


def fused_ec_moe(x, gate_weight, expert_w1, expert_b1, expert_w2, expert_b2,
                 act_type="gelu"):
    """Ref fused_ec_moe op — dense top-1 MoE FFN."""

    def f(v, gw, w1, b1, w2, b2):
        B, S, H = v.shape
        flat = v.reshape(-1, H)
        probs = jax.nn.softmax(flat @ gw, -1)
        top = jnp.argmax(probs, -1)
        topw = jnp.take_along_axis(probs, top[:, None], 1)
        oh = jax.nn.one_hot(top, gw.shape[-1], dtype=v.dtype)
        buckets = jnp.einsum("te,td->etd", oh, flat)
        act = jax.nn.gelu if act_type == "gelu" else jax.nn.relu
        h = act(jnp.einsum("etd,edh->eth", buckets, w1) + b1[:, None])
        out_e = jnp.einsum("eth,ehd->etd", h, w2) + b2[:, None]
        out = jnp.einsum("te,etd->td", oh, out_e) * topw
        return out.reshape(B, S, H)

    return apply_op(f, x, gate_weight, expert_w1, expert_b1, expert_w2, expert_b2)
