"""fluid.backward — static-graph autodiff (ref python/paddle/fluid/backward.py
append_backward/gradients). Our Program replay differentiates with jax.grad at
Executor.run time, so these just mark targets on the recorded Program."""
from paddle_tpu.static.graph import append_backward, gradients  # noqa: F401
