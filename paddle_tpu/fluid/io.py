"""fluid.io — legacy IO (ref python/paddle/fluid/io.py save/load_inference_model,
reader.py:311 DataLoader). Inference programs serialize as StableHLO via
paddle_tpu.static; the DataLoader is the modern one."""
from __future__ import annotations

from paddle_tpu.io import (BatchSampler, DataLoader, Dataset,  # noqa: F401
                           DistributedBatchSampler, IterableDataset)
from paddle_tpu.static.graph import load_inference_model as _load, \
    save_inference_model as _save


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    """Legacy signature: feed names + fetch vars + dirname (not path_prefix)."""
    import os

    from paddle_tpu.static.graph import current_programs

    prog = main_program
    if prog is None:
        prog, _ = current_programs()
    feed_vars = [prog.global_block().var(n) for n in feeded_var_names]
    return _save(os.path.join(dirname, "model"), feed_vars, target_vars,
                 executor=executor, program=prog)


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    import os

    return _load(os.path.join(dirname, "model"), executor=executor)


def save_params(executor, dirname, main_program=None, filename=None):
    import os

    import paddle_tpu as p
    from paddle_tpu.static.graph import current_programs

    prog = main_program or current_programs()[0]
    state = {v.name: v for v in prog.all_parameters()}
    p.save(state, os.path.join(dirname, filename or "params.pdparams"))


def load_params(executor, dirname, main_program=None, filename=None):
    import os

    import paddle_tpu as p
    from paddle_tpu.static.graph import current_programs

    prog = main_program or current_programs()[0]
    state = p.load(os.path.join(dirname, filename or "params.pdparams"))
    for v in prog.all_parameters():
        if v.name in state:
            v.set_value(state[v.name])


save_persistables = save_params
load_persistables = load_params
