"""fluid.clip — gradient clipping (ref python/paddle/fluid/clip.py
ClipGradByGlobalNorm etc., the home of global-norm clipping pre-2.0)."""
from paddle_tpu.nn.clip import (ClipGradByGlobalNorm, ClipGradByNorm,  # noqa: F401
                                ClipGradByValue)

GradientClipByGlobalNorm = ClipGradByGlobalNorm
GradientClipByNorm = ClipGradByNorm
GradientClipByValue = ClipGradByValue
