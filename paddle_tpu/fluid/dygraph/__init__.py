"""fluid.dygraph — legacy eager-mode namespace (ref python/paddle/fluid/dygraph/:
base.py guard/to_variable, layers.py Layer, parallel.py DataParallel:399,
nn.py legacy layer classes)."""
from __future__ import annotations

import contextlib

import numpy as np

from paddle_tpu.distributed.parallel import DataParallel  # noqa: F401
from paddle_tpu.framework.core import Tensor, no_grad  # noqa: F401
from paddle_tpu.nn import (BatchNorm1D, BatchNorm2D, Embedding as _Embedding,  # noqa: F401
                           LayerNorm as _LayerNorm, Linear as _Linear)
from paddle_tpu.nn.layer_base import Layer  # noqa: F401
from paddle_tpu.static.graph import disable_static_mode, enable_static_mode, \
    in_static_mode


def enable_dygraph(place=None):
    disable_static_mode()


def disable_dygraph():
    enable_static_mode()


def enabled() -> bool:
    return not in_static_mode()


def in_dygraph_mode() -> bool:
    return not in_static_mode()


@contextlib.contextmanager
def guard(place=None):
    """ref fluid/dygraph/base.py guard — dygraph context; eager is our default."""
    was_static = in_static_mode()
    disable_static_mode()
    try:
        yield
    finally:
        if was_static:
            enable_static_mode()


def to_variable(value, name=None, zero_copy=None, dtype=None):
    import paddle_tpu as p

    t = p.to_tensor(np.asarray(value) if not isinstance(value, Tensor) else value)
    return t.astype(dtype) if dtype else t


class Linear(_Linear):
    """Legacy fluid.dygraph.Linear(input_dim, output_dim, act=None)."""

    def __init__(self, input_dim, output_dim, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__(input_dim, output_dim, weight_attr=param_attr,
                         bias_attr=bias_attr)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            from paddle_tpu.nn import functional as F

            out = getattr(F, self._act)(out)
        return out


class Embedding(_Embedding):
    """Legacy fluid.dygraph.Embedding(size=[vocab, dim])."""

    def __init__(self, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype="float32"):
        super().__init__(size[0], size[1], padding_idx=padding_idx,
                         sparse=is_sparse, weight_attr=param_attr)


class BatchNorm(BatchNorm2D):
    """Legacy fluid.dygraph.BatchNorm(num_channels, act=None)."""

    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype="float32", data_layout="NCHW", **kw):
        super().__init__(num_channels, momentum=momentum, epsilon=epsilon,
                         weight_attr=param_attr, bias_attr=bias_attr)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            from paddle_tpu.nn import functional as F

            out = getattr(F, self._act)(out)
        return out


class LayerList(Layer):
    def __init__(self, sublayers=None):
        from paddle_tpu.nn import LayerList as LL

        # delegate entirely; kept for `fluid.dygraph.LayerList` imports
        self.__class__ = LL  # type: ignore[assignment]
        LL.__init__(self, sublayers)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    import paddle_tpu as p

    return p.grad(outputs, inputs, grad_outputs=grad_outputs,
                  retain_graph=retain_graph, create_graph=create_graph,
                  allow_unused=allow_unused)
