"""fluid.initializer — legacy initializer class names (ref
python/paddle/fluid/initializer.py: ConstantInitializer etc.)."""
from paddle_tpu.nn.initializer import (Assign, Constant, KaimingNormal,  # noqa: F401
                                       KaimingUniform, Normal, TruncatedNormal,
                                       Uniform, XavierNormal, XavierUniform)

ConstantInitializer = Constant
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
UniformInitializer = Uniform
XavierInitializer = XavierUniform
MSRAInitializer = KaimingUniform
NumpyArrayInitializer = Assign
Xavier = XavierUniform
MSRA = KaimingUniform
