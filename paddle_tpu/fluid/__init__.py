"""paddle.fluid — legacy compat namespace.

The reference keeps its pre-2.0 API alive under ``python/paddle/fluid``
(~269k LoC: framework.py Program/Block/Variable, executor.py, layers/,
dygraph/, io.py, reader.py — SURVEY §2.2 "fluid (legacy)").  Migrating
users import it everywhere (``import paddle.fluid as fluid``), so this
package preserves that surface as thin aliases onto the TPU-native
implementations: the recorded-Program static facade (paddle_tpu/static),
the tape-autograd eager core (paddle_tpu/framework), and the jax-backed
nn/optimizer/io stacks.  No legacy execution machinery is re-implemented —
a fluid Program IS a paddle_tpu.static Program.
"""
from __future__ import annotations

from ..compat import (CPUPlace, CUDAPinnedPlace, CUDAPlace, CustomPlace,  # noqa: F401
                      IPUPlace, MLUPlace, NPUPlace, TPUPlace, XPUPlace)
from ..framework.flags import get_flags, set_flags  # noqa: F401
from ..static.graph import (CompiledProgram, Executor, ParallelExecutor,  # noqa: F401
                            Program, Scope, Variable, default_main_program,
                            default_startup_program, global_scope,
                            program_guard, scope_guard)
from ..static import name_scope, create_global_var  # noqa: F401
from ..framework.param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401

from . import backward  # noqa: F401
from . import clip  # noqa: F401
from . import core  # noqa: F401
from . import data_feeder  # noqa: F401
from . import dygraph  # noqa: F401
from . import executor  # noqa: F401
from . import framework  # noqa: F401
from . import initializer  # noqa: F401
from . import io  # noqa: F401
from . import layers  # noqa: F401
from . import nets  # noqa: F401
from . import optimizer  # noqa: F401
from . import param_attr  # noqa: F401
from . import reader  # noqa: F401
from . import regularizer  # noqa: F401
from . import unique_name  # noqa: F401

from .data_feeder import DataFeeder  # noqa: F401
from .dygraph import disable_dygraph, enable_dygraph, in_dygraph_mode  # noqa: F401
from .framework import cuda_places, cpu_places, device_guard, is_compiled_with_cuda  # noqa: F401
from .io import DataLoader, load_inference_model, save_inference_model  # noqa: F401
from .layers import data, embedding, one_hot  # noqa: F401


def install_check():
    """ref python/paddle/fluid/install_check.py — run a tiny training step to
    verify the install works on the current backend."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer as optim

    lin = nn.Linear(2, 1)
    opt = optim.SGD(learning_rate=0.1, parameters=lin.parameters())
    x = paddle.to_tensor(np.random.rand(4, 2).astype("float32"))
    loss = nn.functional.mse_loss(lin(x), paddle.zeros([4, 1]))
    loss.backward()
    opt.step()
    opt.clear_grad()
    print("Your paddle_tpu works well on SINGLE device.")
    print("install_check PASSED")
