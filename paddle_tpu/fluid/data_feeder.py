"""fluid.data_feeder — ref python/paddle/fluid/data_feeder.py DataFeeder:
converts numpy/list minibatches into the feed dict an Executor expects."""
from __future__ import annotations

import numpy as np


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self._names = [v if isinstance(v, str) else v.name for v in feed_list]

    def feed(self, iterable):
        cols = list(zip(*iterable)) if iterable and not isinstance(
            iterable, dict) else iterable
        if isinstance(cols, dict):
            return {k: np.asarray(v) for k, v in cols.items()}
        return {n: np.asarray(c) for n, c in zip(self._names, cols)}


def check_variable_and_dtype(input, input_name, expected_dtype, op_name,
                             extra_message=""):
    return True


def check_type(input, input_name, expected_type, op_name, extra_message=""):
    return True


def check_dtype(input_dtype, input_name, expected_dtype, op_name,
                extra_message=""):
    return True


def convert_dtype(dtype):
    from paddle_tpu.framework.dtype import dtype_name

    return dtype_name(dtype)
