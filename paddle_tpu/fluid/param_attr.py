"""fluid.param_attr — ref python/paddle/fluid/param_attr.py."""
from paddle_tpu.framework.param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
