"""fluid.nets — convenience composite networks (ref python/paddle/fluid/nets.py)."""
from __future__ import annotations

from .layers import conv2d, fc, pool2d


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    conv_out = conv2d(input, num_filters, filter_size, stride=conv_stride,
                      padding=conv_padding, dilation=conv_dilation,
                      groups=conv_groups, param_attr=param_attr,
                      bias_attr=bias_attr, act=act)
    return pool2d(conv_out, pool_size=pool_size, pool_type=pool_type,
                  pool_stride=pool_stride, pool_padding=pool_padding,
                  global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    from .layers import batch_norm, dropout

    tmp = input
    if not isinstance(conv_num_filter, (list, tuple)):
        conv_num_filter = [conv_num_filter]
    with_bn = conv_with_batchnorm if isinstance(conv_with_batchnorm, list) \
        else [conv_with_batchnorm] * len(conv_num_filter)
    drop = conv_batchnorm_drop_rate if isinstance(
        conv_batchnorm_drop_rate, list) else \
        [conv_batchnorm_drop_rate] * len(conv_num_filter)
    for i, nf in enumerate(conv_num_filter):
        tmp = conv2d(tmp, nf, conv_filter_size, padding=conv_padding,
                     param_attr=param_attr,
                     act=None if with_bn[i] else conv_act)
        if with_bn[i]:
            tmp = batch_norm(tmp, act=conv_act)
            if drop[i] > 0:
                tmp = dropout(tmp, p=drop[i])
    return pool2d(tmp, pool_size=pool_size, pool_stride=pool_stride,
                  pool_type=pool_type)
