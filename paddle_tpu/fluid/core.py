"""fluid.core — shim for the reference's pybind extension module
(ref paddle/fluid/pybind/pybind.cc:625 `libpaddle`).  There is no native
binding layer to expose — XLA owns the runtime — so this provides the
handful of names user code touches: places, dtype enums (VarDesc.VarType),
the eager Tensor type, and flag accessors."""
from __future__ import annotations

import jax.numpy as jnp

from ..compat import (CPUPlace, CUDAPinnedPlace, CUDAPlace, CustomPlace,  # noqa: F401
                      IPUPlace, MLUPlace, NPUPlace, TPUPlace, XPUPlace)
from ..framework.core import Tensor
from ..framework.flags import get_flags as _get_flags, set_flags as _set_flags


class VarDesc:
    """Dtype enum used pervasively by legacy user code
    (``core.VarDesc.VarType.FP32``).  Values map to jnp dtypes."""

    class VarType:
        BOOL = jnp.bool_
        INT8 = jnp.int8
        UINT8 = jnp.uint8
        INT16 = jnp.int16
        INT32 = jnp.int32
        INT64 = jnp.int64
        FP16 = jnp.float16
        BF16 = jnp.bfloat16
        FP32 = jnp.float32
        FP64 = jnp.float64
        COMPLEX64 = jnp.complex64
        COMPLEX128 = jnp.complex128
        # non-dtype var kinds, kept as distinct sentinels
        LOD_TENSOR = "lod_tensor"
        SELECTED_ROWS = "selected_rows"
        LOD_TENSOR_ARRAY = "lod_tensor_array"
        RAW = "raw"


VarBase = Tensor  # legacy dygraph tensor name
LoDTensor = Tensor  # LoD (ragged) metadata is not modeled; dense alias


class _OpsProxy:
    """core.eager.ops.* — the reference exposes generated per-op C functions
    here; ours resolve lazily through paddle_tpu._C_ops' dispatch."""

    def __getattr__(self, name):
        from .. import _C_ops

        return getattr(_C_ops, name)


class eager:
    Tensor = Tensor
    ops = _OpsProxy()


def is_compiled_with_cuda() -> bool:
    from ..device import is_compiled_with_cuda as f

    return f()


def globals_set(name, value):
    _set_flags({name: value})


def globals_get(name):
    return _get_flags([name])[name]


def get_cuda_device_count() -> int:
    import jax

    return len([d for d in jax.devices() if d.platform != "cpu"])


class Scope:
    def __init__(self):
        from ..static.graph import Scope as _S

        self._impl = _S()

    def find_var(self, name):
        return self._impl.find_var(name)


def TCPStore(*args, **kwargs):
    """ref pybind binding core.TCPStore used by init_parallel_env
    (parallel.py:279) — resolves to the native store."""
    from ..distributed.store import TCPStore as _S

    return _S(*args, **kwargs)
