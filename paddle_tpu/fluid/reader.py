"""fluid.reader — ref python/paddle/fluid/reader.py (DataLoader:311)."""
from paddle_tpu.io import DataLoader  # noqa: F401


class PyReader:
    """Legacy PyReader — iterable feeding wrapper over a sample generator."""

    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False):
        self._reader = None
        self._batch = None

    def decorate_sample_list_generator(self, reader, places=None):
        self._reader = reader

    def decorate_batch_generator(self, reader, places=None):
        self._reader = reader

    def __iter__(self):
        import numpy as np

        import paddle_tpu as p

        for batch in self._reader():
            if isinstance(batch, (list, tuple)) and batch and isinstance(
                    batch[0], (list, tuple)):
                cols = list(zip(*batch))
                yield [p.to_tensor(np.asarray(c)) for c in cols]
            else:
                yield [p.to_tensor(np.asarray(b)) for b in batch]
