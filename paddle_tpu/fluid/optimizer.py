"""fluid.optimizer — legacy optimizer classes with *Optimizer names and
`parameter_list` / `.minimize(loss)` conventions (ref
python/paddle/fluid/optimizer.py)."""
from __future__ import annotations

from paddle_tpu import optimizer as _opt


class SGDOptimizer(_opt.SGD):
    def __init__(self, learning_rate=0.001, parameter_list=None,
                 regularization=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate=learning_rate, parameters=parameter_list,
                         grad_clip=grad_clip)


class MomentumOptimizer(_opt.Momentum):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameter_list=None,
                 use_nesterov=False, regularization=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate=learning_rate, momentum=momentum,
                         parameters=parameter_list, use_nesterov=use_nesterov,
                         grad_clip=grad_clip)


class AdamOptimizer(_opt.Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameter_list=None, regularization=None,
                 grad_clip=None, name=None, lazy_mode=False, **kw):
        super().__init__(learning_rate=learning_rate, beta1=beta1, beta2=beta2,
                         epsilon=epsilon, parameters=parameter_list,
                         grad_clip=grad_clip)


class AdamaxOptimizer(_opt.Adamax):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameter_list=None, regularization=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate=learning_rate, beta1=beta1, beta2=beta2,
                         epsilon=epsilon, parameters=parameter_list,
                         grad_clip=grad_clip)


class AdagradOptimizer(_opt.Adagrad):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameter_list=None,
                 regularization=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate=learning_rate, epsilon=epsilon,
                         parameters=parameter_list, grad_clip=grad_clip,
                         initial_accumulator_value=initial_accumulator_value)


class RMSPropOptimizer(_opt.RMSProp):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, parameter_list=None,
                 regularization=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate=learning_rate, rho=rho, epsilon=epsilon,
                         momentum=momentum, centered=centered,
                         parameters=parameter_list, grad_clip=grad_clip)


class LambOptimizer(_opt.Lamb):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameter_list=None,
                 regularization=None, grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None, **kw):
        super().__init__(learning_rate=learning_rate,
                         lamb_weight_decay=lamb_weight_decay, beta1=beta1,
                         beta2=beta2, epsilon=epsilon,
                         parameters=parameter_list, grad_clip=grad_clip,
                         exclude_from_weight_decay_fn=exclude_from_weight_decay_fn)


Adam = AdamOptimizer
SGD = SGDOptimizer
Momentum = MomentumOptimizer
