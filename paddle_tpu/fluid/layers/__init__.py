"""fluid.layers — the legacy flat op namespace (ref python/paddle/fluid/layers/:
nn.py ~15k LoC of ``fluid.layers.*`` functions).  Legacy spellings
(``reduce_mean(dim=...)``, ``fill_constant``, probability-input
``cross_entropy``) delegate to the modern paddle_tpu surface; under
``paddle.enable_static`` every call is recorded into the current Program by
the central dispatch, exactly like the 2.x API."""
from __future__ import annotations

import paddle_tpu as _p
from paddle_tpu import nn as _nn
from paddle_tpu.nn import functional as _F
from paddle_tpu.static.graph import data as _static_data
from paddle_tpu.static.nn import (batch_norm, cond, conv2d, embedding,  # noqa: F401
                                  while_loop)
from paddle_tpu.static.nn import fc as _fc


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """Legacy fc spelling: param_attr/act instead of weight_attr/activation."""
    return _fc(input, size, num_flatten_dims=num_flatten_dims,
               weight_attr=param_attr, bias_attr=bias_attr, activation=act,
               name=name)

# direct re-exports where 2.x name == legacy name
from paddle_tpu import (abs, assign, cast, clip, concat, cumsum, exp,  # noqa: F401
                        expand, flatten, gather, increment, log, matmul,
                        ones, pow, reshape, scale, shape, sigmoid, slice,
                        split, sqrt, square, squeeze, stack, tanh, tile,
                        topk, transpose, tril, triu, unsqueeze, where, zeros)
from paddle_tpu.nn.functional import (dropout, log_softmax, relu, softmax,  # noqa: F401
                                      softmax_with_cross_entropy)
from paddle_tpu.metric import accuracy  # noqa: F401


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True):
    """Legacy fluid.layers.data prepends a -1 batch dim unless told not to."""
    if append_batch_size and (not shape or shape[0] != -1):
        shape = [-1] + list(shape)
    return _static_data(name, shape, dtype, lod_level)


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    return _p.full(shape, value, dtype=dtype)


def mean(x, name=None):
    return _p.mean(x)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _p.mean(input, axis=dim, keepdim=keep_dim)


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _p.sum(input, axis=dim, keepdim=keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _p.max(input, axis=dim, keepdim=keep_dim)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _p.min(input, axis=dim, keepdim=keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _p.prod(input, axis=dim, keepdim=keep_dim)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _act(_p.add(x, y), act)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _act(_p.subtract(x, y), act)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _act(_p.multiply(x, y), act)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _act(_p.divide(x, y), act)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _act(_p.pow(x, y), act)


def _act(x, act):
    return getattr(_F, act)(x) if act else x


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    """Legacy mul op == matmul after flattening to 2-D."""
    xs = x.reshape([-1 if x_num_col_dims else 1,
                    int(_np_prod(x.shape[x_num_col_dims:]))]) \
        if len(x.shape) > 2 else x
    return _p.matmul(xs, y)


def _np_prod(t):
    out = 1
    for v in t:
        out *= int(v)
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100, name=None):
    """Legacy cross_entropy takes PROBABILITIES (post-softmax), not logits
    (ref fluid/layers/loss.py cross_entropy)."""
    import jax.numpy as jnp

    from paddle_tpu.framework.dispatch import apply_op

    if soft_label:
        return apply_op(
            lambda p, l: -(l * jnp.log(jnp.clip(p, 1e-12))).sum(-1, keepdims=True),
            input, label)

    def f(p, l):
        l = l.reshape(p.shape[:-1]).astype(jnp.int32)
        picked = jnp.take_along_axis(p, l[..., None], axis=-1)
        out = -jnp.log(jnp.clip(picked, 1e-12))
        if ignore_index >= 0:
            out = jnp.where(l[..., None] == ignore_index, 0.0, out)
        return out

    return apply_op(f, input, label)


def softmax_with_cross_entropy_legacy(logits, label, **kw):
    return softmax_with_cross_entropy(logits, label, **kw)


def one_hot(input, depth, allow_out_of_range=False):
    return _F.one_hot(input, depth)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, ceil_mode=False, exclusive=True,
           data_format="NCHW", name=None):
    if global_pooling:
        return (_F.adaptive_max_pool2d(input, 1) if pool_type == "max"
                else _F.adaptive_avg_pool2d(input, 1))
    if pool_type == "max":
        return _F.max_pool2d(input, pool_size, stride=pool_stride,
                             padding=pool_padding, ceil_mode=ceil_mode)
    return _F.avg_pool2d(input, pool_size, stride=pool_stride,
                         padding=pool_padding, ceil_mode=ceil_mode,
                         exclusive=exclusive)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from paddle_tpu import create_parameter as cp

    return cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
              default_initializer=default_initializer)


def create_tensor(dtype, name=None, persistable=False):
    return _p.zeros([1], dtype=dtype)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0, name=None):
    return _p.uniform(shape, dtype=dtype, min=min, max=max)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32", name=None):
    return _p.normal(mean=mean, std=std, shape=shape).astype(dtype)


def argmax(x, axis=0, name=None):
    return _p.argmax(x, axis=axis)


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    return _F.hardswish(x)


def relu6(x, name=None):
    return _F.relu6(x)


def leaky_relu(x, alpha=0.02, name=None):
    return _F.leaky_relu(x, negative_slope=alpha)


def batch_norm_legacy(*a, **k):
    return batch_norm(*a, **k)


def sums(input, out=None):
    out_t = input[0]
    for t in input[1:]:
        out_t = _p.add(out_t, t)
    return out_t


def unsqueeze_legacy(input, axes, name=None):
    out = input
    for ax in (axes if isinstance(axes, (list, tuple)) else [axes]):
        out = _p.unsqueeze(out, ax)
    return out


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both"):
    """Legacy debug print op → jax.debug.print under jit, plain print eager."""
    import jax

    from paddle_tpu.framework.dispatch import apply_op

    def f(x):
        jax.debug.print((message or "") + "{x}", x=x)
        return x

    return apply_op(f, input)
