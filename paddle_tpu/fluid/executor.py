"""fluid.executor — ref python/paddle/fluid/executor.py:921 Executor.
The recorded-Program replay executor lives in paddle_tpu/static/graph.py."""
from paddle_tpu.static.graph import (Executor, Scope, global_scope,  # noqa: F401
                                     scope_guard)
