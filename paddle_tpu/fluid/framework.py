"""fluid.framework — legacy framework module (ref python/paddle/fluid/framework.py:
Program/Block/Variable/Parameter classes, dygraph-mode switches, set_flags:7629).
Aliases onto paddle_tpu.static's recorded-Program IR and the eager core."""
from __future__ import annotations

from ..compat import CPUPlace, CUDAPlace  # noqa: F401
from ..framework.core import Parameter, Tensor  # noqa: F401
from ..framework.flags import get_flags, set_flags  # noqa: F401
from ..framework.random import seed as _seed
from ..static.graph import (Block, Operator, Program, Variable,  # noqa: F401
                            default_main_program, default_startup_program,
                            in_static_mode, program_guard)

EagerParamBase = Parameter


def in_dygraph_mode() -> bool:
    """ref fluid/framework.py in_dygraph_mode — true unless paddle.enable_static."""
    return not in_static_mode()


_non_static_mode = in_dygraph_mode
_in_legacy_dygraph = in_dygraph_mode


def _current_expected_place():
    import jax

    d = jax.devices()[0]
    return CUDAPlace(0) if d.platform in ("tpu", "gpu", "axon") else CPUPlace()


def is_compiled_with_cuda() -> bool:
    from ..device import is_compiled_with_cuda as f

    return f()


def cuda_places(device_ids=None):
    import jax

    n = len([d for d in jax.devices() if d.platform != "cpu"]) or 1
    ids = device_ids if device_ids is not None else range(n)
    return [CUDAPlace(i) for i in ids]


def cpu_places(device_count=1):
    return [CPUPlace() for _ in range(device_count)]


def device_guard(device=None):
    import contextlib

    return contextlib.nullcontext()


def set_random_seed(s):
    _seed(s)


class dygraph_only:  # decorator used by legacy code
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, *a, **k):
        return self._fn(*a, **k)
