"""fluid.unique_name — name uniquifier (ref python/paddle/fluid/unique_name.py)."""
from __future__ import annotations

import contextlib
import threading


class _Gen(threading.local):
    def __init__(self):
        self.counters = {}

    def make(self, key):
        n = self.counters.get(key, 0)
        self.counters[key] = n + 1
        return f"{key}_{n}"


_gen = _Gen()


def generate(key: str) -> str:
    return _gen.make(key)


def generate_with_ignorable_key(key: str) -> str:
    return _gen.make(key)


def switch(new_generator=None):
    global _gen
    old = _gen
    _gen = new_generator or _Gen()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        global _gen
        _gen = old
