"""Speculative decoding for the paged serving path — drafters + exact
acceptance.

Paged decode (docs/serving.md) still spends one full target-model forward
per emitted token, so decode latency is bound by model depth, not FLOPs.
Speculative decoding amortizes that: a cheap DRAFTER proposes ``k`` tokens
and the target model scores all ``k+1`` window positions in ONE compiled
program (``models/llama.py paged_verify_step`` over the multi-token verify
op in ``ops/paged_attention.py``) — the same "fewer, bigger programs"
economics that operator fusion exploits in XLA.

Exactness contract (the whole point — speculation must be FREE of quality
cost):

- **greedy** (temperature 0): a draft token is accepted iff it equals the
  target argmax at its position, and the first mismatch position's argmax
  is emitted as the correction — the emitted chain is bit-identical to the
  dense server's, token for token.
- **temperature sampling**: standard speculative rejection sampling
  [Leviathan et al.; Chen et al.]. Draft token ``x`` with draft
  probability ``q(x)`` is accepted with probability
  ``min(1, p(x) / q(x))`` against the *filtered* target distribution ``p``
  (the same temperature/top-k/top-p filtering the dense tick samples
  from, ``models/generation.py``); on rejection the emitted token is drawn
  from the normalized residual ``max(p - q, 0)``, and after a fully
  accepted window a bonus token is drawn from ``p`` directly. The output
  DISTRIBUTION provably equals the target model's — acceptance rate only
  moves throughput, never quality.

Both built-in drafters propose deterministically by default, so their
draft distribution is a point mass and ``min(1, p/q)`` reduces to
``p(x)`` (the one-hot ``q`` is synthesized inside the compiled verify
program — nothing extra crosses the host boundary):

- :class:`NgramDrafter` — prompt-lookup decoding: no extra weights, pure
  host-side numpy over the request's own context (prompt + generated), so
  it runs in tier-1 CPU tests and adds zero device programs.
- :class:`DraftModelDrafter` — a small causal LM sharing the target's
  tokenizer, run as ONE fixed-shape compiled program per tick (k full
  forwards over a (B, max_len) buffer via lax.scan — no KV cache, no
  per-context-length recompiles). With ``sample_draft=True`` it samples
  at the request temperature and ships its full softmax as ``q``.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

__all__ = ["SpecConfig", "NgramDrafter", "DraftModelDrafter",
           "DrafterFault", "speculative_accept", "ngram_propose_device"]


class DrafterFault(RuntimeError):
    """A drafter failed to produce proposals (injected or real).

    Recoverable by design: the serving loop catches this, runs the trip
    through the always-warm plain decode program instead, and holds the
    speculation gate off for a cooldown — the drafter is an accelerator,
    never a correctness dependency."""


# --------------------------------------------------------------------------- #
# Acceptance — the exact rejection sampler (compiled, fixed shapes)
# --------------------------------------------------------------------------- #


def speculative_accept(logits, proposals, temps, topks, topps, kcaps, key,
                       qprobs=None, greedy=False):
    """Vectorized exact accept/reject over one verify window.

    logits: fp32 (B, W, V) target logits for window positions
    ``pos..pos+k`` (W = k+1); ``logits[:, j]`` is the target distribution
    for the token FOLLOWING window position j. proposals: int32 (B, k)
    draft tokens (window positions ``pos+1..pos+k``). temps/topps fp32
    [B], topks int32 [B]: per-row sampling params (temp 0 → greedy).
    kcaps: int32 [B] per-row draft budget ≤ k — positions ≥ kcap are
    force-stopped: no draft is consumed there, the emitted token comes
    from the FULL target distribution (a kcap of 0 reduces the row to a
    plain decode tick). qprobs: optional fp32 (B, k, V) draft
    distributions; None means deterministic proposals (one-hot q).
    greedy: STATIC python bool — True asserts every row has temp 0, so the
    whole sampling machinery (top-k/top-p filtering, residual resampling)
    is dropped at trace time and acceptance compiles to pure argmax
    comparison. Token-identical to the general path at temp 0 (the
    general path already routes temp-0 rows through ``tgt``); the caller
    promises the precondition and keys the jit cache on the flag.

    Returns ``(out, acc)``: out int32 (B, W) where ``out[b, :acc[b]+1]``
    are the emitted tokens — accepted drafts then one
    correction/bonus — and acc int32 [B] is the accepted-draft count.
    Everything is branch-free jnp so the caller can jit it as part of the
    fused verify program.
    """
    import jax
    import jax.numpy as jnp

    from ..models.generation import filtered_probs_rows

    B, W, V = logits.shape
    k = W - 1
    lg = logits.astype(jnp.float32)

    # greedy target chain: argmax per window position (the dense oracle)
    tgt = jnp.argmax(lg, axis=-1).astype(jnp.int32)              # (B, W)

    if greedy:
        jpos = jnp.arange(k)[None, :]                            # (1, k)
        acc_tok = (proposals == tgt[:, :k]) & (jpos < kcaps[:, None])
        acc = jnp.sum(jnp.cumprod(acc_tok.astype(jnp.int32), axis=1),
                      axis=1).astype(jnp.int32)                  # (B,)
        prop_pad = jnp.concatenate(
            [proposals, jnp.zeros((B, 1), jnp.int32)], axis=1)   # (B, W)
        wpos = jnp.arange(W)[None, :]
        out = jnp.where(wpos < acc[:, None], prop_pad, tgt)
        return out, acc

    # filtered target distribution per position for sampling rows — the
    # SAME temperature/top-k/top-p filter the dense tick samples from
    p = filtered_probs_rows(
        lg.reshape(B * W, V),
        jnp.repeat(temps, W), jnp.repeat(topks, W),
        jnp.repeat(topps, W)).reshape(B, W, V)

    if qprobs is None:
        q = jax.nn.one_hot(proposals, V, dtype=jnp.float32)      # (B, k, V)
        q_at_d = jnp.ones((B, k), jnp.float32)
    else:
        q = qprobs.astype(jnp.float32)
        q_at_d = jnp.take_along_axis(q, proposals[..., None],
                                     axis=-1)[..., 0]
    p_at_d = jnp.take_along_axis(p[:, :k], proposals[..., None],
                                 axis=-1)[..., 0]                # (B, k)

    ukey, rkey, bkey = jax.random.split(key, 3)
    jpos = jnp.arange(k)[None, :]                                # (1, k)
    u = jax.random.uniform(ukey, (B, k))
    acc_sample = u * jnp.maximum(q_at_d, 1e-20) < p_at_d
    acc_greedy = proposals == tgt[:, :k]
    acc_tok = jnp.where((temps > 0)[:, None], acc_sample, acc_greedy)
    acc_tok = acc_tok & (jpos < kcaps[:, None])
    # leading-accept count: first rejection (or kcap) stops the chain
    acc = jnp.sum(jnp.cumprod(acc_tok.astype(jnp.int32), axis=1),
                  axis=1).astype(jnp.int32)                      # (B,)

    # correction tokens, one per window index (only index ``acc`` is used):
    # - true rejection (j < kcap): residual max(p - q, 0), renormalized
    # - forced stop / bonus (j >= kcap, incl. j == k): full target p
    resid = jnp.maximum(p[:, :k] - q, 0.0)
    rs = jnp.sum(resid, axis=-1, keepdims=True)
    resid = jnp.where(rs > 0, resid / jnp.maximum(rs, 1e-20), p[:, :k])
    corr_resid = jax.random.categorical(
        rkey, jnp.log(resid + 1e-30), axis=-1).astype(jnp.int32)  # (B, k)
    corr_full = jax.random.categorical(
        bkey, jnp.log(p + 1e-30), axis=-1).astype(jnp.int32)      # (B, W)
    wpos = jnp.arange(W)[None, :]
    corr_resid = jnp.concatenate([corr_resid, corr_full[:, -1:]], axis=1)
    corr_sample = jnp.where(wpos < kcaps[:, None], corr_resid, corr_full)
    corr = jnp.where((temps > 0)[:, None], corr_sample, tgt)      # (B, W)

    prop_pad = jnp.concatenate(
        [proposals, jnp.zeros((B, 1), jnp.int32)], axis=1)        # (B, W)
    out = jnp.where(wpos < acc[:, None], prop_pad, corr)
    return out, acc


# --------------------------------------------------------------------------- #
# Drafters
# --------------------------------------------------------------------------- #


def ngram_propose_device(ctx, pos, k, max_ngram=3, min_ngram=1):
    """Prompt-lookup drafting as a branch-free jnp op — the in-program twin
    of :meth:`NgramDrafter.propose_one`, so the whole
    draft→verify→accept window can live inside ONE compiled program and
    ``GenerationServer`` can lax.scan several windows per host round trip
    (the spec analogue of ``tick_window``).

    ctx: int32 (B, L) token buffer, row b valid through index ``pos[b]``
    (the current token); pos: int32 (B,). Returns int32 (B, k) proposals:
    the continuation of the most recent longest-n-gram match of each row's
    suffix within its own context, clamped at the context end (which pads
    short continuations by repeating the last token, exactly like the host
    drafter); rows with no match ≥ min_ngram repeat their last token.
    """
    import jax.numpy as jnp

    B, L = ctx.shape
    ar = jnp.arange(L)[None, :]                              # (1, L)
    # cont_start[b]: where the proposed continuation begins; initialized to
    # pos so the fallback (and every clamp) repeats the last token
    cont_start = jnp.broadcast_to(pos[:, None], (B, 1))[:, 0]
    found = jnp.zeros((B,), bool)
    for n in range(max_ngram, min_ngram - 1, -1):
        # suffix token j of the n-gram ending at pos: ctx[pos-n+1+j]
        sidx = jnp.clip(pos[:, None] + jnp.arange(1 - n, 1)[None, :], 0,
                        L - 1)                               # (B, n)
        suffix = jnp.take_along_axis(ctx, sidx, axis=1)      # (B, n)
        match = jnp.ones((B, L), bool)
        for j in range(n):
            # window starting at i matches suffix[j] at i+j (clamped reads
            # past L-1 are masked off by the validity bound below)
            shifted = jnp.take_along_axis(
                ctx, jnp.clip(ar + j, 0, L - 1).repeat(B, 0), axis=1)
            match = match & (shifted == suffix[:, j:j + 1])
        # valid starts: window inside ctx[:pos] — excludes the trivial
        # self-match at pos-n+1 and guarantees a continuation token
        valid = match & (ar <= (pos - n)[:, None])
        last = jnp.max(jnp.where(valid, ar, -1), axis=1)     # (B,)
        hit = (last >= 0) & ~found
        cont_start = jnp.where(hit, last + n, cont_start)
        found = found | hit
    pidx = jnp.minimum(cont_start[:, None] + jnp.arange(k)[None, :],
                       pos[:, None])                         # (B, k)
    return jnp.take_along_axis(ctx, pidx, axis=1).astype(jnp.int32)


class NgramDrafter:
    """Prompt-lookup decoding: propose the continuation of the most recent
    longest n-gram match of the context's own suffix.

    Zero extra weights and zero device work — the draft source is the
    request's context (prompt + generated so far), searched host-side with
    numpy. Strong on repeated-suffix workloads (retrieval answers quoting
    the prompt, code edits, self-repeating generations); on a miss it
    falls back to repeating the last token, whose proposals simply get
    rejected (fixed shapes beat adaptive k on TPU).
    """

    deterministic = True
    fusible = True   # has propose_device: drafting can live in-program

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"min_ngram={min_ngram}, max_ngram={max_ngram}")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        # optional FaultInjector (inference/faults.py), wired by the server
        self.faults = None

    def propose_one(self, ctx: Sequence[int], k: int) -> np.ndarray:
        """k proposed continuation tokens for one context (host numpy)."""
        ctx = np.asarray(ctx, np.int32)
        n_ctx = len(ctx)
        for n in range(min(self.max_ngram, n_ctx - 1), self.min_ngram - 1,
                       -1):
            suffix = ctx[n_ctx - n:]
            # candidate starts i <= n_ctx-1-n: the window view over
            # ctx[:-1] excludes the trivial self-match at the very end and
            # guarantees at least one continuation token exists
            windows = np.lib.stride_tricks.sliding_window_view(
                ctx[:n_ctx - 1], n)
            hits = np.nonzero((windows == suffix).all(axis=1))[0]
            if hits.size:
                i = int(hits[-1])                 # most recent occurrence
                cont = ctx[i + n:i + n + k]
                if len(cont) < k:                 # pad: repeat last token
                    pad = np.full(k - len(cont), cont[-1] if len(cont)
                                  else ctx[-1], np.int32)
                    cont = np.concatenate([cont, pad])
                return cont.astype(np.int32)
        return np.full(k, ctx[-1], np.int32)      # miss: repeat last token

    def propose(self, contexts: List[Optional[Sequence[int]]], k: int,
                temps=None, key=None) -> Tuple[np.ndarray, None]:
        """Batch proposals: (B, k) int32, one row per slot (idle slots pass
        None and get zeros — their rows run masked into scratch)."""
        if self.faults is not None and \
                self.faults.fire("drafter") is not None:
            raise DrafterFault("injected drafter failure (ngram)")
        out = np.zeros((len(contexts), k), np.int32)
        for i, ctx in enumerate(contexts):
            if ctx is not None and len(ctx):
                out[i] = self.propose_one(ctx, k)
        return out, None

    def propose_device(self, ctx, pos, k):
        """In-program drafting (traced): :func:`ngram_propose_device` with
        this drafter's n-gram bounds."""
        return ngram_propose_device(ctx, pos, k, max_ngram=self.max_ngram,
                                    min_ngram=self.min_ngram)


class DraftModelDrafter:
    """Small-LM drafter: a cheap causal model sharing the target's
    tokenizer proposes k tokens autoregressively.

    TPU-shaped: ONE compiled program per tick runs k full forwards over a
    fixed (B, max_len) token buffer via lax.scan — no draft KV cache, no
    per-context-length compile family, zero steady-state recompiles. The
    draft model is depth-cheap by construction, so k extra full forwards
    of it still undercut one target forward per token.

    ``sample_draft=False`` (default): greedy proposals — a point-mass
    draft distribution, acceptance reduces to ``p(x)``. ``True``: rows
    with temperature > 0 sample at the request temperature and the full
    draft softmax ships to the verify program as ``q`` for the
    ``min(1, p/q)`` rule (greedy rows still propose argmax with one-hot
    q), which raises acceptance on hot sampled traffic.
    """

    fusible = False  # drafting needs its own program + host orchestration

    def __init__(self, model, max_len: int, sample_draft: bool = False):
        self.model = model
        self.max_len = int(max_len)
        self.sample_draft = bool(sample_draft)
        self.deterministic = not self.sample_draft
        # optional FaultInjector (inference/faults.py), wired by the server
        self.faults = None
        from ..jit import state_values

        self.params = state_values(model)
        self._jit = {}

    def _build(self, k: int):
        import jax
        import jax.numpy as jnp

        from ..framework.core import Tensor
        from ..jit import functional_call

        model = self.model
        sample = self.sample_draft

        def fn(params, buf, pos, temps, key):
            B, L = buf.shape
            rows = jnp.arange(B)

            def body(carry, j):
                buf, p = carry
                logits = functional_call(model, params, Tensor(buf))
                logits = logits[0] if isinstance(logits, (list, tuple)) \
                    else logits
                lg = jnp.take_along_axis(
                    logits.value, p[:, None, None], axis=1
                )[:, 0].astype(jnp.float32)                     # (B, V)
                greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                if sample:
                    scaled = lg / jnp.maximum(temps, 1e-6)[:, None]
                    drawn = jax.random.categorical(
                        jax.random.fold_in(key, j), scaled,
                        axis=-1).astype(jnp.int32)
                    nxt = jnp.where(temps > 0, drawn, greedy)
                    q = jnp.where((temps > 0)[:, None],
                                  jax.nn.softmax(scaled, axis=-1),
                                  jax.nn.one_hot(greedy, lg.shape[-1],
                                                 dtype=jnp.float32))
                else:
                    nxt = greedy
                    q = jnp.zeros((B, 0), jnp.float32)  # unused placeholder
                p2 = jnp.minimum(p + 1, L - 1)
                buf = buf.at[rows, p2].set(nxt)
                return (buf, p2), (nxt, q)

            _, (toks, qs) = jax.lax.scan(body, (buf, pos), jnp.arange(k))
            toks = jnp.swapaxes(toks, 0, 1)                     # (B, k)
            qs = jnp.swapaxes(qs, 0, 1) if sample else None     # (B, k, V)
            return toks, qs

        return jax.jit(fn)

    def propose(self, contexts: List[Optional[Sequence[int]]], k: int,
                temps=None, key=None):
        import jax
        import jax.numpy as jnp

        if self.faults is not None and \
                self.faults.fire("drafter") is not None:
            raise DrafterFault("injected drafter failure (draft model)")
        B = len(contexts)
        buf = np.zeros((B, self.max_len), np.int32)
        pos = np.zeros((B,), np.int32)
        for i, ctx in enumerate(contexts):
            if ctx is not None and len(ctx):
                ctx = list(ctx)[-self.max_len:]
                buf[i, :len(ctx)] = ctx
                pos[i] = len(ctx) - 1
        if k not in self._jit:
            self._jit[k] = self._build(k)
        if temps is None:
            temps = np.zeros((B,), np.float32)
        if key is None:
            key = jax.random.PRNGKey(0)
        toks, qs = self._jit[k](self.params, jnp.asarray(buf),
                                jnp.asarray(pos), jnp.asarray(temps), key)
        return toks, (qs if self.sample_draft else None)


# --------------------------------------------------------------------------- #
# Config
# --------------------------------------------------------------------------- #


@dataclasses.dataclass
class SpecConfig:
    """Speculative-decoding knobs for ``GenerationServer(..., spec=...)``.

    k: draft tokens per verify window (window width = k+1; per-request
    ``submit(..., draft_k=)`` can lower it without changing shapes).
    drafter: ``"ngram"`` (prompt lookup, default), ``"model"`` (requires
    ``draft_model``), or any object with the drafter protocol
    (``deterministic`` attr + ``propose(contexts, k, temps, key)``).

    gate_low / gate_cooldown: the DYNAMIC SPECULATION GATE. A verify
    window costs roughly (k+1)/width more than a plain decode tick but
    advances only 1 token when every draft is rejected — on real streams
    rejection clusters (a request's early tokens, before the drafter has
    context to mine), so paying for drafts there is a pure loss. After
    each speculative trip the server measures mean accepted drafts per
    window per live row; below ``gate_low`` it falls back to the
    already-compiled plain decode program for ``gate_cooldown`` trips,
    then probes speculation again. Both programs exist from warmup, so
    gating switches per trip with zero steady-state compiles.
    ``gate_cooldown=0`` disables the gate (always speculate). The
    break-even acceptance is roughly ``verify_window_cost/tick_cost - 1``
    (~k/2 at small-model shapes) — the default ``gate_low`` is tuned
    for k=4; scale it with k.
    ``gate_ticks`` is the decode-tick count of each gated plain trip —
    independent of the verify ``tick_window``, because the gated-off
    phase is pure sequential decode and wants long trips to amortize the
    host round trip (the probe cadence in tokens is
    ``gate_cooldown * gate_ticks``).

    turbo_windows: the gate's LONG-TRIP tier (fused drafters only,
    default 0 = disabled). When a trip's mean accepted drafts per window
    reaches ``k - 1`` across the batch, streams have locked into
    drafter-predictable runs — the next trips fuse ``turbo_windows``
    windows per program instead of ``tick_window``, amortizing the host
    round trip over up to ``turbo_windows*(k+1)`` tokens. Drops back the
    moment acceptance dips. A third compiled variant, built once. Worth
    enabling when the host<->device round trip dominates (tunneled
    backends); on a local backend the coarser slot-refill granularity
    of long trips usually costs more than the saved round trips.
    """

    k: int = 4
    drafter: Union[str, Any] = "ngram"
    ngram_max: int = 3
    ngram_min: int = 1
    draft_model: Any = None
    sample_draft: bool = False
    gate_low: float = 2.0
    gate_cooldown: int = 3
    gate_ticks: int = 16
    turbo_windows: int = 0

    def validate(self) -> None:
        if isinstance(self.k, bool) or not isinstance(self.k, int) \
                or self.k < 1:
            raise ValueError(f"spec.k must be an int >= 1, got {self.k!r}")
        if isinstance(self.drafter, str) and \
                self.drafter not in ("ngram", "model"):
            raise ValueError(
                f"spec.drafter must be 'ngram', 'model', or a drafter "
                f"object, got {self.drafter!r}")
        if self.drafter == "model" and self.draft_model is None:
            raise ValueError(
                "spec.drafter='model' requires spec.draft_model (a small "
                "causal LM sharing the target tokenizer)")
        if self.ngram_min < 1 or self.ngram_max < self.ngram_min:
            raise ValueError(
                f"need 1 <= ngram_min <= ngram_max, got "
                f"ngram_min={self.ngram_min}, ngram_max={self.ngram_max}")
        if not isinstance(self.gate_cooldown, int) \
                or isinstance(self.gate_cooldown, bool) \
                or self.gate_cooldown < 0:
            raise ValueError(f"spec.gate_cooldown must be an int >= 0 "
                             f"(0 disables the gate), got "
                             f"{self.gate_cooldown!r}")
        if not self.gate_low >= 0.0:
            raise ValueError(
                f"spec.gate_low must be >= 0, got {self.gate_low!r}")
        if not isinstance(self.gate_ticks, int) \
                or isinstance(self.gate_ticks, bool) or self.gate_ticks < 1:
            raise ValueError(f"spec.gate_ticks must be an int >= 1, got "
                             f"{self.gate_ticks!r}")
        if not isinstance(self.turbo_windows, int) \
                or isinstance(self.turbo_windows, bool) \
                or self.turbo_windows < 0:
            raise ValueError(f"spec.turbo_windows must be an int >= 0 "
                             f"(0 disables the turbo tier), got "
                             f"{self.turbo_windows!r}")

    def describe(self) -> Dict[str, Any]:
        """Flat JSON-safe knob dict for telemetry snapshots — records the
        speculation configuration next to the numbers it produced (an
        acceptance rate is meaningless without k and the gate settings)."""
        return {"k": self.k,
                "drafter": (self.drafter if isinstance(self.drafter, str)
                            else type(self.drafter).__name__),
                "ngram_max": self.ngram_max, "ngram_min": self.ngram_min,
                "sample_draft": self.sample_draft,
                "gate_low": self.gate_low,
                "gate_cooldown": self.gate_cooldown,
                "gate_ticks": self.gate_ticks,
                "turbo_windows": self.turbo_windows}

    def build_drafter(self, max_len: int):
        if not isinstance(self.drafter, str):
            return self.drafter
        if self.drafter == "ngram":
            return NgramDrafter(max_ngram=self.ngram_max,
                                min_ngram=self.ngram_min)
        return DraftModelDrafter(self.draft_model, max_len,
                                 sample_draft=self.sample_draft)
