"""Fleet-scale serving: N engines behind one router.

A :class:`FleetRouter` composes N unmodified
:class:`~.serving.GenerationServer` replicas — bare in-process servers
or :mod:`~.transport` handles fronting other OS processes; the router
only speaks the shared duck-typed surface — into a *service* that
survives replica loss (ROADMAP 5) — the GSPMD argument applied to
serving: scale by composing the same program, not by writing a new one.
Three mechanisms, all host-side:

- **prefix-aware routing** — each submission is scored against every
  eligible replica: chained-hash prefix overlap from the allocator's
  content-addressed cache (``BlockAllocator.probe_prefix``, a read-only
  walk that takes no refs) blended with load (queue depth + occupied
  slots from ``load_metrics()``) and admission headroom. Routing is a
  *hint*: a misroute costs prefix reuse, never correctness — which is
  what the ``route`` fault site proves.

- **health-checked membership** — per-replica liveness is driven by
  tick-progress heartbeats (``GenerationServer.steps`` must advance
  while the replica holds work) plus periodic flight-recorder watchdog
  probes, against an injectable clock. States move ``live → degraded →
  draining → dead``: degraded replicas are deprioritized by routing and
  recover after a cooldown; wedged or crashed replicas are killed and
  salvaged.

- **live token-exact migration** — ``drain()`` captures a replica via
  ``snapshot()``/``evacuate()`` and re-admits every in-flight request on
  peers through the normal restore/swap-in path
  (``GenerationServer.admit_migrated``): KV payloads ride CRC-checked
  into the peer's host pool and resume via the compile-once swap-in
  program; a payload corrupted in transit (the ``migrate_payload``
  fault site) degrades to token-exact re-prefill. A replica killed
  mid-decode (``replica_down``) is salvaged from host state only
  (``snapshot(trust_kv=False)``) — its requests re-enter peers through
  the corruption-recovery replay rung, so greedy outputs stay identical
  to an undisturbed single-engine run.

Replicas get disjoint rid spaces (``set_rid_base``) so a migrated
request can never collide with a peer's own; the router's rid IS the
replica rid, so results map back without translation.

**Disaggregated prefill/decode fleets** (ROADMAP: multi-chip serving):
replicas constructed with ``role="prefill"`` / ``role="decode"`` split
the fleet into two classes. Fresh submissions route only among the
prefill-capable class; a prefill replica runs chunked prefill, samples
the first token, parks the request (``handoff_ready``), and the
router's per-tick sweep moves it to the decode class over the SAME
CRC-verified ``evacuate(trust_kv=True, rids=...)``/``admit_migrated``
path every other migration uses — no bespoke handoff channel. Decode
replicas refuse nothing (they can re-prefill a salvaged prompt), but a
prefill replica refuses decode-phase admits at the door, so a misroute
fails loudly instead of wedging. When a prefill replica dies
mid-chunk, its requests salvage onto the decode class through the
usual host-state replay rung — token output stays identical.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .faults import EngineFailedError, FaultInjector, NULL_INJECTOR
from .scheduler import AdmissionError

__all__ = [
    "FleetRouter", "ReplicaInfo",
    "REPLICA_LIVE", "REPLICA_DEGRADED", "REPLICA_DRAINING", "REPLICA_DEAD",
    "RID_STRIDE",
]

REPLICA_LIVE = "live"
REPLICA_DEGRADED = "degraded"
REPLICA_DRAINING = "draining"
REPLICA_DEAD = "dead"

#: Each replica's rid counter starts at ``idx * RID_STRIDE`` — wide
#: enough that no replica can ever walk into a peer's space.
RID_STRIDE = 1 << 32


@dataclass
class ReplicaInfo:
    """Router-side record for one managed engine."""

    idx: int
    server: Any
    state: str = REPLICA_LIVE
    # replica class ("any" | "prefill" | "decode") — copied from the
    # engine's role at construction and NEVER mutated by health
    # transitions: a degraded prefill replica recovers as a prefill
    # replica
    role: str = "any"
    # heartbeat state (router clock / engine step counter)
    last_progress_t: float = 0.0
    last_steps: int = 0
    last_remaining: int = 0
    # last observation freshness marker from a transport-aware handle
    # (``progress_seq``); -1 = never observed, so the first sample is
    # always treated as fresh
    last_seq: int = -1
    stall_ticks: int = 0
    degraded_t: float = 0.0
    last_findings: int = 0
    # (clock, state) transition log — the observable state machine
    history: List[Tuple[float, str]] = field(default_factory=list)


#: default per-tenant latency objectives for the SLO roll-up. A request
#: "attains" when its TTFT / TPOT lands at or under the objective;
#: ``target`` is the attainment goal, so the error budget is
#: ``1 - target`` and ``burn_rate = violating_fraction / (1 - target)``
#: — 1.0 means the tenant is consuming its budget exactly, > 1.0 means
#: the budget will exhaust before the window rolls over. ``window`` is
#: the rolling per-tenant sample count the roll-up looks back over.
DEFAULT_SLO = {"ttft_s": 1.0, "tpot_ms": 200.0, "target": 0.95,
               "window": 256}


class FleetRouter:
    """Prefix-aware, health-checked router over in-process replicas.

    Usage::

        fleet = FleetRouter([srv0, srv1])
        rid = fleet.submit([1, 5, 9], max_new_tokens=16)
        out = fleet.run()          # drain all replicas
        tokens = out[rid]

    ``servers`` must be FRESH (nothing submitted), paged, and
    configuration-homogeneous — the same compiled-shape fingerprint
    everywhere is what makes any replica a valid migration target for
    any other. All timing flows through ``clock`` (injectable; default
    ``time.monotonic``) so chaos replays stay deterministic.
    """

    def __init__(self, servers: Sequence[Any], *,
                 clock: Callable[[], float] = time.monotonic,
                 faults: Optional[FaultInjector] = None,
                 registry=None,
                 prefix_weight: float = 1.0,
                 load_weight: Optional[float] = None,
                 degraded_penalty: float = 1e6,
                 probe_every: int = 16,
                 stall_ticks_degraded: int = 8,
                 stall_ticks_dead: int = 64,
                 heartbeat_timeout_s: Optional[float] = None,
                 degrade_cooldown_s: float = 0.0,
                 slos: Optional[Dict[str, Dict[str, float]]] = None):
        if not servers:
            raise ValueError("FleetRouter needs at least one server")
        if faults is None:
            self._faults = NULL_INJECTOR
        elif isinstance(faults, FaultInjector):
            self._faults = faults
        else:
            raise ValueError(
                f"faults must be None or a FaultInjector, got {faults!r}")
        self.faults = self._faults
        self._clock = clock
        want = None
        for i, srv in enumerate(servers):
            if srv.cache_mode != "paged":
                raise ValueError(
                    f"replica {i} has cache={srv.cache_mode!r} — fleet "
                    f"migration needs the paged per-request KV capture")
            fp = dict(srv._snapshot_fingerprint())
            fp.pop("num_blocks")  # may differ; restore checks >= per move
            if want is None:
                want = fp
            elif fp != want:
                raise ValueError(
                    f"replica {i} config differs from replica 0 — fleet "
                    f"replicas must be homogeneous so any replica can "
                    f"receive any migration ({fp!r} vs {want!r})")
            srv.set_rid_base(i * RID_STRIDE)
        #: the fleet's homogeneity fingerprint (num_blocks excluded) —
        #: every later ``add_replica`` must match it exactly
        self._fp_want = want
        roles = [getattr(srv, "role", "any") for srv in servers]
        #: True when any replica declared a class — the fleet then runs
        #: disaggregated: submissions route to the prefill class, the
        #: per-tick handoff sweep moves finished prefills to decode.
        self.disagg = any(r != "any" for r in roles)
        if self.disagg:
            if not any(r in ("prefill", "any") for r in roles):
                raise ValueError(
                    "disaggregated fleet has no prefill-capable replica "
                    "— nothing could ever accept a submission")
            if not any(r in ("decode", "any") for r in roles):
                raise ValueError(
                    "disaggregated fleet has no decode-capable replica "
                    "— finished prefills would park forever")
        now = self._clock()
        self._replicas = [ReplicaInfo(idx=i, server=srv, role=roles[i],
                                      last_progress_t=now,
                                      history=[(now, REPLICA_LIVE)])
                          for i, srv in enumerate(servers)]
        self.prefix_weight = float(prefix_weight)
        self.load_weight = (float(load_weight) if load_weight is not None
                            else float(servers[0].block_size))
        self.degraded_penalty = float(degraded_penalty)
        self.probe_every = int(probe_every)
        self.stall_ticks_degraded = int(stall_ticks_degraded)
        self.stall_ticks_dead = int(stall_ticks_dead)
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.degrade_cooldown_s = float(degrade_cooldown_s)
        self._ticks = 0
        self._home: Dict[int, int] = {}        # rid -> replica idx
        self._results: Dict[int, List[int]] = {}
        self._dropped: Dict[int, str] = {}
        # per-request migration latency samples (seconds on the injected
        # clock), covering evacuate→absorb→admit for handoffs, drains and
        # failovers alike; bounded so a long-lived router can't grow it
        self._migration_lat: List[float] = []
        self._migration_lat_cap = 4096
        self._handoff_requests = 0
        if registry is None:
            from .telemetry import MetricsRegistry

            registry = MetricsRegistry()
        self.registry = registry
        self._c_routed = registry.counter(
            "fleet_requests_routed",
            "submissions routed to a replica (replica label)")
        self._c_misroutes = registry.counter(
            "fleet_route_misroutes",
            "submissions deliberately misrouted by an injected route fault")
        self._c_migrated = registry.counter(
            "fleet_migrated_requests",
            "requests re-admitted on a peer (phase label: kv/queued)")
        self._c_migrations = registry.counter(
            "fleet_migrations",
            "replica evacuations performed (reason label: drain/failover)")
        self._c_deaths = registry.counter(
            "fleet_replica_deaths",
            "replicas removed from membership (reason label)")
        self._c_drains = registry.counter(
            "fleet_drains", "graceful drains completed")
        self._c_corrupt = registry.counter(
            "fleet_migrate_corruptions",
            "migrating payloads corrupted in transit (injected; the "
            "receiver's CRC check downgrades each to re-prefill)")
        self._c_degraded = registry.counter(
            "fleet_degraded_events",
            "live->degraded transitions (kind label)")
        self._c_stalls = registry.counter(
            "fleet_heartbeat_stalls",
            "router ticks a replica held work without progressing")
        self._c_quarantined = registry.counter(
            "fleet_quarantined_requests",
            "requests with no surviving migration target (terminal)")
        self._c_handoffs = registry.counter(
            "fleet_prefill_handoffs",
            "prefill→decode handoff sweeps performed (replica label)")
        self._c_warm_migrated = registry.counter(
            "fleet_migrated_warm_blocks",
            "warm-tier prefix blocks adopted by a peer during migration "
            "(a shared prompt prefilled once survives its replica)")
        # per-tenant SLO objectives: ``slos`` maps tenant → overrides of
        # DEFAULT_SLO; the "default" entry re-bases every other tenant
        base_slo = dict(DEFAULT_SLO)
        if slos and "default" in slos:
            base_slo.update(slos["default"])
        self._slo_default = base_slo
        self._slo_overrides = {t: dict(base_slo, **ov)
                               for t, ov in (slos or {}).items()
                               if t != "default"}

    # ---------------------------------------------------------------- routing
    def _eligible(self) -> List[ReplicaInfo]:
        return [r for r in self._replicas
                if r.state in (REPLICA_LIVE, REPLICA_DEGRADED)]

    @staticmethod
    def _prefill_capable(rep: ReplicaInfo) -> bool:
        return rep.role in ("prefill", "any")

    @staticmethod
    def _decode_capable(rep: ReplicaInfo) -> bool:
        return rep.role in ("decode", "any")

    def _score(self, rep: ReplicaInfo, prompt: Sequence[int]) -> float:
        """Routing score: cached-prefix tokens minus load, minus a large
        penalty for degraded replicas. Read-only on the replica."""
        srv = rep.server
        hits = srv.probe_prefix(prompt)
        lm = srv.load_metrics()
        score = (self.prefix_weight * hits * srv.block_size
                 - self.load_weight * (lm["queue_depth"]
                                       + lm["slots_occupied"]))
        if lm.get("blocks_headroom", 1) <= 0:
            score -= 4.0 * self.load_weight   # admission-headroom pressure
        if rep.state == REPLICA_DEGRADED:
            score -= self.degraded_penalty
        return score

    def _route(self, prompt: Sequence[int]) -> List[ReplicaInfo]:
        """Eligible replicas in routing-preference order (best first).
        In a disaggregated fleet only the prefill class is scored — a
        fresh submission always starts with chunked prefill, so scoring
        decode-class peers would just misroute it into a replica whose
        output must immediately hand off right back. If the whole
        prefill class is down, submissions fall through to the decode
        class (which re-prefills — the degradation ladder, not a new
        path). An injected ``route`` fault reverses the preference — a
        misroute must only cost prefix reuse, never correctness."""
        reps = self._eligible()
        if not reps:
            raise EngineFailedError(
                "no live replicas — the fleet is fully dead or draining")
        if self.disagg:
            pre = [r for r in reps if self._prefill_capable(r)]
            reps = pre or [r for r in reps if self._decode_capable(r)]
        reps = sorted(reps, key=lambda r: (-self._score(r, prompt), r.idx))
        if self._faults.fire("route") is not None:
            self._c_misroutes.inc()
            reps = list(reversed(reps))
        return reps

    def submit(self, prompt: Sequence[int], **kw) -> int:
        """Route one request to the best replica; same keyword surface
        as :meth:`~.serving.GenerationServer.submit`, same rid contract
        (the replica's rid IS the fleet rid — spaces are disjoint).
        Falls through to the next-best replica on
        :class:`~.scheduler.AdmissionError` backpressure; re-raises only
        when every eligible replica refused."""
        last: Optional[AdmissionError] = None
        for rep in self._route(prompt):
            try:
                rid = rep.server.submit(prompt, **kw)
            except AdmissionError as e:
                last = e
                continue
            self._home[rid] = rep.idx
            self._c_routed.inc(replica=str(rep.idx))
            return rid
        raise last if last is not None else EngineFailedError(
            "no live replicas accepted the request")

    # ----------------------------------------------------------------- health
    def _set_state(self, rep: ReplicaInfo, state: str) -> None:
        if rep.state != state:
            rep.state = state
            rep.history.append((self._clock(), state))

    def _degrade(self, rep: ReplicaInfo, kind: str) -> None:
        if rep.state == REPLICA_LIVE:
            self._set_state(rep, REPLICA_DEGRADED)
            rep.degraded_t = self._clock()
            self._c_degraded.inc(kind=kind)

    def _kill(self, rep: ReplicaInfo, reason: str) -> None:
        """Remove a replica from membership and fail over: poison the
        engine, salvage its in-flight requests from host state (device
        KV is untrusted after a crash) and re-admit them on peers."""
        rep.server.fail(f"fleet: {reason}")
        self._set_state(rep, REPLICA_DEAD)
        self._c_deaths.inc(reason=reason.split(":")[0])
        t0 = self._clock()
        snap = rep.server.evacuate(trust_kv=False)
        self._absorb(snap)
        moved = self._migrate(snap, exclude=rep.idx, reason="failover")
        self._record_migration_latency(self._clock() - t0, moved)

    def _heartbeat(self, rep: ReplicaInfo, remaining: int) -> None:
        """Tick-progress liveness: a replica holding work must advance
        its step counter; one that doesn't accrues stall ticks →
        degraded → dead. Clock-based timeout (``heartbeat_timeout_s``)
        rides the same injectable clock."""
        steps = rep.server.steps
        now = self._clock()
        # transport-aware handles stamp every observation with a
        # monotone ``progress_seq``; when no FRESH sample has crossed
        # the boundary since the last heartbeat, a repeated step count
        # is *staleness*, not a stall — charging it would let ordinary
        # transport round-trip latency degrade a healthy remote
        # replica. In-process servers have no such attribute and keep
        # the original always-fresh accounting.
        seq = getattr(rep.server, "progress_seq", None)
        if seq is not None:
            if seq == rep.last_seq:
                return
            rep.last_seq = seq
        progressed = (steps != rep.last_steps
                      or remaining < rep.last_remaining)
        if remaining and not progressed:
            rep.stall_ticks += 1
            self._c_stalls.inc()
            timed_out = (self.heartbeat_timeout_s is not None
                         and now - rep.last_progress_t
                         > self.heartbeat_timeout_s)
            if rep.stall_ticks >= self.stall_ticks_dead or (
                    timed_out and rep.state == REPLICA_DEGRADED):
                self._kill(rep, "heartbeat: wedged with work")
                return
            if rep.stall_ticks >= self.stall_ticks_degraded or timed_out:
                self._degrade(rep, "heartbeat_stall")
        else:
            rep.stall_ticks = 0
            if progressed:
                rep.last_progress_t = now
            if (rep.state == REPLICA_DEGRADED
                    and now - rep.degraded_t >= self.degrade_cooldown_s):
                self._set_state(rep, REPLICA_LIVE)
        rep.last_steps = steps
        rep.last_remaining = remaining

    def _probe_watchdog(self, rep: ReplicaInfo) -> None:
        """Flight-recorder probe: any watchdog finding (preemption storm,
        pool-pressure stall, steady-state recompile) flips the replica
        degraded so routing sheds load off it while it recovers."""
        try:
            findings = rep.server.watchdog_findings()
        except Exception:
            return
        # degrade on NEW findings only: the flight dump is cumulative
        # over the ring, and re-penalizing one old storm forever would
        # pin the replica degraded long after it recovered
        if len(findings) > rep.last_findings:
            self._degrade(rep, findings[-1].get("kind", "watchdog"))
        rep.last_findings = len(findings)

    # --------------------------------------------------------------- stepping
    def step(self) -> int:
        """One router tick: probe health, advance every live/degraded
        replica one engine step, harvest results; returns total work
        remaining across the fleet. The ``replica_down`` fault site
        fires once per probed replica per tick (ordinal = probe count),
        so a seeded plan kills a deterministic (tick, replica) pair
        mid-decode."""
        self._ticks += 1
        for rep in self._replicas:
            if rep.state in (REPLICA_DEAD, REPLICA_DRAINING):
                continue
            # sweep BEFORE stepping: requests parked last tick leave
            # before this step runs, so a prefill replica whose only
            # work is parked never reads as "holding work without
            # progressing" to the heartbeat below
            if rep.role == "prefill":
                self._sweep_handoff(rep)
            if self._faults.fire("replica_down") is not None:
                self._kill(rep, "injected replica_down")
                continue
            try:
                remaining = rep.server.step()
            except Exception as e:
                rep.server.fail(f"step raised: {e!r}")
                self._kill(rep, f"step_error: {type(e).__name__}")
                continue
            self._heartbeat(rep, remaining)
            if rep.state == REPLICA_DEAD:
                continue
            if self.probe_every and self._ticks % self.probe_every == 0:
                self._probe_watchdog(rep)
            self._results.update(rep.server.take_results())
        # recount AFTER the sweep, not during: a replica killed mid-loop
        # salvages its requests onto peers that may already have stepped
        # this tick, and their step() return would undercount — run()
        # must not stop while migrated work sits queued on a survivor
        total = 0
        for rep in self._eligible():
            lm = rep.server.load_metrics()
            total += lm["queue_depth"] + lm["slots_occupied"]
        return total

    def run(self) -> Dict[int, List[int]]:
        """Drain every replica; returns {rid: prompt+generated ids}
        merged across the fleet (rid spaces are disjoint)."""
        while self.step():
            pass
        for rep in self._replicas:
            if rep.state == REPLICA_DEAD:
                # evacuated at death — finished work already folded into
                # the router's ledgers, and a dead PROCESS has no socket
                # left to ask
                continue
            self._results.update(rep.server.take_results())
        out, self._results = self._results, {}
        return out

    # -------------------------------------------------------------- migration
    def _sweep_handoff(self, rep: ReplicaInfo) -> int:
        """Move every request this prefill replica has parked
        (``handoff_ready``) to the decode class: a partial
        ``evacuate(trust_kv=True, rids=...)`` captures ONLY the parked
        requests — the replica keeps streaming its other prompts — and
        ``_migrate`` re-admits each KV payload on the best decode peer
        through the standard CRC-verified path. Returns requests moved."""
        rids = rep.server.handoff_ready()
        if not rids:
            return 0
        t0 = self._clock()
        snap = rep.server.evacuate(trust_kv=True, rids=rids)
        self._absorb(snap)
        moved = self._migrate(snap, exclude=rep.idx, reason="handoff")
        self._record_migration_latency(self._clock() - t0, moved)
        self._handoff_requests += moved
        self._c_handoffs.inc(replica=str(rep.idx))
        return moved

    def _record_migration_latency(self, dt: float, moved: int) -> None:
        if moved <= 0:
            return
        lat = self._migration_lat
        lat.extend([dt] * moved)
        if len(lat) > self._migration_lat_cap:
            del lat[:len(lat) - self._migration_lat_cap]

    def _absorb(self, snap: Dict[str, Any]) -> None:
        """Fold an evacuated replica's finished work into the router's
        ledgers so ``status``/``run`` keep answering for it."""
        self._results.update(
            {int(r): list(t) for r, t in snap["results"].items()})
        self._dropped.update(snap["dropped"])

    def _migrate(self, snap: Dict[str, Any], *, exclude: int,
                 reason: str) -> int:
        """Re-admit every captured request on the best-scoring peer
        through the normal restore/swap-in path. KV payloads pass the
        ``migrate_payload`` fault site on the way (an injected bit-flip
        is caught by the receiver's CRC check and degrades to
        re-prefill). Requests with no surviving target are quarantined,
        not silently dropped."""
        self._c_migrations.inc(reason=reason)
        moved = 0
        for d in sorted(snap["requests"], key=lambda d: d["sched"]["seq"]):
            targets = [r for r in self._eligible() if r.idx != exclude]
            if self.disagg:
                # class-aware targeting: decode-phase payloads (a KV
                # handoff, or anything that already generated tokens)
                # MUST land on the decode class — a prefill replica
                # refuses them at the door; pure-prompt payloads prefer
                # the prefill class but fall back to decode, which
                # re-prefills (the chaos-kill salvage path)
                decode_phase = (d["phase"] == "kv"
                                or bool(d.get("generated")))
                if decode_phase:
                    targets = [r for r in targets
                               if self._decode_capable(r)]
                else:
                    pre = [r for r in targets
                           if self._prefill_capable(r)]
                    targets = pre or [r for r in targets
                                      if self._decode_capable(r)]
            if not targets:
                self._dropped[int(d["rid"])] = "failed"
                self._c_quarantined.inc()
                continue
            target = min(targets,
                         key=lambda r: (-self._score(r, d["prompt"]),
                                        r.idx))
            if d["phase"] == "kv":
                if self._faults.fire("migrate_payload") is not None:
                    # snapshot arrays are read-only device views; the
                    # corrupted copy keeps the ORIGINAL checksum, so the
                    # receiver's CRC verify must catch the flip
                    d["kv"]["arrays"] = [np.array(a)
                                         for a in d["kv"]["arrays"]]
                    self._faults.corrupt(d["kv"]["arrays"])
                    self._c_corrupt.inc()
            target.server.admit_migrated(d, source_config=snap["config"])
            self._home[int(d["rid"])] = target.idx
            self._c_migrated.inc(phase=d["phase"])
            moved += 1
        warm = snap.get("warm_tier") or []
        if warm:
            # offer the dead/draining replica's warm prefix blocks to ONE
            # surviving peer (prefill-capable preferred — promotion
            # happens at admission), least loaded first; adopt_warm CRC-
            # verifies per entry, so a corrupted payload just misses
            targets = [r for r in self._eligible() if r.idx != exclude]
            if self.disagg:
                pre = [r for r in targets if self._prefill_capable(r)]
                targets = pre or targets
            if targets:
                def _load(r):
                    lm = r.server.load_metrics()
                    return (lm["queue_depth"] + lm["slots_occupied"], r.idx)
                adopted = min(targets, key=_load).server.adopt_warm(warm)
                if adopted:
                    self._c_warm_migrated.inc(adopted)
        return moved

    def drain(self, idx: int) -> int:
        """Gracefully drain replica ``idx``: stop routing to it, migrate
        every in-flight request (KV payloads included — this is the
        trusted-device path) to peers, then retire it. Returns the
        number of requests migrated."""
        rep = self._replicas[idx]
        if rep.state == REPLICA_DEAD:
            raise ValueError(f"replica {idx} is already dead")
        self._set_state(rep, REPLICA_DRAINING)
        t0 = self._clock()
        snap = rep.server.evacuate(trust_kv=True)
        self._absorb(snap)
        moved = self._migrate(snap, exclude=idx, reason="drain")
        self._record_migration_latency(self._clock() - t0, moved)
        self._set_state(rep, REPLICA_DEAD)
        self._c_drains.inc()
        return moved

    def kill(self, idx: int, reason: str = "operator kill") -> None:
        """Forcibly remove replica ``idx`` as if it crashed: poison the
        engine and fail its requests over to peers via host-state
        salvage (the deterministic twin of the ``replica_down`` fault)."""
        rep = self._replicas[idx]
        if rep.state == REPLICA_DEAD:
            raise ValueError(f"replica {idx} is already dead")
        self._kill(rep, reason)

    # ------------------------------------------------------------- elasticity
    def add_replica(self, server: Any) -> int:
        """Grow the fleet by one FRESH replica mid-flight — the
        autoscaler's scale-up primitive. The newcomer passes the same
        gate the constructor applies (paged, fingerprint-homogeneous,
        nothing submitted) and gets the next disjoint rid space; it is
        immediately live and routable, and every in-flight rid keeps
        its meaning. Returns the new replica index."""
        if server.cache_mode != "paged":
            raise ValueError(
                f"new replica has cache={server.cache_mode!r} — fleet "
                f"migration needs the paged per-request KV capture")
        fp = dict(server._snapshot_fingerprint())
        fp.pop("num_blocks")
        if fp != self._fp_want:
            raise ValueError(
                f"new replica config differs from the fleet — replicas "
                f"must stay homogeneous so any replica can receive any "
                f"migration ({fp!r} vs {self._fp_want!r})")
        idx = len(self._replicas)
        server.set_rid_base(idx * RID_STRIDE)
        now = self._clock()
        rep = ReplicaInfo(idx=idx, server=server,
                          role=getattr(server, "role", "any"),
                          last_progress_t=now,
                          history=[(now, REPLICA_LIVE)])
        self._replicas.append(rep)
        # adding a classed replica can flip the fleet disaggregated;
        # the constructor's capability invariants can only get easier
        self.disagg = any(r.role != "any" for r in self._replicas)
        return idx

    def live_indices(self) -> List[int]:
        """Indices currently accepting work (live or degraded) — the
        autoscaler's census of drainable/routable capacity."""
        return [r.idx for r in self._replicas
                if r.state in (REPLICA_LIVE, REPLICA_DEGRADED)]

    # ------------------------------------------------------------ observation
    def status(self, rid: int) -> str:
        """Fleet-wide request status — the router's ledgers first (they
        answer for dead replicas), then the request's home replica."""
        if rid in self._results:
            return "done"
        if rid in self._dropped:
            return self._dropped[rid]
        idx = self._home.get(rid)
        if idx is None:
            return "unknown"
        return self._replicas[idx].server.status(rid)

    def cancel(self, rid: int) -> bool:
        """Cancel on the request's current home replica."""
        idx = self._home.get(rid)
        if idx is None or rid in self._results or rid in self._dropped:
            return False
        return self._replicas[idx].server.cancel(rid)

    def replica_states(self) -> List[str]:
        return [r.state for r in self._replicas]

    def assert_conserved(self) -> Dict[int, Dict[str, int]]:
        """Run every engine's conservation audit (dead replicas were
        evacuated, so theirs must hold trivially); returns the audited
        numbers per replica index."""
        return {r.idx: r.server.assert_conserved()
                for r in self._replicas}

    def _slo_for(self, tenant: str) -> Dict[str, float]:
        return self._slo_overrides.get(tenant, self._slo_default)

    def slo_rollup(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant TTFT/TPOT SLO attainment and burn rate, rolled up
        across every replica's registry.

        Reads the tenant-labeled ``serving_ttft_s`` / ``serving_tpot_ms``
        histograms each replica's server already records (dead replicas
        included — their completed requests still count against the
        tenant's budget), keeps the last ``window`` samples per tenant,
        and computes ``attainment`` (fraction at or under the objective)
        and ``burn_rate`` (violating fraction / error budget). The
        ``fleet_slo_{ttft,tpot}_{attainment,burn_rate}{tenant=...}``
        gauges land in ``self.registry`` — so the Prometheus exposition
        carries them — and the same rows come back as the ``slo`` key of
        :meth:`fleet_metrics`, which is what a canary-promotion gate
        polls."""
        reg = self.registry
        gathered: Dict[str, Dict[str, List[float]]] = {}
        for rep in self._replicas:
            try:
                obs = rep.server.slo_observations()
            except Exception:
                # a replica whose PROCESS is gone can't ship samples —
                # its completed requests were already harvested; an
                # in-process dead replica still answers from host state
                continue
            for key in ("ttft", "tpot"):
                for tenant, samples in sorted((obs.get(key) or {}).items()):
                    w = int(self._slo_for(tenant)["window"])
                    gathered.setdefault(
                        tenant, {"ttft": [], "tpot": []})[key].extend(
                        list(samples)[-w:])
        out: Dict[str, Dict[str, Any]] = {}
        for tenant in sorted(gathered):
            slo = self._slo_for(tenant)
            budget = max(1e-9, 1.0 - float(slo["target"]))
            row: Dict[str, Any] = {"target": float(slo["target"]),
                                   "window": int(slo["window"])}
            for key, obj_key in (("ttft", "ttft_s"), ("tpot", "tpot_ms")):
                objective = float(slo[obj_key])
                samples = gathered[tenant][key][-int(slo["window"]):]
                viol = (sum(1 for v in samples if v > objective)
                        / len(samples)) if samples else 0.0
                attain = 1.0 - viol
                burn = viol / budget
                row[key] = {"objective": objective,
                            "samples": len(samples),
                            "attainment": attain, "burn_rate": burn}
                reg.gauge(
                    f"fleet_slo_{key}_attainment",
                    f"fraction of the rolling window at or under the "
                    f"{key} objective (tenant label)").set(
                    attain, tenant=tenant)
                reg.gauge(
                    f"fleet_slo_{key}_burn_rate",
                    f"{key} violating fraction / error budget; > 1 "
                    f"exhausts the budget (tenant label)").set(
                    burn, tenant=tenant)
                reg.gauge(
                    f"fleet_slo_{key}_objective",
                    f"configured {key} objective "
                    f"({'seconds' if key == 'ttft' else 'ms'}; "
                    f"tenant label)").set(objective, tenant=tenant)
            out[tenant] = row
        return out

    def fleet_metrics(self) -> Dict[str, Any]:
        """Sync the ``fleet_*`` gauges and return the fleet view: state
        census, router counters, per-tenant SLO roll-up (``slo`` key),
        and one row per replica (state, load, prefix-cache
        effectiveness, routed share) — the ``serving_benchmark
        --fleet N`` table."""
        reg = self.registry
        census = {s: 0 for s in (REPLICA_LIVE, REPLICA_DEGRADED,
                                 REPLICA_DRAINING, REPLICA_DEAD)}
        rows = []
        for rep in self._replicas:
            census[rep.state] += 1
            srv = rep.server
            try:
                lm = srv.load_metrics()
                ks = srv.kv_stats()
            except Exception:
                # a dead process answers nothing; report its row empty
                lm, ks = {"queue_depth": 0, "slots_occupied": 0}, {}
            row = {"replica": rep.idx, "state": rep.state,
                   "role": rep.role,
                   "steps": srv.steps,
                   "queue_depth": lm["queue_depth"],
                   "slots_occupied": lm["slots_occupied"],
                   "blocks_headroom": lm.get("blocks_headroom", 0),
                   "prefix_hit_rate": ks.get("prefix_hit_rate", 0.0),
                   "routed": int(self._c_routed.total(
                       where={"replica": str(rep.idx)})),
                   "stall_ticks": rep.stall_ticks,
                   "transitions": [s for _, s in rep.history]}
            rows.append(row)
            reg.gauge("fleet_replica_queue_depth",
                      "per-replica queue depth").set(
                float(lm["queue_depth"]), replica=str(rep.idx))
            reg.gauge("fleet_replica_slots_occupied",
                      "per-replica occupied slots").set(
                float(lm["slots_occupied"]), replica=str(rep.idx))
            reg.gauge("fleet_replica_up",
                      "1 while the replica accepts work").set(
                1.0 if rep.state in (REPLICA_LIVE, REPLICA_DEGRADED)
                else 0.0, replica=str(rep.idx))
        for s, n in census.items():
            reg.gauge(f"fleet_replicas_{s}",
                      f"replicas in state {s}").set(float(n))
        up = [r for r in self._replicas
              if r.state in (REPLICA_LIVE, REPLICA_DEGRADED)]
        lat = sorted(self._migration_lat)

        def _pct(p: float) -> float:
            if not lat:
                return 0.0
            return lat[min(len(lat) - 1, int(p * len(lat)))]

        return {"replicas": rows, "states": census,
                "slo": self.slo_rollup(),
                "disagg": self.disagg,
                "prefill_replicas": sum(r.role == "prefill" for r in up),
                "decode_replicas": sum(r.role == "decode" for r in up),
                "handoffs": int(self._c_handoffs.total()),
                "handoff_requests": self._handoff_requests,
                "migration_latency_p50_s": _pct(0.50),
                "migration_latency_p95_s": _pct(0.95),
                "migration_latency_samples": len(lat),
                "ticks": self._ticks,
                "routed": int(self._c_routed.total()),
                "misroutes": int(self._c_misroutes.total()),
                "migrations": int(self._c_migrations.total()),
                "migrated_requests": int(self._c_migrated.total()),
                "migrated_kv": int(self._c_migrated.total(
                    where={"phase": "kv"})),
                "migrated_warm_blocks": int(self._c_warm_migrated.total()),
                "migrate_corruptions": int(self._c_corrupt.total()),
                "deaths": int(self._c_deaths.total()),
                "drains": int(self._c_drains.total()),
                "degraded_events": int(self._c_degraded.total()),
                "heartbeat_stalls": int(self._c_stalls.total()),
                "quarantined": int(self._c_quarantined.total())}
