"""Paged serving executor — the device half of the engine/executor split.

``GenerationServer`` (serving.py) is the ENGINE: request lifecycle,
scheduling, slot bookkeeping, preemption policy, harvest — all host-side
numpy state. :class:`PagedExecutor` is everything that touches the
accelerator: the KV block pools, the compiled programs (chunked prefill,
decode window, both speculative verify paths), and — new in this layer —
their placement onto a multi-chip ``tp`` mesh.

The split is the roadmap's TP unlock: the engine's host loop is mesh-
oblivious (block tables, positions, sampling params are tiny replicated
arrays), so multi-chip serving is PURELY an executor concern. With
``tp > 1`` the executor places params, KV pools, int8 scale rows, and the
LoRA page pool onto a 1-D ``tp`` mesh (parallel/serving_mesh.py) and jits
the very same program bodies — GSPMD slices the attention heads and MLP
hidden dim and inserts the collectives, keeping each trip ONE compiled
program (the XLA fusion argument from PAPERS.md). Per-shard pools share
the engine's single host-side block table: every shard holds its kv-head
slice of every block, so block ids, prefix hashes, swap payloads, and
snapshots stay tp-agnostic.

Compile discipline is unchanged: programs are keyed on shapes + the two
static args (greedy, trip length); pool donation rotates buffers in
place. The executor additionally guarantees donation never silently
drops the tp layout (:meth:`shard_audit`, wired into
``GenerationServer.assert_conserved``).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..jit import functional_call

__all__ = ["PagedExecutor"]


class PagedExecutor:
    """Owns the paged device state + compiled programs for one engine.

    ``engine`` is the owning :class:`~.serving.GenerationServer`; the
    executor reads its construction-time configuration (model, spec/LoRA
    wiring, tick window) and nothing else — all mutable scheduling state
    stays on the engine side. ``tp=1`` (or None) is the single-chip
    executor, byte-for-byte the pre-split behavior.
    """

    def __init__(self, engine, num_blocks: int, tp: Optional[int] = None,
                 cp: Optional[int] = None):
        from ..framework.dtype import convert_dtype

        self.engine = engine
        cfg = engine.cfg
        kv = cfg.num_key_value_heads
        d = cfg.hidden_size // cfg.num_attention_heads
        cdtype = convert_dtype(cfg.dtype)
        bs = engine.block_size
        kv_quant = engine.kv_quant
        if kv_quant == "int8":
            # per layer: K codes, K scales, V codes, V scales — the
            # scale rows ride in the flat pool list so donation and
            # in-place updates cover them too
            self.pools: List[Any] = []
            for _ in range(cfg.num_hidden_layers):
                for _kv in range(2):
                    self.pools.append(jnp.zeros(
                        (int(num_blocks), bs, kv, d), jnp.int8))
                    self.pools.append(jnp.zeros(
                        (int(num_blocks), kv), jnp.float32))
        else:
            self.pools = [jnp.zeros((int(num_blocks), bs, kv, d), cdtype)
                          for _ in range(2 * cfg.num_hidden_layers)]
        # tensors per layer entry in the flat pool list: fp (K, V) = 2;
        # int8 (Kq, Kscale, Vq, Vscale) = 4
        self.pool_stride = 4 if kv_quant == "int8" else 2

        self.mesh = None
        self.tp = 1
        self.cp = 1
        tp = 1 if tp is None else int(tp)
        cp = 1 if cp is None else int(cp)
        if tp > 1 or cp > 1:
            from ..parallel import serving_mesh as sm

            if tp > 1:
                sm.validate_tp(cfg, tp)
            sm.validate_cp(cp, engine.prefill_chunk)
            self.mesh = sm.build_serving_mesh(tp, cp)
            self.tp = tp
            self.cp = cp
            # construction-time placement is the ONLY transfer the tp
            # path adds: params + pools commit to the mesh once, then
            # every program's outputs inherit the layout via donation
            engine.params = sm.place_params(engine.model, engine.params,
                                            self.mesh)
            self.pools = sm.place_pools(self.pools, self.mesh)
            if engine._lora is not None:
                lp = engine._lora
                lp.place_device_tensors(
                    lambda flat: sm.place_lora_flat(lp.targets, flat,
                                                    self.mesh))

        # megakernel (kernels="megakernel"): the structural/shape guard
        # runs EAGERLY here — every deciding shape is static at
        # construction, so the megakernel→pallas rung of the dispatch
        # ladder resolves once, not per trace. On rejection the reason is
        # recorded and the per-layer programs compile exactly as before.
        self.megakernel = False
        self.megakernel_reason: Optional[str] = None
        self._mk_geometry = None
        self._mk_weights = None
        # per-layer kernel geometry, resolved by the engine ctor from
        # the installed winner cache (autotune/kernel_geometry.py) —
        # recorded here like _mk_geometry so the executor's compiled
        # programs are attributable to the schedules they traced under
        self.kernel_geometry = dict(getattr(engine, "kernel_geometry",
                                            None) or {})
        from .. import ops

        if ops.use_megakernel():
            from ..ops import decode_megakernel as mk

            geom = engine.mk_geometry or mk.MegakernelGeometry()
            reason = mk.megakernel_supported(
                engine.model, cfg, tp=self.tp, cp=self.cp, block_size=bs,
                geometry=geom, lora=engine._lora is not None)
            if reason is None:
                self.megakernel = True
                self._mk_geometry = geom
                # one-time (L, in, out) stacks become closure constants
                # of the jitted decode programs (XLA parameters, not
                # baked into the executable). This DOUBLES the served
                # model's weight HBM — the per-layer params stay alive
                # for prefill — the megakernel's documented tradeoff.
                self._mk_weights = mk.stack_layer_weights(engine.model)
            else:
                self.megakernel_reason = reason

        # ``greedy`` (the trailing static arg) specializes the program
        # for all-temp-0 ticks: XLA folds the whole sampling pipeline
        # (top-k/top-p filtering = per-row sorts over the vocab) down
        # to one argmax — measured ~2.3ms/window at CPU bench shapes.
        # At most two variants ever compile (greedy / mixed).
        decode_body = (self._decode_megakernel_fn if self.megakernel
                       else self._decode_paged_fn)
        self.decode_paged = jax.jit(decode_body,
                                    donate_argnums=(2,),
                                    static_argnums=(12, 13))
        self.chunk_prefill = jax.jit(self._chunk_prefill_fn,
                                     donate_argnums=(2,))
        self.spec_scan = None
        self.spec_verify = None
        if engine.spec is not None:
            if engine._spec_fused:
                scan_body = (self._spec_scan_megakernel_fn
                             if self.megakernel else self._spec_scan_fn)
                self.spec_scan = jax.jit(scan_body,
                                         donate_argnums=(2,),
                                         static_argnums=(13, 14))
            else:
                verify_body = (self._spec_verify_megakernel_fn
                               if self.megakernel
                               else self._spec_verify_fn)
                self.spec_verify = jax.jit(verify_body,
                                           donate_argnums=(3,),
                                           static_argnums=(14,))

    # ----------------------------------------------------------- mesh state
    @property
    def mesh_fingerprint(self) -> str:
        from ..parallel import serving_mesh as sm

        return sm.mesh_fingerprint(self.mesh)

    def shard_audit(self) -> Dict[str, int]:
        """Verify the pools still carry their tp layout (donation must
        rotate buffers, never reshard them) — {} on a single-chip
        executor. Raises AssertionError on a lost sharding."""
        if self.mesh is None:
            return {}
        from ..parallel import serving_mesh as sm

        return sm.audit_pool_shardings(self.pools, self.mesh)

    # ------------------------------------------------------------ pool views
    def _pool_views(self, flat_p):
        """Group the flat per-layer pool list back into per-layer tuples:
        fp → (K, V); int8 → (Kq, Kscale, Vq, Vscale). The model's paged
        methods branch on the tuple arity, so the same compiled-fn bodies
        serve both pool formats."""
        st = self.pool_stride
        return [tuple(Tensor(flat_p[st * i + j]) for j in range(st))
                for i in range(self.engine.cfg.num_hidden_layers)]

    @staticmethod
    def _flat_pools(new):
        return [t.value for entry in new for t in entry]

    def _gather_lora(self, lora_flat, aidx):
        """Gather each row's adapter factors from the paged LoRA pool —
        one batched take per stacked tensor, inside the compiled program.
        ``lora_flat`` is empty when LoRA is off → None (the model's paged
        methods skip the delta entirely)."""
        if not lora_flat:
            return None
        return self.engine._lora.gather_rows(list(lora_flat), aidx)

    # ------------------------------------------------------------- programs
    def _decode_paged_fn(self, params, tokens, flat_pools, tables, pos,
                         temps, topks, topps, active, key, aidx=None,
                         lora_flat=(), greedy=False, ticks=None):
        """Paged decode window: K/V reads/writes go through per-slot
        block tables into the shared pool. ``tables``: int32
        (B, table_width) — the engine zeroes rows of idle/prefilling slots
        so their masked ticks write only the scratch block. ``greedy`` is
        STATIC (jit cache key): True promises every active row has temp 0
        and compiles sampling down to argmax. ``ticks`` (STATIC) overrides
        ``tick_window`` — the speculative server's gated plain trips run
        longer windows than its verify trips (SpecConfig.gate_ticks).
        ``aidx``/``lora_flat``: per-slot adapter page indices + the LoRA
        pool's stacked factor tensors — gathered ONCE per trip (rows are
        loop-invariant across ticks) and applied in-program (BGMV)."""
        engine = self.engine
        model = engine.model
        lora = self._gather_lora(lora_flat, aidx)

        def one_tick(carry, k):
            toks, flat_p, p = carry
            pools = self._pool_views(flat_p)

            def call():
                h, new = model.model.paged_decode_step(Tensor(toks[:, None]),
                                                       pools, tables, p,
                                                       lora=lora)
                return engine._head(h), new

            logits, new = functional_call(model, params, call_fn=call)
            flat = self._flat_pools(new)
            lg = logits.value[:, 0].astype(jnp.float32)   # (B, V)
            if greedy:
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            else:
                from ..models.generation import sample_token_rows

                nxt = sample_token_rows(lg, jax.random.fold_in(key, k),
                                        temps, topks, topps)
            return (nxt, flat, p + active), nxt

        n = engine.tick_window if ticks is None else ticks
        if n == 1:
            (_, flat, _), stack = one_tick((tokens, flat_pools, pos), 0)
            return stack[None], flat
        (_, flat, _), stack = jax.lax.scan(
            one_tick, (tokens, flat_pools, pos), jnp.arange(n))
        return stack, flat

    def _chunk_prefill_fn(self, params, chunk, flat_pools, table, start,
                          last_idx, aidx=None, lora_flat=()):
        """ONE compiled program for every prefill chunk of every prompt
        length: chunk (1, C) right-padded; K/V scatter into the slot's
        block table at block-aligned ``start``; returns fp32 logits at
        local index ``last_idx`` (the last real prompt token on the final
        chunk; ignored on earlier chunks) + updated pools. ``aidx`` is the
        prefilling slot's adapter page index, shape (1,) — prompt tokens
        must see the same adapter delta the decode ticks will.

        Context parallelism is a one-line steer: at ``cp > 1`` the chunk
        is constrained to shard its sequence dim over the ``cp`` axis.
        Params and pools name only ``tp``, so GSPMD partitions the
        per-token work (embedding, projections, rope) across the cp
        group, all-gathers the chunk's K/V where the replicated pool
        scatter needs the full chunk, and leaves every reduction's order
        unchanged — each shard attends over the full prefix, so tokens
        are bit-identical to cp=1. The constraint lives INSIDE the
        traced body: one compile covers every chunk, zero steady-state
        recompiles."""
        if self.cp > 1:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from ..parallel.serving_mesh import SERVING_CP_AXIS

            chunk = jax.lax.with_sharding_constraint(
                chunk, NamedSharding(self.mesh, P(None, SERVING_CP_AXIS)))
        engine = self.engine
        model = engine.model
        pools = self._pool_views(flat_pools)
        lora = self._gather_lora(lora_flat, aidx)

        def call():
            h, new = model.model.paged_prefill_chunk(Tensor(chunk), pools,
                                                     table, start,
                                                     lora=lora)
            last = jax.lax.dynamic_slice_in_dim(h.value, last_idx, 1, 1)
            return engine._head(Tensor(last)), new

        logits, new = functional_call(model, params, call_fn=call)
        return logits.value[:, 0].astype(jnp.float32), self._flat_pools(new)

    def _spec_verify_fn(self, params, tokens, proposals, flat_pools, tables,
                        pos, temps, topks, topps, kcaps, key, qprobs,
                        aidx=None, lora_flat=(), greedy=False):
        """ONE fused speculative tick: target-score the whole window
        [current token, k drafts] through the paged verify path, then run
        exact accept/reject — all on device, so the host sees only the
        (B, W) emitted-token block and the (B,) accepted counts (one sync
        per tick, same as plain decode). ``qprobs`` is None for
        deterministic drafters (one-hot q synthesized inside the program);
        per-row ``kcaps`` force-stop lets requests run mixed draft_k (and
        masks idle slots at kcap 0) without changing compiled shapes."""
        engine = self.engine
        model = engine.model
        pools = self._pool_views(flat_pools)
        lora = self._gather_lora(lora_flat, aidx)
        window = jnp.concatenate([tokens[:, None], proposals], axis=1)

        def call():
            h, new = model.model.paged_verify_step(Tensor(window), pools,
                                                   tables, pos, lora=lora)
            return engine._head(h), new

        logits, new = functional_call(model, params, call_fn=call)
        flat = self._flat_pools(new)
        from .speculative import speculative_accept

        out, acc = speculative_accept(
            logits.value.astype(jnp.float32), proposals, temps, topks,
            topps, kcaps, key, qprobs, greedy=greedy)
        return out, acc, flat

    def _spec_scan_fn(self, params, ctx, flat_pools, tables, pos, temps,
                      topks, topps, kcaps, active, key, aidx=None,
                      lora_flat=(), greedy=False, windows=None):
        """``tick_window`` speculative windows as ONE compiled program —
        the drafter runs IN-PROGRAM (``drafter.propose_device``, e.g. the
        jnp prompt-lookup matcher), so draft → multi-token verify → exact
        accept → context/position update runs on device and the host pays
        one round trip per ``tick_window·(k+1)`` potential tokens.
        ``ctx``: int32 (B, max_len), row b's prompt+generated tokens
        valid through index ``pos[b]`` — accepted tokens are appended to
        it after each window so the next window drafts from them.
        Emitted-token surplus past eos/max-new is discarded by the host
        harvest, exactly like the plain ``tick_window`` decode scan.
        ``windows`` (STATIC) overrides the per-trip window count — the
        turbo tier of the speculation gate (SpecConfig.turbo_windows)
        runs long trips while the whole batch is accepting near-k."""
        engine = self.engine
        model = engine.model
        k = engine.spec_k
        W = k + 1
        B, L = ctx.shape
        S = engine._spec_windows if windows is None else windows
        rows = jnp.arange(B)
        lora = self._gather_lora(lora_flat, aidx)
        from .speculative import speculative_accept

        def one_window(carry, w):
            c, flat_p, p = carry
            pools = self._pool_views(flat_p)
            cur = jnp.take_along_axis(c, p[:, None], axis=1)      # (B, 1)
            proposals = engine.drafter.propose_device(c, p, k)
            window = jnp.concatenate([cur, proposals], axis=1)

            def call():
                h, new = model.model.paged_verify_step(Tensor(window),
                                                       pools, tables, p,
                                                       lora=lora)
                return engine._head(h), new

            logits, new = functional_call(model, params, call_fn=call)
            flat = self._flat_pools(new)
            out, acc = speculative_accept(
                logits.value.astype(jnp.float32), proposals, temps, topks,
                topps, kcaps, jax.random.fold_in(key, w), None,
                greedy=greedy)
            # append the emitted tokens (accepted drafts + correction) to
            # the context so the next window drafts from them; clamped
            # writes past L-1 only touch rows the harvest will release
            widx = jnp.minimum(p[:, None] + 1 + jnp.arange(W)[None, :],
                               L - 1)
            keep = ((jnp.arange(W)[None, :] <= acc[:, None])
                    & (active > 0)[:, None])
            vals = jnp.where(keep, out, jnp.take_along_axis(c, widx, axis=1))
            c = c.at[rows[:, None], widx].set(vals)
            # clamp: only surplus windows past max_len (discarded by the
            # harvest) ever hit L-1 — without it the ``cur`` gather goes
            # out of bounds (fill-mode -> garbage token id -> NaN
            # embedding) and the NaN K/V written to scratch poisons every
            # row whose table padding points there (0 * NaN in p @ V)
            p = jnp.minimum(p + (acc + 1) * active, L - 1)
            return (c, flat, p), (out, acc)

        # UNROLLED, not lax.scan/while_loop: on CPU the loop constructs
        # copy the multi-MB KV pools through the carry every trip (~ms of
        # pure memcpy); straight-line code lets XLA alias the pool
        # buffers through all S windows for free. S is small and static,
        # so program size stays modest and the jit cache sees one shape.
        carry = (ctx, flat_pools, pos)
        outs, accs = [], []
        for w in range(S):
            carry, (out, acc) = one_window(carry, w)
            outs.append(out)
            accs.append(acc)
        _, flat, _ = carry
        return jnp.stack(outs), jnp.stack(accs), flat

    # ------------------------------------------------- megakernel programs
    def _mk_lora(self, lora_flat, aidx):
        """Gathered per-layer factor dicts → the per-target (L, B, ·, ·)
        stacks the megakernel streams (None when LoRA is off)."""
        if not lora_flat:
            return None
        from ..ops import decode_megakernel as mk

        return mk.stack_lora(self._gather_lora(lora_flat, aidx))

    def _mk_window(self, params, window, flat_pools, tables, pos, lstk):
        """One W-token tick through the whole-tick megakernel: embed →
        ``decode_tick`` (all layers as ONE Pallas program, pools aliased
        in place) → final norm → head. Returns (fp32 logits (B, W, V),
        new flat pool list). The kernel's shape guard raises
        ``NotImplementedError`` at trace time — callers catch it and
        delegate to the per-layer program (the dispatch ladder)."""
        from ..ops import decode_megakernel as mk

        engine = self.engine
        model = engine.model
        m = model.model
        W = window.shape[1]

        def call():
            x = m.embed_tokens(Tensor(window))
            cosr, sinr = mk.gather_rope_rows(m._cos, m._sin, pos, W)
            xo, new = mk.decode_tick(
                x.value, list(flat_pools), tables, pos, self._mk_weights,
                cosr, sinr, block_size=engine.block_size,
                geometry=self._mk_geometry, eps=engine.cfg.rms_norm_eps,
                lora=lstk)
            return engine._head(m.norm(Tensor(xo))), new

        logits, new = functional_call(model, params, call_fn=call)
        return logits.value.astype(jnp.float32), list(new)

    def _decode_megakernel_fn(self, params, tokens, flat_pools, tables,
                              pos, temps, topks, topps, active, key,
                              aidx=None, lora_flat=(), greedy=False,
                              ticks=None):
        """The whole-tick twin of :meth:`_decode_paged_fn` — identical
        signature, sampling pipeline, and trip structure; only the
        per-tick model call collapses into the ONE persistent Pallas
        program. A trace-time ``NotImplementedError`` from the kernel's
        shape guard delegates the whole body to the per-layer program."""
        engine = self.engine
        try:
            lstk = self._mk_lora(lora_flat, aidx)

            def one_tick(carry, k):
                toks, flat_p, p = carry
                lg, flat = self._mk_window(params, toks[:, None], flat_p,
                                           tables, p, lstk)
                lg = lg[:, 0]                                 # (B, V)
                if greedy:
                    nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                else:
                    from ..models.generation import sample_token_rows

                    nxt = sample_token_rows(lg, jax.random.fold_in(key, k),
                                            temps, topks, topps)
                return (nxt, flat, p + active), nxt

            n = engine.tick_window if ticks is None else ticks
            if n == 1:
                (_, flat, _), stack = one_tick((tokens, flat_pools, pos), 0)
                return stack[None], flat
            (_, flat, _), stack = jax.lax.scan(
                one_tick, (tokens, flat_pools, pos), jnp.arange(n))
            return stack, flat
        except NotImplementedError:
            return self._decode_paged_fn(
                params, tokens, flat_pools, tables, pos, temps, topks,
                topps, active, key, aidx=aidx, lora_flat=lora_flat,
                greedy=greedy, ticks=ticks)

    def _spec_verify_megakernel_fn(self, params, tokens, proposals,
                                   flat_pools, tables, pos, temps, topks,
                                   topps, kcaps, key, qprobs, aidx=None,
                                   lora_flat=(), greedy=False):
        """Whole-tick twin of :meth:`_spec_verify_fn`: the W = k+1 verify
        window is the megakernel's natural shape — one persistent program
        scores the whole window, then the exact accept/reject runs
        unchanged."""
        try:
            lstk = self._mk_lora(lora_flat, aidx)
            window = jnp.concatenate([tokens[:, None], proposals], axis=1)
            lg, flat = self._mk_window(params, window, flat_pools, tables,
                                       pos, lstk)
            from .speculative import speculative_accept

            out, acc = speculative_accept(lg, proposals, temps, topks,
                                          topps, kcaps, key, qprobs,
                                          greedy=greedy)
            return out, acc, flat
        except NotImplementedError:
            return self._spec_verify_fn(
                params, tokens, proposals, flat_pools, tables, pos, temps,
                topks, topps, kcaps, key, qprobs, aidx=aidx,
                lora_flat=lora_flat, greedy=greedy)

    def _spec_scan_megakernel_fn(self, params, ctx, flat_pools, tables,
                                 pos, temps, topks, topps, kcaps, active,
                                 key, aidx=None, lora_flat=(),
                                 greedy=False, windows=None):
        """Whole-tick twin of :meth:`_spec_scan_fn` — same unrolled
        window loop, drafter, accept/reject, and context update; each
        window's target scoring is the ONE persistent program."""
        engine = self.engine
        try:
            model_k = engine.spec_k
            W = model_k + 1
            B, L = ctx.shape
            S = engine._spec_windows if windows is None else windows
            rows = jnp.arange(B)
            lstk = self._mk_lora(lora_flat, aidx)
            from .speculative import speculative_accept

            def one_window(carry, w):
                c, flat_p, p = carry
                cur = jnp.take_along_axis(c, p[:, None], axis=1)   # (B, 1)
                proposals = engine.drafter.propose_device(c, p, model_k)
                window = jnp.concatenate([cur, proposals], axis=1)
                lg, flat = self._mk_window(params, window, flat_p, tables,
                                           p, lstk)
                out, acc = speculative_accept(
                    lg, proposals, temps, topks, topps, kcaps,
                    jax.random.fold_in(key, w), None, greedy=greedy)
                # context/position update — verbatim from _spec_scan_fn
                # (including the L-1 clamp rationale documented there)
                widx = jnp.minimum(p[:, None] + 1
                                   + jnp.arange(W)[None, :], L - 1)
                keep = ((jnp.arange(W)[None, :] <= acc[:, None])
                        & (active > 0)[:, None])
                vals = jnp.where(keep, out,
                                 jnp.take_along_axis(c, widx, axis=1))
                c = c.at[rows[:, None], widx].set(vals)
                p = jnp.minimum(p + (acc + 1) * active, L - 1)
                return (c, flat, p), (out, acc)

            carry = (ctx, flat_pools, pos)
            outs, accs = [], []
            for w in range(S):
                carry, (out, acc) = one_window(carry, w)
                outs.append(out)
                accs.append(acc)
            _, flat, _ = carry
            return jnp.stack(outs), jnp.stack(accs), flat
        except NotImplementedError:
            return self._spec_scan_fn(
                params, ctx, flat_pools, tables, pos, temps, topks, topps,
                kcaps, active, key, aidx=aidx, lora_flat=lora_flat,
                greedy=greedy, windows=windows)
