"""Process-isolated replicas: one handle interface, two backends.

The fleet router (``fleet.py``) was written against the router-facing
surface of :class:`~.serving.GenerationServer` — submit / step / status
/ cancel / take_results / load_metrics / snapshot / evacuate /
admit_migrated and friends. This module puts a *real process boundary*
behind that surface without changing it:

- :class:`InProcessReplica` wraps a live server in the same handle
  shape (the zero-cost backend — what every existing test exercises);
- :class:`SubprocessReplica` spawns ``python -m
  paddle_tpu.inference.replica_worker`` connected over a
  ``socket.socketpair()`` and serializes every call as a length-prefixed,
  CRC-stamped, pickled frame with request/response correlation ids.

The snapshot/migration payloads were already wire-shaped (host numpy
arrays behind per-payload CRCs — PR 8/9), so migration across the
process boundary is the SAME bytes the in-process path moves; the
transport adds its own frame CRC on top, and a frame corrupted in
transit surfaces as :class:`ReplicaTransportError`, never as silently
wrong state.

**Liveness across the boundary.** Every worker reply piggybacks the
engine's current step counter plus a monotone reply sequence number;
the handle caches both. ``handle.steps`` is therefore the *last
observed* value — possibly stale between RPCs — and
``handle.progress_seq`` tells the router whether a FRESH observation
arrived since it last looked, which is what lets the heartbeat
tolerate transport round-trip latency without mis-counting stalls
(see ``FleetRouter._heartbeat``). ``ping()`` refreshes both without
stepping the engine.

**Real crashes.** The PR 8/9 fault sites modelled ``replica_down`` as
a poisoned in-process object; with a subprocess backend the same event
is a dead socket. The handle keeps a host-side *journal* of every
request it admitted (prompt + sampling/scheduling parameters, updated
on migration in/out, pruned on harvest), so when the connection drops
it can still answer ``evacuate(trust_kv=False)`` locally: it
synthesizes a salvage snapshot of journaled requests as replay-queued
work, and the router re-admits them on peers through the normal
corruption-recovery rung. Greedy continuations are token-exact by the
same argument as the CRC-mismatch fallback — re-prefilling a known
prefix regenerates the same tokens. (Sampled requests re-draw their
tail; the chaos contract has always been greedy.)

No wall-clock waits live here: blocking is bounded by *socket
timeouts* only, and all engine-side timing stays behind the injectable
clock (graftlint GL012/GL015 enforce both).
"""
from __future__ import annotations

import pickle
import socket
import struct
import subprocess
import sys
import zlib
from typing import Any, Dict, List, Optional, Sequence

from .faults import EngineFailedError
from .scheduler import PRIORITY_NORMAL, AdmissionError

__all__ = [
    "CountingClock", "InProcessReplica", "RemoteReplicaError",
    "ReplicaHandle", "ReplicaTransportError", "SubprocessReplica",
    "recv_frame", "send_frame",
]

#: frame header: magic, flags (reserved), payload CRC32, payload length
FRAME_MAGIC = b"Pf"
_HEADER = struct.Struct(">2sHIQ")
#: refuse absurd frames before allocating for them (a corrupted length
#: field must not look like a 2**60-byte read)
MAX_FRAME_BYTES = 1 << 31


class ReplicaTransportError(ConnectionError):
    """The transport itself failed — connection dropped, timed out, or
    delivered a corrupt frame. Distinct from any error the remote engine
    *raised* (those re-raise as their own types / RemoteReplicaError)."""


class RemoteReplicaError(RuntimeError):
    """The worker's engine raised an exception type the handle does not
    reconstruct; carries the remote type name and message."""

    def __init__(self, type_name: str, msg: str):
        super().__init__(f"{type_name}: {msg}")
        self.type_name = type_name


class CountingClock:
    """Deterministic time source: every read advances by ``dt``. The
    worker builds its engine on one of these (``spec["server"]["clock"]
    = "counting"``) so cross-process runs produce byte-identical
    latency metrics at a fixed seed."""

    def __init__(self, dt: float = 0.001, start: float = 0.0):
        self.dt = float(dt)
        self.t = float(start)

    def __call__(self) -> float:
        self.t += self.dt
        return self.t


# --------------------------------------------------------------------- frames
def send_frame(sock: socket.socket, obj: Any) -> None:
    """Pickle ``obj`` and send it as one length-prefixed, CRC-stamped
    frame. Raises :class:`ReplicaTransportError` on a dead socket."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    header = _HEADER.pack(FRAME_MAGIC, 0, crc, len(payload))
    try:
        sock.sendall(header + payload)
    except (OSError, ValueError) as e:
        raise ReplicaTransportError(f"send failed: {e}") from e


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(min(n - len(buf), 1 << 20))
        except socket.timeout as e:
            raise ReplicaTransportError(
                f"receive timed out after {sock.gettimeout()}s "
                f"({len(buf)}/{n} bytes)") from e
        except OSError as e:
            raise ReplicaTransportError(f"receive failed: {e}") from e
        if not chunk:
            raise ReplicaTransportError(
                "connection closed by peer"
                + (" mid-frame" if buf else ""))
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket) -> Any:
    """Receive one frame; verifies magic, length bound, and CRC before
    unpickling. Any violation is :class:`ReplicaTransportError`."""
    magic, _flags, crc, length = _HEADER.unpack(
        _recv_exact(sock, _HEADER.size))
    if magic != FRAME_MAGIC:
        raise ReplicaTransportError(f"bad frame magic {magic!r}")
    if length > MAX_FRAME_BYTES:
        raise ReplicaTransportError(f"frame length {length} exceeds cap")
    payload = _recv_exact(sock, length)
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise ReplicaTransportError("frame CRC mismatch")
    try:
        return pickle.loads(payload)
    except Exception as e:   # truncated/garbage pickle
        raise ReplicaTransportError(f"frame unpickle failed: {e}") from e


#: remote exception types the handle reconstructs as themselves, so the
#: router's existing except-clauses (AdmissionError backpressure
#: fallthrough, EngineFailedError refusal) work unmodified across the
#: process boundary
_EXC_TYPES: Dict[str, type] = {
    "AdmissionError": AdmissionError,
    "EngineFailedError": EngineFailedError,
    "ValueError": ValueError,
    "KeyError": KeyError,
    "TypeError": TypeError,
    "RuntimeError": RuntimeError,
}


def _raise_remote(err: Dict[str, str]) -> None:
    cls = _EXC_TYPES.get(err.get("type", ""))
    if cls is not None:
        raise cls(err.get("msg", ""))
    raise RemoteReplicaError(err.get("type", "?"), err.get("msg", ""))


# -------------------------------------------------------------------- handles
class ReplicaHandle:
    """One interface in front of a replica regardless of where it runs.

    A handle exposes the router-facing :class:`GenerationServer`
    surface (submit/step/status/cancel/take_results/load_metrics/
    kv_stats/snapshot/evacuate/admit_migrated/adopt_warm/handoff_ready/
    set_rid_base/fail/probe_prefix/watchdog_findings/slo_observations/
    assert_conserved, plus ``steps``/``cache_mode``/``block_size``/
    ``role``) and adds two transport-aware members:

    - ``progress_seq`` — monotone count of fresh replica observations
      this handle has delivered; the router's heartbeat only charges a
      stall when a FRESH sample shows no progress;
    - ``close()`` — release the backend (a no-op in-process).
    """

    backend = "abstract"

    @property
    def steps(self) -> int:
        raise NotImplementedError

    @property
    def progress_seq(self) -> int:
        raise NotImplementedError

    def ping(self) -> None:
        """Refresh liveness state without stepping the engine."""

    def close(self) -> None:
        """Release the backend. Idempotent."""


class InProcessReplica(ReplicaHandle):
    """Zero-cost handle around a live in-process server: every
    observation is fresh by construction, so ``progress_seq`` advances
    on each ``steps`` read and the heartbeat behaves exactly as it does
    against a bare server."""

    backend = "inproc"

    def __init__(self, server: Any):
        self._server = server
        self._seq = 0

    @property
    def server(self) -> Any:
        return self._server

    @property
    def steps(self) -> int:
        self._seq += 1
        return self._server.steps

    @property
    def progress_seq(self) -> int:
        return self._seq

    def ping(self) -> None:
        self._seq += 1

    def __getattr__(self, name: str) -> Any:
        return getattr(self._server, name)


class _TelemetryProxy:
    """The slice of ``server.telemetry`` callers poke across the
    boundary (watchdog probe, between-pass counter reset)."""

    def __init__(self, handle: "SubprocessReplica"):
        self._handle = handle

    def watchdog(self) -> List[Dict[str, Any]]:
        return self._handle.watchdog_findings()

    def reset(self, counters: bool = False) -> None:
        self._handle._call("telemetry_reset", counters=counters)


#: worker ops forwarded 1:1 to the engine — anything else is refused at
#: the worker, so a corrupt frame cannot name an arbitrary attribute
PASSTHROUGH_OPS = frozenset({
    "submit", "step", "status", "cancel", "take_results", "load_metrics",
    "kv_stats", "sched_metrics", "spec_metrics", "assert_conserved",
    "snapshot", "restore", "evacuate", "admit_migrated", "adopt_warm",
    "handoff_ready", "fail", "set_rid_base", "probe_prefix",
    "watchdog_findings", "slo_observations", "telemetry_snapshot",
})


class SubprocessReplica(ReplicaHandle):
    """A replica living in its own OS process, driven over a socketpair.

    ``spec`` describes how the worker builds its engine::

        {"model": {"config": {...LlamaConfig kwargs...}, "seed": 7},
         "server": {...GenerationServer kwargs..., "clock": "counting"}}

    The worker rebuilds the model deterministically from (config, seed)
    — weights are never shipped — and replies to the hello frame with
    its snapshot fingerprint, which the fleet's homogeneity check reads
    exactly as it would a local server's.

    All calls are synchronous request/response with correlation ids;
    a reply that outlives its timed-out request is drained and its
    piggybacked progress recorded, never misdelivered. Once the
    connection drops the handle answers ``evacuate(trust_kv=False)``
    from its journal (see module docstring) and every other RPC raises
    :class:`ReplicaTransportError`.
    """

    backend = "subprocess"

    def __init__(self, spec: Dict[str, Any], *,
                 rpc_timeout_s: float = 300.0,
                 python: str = sys.executable,
                 env: Optional[Dict[str, str]] = None):
        self.spec = dict(spec)
        parent, child = socket.socketpair()
        try:
            self._proc = subprocess.Popen(
                [python, "-m", "paddle_tpu.inference.replica_worker",
                 "--fd", str(child.fileno())],
                pass_fds=(child.fileno(),), env=env)
        except Exception:
            parent.close()
            child.close()
            raise
        child.close()
        self._sock = parent
        self._sock.settimeout(float(rpc_timeout_s))
        self._alive = True
        self._down_reason: Optional[str] = None
        self._failed: Optional[str] = None
        self._next_id = 1
        self._steps = 0
        self._seq = 0
        self._journal: Dict[int, Dict[str, Any]] = {}
        self._journal_seq = 0
        try:
            send_frame(self._sock, {"id": 0, "op": "__hello__",
                                    "spec": self.spec})
            info = self._transact(0)
        except BaseException:
            self._mark_down("worker failed to boot")
            self._proc.kill()
            self._proc.wait()
            raise
        self._info = info

    # ----------------------------------------------------------------- rpc
    def _mark_down(self, reason: str) -> None:
        if self._alive:
            self._alive = False
            self._down_reason = reason
            try:
                self._sock.close()
            except OSError:
                pass

    def _note_progress(self, reply: Dict[str, Any]) -> None:
        seq = reply.get("seq")
        if seq is not None and int(seq) > self._seq:
            self._seq = int(seq)
            self._steps = int(reply.get("steps", self._steps))

    def _transact(self, mid: int) -> Any:
        """Receive until the reply correlated with ``mid`` arrives;
        record piggybacked progress from every frame on the way."""
        while True:
            reply = recv_frame(self._sock)
            self._note_progress(reply)
            if reply.get("id") != mid:
                continue     # stale reply from an earlier timed-out call
            if not reply.get("ok"):
                _raise_remote(reply.get("error") or {})
            return reply.get("value")

    def _call(self, op: str, *args: Any, **kw: Any) -> Any:
        if not self._alive:
            raise ReplicaTransportError(
                f"replica process is gone ({self._down_reason})")
        mid = self._next_id
        self._next_id += 1
        try:
            send_frame(self._sock,
                       {"id": mid, "op": op, "args": args, "kw": kw})
            return self._transact(mid)
        except ReplicaTransportError as e:
            self._mark_down(str(e))
            raise

    # ------------------------------------------------------------- identity
    @property
    def alive(self) -> bool:
        return self._alive

    @property
    def pid(self) -> int:
        return self._proc.pid

    @property
    def cache_mode(self) -> str:
        return self._info["cache_mode"]

    @property
    def block_size(self) -> int:
        return self._info["block_size"]

    @property
    def role(self) -> str:
        return self._info["role"]

    @property
    def telemetry(self) -> _TelemetryProxy:
        return _TelemetryProxy(self)

    def _snapshot_fingerprint(self) -> Dict[str, Any]:
        return dict(self._info["fingerprint"])

    # ------------------------------------------------------------- liveness
    @property
    def steps(self) -> int:
        """Last OBSERVED step counter (piggybacked on every reply) —
        read ``progress_seq`` to learn whether it is fresh."""
        return self._steps

    @property
    def progress_seq(self) -> int:
        return self._seq

    def ping(self) -> None:
        self._call("ping")

    # -------------------------------------------------------------- journal
    def _journal_submit(self, rid: int, prompt: List[int],
                        max_new_tokens: int, temperature: float,
                        top_k: int, top_p: float, draft_k: Optional[int],
                        adapter: Optional[str], priority: int, tenant: str,
                        ttl_s: Optional[float],
                        generated: Optional[List[int]] = None,
                        replay: Optional[List[int]] = None,
                        sched: Optional[Dict[str, Any]] = None) -> None:
        self._journal_seq += 1
        self._journal[int(rid)] = {
            "rid": int(rid), "prompt": list(prompt),
            "max_new_tokens": int(max_new_tokens),
            "temperature": float(temperature), "top_k": int(top_k),
            "top_p": float(top_p), "draft_k": draft_k,
            "adapter": adapter, "generated": list(generated or ()),
            "replay": (list(replay) if replay is not None else None),
            "hashes": [], "phase": "queued",
            "sched": dict(sched) if sched is not None else {
                "priority": int(priority), "tenant": tenant,
                "ttl_remaining": ttl_s, "seq": self._journal_seq,
                "cost": float(len(prompt) + max_new_tokens),
                "vtag": 0.0, "preempted": False, "started": False}}

    def _journal_snapshot_request(self, d: Dict[str, Any]) -> None:
        """Journal a request admitted via restore/admit_migrated: keep
        its known token prefix as the replay rung for a later salvage."""
        gen = list(d.get("generated") or ())
        replay = d.get("replay")
        if replay is None and gen:
            replay = list(d["prompt"]) + gen
        self._journal_submit(
            int(d["rid"]), list(d["prompt"]), int(d["max_new_tokens"]),
            float(d["temperature"]), int(d["top_k"]), float(d["top_p"]),
            d.get("draft_k"), d.get("adapter"),
            int(d["sched"]["priority"]), d["sched"]["tenant"],
            d["sched"]["ttl_remaining"], generated=gen, replay=replay,
            sched=d["sched"])

    def _salvage_snapshot(self, rids: Optional[Sequence[int]]
                          ) -> Dict[str, Any]:
        """Synthesize an ``evacuate(trust_kv=False)``-shaped snapshot
        from the journal — the handle's answer when the process is
        already gone. Requests re-enter peers as replay-queued work."""
        keep = None if rids is None else {int(r) for r in rids}
        reqs = []
        for rid in sorted(self._journal):
            if keep is not None and rid not in keep:
                continue
            d = self._journal[rid]
            reqs.append({**d, "prompt": list(d["prompt"]),
                         "generated": list(d["generated"]),
                         "replay": (list(d["replay"])
                                    if d["replay"] is not None else None),
                         "hashes": [], "sched": dict(d["sched"])})
        for d in reqs:
            self._journal.pop(d["rid"], None)
        return {"format": 1, "salvaged": True,
                "config": self._snapshot_fingerprint(),
                "requests": reqs, "results": {}, "dropped": {},
                "warm_tier": []}

    def _prune_journal(self, rids) -> None:
        for r in rids:
            self._journal.pop(int(r), None)

    # ----------------------------------------------------- engine surface
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 0.0, draft_k: Optional[int] = None,
               priority: int = PRIORITY_NORMAL, tenant: str = "default",
               ttl_s: Optional[float] = None,
               adapter: Optional[str] = None) -> int:
        if self._failed is not None:
            raise EngineFailedError(
                f"replica handle is failed ({self._failed})")
        prompt = list(prompt)
        rid = int(self._call(
            "submit", prompt, max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p,
            draft_k=draft_k, priority=priority, tenant=tenant,
            ttl_s=ttl_s, adapter=adapter))
        self._journal_submit(rid, prompt, max_new_tokens, temperature,
                             top_k, top_p, draft_k, adapter, priority,
                             tenant, ttl_s)
        return rid

    def step(self) -> int:
        return int(self._call("step"))

    def status(self, rid: int) -> str:
        return self._call("status", int(rid))

    def cancel(self, rid: int) -> bool:
        ok = bool(self._call("cancel", int(rid)))
        if ok:
            self._journal.pop(int(rid), None)
        return ok

    def take_results(self) -> Dict[int, List[int]]:
        out = {int(r): list(t)
               for r, t in self._call("take_results").items()}
        self._prune_journal(out)
        return out

    def load_metrics(self) -> Dict[str, int]:
        return self._call("load_metrics")

    def kv_stats(self) -> Dict[str, int]:
        return self._call("kv_stats")

    def sched_metrics(self) -> Dict[str, Any]:
        return self._call("sched_metrics")

    def spec_metrics(self) -> Dict[str, float]:
        return self._call("spec_metrics")

    def assert_conserved(self) -> Dict[str, int]:
        if not self._alive:
            # a dead process holds no device state to audit; the journal
            # is empty once the router salvaged it
            return {}
        return self._call("assert_conserved")

    def probe_prefix(self, prompt: Sequence[int]) -> int:
        return int(self._call("probe_prefix", list(prompt)))

    def watchdog_findings(self) -> List[Dict[str, Any]]:
        return self._call("watchdog_findings")

    def slo_observations(self) -> Dict[str, Dict[str, List[float]]]:
        return self._call("slo_observations")

    def telemetry_snapshot(self) -> Dict[str, Any]:
        return self._call("telemetry_snapshot")

    def set_rid_base(self, base: int) -> None:
        self._call("set_rid_base", int(base))

    def handoff_ready(self) -> List[int]:
        return list(self._call("handoff_ready"))

    def snapshot(self, *, trust_kv: bool = True) -> Dict[str, Any]:
        return self._call("snapshot", trust_kv=trust_kv)

    def restore(self, snap: Dict[str, Any]) -> int:
        n = int(self._call("restore", snap))
        for d in snap.get("requests", ()):
            self._journal_snapshot_request(d)
        return n

    def evacuate(self, *, trust_kv: bool = True,
                 rids: Optional[Sequence[int]] = None) -> Dict[str, Any]:
        """Real drain over the wire while the worker lives (KV payloads
        and all); journal salvage once it does not — the subprocess
        twin of ``snapshot(trust_kv=False)`` on a crashed engine."""
        if self._alive:
            try:
                snap = self._call("evacuate", trust_kv=trust_kv,
                                  rids=rids)
            except ReplicaTransportError:
                return self._salvage_snapshot(rids)
            self._prune_journal(
                [d["rid"] for d in snap.get("requests", ())]
                if rids is not None else list(self._journal))
            return snap
        return self._salvage_snapshot(rids)

    def admit_migrated(self, d: Dict[str, Any], *,
                       source_config: Optional[Dict[str, Any]] = None
                       ) -> int:
        rid = int(self._call("admit_migrated", d,
                             source_config=source_config))
        self._journal_snapshot_request(d)
        return rid

    def adopt_warm(self, entries: Sequence[Dict[str, Any]]) -> int:
        return int(self._call("adopt_warm", list(entries)))

    def fail(self, reason: str) -> None:
        """Poison the replica (local flag first — idempotent and always
        effective — then best-effort over the wire)."""
        if self._failed is None:
            self._failed = str(reason)
        if self._alive:
            try:
                self._call("fail", str(reason))
            except (ReplicaTransportError, RemoteReplicaError):
                pass

    # ------------------------------------------------------------ lifecycle
    def kill_process(self) -> None:
        """Hard-kill the worker — the REAL-process twin of the
        ``replica_down`` fault site: the next RPC sees a dead socket."""
        self._proc.kill()
        self._proc.wait()
        self._mark_down("process killed")

    def close(self) -> None:
        if self._alive:
            try:
                send_frame(self._sock, {"id": self._next_id,
                                        "op": "shutdown",
                                        "args": (), "kw": {}})
            except ReplicaTransportError:
                pass
        try:
            self._proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            self._proc.kill()
            self._proc.wait()
        self._mark_down("closed")

    def __enter__(self) -> "SubprocessReplica":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
