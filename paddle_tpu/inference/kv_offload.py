"""Host KV offload — swap-preemption for the paged serving pool.

The mechanism half of overload handling (policy lives in
``inference/scheduler.py``): when the device block pool runs dry, the
server preempts a victim request by copying its KV blocks — fp rows, or
int8 codes + f32 scales, the engine is pool-format agnostic — into a
host-memory pool, freeing the HBM blocks for more urgent work. On resume
the blocks are restored and the request continues exactly where it
stopped: greedy output is token-identical to an un-preempted run because
the round trip is a bit-exact copy of whatever the pool held.

Why swapping beats recompute here: a decoding request's KV past the
prompt was produced by its own sampled continuation — re-prefilling
``prompt + generated`` would rebuild it through a different program
(chunked prefill vs decode steps) with different float rounding, beyond
re-spending the FLOPs. Prefill-only work IS recomputable, which is why
``GenerationServer`` aborts (not swaps) victims still in prefill.

Compile discipline (the zero-steady-state-recompile guarantee must
survive preemption):

- The device↔host copies are EAGER ops, not new jitted programs, and
  they run at ONE fixed shape: every gather/scatter covers the full
  ``table_width`` rows of the slot's block table, padded with the
  scratch block. A swap of 3 blocks and a swap of 30 compile the same
  executables (once, at the first preemption); nothing is keyed on how
  many blocks a victim happens to hold.
- Scatter padding targets block 0 — the reserved scratch block that
  absorbs masked writes everywhere else in the paged path — so the
  fixed-width restore can never touch a live block.

Prefix-cache integration: the victim's chain hashes ride along in the
:class:`SwapHandle`. Swap-out releases the device blocks through the
normal refcount path, so hashed prompt blocks land on the allocator's
LRU — still resident, still shareable. Swap-in first re-matches those
hashes (``BlockAllocator.match_hashes``): every hit is a block restored
WITHOUT an upload (or a byte of HBM traffic), and every uploaded full
prompt block is re-registered under its hash so restored requests keep
participating in prefix sharing.
"""
from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

__all__ = ["SwapHandle", "HostKVPool", "KVOffloadEngine",
           "payload_checksum"]


def payload_checksum(arrays: Sequence[np.ndarray]) -> int:
    """CRC32 over a parked payload's raw bytes (order-sensitive).

    Cheap enough to run on every swap boundary and strong enough to catch
    the single-bit-flip corruption the chaos plans inject; a mismatch on
    swap-in means the parked copy cannot be trusted and the server falls
    back to re-prefilling the request's tokens.
    """
    c = 0
    for a in arrays:
        c = zlib.crc32(np.ascontiguousarray(a).reshape(-1).view(np.uint8), c)
    return c


@dataclass
class SwapHandle:
    """Resume ticket for one preempted request: where it stopped, which
    chain hashes its prompt blocks carry, and how much host memory the
    parked copy occupies. The block CONTENTS live in the
    :class:`HostKVPool` under ``rid``."""

    rid: int
    n_tokens: int            # KV-valid positions [0, n_tokens)
    last_token: int          # next decode input (its KV is not written yet)
    n_blocks: int            # live table entries parked on host
    hashes: List[int] = field(default_factory=list)  # leading full-prompt-block chain hashes
    nbytes: int = 0          # logical bytes charged to the host pool
    checksum: int = 0        # CRC32 of the parked payload (0 = unverified)


class HostKVPool:
    """Byte-budgeted host store for swapped block stacks.

    ``capacity_bytes=None`` means unbounded (the default server setting —
    host DRAM dwarfs HBM); a bounded pool makes :meth:`put` refuse once
    full, which the server treats as "this victim cannot be preempted".
    """

    def __init__(self, capacity_bytes: Optional[int] = None):
        if capacity_bytes is not None and capacity_bytes < 0:
            raise ValueError(
                f"capacity_bytes must be >= 0 or None, got {capacity_bytes}")
        self.capacity_bytes = capacity_bytes
        self._store: Dict[int, List[np.ndarray]] = {}
        self.bytes_in_use = 0
        self.bytes_peak = 0
        self.puts = 0
        self.takes = 0

    def fits(self, nbytes: int) -> bool:
        return (self.capacity_bytes is None
                or self.bytes_in_use + nbytes <= self.capacity_bytes)

    def put(self, rid: int, arrays: List[np.ndarray], nbytes: int) -> bool:
        if rid in self._store:
            raise KeyError(f"request {rid} already has a parked KV copy")
        if not self.fits(nbytes):
            return False
        self._store[rid] = arrays
        self.bytes_in_use += nbytes
        self.bytes_peak = max(self.bytes_peak, self.bytes_in_use)
        self.puts += 1
        return True

    def take(self, rid: int, nbytes: int) -> List[np.ndarray]:
        arrays = self._store.pop(rid)
        self.bytes_in_use -= nbytes
        self.takes += 1
        return arrays

    def peek(self, rid: int) -> List[np.ndarray]:
        """Read a parked payload without removing it — snapshot() copies
        already-swapped requests' KV through this."""
        return self._store[rid]

    def discard(self, rid: int, nbytes: int) -> None:
        if self._store.pop(rid, None) is not None:
            self.bytes_in_use -= nbytes

    def stats(self) -> Dict[str, int]:
        return {"bytes_in_use": self.bytes_in_use,
                "bytes_peak": self.bytes_peak,
                "puts": self.puts, "takes": self.takes,
                "parked": len(self._store)}

    def __len__(self) -> int:
        return len(self._store)


class KVOffloadEngine:
    """Swap-out / swap-in over a server's flat pool list.

    Stateless between calls except for the host pool: the caller passes
    the current (donation-rotated) ``pools`` list each time and takes the
    updated list back from :meth:`swap_in`.
    """

    def __init__(self, alloc, table_width: int,
                 capacity_bytes: Optional[int] = None):
        self.alloc = alloc
        self.table_width = int(table_width)
        self.host = HostKVPool(capacity_bytes)
        # optional ServingTelemetry (inference/telemetry.py): the owning
        # server sets this so swap copies emit per-request spans + the
        # serving_swap_{out,in}_s histograms. The copies themselves are
        # untouched — timing wraps the whole eager d2h/h2d sequence.
        self.telemetry = None
        # optional FaultInjector (inference/faults.py): host-pool refusal
        # and swap-payload corruption hooks for chaos plans
        self.faults = None

    # ------------------------------------------------------------ KV capture
    def gather_payload(self, table: Sequence[int],
                       pools: List[Any]) -> List[np.ndarray]:
        """Non-destructive fixed-width device→host gather of a table's
        blocks — the same one-compile program :meth:`swap_out` rides, so
        ``GenerationServer.snapshot()`` can capture a warm server's KV
        without compiling anything new. Blocks are pinned for the copy
        and left exactly as they were."""
        import jax.numpy as jnp

        a = self.alloc
        idx = np.zeros((self.table_width,), np.int32)
        idx[:len(table)] = table
        for bid in table:                 # freeze against LRU churn mid-copy
            a.pin(bid)
        try:
            didx = jnp.asarray(idx)
            # the d2h pull IS the point — one sync per pool tensor,
            # outside any trace
            arrays = [np.asarray(p[didx]) for p in pools]  # graftlint: noqa[host-sync]
        finally:
            for bid in table:
                a.unpin(bid)
        return arrays

    # ------------------------------------------------------------- swap out
    def swap_out(self, rid: int, table: Sequence[int], hashes: Sequence[int],
                 pools: List[Any], n_tokens: int,
                 last_token: int) -> Optional[SwapHandle]:
        """Park a request's KV on host and free its device blocks.

        ``table`` must already be truncated to exactly the blocks covering
        ``n_tokens`` (the server drops speculative reservations first).
        Returns None — and changes nothing — when the host pool is full
        (or an injected ``host_put`` fault says it is).
        """
        tel = self.telemetry
        _t0 = tel.clock() if tel is not None and tel.enabled else None
        a = self.alloc
        n = len(table)
        nbytes = n * a.bytes_per_block
        if self.faults is not None and self.faults.fire("host_put") is not None:
            return None
        if not self.host.fits(nbytes):
            return None
        arrays = self.gather_payload(table, pools)
        checksum = payload_checksum(arrays)
        if not self.host.put(rid, arrays, nbytes):
            return None
        for bid in table:
            a.free(bid)                   # hashed blocks land on the LRU
        a.note_swap_out(n, nbytes)
        if _t0 is not None:
            _t1 = tel.clock()
            tel.registry.histogram(
                "serving_swap_out_s",
                "device->host KV swap-out wall time").observe(_t1 - _t0)
            tel.registry.counter(
                "serving_swap_out_bytes",
                "KV bytes parked to host").inc(nbytes)
            tel.tracer.complete(rid, "swap_out", _t0, _t1,
                                blocks=n, bytes=nbytes)
        return SwapHandle(rid=rid, n_tokens=int(n_tokens),
                          last_token=int(last_token), n_blocks=n,
                          hashes=list(hashes), nbytes=nbytes,
                          checksum=checksum)

    # -------------------------------------------------------------- swap in
    def restore_cost(self, handle: SwapHandle) -> int:
        """Upper bound on fresh device blocks a resume needs (hash matches
        can only lower it) — the server's admission headroom check."""
        return handle.n_blocks

    def swap_in(self, handle: SwapHandle, pools: List[Any]
                ) -> Union[None, str, Tuple[List[int], List[Any]]]:
        """Restore a parked request: re-match still-resident prefix blocks
        by chain hash (free — no upload), allocate + upload the rest, and
        re-register restored full prompt blocks for prefix sharing.

        Returns ``(table, pools)`` with the updated pool list; None —
        changing nothing — if the device pool lacks headroom (the caller
        keeps the entry queued and tries again later); or the string
        ``"corrupt"`` when the parked payload fails its CRC check — the
        payload is dropped, device and host accounting are rolled back,
        and the caller must re-prefill the request from its tokens.
        """
        import jax.numpy as jnp

        tel = self.telemetry
        _t0 = tel.clock() if tel is not None and tel.enabled else None
        a = self.alloc
        matched = a.match_hashes(handle.hashes)
        need = handle.n_blocks - len(matched)
        if a.blocks_free + a.evictable_cached < need:
            for bid in matched:           # roll back: nothing restored
                a.free(bid)
            return None
        fresh: List[int] = []
        try:
            for _ in range(need):
                fresh.append(a.alloc())
        except RuntimeError:
            # headroom said yes but alloc refused (an injected exhaustion
            # fault, or a pin racing the estimate): roll everything back
            for bid in fresh + matched:
                a.free(bid)
            return None
        table = matched + fresh
        arrays = self.host.take(handle.rid, handle.nbytes)
        if self.faults is not None and \
                self.faults.fire("swap_corrupt") is not None:
            # the parked payload may be a read-only device-array view —
            # rewrap writable before flipping the bit
            arrays = [np.array(x) for x in arrays]
            self.faults.corrupt(arrays)
        if handle.checksum and payload_checksum(arrays) != handle.checksum:
            # the parked copy is damaged: drop it, release the claimed
            # blocks (host.take already uncharged the host pool)
            for bid in table:
                a.free(bid)
            a.note_host_release(handle.nbytes)
            if tel is not None and tel.enabled:
                tel.registry.counter(
                    "serving_swap_corruptions",
                    "parked KV payloads that failed CRC verification"
                ).inc()
            return "corrupt"
        if fresh:
            # fixed-width scatter: matched rows and padding target the
            # scratch block (duplicate writes there are discarded noise)
            idx = np.zeros((self.table_width,), np.int32)
            idx[len(matched):handle.n_blocks] = fresh
            didx = jnp.asarray(idx)
            pools = [p.at[didx].set(jnp.asarray(arr).astype(p.dtype))
                     for p, arr in zip(pools, arrays)]
        for i in range(len(matched), min(len(handle.hashes), len(table))):
            a.register(table[i], handle.hashes[i])
        a.note_swap_in(handle.n_blocks, handle.nbytes)
        if _t0 is not None:
            _t1 = tel.clock()
            tel.registry.histogram(
                "serving_swap_in_s",
                "host->device KV swap-in wall time").observe(_t1 - _t0)
            tel.registry.counter(
                "serving_swap_in_bytes",
                "KV bytes restored from host").inc(handle.nbytes)
            tel.tracer.complete(handle.rid, "swap_in", _t0, _t1,
                                blocks=handle.n_blocks,
                                prefix_hits=len(matched),
                                bytes=handle.nbytes)
        return table, pools

    def discard(self, handle: SwapHandle) -> None:
        """Drop a parked copy without restoring it (cancelled request)."""
        self.host.discard(handle.rid, handle.nbytes)
        self.alloc.note_host_release(handle.nbytes)

    def adopt(self, handle: SwapHandle, arrays: List[np.ndarray]) -> None:
        """Re-park a payload captured by ``GenerationServer.snapshot()``
        into this engine's host pool (restore / migration): the request
        then resumes through the normal checksum-verified :meth:`swap_in`
        path, so a corrupted migration payload degrades to re-prefill
        instead of silently wrong tokens."""
        if not self.host.put(handle.rid, arrays, handle.nbytes):
            raise RuntimeError(
                f"host pool cannot hold restored request {handle.rid} "
                f"({handle.nbytes} bytes) — raise host_pool_bytes on the "
                f"restoring server")
        self.alloc.note_swap_out(handle.n_blocks, handle.nbytes)
