"""Host KV offload — swap-preemption for the paged serving pool.

The mechanism half of overload handling (policy lives in
``inference/scheduler.py``): when the device block pool runs dry, the
server preempts a victim request by copying its KV blocks — fp rows, or
int8 codes + f32 scales, the engine is pool-format agnostic — into a
host-memory pool, freeing the HBM blocks for more urgent work. On resume
the blocks are restored and the request continues exactly where it
stopped: greedy output is token-identical to an un-preempted run because
the round trip is a bit-exact copy of whatever the pool held.

Why swapping beats recompute here: a decoding request's KV past the
prompt was produced by its own sampled continuation — re-prefilling
``prompt + generated`` would rebuild it through a different program
(chunked prefill vs decode steps) with different float rounding, beyond
re-spending the FLOPs. Prefill-only work IS recomputable, which is why
``GenerationServer`` aborts (not swaps) victims still in prefill.

Compile discipline (the zero-steady-state-recompile guarantee must
survive preemption):

- The device↔host copies are EAGER ops, not new jitted programs, and
  they run at ONE fixed shape: every gather/scatter covers the full
  ``table_width`` rows of the slot's block table, padded with the
  scratch block. A swap of 3 blocks and a swap of 30 compile the same
  executables (once, at the first preemption); nothing is keyed on how
  many blocks a victim happens to hold.
- Scatter padding targets block 0 — the reserved scratch block that
  absorbs masked writes everywhere else in the paged path — so the
  fixed-width restore can never touch a live block.

Prefix-cache integration: the victim's chain hashes ride along in the
:class:`SwapHandle`. Swap-out releases the device blocks through the
normal refcount path, so hashed prompt blocks land on the allocator's
LRU — still resident, still shareable. Swap-in first re-matches those
hashes (``BlockAllocator.match_hashes``): every hit is a block restored
WITHOUT an upload (or a byte of HBM traffic), and every uploaded full
prompt block is re-registered under its hash so restored requests keep
participating in prefix sharing.
"""
from __future__ import annotations

import zlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

__all__ = ["SwapHandle", "HostKVPool", "WarmTier", "KVOffloadEngine",
           "payload_checksum"]


def payload_checksum(arrays: Sequence[np.ndarray]) -> int:
    """CRC32 over a parked payload's raw bytes (order-sensitive).

    Cheap enough to run on every swap boundary and strong enough to catch
    the single-bit-flip corruption the chaos plans inject; a mismatch on
    swap-in means the parked copy cannot be trusted and the server falls
    back to re-prefilling the request's tokens.
    """
    c = 0
    for a in arrays:
        c = zlib.crc32(np.ascontiguousarray(a).reshape(-1).view(np.uint8), c)
    return c


@dataclass
class SwapHandle:
    """Resume ticket for one preempted request: where it stopped, which
    chain hashes its prompt blocks carry, and how much host memory the
    parked copy occupies. The block CONTENTS live in the
    :class:`HostKVPool` under ``rid``."""

    rid: int
    n_tokens: int            # KV-valid positions [0, n_tokens)
    last_token: int          # next decode input (its KV is not written yet)
    n_blocks: int            # live table entries parked on host
    hashes: List[int] = field(default_factory=list)  # leading full-prompt-block chain hashes
    nbytes: int = 0          # logical bytes charged to the host pool
    checksum: int = 0        # CRC32 of the parked payload (0 = unverified)


class HostKVPool:
    """Byte-budgeted host store for swapped block stacks.

    ``capacity_bytes=None`` means unbounded (the default server setting —
    host DRAM dwarfs HBM); a bounded pool makes :meth:`put` refuse once
    full, which the server treats as "this victim cannot be preempted".
    """

    def __init__(self, capacity_bytes: Optional[int] = None):
        if capacity_bytes is not None and capacity_bytes < 0:
            raise ValueError(
                f"capacity_bytes must be >= 0 or None, got {capacity_bytes}")
        self.capacity_bytes = capacity_bytes
        self._store: Dict[int, List[np.ndarray]] = {}
        self.bytes_in_use = 0
        self.bytes_peak = 0
        self.puts = 0
        self.takes = 0
        # refusals are a capacity signal, not a silent drop: the server's
        # telemetry snapshot exports every stats() field as a
        # serving_host_pool_* gauge, so rejects reaching stats() is what
        # makes "the host pool is too small" observable
        self.rejects = 0

    def fits(self, nbytes: int) -> bool:
        return (self.capacity_bytes is None
                or self.bytes_in_use + nbytes <= self.capacity_bytes)

    def put(self, rid: int, arrays: List[np.ndarray], nbytes: int) -> bool:
        if rid in self._store:
            raise KeyError(f"request {rid} already has a parked KV copy")
        if not self.fits(nbytes):
            self.rejects += 1
            return False
        self._store[rid] = arrays
        self.bytes_in_use += nbytes
        self.bytes_peak = max(self.bytes_peak, self.bytes_in_use)
        self.puts += 1
        return True

    def take(self, rid: int, nbytes: int) -> List[np.ndarray]:
        arrays = self._store.pop(rid)
        self.bytes_in_use -= nbytes
        self.takes += 1
        return arrays

    def peek(self, rid: int) -> List[np.ndarray]:
        """Read a parked payload without removing it — snapshot() copies
        already-swapped requests' KV through this."""
        return self._store[rid]

    def discard(self, rid: int, nbytes: int) -> None:
        if self._store.pop(rid, None) is not None:
            self.bytes_in_use -= nbytes

    def stats(self) -> Dict[str, int]:
        return {"bytes_in_use": self.bytes_in_use,
                "bytes_peak": self.bytes_peak,
                "puts": self.puts, "takes": self.takes,
                "rejects": self.rejects,
                "parked": len(self._store)}

    def __len__(self) -> int:
        return len(self._store)


class WarmTier:
    """Hash-keyed warm tier: per-block host copies of DEMOTED prefix
    blocks, addressable by the same chain hash the allocator's hot-tier
    prefix cache uses.

    Where :class:`HostKVPool` parks whole per-request block stacks under
    a rid (swap preemption), the warm tier holds individual shareable
    prompt blocks under their content hash — the second rung of the
    hot (HBM) → warm (host) → cold (re-prefill) ladder. A block demoted
    here left HBM entirely; a later prefix match promotes it back
    through the compile-once fixed-width scatter, CRC-verified, and a
    failed check simply breaks the chain walk (the request re-prefills
    those tokens — the cold rung, never wrong tokens).

    LRU over chain hashes; a bounded tier evicts its coldest entries to
    make room (eviction = the block falls to the cold tier). Bytes are
    ledgered separately from the swap pool so the server's conservation
    audit can hold each ledger to its own invariant.
    """

    def __init__(self, capacity_bytes: Optional[int] = None):
        if capacity_bytes is not None and capacity_bytes < 0:
            raise ValueError(
                f"capacity_bytes must be >= 0 or None, got {capacity_bytes}")
        self.capacity_bytes = capacity_bytes
        # chain_hash -> (per-pool block arrays, nbytes, checksum)
        self._store: "OrderedDict[int, Tuple[List[np.ndarray], int, int]]" \
            = OrderedDict()
        self.bytes_in_use = 0
        self.bytes_peak = 0
        self.demoted_blocks = 0
        self.promoted_blocks = 0
        self.hit_blocks = 0
        self.rejects = 0
        self.evictions = 0
        self.corruptions = 0

    def __contains__(self, chain_hash: int) -> bool:
        return chain_hash in self._store

    def __len__(self) -> int:
        return len(self._store)

    def put(self, chain_hash: int, arrays: List[np.ndarray],
            nbytes: int, checksum: int) -> bool:
        """Admit one demoted block; evicts coldest entries until it
        fits. False (and ``rejects`` ticks) when it can never fit."""
        if chain_hash in self._store:
            return True                   # already warm — nothing to copy
        if self.capacity_bytes is not None:
            if nbytes > self.capacity_bytes:
                self.rejects += 1
                return False
            while self.bytes_in_use + nbytes > self.capacity_bytes:
                _, (_, old_bytes, _) = self._store.popitem(last=False)
                self.bytes_in_use -= old_bytes
                self.evictions += 1
        self._store[chain_hash] = (arrays, int(nbytes), int(checksum))
        self.bytes_in_use += nbytes
        self.bytes_peak = max(self.bytes_peak, self.bytes_in_use)
        self.demoted_blocks += 1
        return True

    def peek(self, chain_hash: int) -> Tuple[List[np.ndarray], int, int]:
        """Read an entry without removing it (refreshes LRU position)."""
        self._store.move_to_end(chain_hash)
        return self._store[chain_hash]

    def take(self, chain_hash: int) -> Tuple[List[np.ndarray], int, int]:
        """Remove an entry — promotion back to HBM (the bytes move
        tiers) or a corruption drop."""
        entry = self._store.pop(chain_hash)
        self.bytes_in_use -= entry[1]
        return entry

    def drop_corrupt(self, chain_hash: int) -> None:
        self.take(chain_hash)
        self.corruptions += 1

    def entries(self) -> List[Tuple[int, List[np.ndarray], int, int]]:
        """(hash, arrays, nbytes, checksum) rows in LRU order — the
        fleet migration capture (``GenerationServer.evacuate``)."""
        return [(h, arrs, nb, crc)
                for h, (arrs, nb, crc) in self._store.items()]

    def clear(self) -> None:
        """Drop every entry (a full evacuate — the snapshot carries the
        copies). Counters keep their history; only occupancy resets."""
        self._store.clear()
        self.bytes_in_use = 0

    def stats(self) -> Dict[str, int]:
        return {"blocks": len(self._store),
                "bytes_in_use": self.bytes_in_use,
                "bytes_peak": self.bytes_peak,
                "demoted_blocks": self.demoted_blocks,
                "promoted_blocks": self.promoted_blocks,
                "hit_blocks": self.hit_blocks,
                "rejects": self.rejects,
                "evictions": self.evictions,
                "corruptions": self.corruptions}


class KVOffloadEngine:
    """Swap-out / swap-in over a server's flat pool list.

    Stateless between calls except for the host pool: the caller passes
    the current (donation-rotated) ``pools`` list each time and takes the
    updated list back from :meth:`swap_in`.
    """

    def __init__(self, alloc, table_width: int,
                 capacity_bytes: Optional[int] = None,
                 warm_capacity_bytes: Optional[int] = None):
        self.alloc = alloc
        self.table_width = int(table_width)
        self.host = HostKVPool(capacity_bytes)
        # hash-addressed warm tier for demoted prefix blocks; the
        # allocator's read-only probe consults it through warm_probe so
        # fleet routing scores warm residency without any side effect
        self.warm = WarmTier(warm_capacity_bytes)
        if hasattr(alloc, "warm_probe"):
            alloc.warm_probe = self.warm.__contains__
        # optional ServingTelemetry (inference/telemetry.py): the owning
        # server sets this so swap copies emit per-request spans + the
        # serving_swap_{out,in}_s histograms. The copies themselves are
        # untouched — timing wraps the whole eager d2h/h2d sequence.
        self.telemetry = None
        # optional FaultInjector (inference/faults.py): host-pool refusal
        # and swap-payload corruption hooks for chaos plans
        self.faults = None

    # ------------------------------------------------------------ KV capture
    def gather_payload(self, table: Sequence[int],
                       pools: List[Any]) -> List[np.ndarray]:
        """Non-destructive fixed-width device→host gather of a table's
        blocks — the same one-compile program :meth:`swap_out` rides, so
        ``GenerationServer.snapshot()`` can capture a warm server's KV
        without compiling anything new. Blocks are pinned for the copy
        and left exactly as they were."""
        import jax.numpy as jnp

        a = self.alloc
        idx = np.zeros((self.table_width,), np.int32)
        idx[:len(table)] = table
        for bid in table:                 # freeze against LRU churn mid-copy
            a.pin(bid)
        try:
            didx = jnp.asarray(idx)
            # the d2h pull IS the point — one sync per pool tensor,
            # outside any trace
            arrays = [np.asarray(p[didx]) for p in pools]  # graftlint: noqa[host-sync]
        finally:
            for bid in table:
                a.unpin(bid)
        return arrays

    # ----------------------------------------------------------- tier ladder
    def demote(self, victims: Sequence[Tuple[int, int]],
               pools: List[Any]) -> int:
        """Move cached (ref==0) prefix blocks HBM → warm tier.

        ``victims`` is ``[(bid, chain_hash), ...]`` straight from
        ``BlockAllocator.coldest_cached``. One fixed-width gather — the
        SAME compiled shape ``gather_payload``/``swap_out`` already use,
        so pressure-driven demotion adds zero steady-state compiles —
        pulls every victim at once; each block is then sliced out,
        CRC-stamped, and admitted to the warm tier individually, and
        only blocks the tier accepted are evicted from HBM. Returns the
        number of blocks demoted."""
        if not victims:
            return 0
        tel = self.telemetry
        _t0 = tel.clock() if tel is not None and tel.enabled else None
        a = self.alloc
        bids = [bid for bid, _ in victims]
        arrays = self.gather_payload(bids, pools)
        moved = 0
        for i, (bid, h) in enumerate(victims):
            block = [np.asarray(p[i]) for p in arrays]
            if not self.warm.put(h, block, a.bytes_per_block,
                                 payload_checksum(block)):
                break                     # tier can never hold it — stay hot
            a.evict_cached(bid)
            moved += 1
        if _t0 is not None and moved:
            _t1 = tel.clock()
            tel.registry.histogram(
                "serving_tier_demote_s",
                "HBM->warm tier demotion wall time (batched)"
            ).observe(_t1 - _t0)
            tel.registry.counter(
                "serving_tier_demoted_bytes",
                "KV bytes demoted to the warm tier"
            ).inc(moved * a.bytes_per_block)
        return moved

    def match_prefix_tiered(self, tokens: Sequence[int], pools: List[Any]
                            ) -> Tuple[List[int], List[Any], Dict[str, int]]:
        """Cross-tier prefix match: the warm-aware twin of
        ``BlockAllocator.match_prefix``.

        Walks the chain hashes of ``tokens`` (last-token rule applies):
        a hot hit re-refs the resident block as before; a warm hit
        allocates a fresh device block, CRC-verifies the parked copy and
        promotes it back through ONE batched fixed-width scatter — the
        same compiled shape ``swap_in`` uses — then re-registers it
        under its hash so the promotion is shareable. The first miss
        (or a failed CRC, or a dry device pool) stops the walk; tokens
        past it re-prefill normally, which IS the cold tier.

        Returns ``(table, pools, {"hot": n, "warm": n})`` — every block
        in ``table`` is ref'd for the caller, ``pools`` reflects the
        promotion scatter (unchanged when nothing was promoted)."""
        import jax.numpy as jnp

        a = self.alloc
        n = len(tokens)
        limit = max((n - 1) // a.block_size, 0)
        hashes = a.chain_hashes(tokens)[:limit]
        table: List[int] = []
        warm_bids: List[int] = []
        warm_hashes: List[int] = []
        warm_blocks: List[List[np.ndarray]] = []
        hot = 0
        for h in hashes:
            bid = a.ref_hash(h)
            if bid is not None:
                table.append(bid)
                hot += 1
                continue
            if h not in self.warm:
                break
            arrs, nbytes, checksum = self.warm.peek(h)
            if self.faults is not None and \
                    self.faults.fire("warm_corrupt") is not None:
                arrs = [np.array(x) for x in arrs]
                self.faults.corrupt(arrs)
            if checksum and payload_checksum(arrs) != checksum:
                # damaged parked block: drop it (cold tier from here on)
                self.warm.drop_corrupt(h)
                tel = self.telemetry
                if tel is not None and tel.enabled:
                    tel.registry.counter(
                        "serving_tier_corruptions",
                        "warm-tier blocks that failed CRC verification"
                    ).inc()
                break
            if a.blocks_free + a.evictable_cached < 1:
                break                     # no headroom to promote into
            try:
                bid = a.alloc()
            except RuntimeError:
                break
            table.append(bid)
            warm_bids.append(bid)
            warm_hashes.append(h)
            warm_blocks.append(arrs)
        a.prefix_lookup_blocks += len(hashes)
        a.prefix_hit_blocks += hot
        if warm_bids:
            tel = self.telemetry
            _t0 = tel.clock() if tel is not None and tel.enabled else None
            # batched fixed-width promotion scatter: rows past the warm
            # hits target the scratch block, exactly like swap_in
            idx = np.zeros((self.table_width,), np.int32)
            idx[:len(warm_bids)] = warm_bids
            didx = jnp.asarray(idx)
            new_pools = []
            for j, p in enumerate(pools):
                stack = np.zeros((self.table_width,)
                                 + warm_blocks[0][j].shape,
                                 dtype=warm_blocks[0][j].dtype)
                for i, blk in enumerate(warm_blocks):
                    stack[i] = blk[j]
                new_pools.append(
                    p.at[didx].set(jnp.asarray(stack).astype(p.dtype)))
            pools = new_pools
            for bid, h in zip(warm_bids, warm_hashes):
                a.register(bid, h)
                self.warm.take(h)         # bytes move tiers with the block
            self.warm.promoted_blocks += len(warm_bids)
            self.warm.hit_blocks += len(warm_bids)
            a.note_promote(len(warm_bids))
            if _t0 is not None:
                _t1 = tel.clock()
                tel.registry.histogram(
                    "serving_tier_promote_s",
                    "warm->HBM tier promotion wall time (batched)"
                ).observe(_t1 - _t0)
                tel.registry.counter(
                    "serving_tier_promoted_bytes",
                    "KV bytes promoted back from the warm tier"
                ).inc(len(warm_bids) * a.bytes_per_block)
        return table, pools, {"hot": hot, "warm": len(warm_bids)}

    def forget_warm(self, chain_hash: int) -> None:
        """A hash just (re)registered in the hot prefix cache supersedes
        any warm copy — same chain hash means bit-identical KV by
        construction, so keeping both only wastes host RAM (and would
        trip the conservation audit's cross-tier exclusivity check).
        Call after every ``BlockAllocator.register`` that can re-create
        a previously demoted block."""
        if chain_hash in self.warm:
            self.warm.take(chain_hash)

    def tier_stats(self) -> Dict[str, int]:
        """Warm-tier occupancy/traffic, ``warm_``-prefixed for merging
        into ``GenerationServer.kv_stats()``."""
        return {f"warm_{k}": v for k, v in self.warm.stats().items()}

    # ------------------------------------------------------------- swap out
    def swap_out(self, rid: int, table: Sequence[int], hashes: Sequence[int],
                 pools: List[Any], n_tokens: int,
                 last_token: int) -> Optional[SwapHandle]:
        """Park a request's KV on host and free its device blocks.

        ``table`` must already be truncated to exactly the blocks covering
        ``n_tokens`` (the server drops speculative reservations first).
        Returns None — and changes nothing — when the host pool is full
        (or an injected ``host_put`` fault says it is).
        """
        tel = self.telemetry
        _t0 = tel.clock() if tel is not None and tel.enabled else None
        a = self.alloc
        n = len(table)
        nbytes = n * a.bytes_per_block
        if self.faults is not None and self.faults.fire("host_put") is not None:
            return None
        if not self.host.fits(nbytes):
            return None
        arrays = self.gather_payload(table, pools)
        checksum = payload_checksum(arrays)
        if not self.host.put(rid, arrays, nbytes):
            return None
        for bid in table:
            a.free(bid)                   # hashed blocks land on the LRU
        a.note_swap_out(n, nbytes)
        if _t0 is not None:
            _t1 = tel.clock()
            tel.registry.histogram(
                "serving_swap_out_s",
                "device->host KV swap-out wall time").observe(_t1 - _t0)
            tel.registry.counter(
                "serving_swap_out_bytes",
                "KV bytes parked to host").inc(nbytes)
            tel.tracer.complete(rid, "swap_out", _t0, _t1,
                                blocks=n, bytes=nbytes)
        return SwapHandle(rid=rid, n_tokens=int(n_tokens),
                          last_token=int(last_token), n_blocks=n,
                          hashes=list(hashes), nbytes=nbytes,
                          checksum=checksum)

    # -------------------------------------------------------------- swap in
    def restore_cost(self, handle: SwapHandle) -> int:
        """Upper bound on fresh device blocks a resume needs — the
        server's admission headroom check. Resident-hash-aware: leading
        chain hashes still hot in the allocator restore for free
        (``match_hashes`` will re-ref them), so only the remainder costs
        fresh blocks. Read-only."""
        resident = 0
        for h in handle.hashes:
            if not self.alloc.contains_hash(h):
                break
            resident += 1
        return max(handle.n_blocks - resident, 0)

    def swap_in(self, handle: SwapHandle, pools: List[Any]
                ) -> Union[None, str, Tuple[List[int], List[Any]]]:
        """Restore a parked request: re-match still-resident prefix blocks
        by chain hash (free — no upload), allocate + upload the rest, and
        re-register restored full prompt blocks for prefix sharing.

        Returns ``(table, pools)`` with the updated pool list; None —
        changing nothing — if the device pool lacks headroom (the caller
        keeps the entry queued and tries again later); or the string
        ``"corrupt"`` when the parked payload fails its CRC check — the
        payload is dropped, device and host accounting are rolled back,
        and the caller must re-prefill the request from its tokens.
        """
        import jax.numpy as jnp

        tel = self.telemetry
        _t0 = tel.clock() if tel is not None and tel.enabled else None
        a = self.alloc
        matched = a.match_hashes(handle.hashes)
        need = handle.n_blocks - len(matched)
        if a.blocks_free + a.evictable_cached < need:
            for bid in matched:           # roll back: nothing restored
                a.free(bid)
            return None
        fresh: List[int] = []
        try:
            for _ in range(need):
                fresh.append(a.alloc())
        except RuntimeError:
            # headroom said yes but alloc refused (an injected exhaustion
            # fault, or a pin racing the estimate): roll everything back
            for bid in fresh + matched:
                a.free(bid)
            return None
        table = matched + fresh
        arrays = self.host.take(handle.rid, handle.nbytes)
        if self.faults is not None and \
                self.faults.fire("swap_corrupt") is not None:
            # the parked payload may be a read-only device-array view —
            # rewrap writable before flipping the bit
            arrays = [np.array(x) for x in arrays]
            self.faults.corrupt(arrays)
        if handle.checksum and payload_checksum(arrays) != handle.checksum:
            # the parked copy is damaged: drop it, release the claimed
            # blocks (host.take already uncharged the host pool)
            for bid in table:
                a.free(bid)
            a.note_host_release(handle.nbytes)
            if tel is not None and tel.enabled:
                tel.registry.counter(
                    "serving_swap_corruptions",
                    "parked KV payloads that failed CRC verification"
                ).inc()
            return "corrupt"
        if fresh:
            # fixed-width scatter: matched rows and padding target the
            # scratch block (duplicate writes there are discarded noise)
            idx = np.zeros((self.table_width,), np.int32)
            idx[len(matched):handle.n_blocks] = fresh
            didx = jnp.asarray(idx)
            pools = [p.at[didx].set(jnp.asarray(arr).astype(p.dtype))
                     for p, arr in zip(pools, arrays)]
        for i in range(len(matched), min(len(handle.hashes), len(table))):
            a.register(table[i], handle.hashes[i])
            self.forget_warm(handle.hashes[i])
        a.note_swap_in(handle.n_blocks, handle.nbytes)
        if _t0 is not None:
            _t1 = tel.clock()
            tel.registry.histogram(
                "serving_swap_in_s",
                "host->device KV swap-in wall time").observe(_t1 - _t0)
            tel.registry.counter(
                "serving_swap_in_bytes",
                "KV bytes restored from host").inc(handle.nbytes)
            tel.tracer.complete(handle.rid, "swap_in", _t0, _t1,
                                blocks=handle.n_blocks,
                                prefix_hits=len(matched),
                                bytes=handle.nbytes)
        return table, pools

    def discard(self, handle: SwapHandle) -> None:
        """Drop a parked copy without restoring it (cancelled request)."""
        self.host.discard(handle.rid, handle.nbytes)
        self.alloc.note_host_release(handle.nbytes)

    def adopt(self, handle: SwapHandle, arrays: List[np.ndarray]) -> None:
        """Re-park a payload captured by ``GenerationServer.snapshot()``
        into this engine's host pool (restore / migration): the request
        then resumes through the normal checksum-verified :meth:`swap_in`
        path, so a corrupted migration payload degrades to re-prefill
        instead of silently wrong tokens."""
        if not self.host.put(handle.rid, arrays, handle.nbytes):
            raise RuntimeError(
                f"host pool cannot hold restored request {handle.rid} "
                f"({handle.nbytes} bytes) — raise host_pool_bytes on the "
                f"restoring server")
        self.alloc.note_swap_out(handle.n_blocks, handle.nbytes)
