"""Continuous-batching generation server — the TPU serving engine.

Ref capability: the reference serves models through AnalysisPredictor /
DistModel (inference/api/, fleet_executor/dist_model.cc) with request-level
batching. The TPU-native redesign follows modern LLM serving: a FIXED pool
of ``max_batch`` slots, each with its own KV-cache rows and position; ONE
compiled decode step advances every active slot per tick (static shapes —
compiled exactly once), and finished slots are freed and refilled mid-flight
so throughput is never quantized by batch boundaries (continuous batching).

Prefill runs per request at bucketed prompt lengths (one compile per
bucket), producing cache rows that are scattered into the slot. The decode
step uses the model's vector-position path (`LlamaAttention.decode` with
``pos [B]``): every slot attends at its own depth.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..jit import functional_call, state_values


@dataclass
class _Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    generated: List[int] = field(default_factory=list)
    done: bool = False


class GenerationServer:
    """Continuous-batching decode server for a ``LlamaForCausalLM`` —
    greedy by default, per-request temperature sampling via
    ``submit(..., temperature=...)``.

    Usage::

        srv = GenerationServer(model, max_batch=4, max_len=256)
        rid = srv.submit([1, 5, 9], max_new_tokens=16)
        out = srv.run()          # drain all pending requests
        tokens = out[rid]        # prompt + generated ids
    """

    def __init__(self, model, max_batch: int = 4, max_len: int = 256,
                 prompt_buckets: Sequence[int] = (32, 64, 128),
                 eos_token_id: Optional[int] = None, seed: int = 0,
                 tick_window: int = 1):
        """``tick_window``: decode ticks per host round trip. 1 = exact
        per-token semantics. k>1 runs k ticks as ONE compiled lax.scan
        before the host sees the tokens — eos detection and slot refill lag
        by up to k-1 tokens (the surplus is discarded), in exchange for
        amortizing the device→host sync: on a tunneled backend the
        round-trip dominates a decode tick by ~100×, and even on a local
        host it bounds tick-rate. The serving analogue of generate()'s
        fully-compiled scan loop."""
        cfg = model.cfg
        assert max_len <= cfg.max_position_embeddings
        self.model = model
        self.cfg = cfg
        self.max_batch = max_batch
        self.max_len = max_len
        self.buckets = sorted(b for b in prompt_buckets if b <= max_len)
        if not self.buckets:
            raise ValueError(
                f"no prompt bucket fits max_len={max_len} "
                f"(prompt_buckets={tuple(prompt_buckets)})")
        self.eos = eos_token_id
        if tick_window < 1:
            raise ValueError(f"tick_window must be >= 1, got {tick_window}")
        self.tick_window = int(tick_window)
        self.params = state_values(model)

        from ..framework.dtype import convert_dtype

        kv = cfg.num_key_value_heads
        d = cfg.hidden_size // cfg.num_attention_heads
        cdtype = convert_dtype(cfg.dtype)
        self._caches = [jnp.zeros((max_batch, max_len, kv, d), cdtype)
                        for _ in range(2 * cfg.num_hidden_layers)]
        # per-slot scalars live HOST-side (numpy): slot assignment would
        # otherwise cost one eager device dispatch per field per request —
        # each a full round trip on a tunneled backend
        self.pos = np.zeros((max_batch,), np.int32)
        self.tokens = np.zeros((max_batch,), np.int32)
        self.temps = np.zeros((max_batch,), np.float32)
        self._step_no = 0
        self._base_key = jax.random.PRNGKey(seed)
        self._slots: List[Optional[_Request]] = [None] * max_batch
        self._queue: deque = deque()
        self._results: Dict[int, List[int]] = {}
        self._next_rid = 0
        # donate the KV pool: XLA updates the caches in place instead of
        # copying 2·L·(max_batch, max_len, KV, D) every decoded token
        self._decode = jax.jit(self._decode_fn, donate_argnums=(2,))
        self._prefills: Dict[int, object] = {}  # bucket -> jitted fn

    # ------------------------------------------------------------ compiled fns
    def _head(self, h):
        from ..framework.dispatch import apply_op

        if self.cfg.tie_word_embeddings:
            return apply_op(lambda v, w: jnp.matmul(v, w.T), h,
                            self.model.model.embed_tokens.weight)
        return self.model.lm_head(h)

    def _decode_fn(self, params, tokens, flat_caches, pos, temps, active,
                   key):
        """``tick_window`` ticks as one compiled region: each tick advances
        every slot by one token (per-slot temperature: temp == 0 → greedy
        argmax; temp > 0 → categorical at that temperature). ``active``
        masks position advance so idle slots don't drift their cache write
        row. Returns the (k, B) token stack + final caches."""
        model = self.model

        def one_tick(carry, k):
            toks, flat_c, p = carry
            caches = [(Tensor(flat_c[2 * i]), Tensor(flat_c[2 * i + 1]))
                      for i in range(self.cfg.num_hidden_layers)]

            def call():
                h, new = model.model.decode_step(Tensor(toks[:, None]),
                                                 caches, p)
                return self._head(h), new

            logits, new = functional_call(model, params, call_fn=call)
            flat = []
            for ck, cv in new:
                flat += [ck.value, cv.value]
            lg = logits.value[:, 0].astype(jnp.float32)   # (B, V)
            greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            sampled = jax.random.categorical(
                jax.random.fold_in(key, k),
                lg / jnp.maximum(temps, 1e-6)[:, None]).astype(jnp.int32)
            nxt = jnp.where(temps > 0, sampled, greedy)
            return (nxt, flat, p + active), nxt

        if self.tick_window == 1:
            (_, flat, _), stack = one_tick((tokens, flat_caches, pos), 0)
            return stack[None], flat
        (_, flat, _), stack = jax.lax.scan(
            one_tick, (tokens, flat_caches, pos),
            jnp.arange(self.tick_window))
        return stack, flat

    def _prefill(self, bucket: int):
        """Prefill + slot scatter as ONE jitted call (donated pool): the
        per-layer eager `.at[slot].set` scatters cost 2·L dispatches per
        request otherwise — each a tunnel round trip."""
        if bucket not in self._prefills:
            model = self.model

            def fn(params, prompt, true_len, pool, slot):
                """prompt [1, bucket] right-padded; logits at true_len-1;
                the request's cache rows scatter into pool[slot]."""
                kvs = self.cfg.num_key_value_heads
                d = self.cfg.hidden_size // self.cfg.num_attention_heads
                from ..framework.dtype import convert_dtype

                cdtype = convert_dtype(self.cfg.dtype)
                caches = [(Tensor(jnp.zeros((1, self.max_len, kvs, d), cdtype)),
                           Tensor(jnp.zeros((1, self.max_len, kvs, d), cdtype)))
                          for _ in range(self.cfg.num_hidden_layers)]

                def call():
                    h, new = model.model.prefill(Tensor(prompt), caches)
                    last = jax.lax.dynamic_slice_in_dim(
                        h.value, true_len - 1, 1, 1)
                    return self._head(Tensor(last)), new

                logits, new = functional_call(model, params, call_fn=call)
                flat = []
                for ck, cv in new:
                    flat += [ck.value, cv.value]
                pool = [p.at[slot].set(row[0]) for p, row in zip(pool, flat)]
                return logits.value[:, 0].astype(jnp.float32), pool

            self._prefills[bucket] = jax.jit(fn, donate_argnums=(3,))
        return self._prefills[bucket]

    # --------------------------------------------------------------- requests
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               temperature: float = 0.0) -> int:
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_len={self.max_len}")
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        self._bucket_for(len(prompt))  # validate against buckets up front
        rid = self._next_rid
        self._next_rid += 1
        self._queue.append(_Request(rid, list(prompt), max_new_tokens,
                                    temperature=float(temperature)))
        return rid

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt length {n} exceeds largest bucket "
                         f"{self.buckets[-1]}")

    def _assign(self, slot: int, req: _Request) -> None:
        n = len(req.prompt)
        bucket = self._bucket_for(n)
        prompt = np.zeros((1, bucket), np.int32)
        prompt[0, :n] = req.prompt
        # one compiled call: prefill + scatter into the slot's pool rows.
        # Rows beyond the true prompt length hold right-pad garbage, but
        # decode writes sequentially from pos=n, overwriting each such row
        # BEFORE the attention mask (arange <= pos) can reach it.
        lg, self._caches = self._prefill(bucket)(
            self.params, jnp.asarray(prompt), n, self._caches, slot)
        # the FIRST generated token honors the request temperature too;
        # sample/argmax on the still-on-device logits so each assignment
        # costs exactly ONE host sync
        if req.temperature > 0:
            k = jax.random.fold_in(self._base_key, (req.rid << 20) | 1)
            first = int(jax.random.categorical(
                k, lg / max(req.temperature, 1e-6))[0])
        else:
            first = int(jnp.argmax(lg, axis=-1)[0])
        self.pos[slot] = n
        self.tokens[slot] = first
        self.temps[slot] = req.temperature
        req.generated.append(first)
        self._slots[slot] = req

    def _fill_free_slots(self) -> None:
        for s in range(self.max_batch):
            if self._slots[s] is None and self._queue:
                self._assign(s, self._queue.popleft())

    def step(self) -> int:
        """One decode window (``tick_window`` ticks) across all occupied
        slots; returns #active."""
        self._fill_free_slots()
        active = [s for s in range(self.max_batch)
                  if self._slots[s] is not None]
        if not active:
            return 0
        self._step_no += 1
        key = jax.random.fold_in(self._base_key, self._step_no)
        active_mask = np.zeros((self.max_batch,), np.int32)
        active_mask[active] = 1
        # only occupied slots advance — idle slots must not drift their
        # write position (their garbage scatters would eventually go OOB)
        stack, self._caches = self._decode(
            self.params, jnp.asarray(self.tokens), self._caches,
            jnp.asarray(self.pos), jnp.asarray(self.temps),
            jnp.asarray(active_mask), key)
        k = self.tick_window
        nxt_host = np.asarray(stack)          # (k, B)
        self.pos = self.pos + active_mask * k
        self.tokens = nxt_host[-1].copy()
        pos_after = self.pos
        for s in active:
            req = self._slots[s]
            done = False
            for t in range(k):
                tok = int(nxt_host[t, s])
                finished_last = (self.eos is not None and
                                 req.generated[-1] == self.eos)
                if not finished_last:
                    req.generated.append(tok)
                pos_t = int(pos_after[s]) - k + t + 1
                if (finished_last
                        or len(req.generated) >= req.max_new_tokens
                        or pos_t >= self.max_len - 1):
                    done = True
                    break
            if done:
                # window surplus past completion is discarded (tick_window
                # semantics); the slot frees for next window's refill
                self._results[req.rid] = req.prompt + req.generated[
                    :req.max_new_tokens]
                self._slots[s] = None
        return sum(sl is not None for sl in self._slots) + len(self._queue)

    def run(self) -> Dict[int, List[int]]:
        """Drain the queue; returns {rid: prompt+generated token ids}."""
        while self.step():
            pass
        out, self._results = self._results, {}
        return out
