"""Continuous-batching generation server — the TPU serving engine.

Ref capability: the reference serves models through AnalysisPredictor /
DistModel (inference/api/, fleet_executor/dist_model.cc) with request-level
batching. The TPU-native redesign follows modern LLM serving: a FIXED pool
of ``max_batch`` slots, each with its own KV-cache rows and position; ONE
compiled decode step advances every active slot per tick (static shapes —
compiled exactly once), and finished slots are freed and refilled mid-flight
so throughput is never quantized by batch boundaries (continuous batching).

Two KV-cache backends share the slot machinery (``cache=`` ctor arg):

- ``"dense"`` (the reference oracle): a ``2·L·(max_batch, max_len, KV, D)``
  slab, one cache row span per slot. Prefill runs per request at bucketed
  prompt lengths (one compile per bucket) and scatters into the slot.
- ``"paged"``: a shared pool of fixed-size blocks + per-slot block tables
  (ops/paged_attention.py, inference/paged_cache.py). HBM is proportional
  to ACTIVE tokens instead of ``max_batch · max_len``; prompts stream
  through ONE compiled fixed-chunk prefill program (chunked prefill — no
  per-bucket compile family, no head-of-line blocking: each server step
  advances one chunk per prefilling slot, then runs the decode tick for
  the slots already decoding); full prompt blocks are content-hashed and
  refcount-shared, so a repeated prefix (shared system prompt) prefills
  once (prefix caching). Greedy outputs are token-exact vs the dense
  server. See docs/serving.md.

The decode step uses the model's vector-position path (``pos [B]``): every
slot attends at its own depth. Sampling routes through
``models/generation.py`` (``sample_token_rows`` in the compiled tick,
``next_token`` for the prefill-produced first token) so per-request
``temperature``/``top_k``/``top_p`` match ``model.generate`` semantics.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..jit import functional_call, state_values
from .scheduler import PRIORITY_NORMAL, SchedEntry, Scheduler


def kv_block_bytes(cfg, block_size: int, kv_quant: str = "none") -> int:
    """HBM bytes one KV block costs across ALL layers (K + V pools, plus
    the f32 scale rows for the int8 pool) — the unit `pool_bytes=` sizing
    and the benchmark's ``kv_bytes_per_token`` are derived from."""
    from ..framework.dtype import convert_dtype

    import jax.numpy as jnp

    kv = cfg.num_key_value_heads
    d = cfg.hidden_size // cfg.num_attention_heads
    if kv_quant == "int8":
        # int8 codes + one f32 scale per (block, kv head)
        per_pool = block_size * kv * d * 1 + kv * 4
    else:
        itemsize = jnp.zeros((), convert_dtype(cfg.dtype)).dtype.itemsize
        per_pool = block_size * kv * d * itemsize
    return 2 * cfg.num_hidden_layers * per_pool


@dataclass
class _Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    generated: List[int] = field(default_factory=list)
    done: bool = False
    draft_k: Optional[int] = None                    # per-request spec budget
    adapter: Optional[str] = None                    # LoRA adapter (None = base)
    sched: Any = None                                # its scheduler.SchedEntry
    # paged-path state
    table: List[int] = field(default_factory=list)   # block ids, in order
    hashes: List[int] = field(default_factory=list)  # chain hash per full blk
    pf_next: int = 0                                 # next prefill position
    # corruption-recovery replay: when a swap payload fails its CRC, the
    # request re-prefills prompt+generated[:-1] (this sequence) through
    # the token-exact chunked-prefill program instead of restoring bits
    replay: Optional[List[int]] = None


class GenerationServer:
    """Continuous-batching decode server for a ``LlamaForCausalLM`` —
    greedy by default, per-request sampling via
    ``submit(..., temperature=, top_k=, top_p=)``.

    Usage::

        srv = GenerationServer(model, max_batch=4, max_len=256)
        rid = srv.submit([1, 5, 9], max_new_tokens=16)
        out = srv.run()          # drain all pending requests
        tokens = out[rid]        # prompt + generated ids
    """

    def __init__(self, model, max_batch: int = 4, max_len: int = 256,
                 prompt_buckets: Sequence[int] = (32, 64, 128),
                 eos_token_id: Optional[int] = None, seed: int = 0,
                 tick_window: int = 1, cache: str = "dense",
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 prefill_chunk: int = 32, spec=None,
                 kv_quant: str = "none",
                 pool_bytes: Optional[int] = None,
                 policy=None,
                 host_pool_bytes: Optional[int] = None,
                 warm_pool_bytes: Optional[int] = None,
                 tier_demote_low: Optional[float] = None,
                 tier_demote_high: Optional[float] = None,
                 lora=None, telemetry=None, faults=None,
                 fault_retries: int = 3, kernels: str = "auto",
                 mk_geometry=None,
                 mesh=None, role: str = "any", profile=None,
                 clock=None):
        """``tick_window``: decode ticks per host round trip. 1 = exact
        per-token semantics. k>1 runs k ticks as ONE compiled lax.scan
        before the host sees the tokens — eos detection and slot refill lag
        by up to k-1 tokens (the surplus is discarded), in exchange for
        amortizing the device→host sync: on a tunneled backend the
        round-trip dominates a decode tick by ~100×, and even on a local
        host it bounds tick-rate. The serving analogue of generate()'s
        fully-compiled scan loop.

        ``cache="paged"``: block-table KV pool. ``block_size`` tokens per
        block; ``num_blocks`` bounds total KV memory (default: dense
        parity, ``max_batch·ceil(max_len/block_size)+1``); prompts prefill
        in fixed ``prefill_chunk``-token chunks (rounded up to a block
        multiple). ``prompt_buckets`` is ignored on the paged path.

        ``spec=SpecConfig(k=4)``: speculative decoding on the paged path —
        a drafter proposes k tokens per tick and ONE compiled verify
        program scores all k+1 window positions with exact accept/reject
        (greedy output token-exact vs the plain server; sampling output
        distribution provably unchanged). Requires ``cache='paged'`` and
        ``tick_window=1``. See inference/speculative.py, docs/serving.md.

        ``kv_quant="int8"`` (paged only): store the KV pool as int8 codes
        + f32 per-block-per-head scales (symmetric absmax) — half the
        bytes of bf16 per block, so ~2× resident blocks at the same pool
        budget and ~2× less KV traffic per decode tick. Dequant is FUSED
        into the compiled attention programs (ops/paged_attention.py
        ``*_q`` twins); the quant mode is fixed at construction so every
        program compiles once at warmup, same as the fp path.

        ``pool_bytes``: size the pool by HBM byte budget instead of block
        count — ``num_blocks = pool_bytes // kv_block_bytes(...)``. The
        int8 pool reports ~2× (bf16) / ~4× (f32) the blocks for the same
        budget. Mutually exclusive with ``num_blocks``.

        ``policy``: request-scheduling hook — None (FIFO, the
        pre-scheduler behavior), a policy name (``"fifo"`` / ``"priority"``
        / ``"wfq"``), or a configured :class:`~.scheduler.Scheduler`
        (for ``max_queue``/TTL/tenant weights). See inference/scheduler.py.

        ``host_pool_bytes`` (paged only): byte cap for the host KV pool
        that swap-preemption parks victim blocks in. None = unbounded
        (host DRAM dwarfs HBM); 0 disables swapping entirely — under
        pressure victims then stall instead of parking.

        ``warm_pool_bytes`` / ``tier_demote_low`` / ``tier_demote_high``
        (paged only): the tiered hot→warm→cold KV ladder
        (docs/serving.md, "Long-context serving"). When both watermarks
        are set (``0 < low < high <= 1``, fractions of usable blocks
        FREE), each paged tick that finds the free fraction below
        ``low`` demotes LRU prefix-cached blocks to the warm tier (a
        hash-keyed, CRC-guarded host store capped at
        ``warm_pool_bytes``; None = unbounded, 0 disables demotion)
        until the free fraction reaches ``high``. Warm blocks promoted
        back on a prefix hit skip their chunked-prefill work; blocks
        that fall off the warm tier re-prefill from replay (cold). Both
        watermarks unset (the default) keeps demotion off — the
        pre-tier behavior.

        ``lora=LoRAConfig(registry, ...)`` (paged only): multi-tenant LoRA
        serving. Each request may name an adapter (``submit(adapter=...)``)
        whose low-rank factors live in a paged device pool
        (inference/lora.py) alongside the KV pool; the compiled
        decode/prefill/verify programs gather each slot's factors by
        adapter index and apply the delta in-program (BGMV), padded to the
        config's static ``max_live_adapters``/``max_rank`` — so adapter
        churn (register/evict/swap) causes zero steady-state recompiles.
        Greedy output with adapter X is token-identical to the dense model
        with X's weights merged in. See docs/serving.md.

        ``telemetry``: observability (inference/telemetry.py). None/False
        (default) keeps span tracing and the tick flight recorder OFF —
        the metrics registry is still live (``sched_metrics()`` and the
        tenant percentiles read through it; counter updates are host dict
        writes) but the traced hot path pays only a truthiness check.
        True enables spans + flight recording; or pass a configured
        :class:`~.telemetry.ServingTelemetry` (injectable clock, ring
        size). See docs/observability.md.

        ``faults``: deterministic fault injection (inference/faults.py).
        None (default) wires the shared disabled injector — every hook
        site is a single attribute check. Pass a
        :class:`~.faults.FaultInjector` built from a scripted
        :class:`~.faults.FaultPlan` to replay pool exhaustion, tick
        faults, drafter failures, and swap corruption deterministically
        (the chaos-soak harness). ``fault_retries``: tick-fault strikes a
        request survives before quarantine to terminal ``failed``.

        ``mesh`` (paged only): multi-chip serving — ``"tp=N"`` (or the
        int N) shards the executor's compiled programs over an N-way
        ``tp`` mesh: attention/kv heads, MLP hidden dim, the KV block
        pool (+ its int8 scale rows), and the LoRA page pool all split on
        the same axis (parallel/serving_mesh.py), while block tables,
        scheduling, snapshots, and swap payloads stay tp-agnostic host
        state. ``"cp=M"`` / ``"tp=NxCp=M"`` adds a context-parallel axis
        that shards ONLY the chunked-prefill sequence dimension (params
        and pools replicate over cp; GSPMD all-gathers the chunk K/V
        before the pool scatter), multiplying prefill tok/s for long
        prompts. Greedy output is token-identical to the single-chip
        engine either way; every tp-sharded dim must divide N and
        ``prefill_chunk`` must divide by M. None/1 = single chip.

        ``role`` (paged only): replica class for disaggregated fleets —
        ``"any"`` (default) serves the full lifecycle; ``"prefill"``
        runs chunked prefill only, parking each request once its first
        token is sampled for ``FleetRouter`` to hand off (see
        :meth:`handoff_ready`/:meth:`evacuate`) and refusing decode-phase
        admits; ``"decode"`` marks the replica as a handoff target
        (routing sends it no fresh prompts, but it can still re-prefill
        salvaged replay work).

        ``kernels``: attention/projection kernel dispatch for the compiled
        serving programs — ``"auto"`` (default) picks the Pallas kernels on
        a TPU backend and the jnp reference elsewhere, ``"pallas"`` forces
        the kernels (interpret mode off-TPU — CPU parity testing),
        ``"megakernel"`` requests the whole-tick persistent kernel
        (ops/decode_megakernel.py): the full decode / spec-verify tick —
        all layers — as ONE Pallas program, degrading to the per-layer
        kernels when the executor's structural/shape guard rejects the
        model (``PagedExecutor.megakernel_reason`` records why), and
        ``"reference"`` pins the jnp compositions. Process-wide
        (``ops.set_kernel_mode``) and read at trace time, so it must agree
        across servers compiling in one process; ``"auto"`` leaves the
        current mode untouched. Recorded in the snapshot fingerprint —
        restore refuses a snapshot taken under a different mode (greedy
        tokens are kernel-identical, but sampling paths need not be
        bit-equal across kernels).

        ``mk_geometry``: a :class:`~..ops.decode_megakernel
        .MegakernelGeometry` overriding the whole-tick kernel's schedule
        (FFN tile width, weight-prefetch depth, int8 dequant placement).
        Only meaningful — and only accepted — with
        ``kernels="megakernel"``; part of the snapshot fingerprint. The
        autotuner searches it (autotune/space.py kernel tier).

        ``profile``: a tuned profile from the autotuner
        (``paddle_tpu/autotune/``) — a path to the profile JSON, a
        parsed dict, or a :class:`~paddle_tpu.autotune.TunedProfile`.
        Applies the tuned serving knobs (cache geometry, tick window,
        speculation, kv_quant, pool sizing, policy) wherever the caller
        left the ctor argument at its declared default; an explicitly
        passed non-default argument wins over the profile. The loaded
        profile re-verifies its config fingerprint, so a hand-edited
        config fails here, loudly.

        ``clock``: injectable time source (``() -> float``) for request
        wall metrics, the default scheduler, and default-constructed
        telemetry — the autotuner injects a counting clock to make
        measured trials (and therefore tuned profiles) deterministic.
        None = ``time.monotonic``. A ``telemetry=``/``policy=`` instance
        you construct yourself keeps its own clock."""
        self.profile = None
        if profile is not None:
            from ..autotune.profile import resolve_profile

            self.profile = resolve_profile(profile)
            _pkw = self.profile.server_kwargs(
                model.cfg, max_batch=max_batch, max_len=max_len)
            # tuned knobs fill ctor args still at their declared
            # defaults; explicit caller choices always win
            if cache == "dense":
                cache = _pkw["cache"]
            if block_size == 16:
                block_size = _pkw["block_size"]
            if tick_window == 1:
                tick_window = _pkw["tick_window"]
            if prefill_chunk == 32:
                prefill_chunk = _pkw["prefill_chunk"]
            if spec is None:
                spec = _pkw.get("spec")
            if kv_quant == "none":
                kv_quant = _pkw["kv_quant"]
            if policy is None:
                policy = _pkw["policy"]
            if pool_bytes is None and num_blocks is None:
                pool_bytes = _pkw.get("pool_bytes")
            if host_pool_bytes is None:
                host_pool_bytes = _pkw.get("host_pool_bytes")
        cfg = model.cfg
        assert max_len <= cfg.max_position_embeddings
        if cache not in ("dense", "paged"):
            raise ValueError(f"cache must be 'dense' or 'paged', got {cache!r}")
        if kv_quant not in ("none", "int8"):
            raise ValueError(
                f"kv_quant must be 'none' or 'int8', got {kv_quant!r}")
        if kv_quant != "none" and cache != "paged":
            raise ValueError("kv_quant='int8' requires cache='paged' "
                             "(the dense slab has no block pool to quantize)")
        if pool_bytes is not None:
            if cache != "paged":
                raise ValueError("pool_bytes= requires cache='paged'")
            if num_blocks is not None:
                raise ValueError(
                    "pass either num_blocks= or pool_bytes=, not both")
        if host_pool_bytes is not None and cache != "paged":
            raise ValueError("host_pool_bytes= requires cache='paged' "
                             "(only the block pool can swap to host)")
        if lora is not None and cache != "paged":
            raise ValueError("lora= (multi-adapter serving) requires "
                             "cache='paged' — the adapter pool shares the "
                             "paged slot/eviction machinery")
        if role not in ("any", "prefill", "decode"):
            raise ValueError(
                f"role must be 'any', 'prefill', or 'decode', got {role!r}")
        if role != "any" and cache != "paged":
            raise ValueError("role= (disaggregated replica classes) "
                             "requires cache='paged' — handoff rides the "
                             "paged snapshot/migration path")
        self.role = role
        from ..parallel.serving_mesh import parse_mesh

        tp, cp = parse_mesh(mesh)
        if (tp > 1 or cp > 1) and cache != "paged":
            raise ValueError("mesh= (multi-chip serving) requires "
                             "cache='paged' — only the paged executor "
                             "places its programs on a mesh")
        self._tp = tp
        self._cp = cp
        if (tier_demote_low is None) != (tier_demote_high is None):
            raise ValueError(
                "tier_demote_low/tier_demote_high come as a pair — set "
                "both watermarks (or neither to keep demotion off)")
        if tier_demote_low is not None:
            if cache != "paged":
                raise ValueError("tier_demote_low/high (tiered KV) "
                                 "require cache='paged'")
            low, high = float(tier_demote_low), float(tier_demote_high)
            if not (0.0 < low < high <= 1.0):
                raise ValueError(
                    f"tier watermarks must satisfy 0 < low < high <= 1, "
                    f"got low={tier_demote_low} high={tier_demote_high}")
            tier_demote_low, tier_demote_high = low, high
        if warm_pool_bytes is not None and cache != "paged":
            raise ValueError("warm_pool_bytes= requires cache='paged'")
        self.tier_demote_low = tier_demote_low
        self.tier_demote_high = tier_demote_high
        from ..ops import KERNEL_MODES, set_kernel_mode

        if kernels not in KERNEL_MODES:
            raise ValueError(
                f"kernels must be one of {KERNEL_MODES}, got {kernels!r}")
        if kernels == "megakernel" and cache != "paged":
            raise ValueError("kernels='megakernel' requires cache='paged' "
                             "(the whole-tick kernel serves the paged "
                             "decode path)")
        if mk_geometry is not None:
            if kernels != "megakernel":
                raise ValueError("mk_geometry= requires "
                                 "kernels='megakernel' (the geometry only "
                                 "parameterizes the whole-tick kernel)")
            mk_geometry.validate()
        self.mk_geometry = mk_geometry
        if kernels != "auto":
            set_kernel_mode(kernels)
        self.kernels = kernels
        self.kv_quant = kv_quant
        # per-layer kernel geometry (autotune/kernel_geometry.py): a
        # profile carrying a winner cache installs it process-wide
        # BEFORE anything traces — the op seams read it at trace time,
        # same contract as set_kernel_mode above. Without a profile
        # cache, an already-installed swept cache (install_geometry_
        # cache from a sweep artifact) stays in effect. The resolved
        # per-op (geometry, source) map feeds the snapshot fingerprint
        # and the serving_kernel_geometry telemetry gauge.
        from ..autotune.kernel_geometry import (install_geometry_cache,
                                                resolve_server_geometries)
        from ..framework.dtype import convert_dtype as _cvt

        if self.profile is not None \
                and self.profile.kernel_geometry is not None:
            install_geometry_cache(self.profile.geometry_cache(),
                                   source="profile")
        self.kernel_geometry = resolve_server_geometries(
            head_dim=cfg.hidden_size // cfg.num_attention_heads,
            hidden=cfg.hidden_size,
            dtype=str(jnp.zeros((), _cvt(cfg.dtype)).dtype),
            kv_quant=kv_quant,
            lora_rank=(int(lora.max_rank) if lora is not None
                       and hasattr(lora, "max_rank") else None))
        self.spec = None
        if spec is not None:
            if cache != "paged":
                raise ValueError(
                    "spec= (speculative decoding) requires cache='paged'")
            spec.validate()
            self.spec = spec
        self.model = model
        self.cfg = cfg
        self.cache_mode = cache
        self.max_batch = max_batch
        self.max_len = max_len
        self.eos = eos_token_id
        if tick_window < 1:
            raise ValueError(f"tick_window must be >= 1, got {tick_window}")
        self.tick_window = int(tick_window)
        self.params = state_values(model)

        from ..framework.dtype import convert_dtype

        kv = cfg.num_key_value_heads
        d = cfg.hidden_size // cfg.num_attention_heads
        cdtype = convert_dtype(cfg.dtype)
        # per-slot scalars live HOST-side (numpy): slot assignment would
        # otherwise cost one eager device dispatch per field per request —
        # each a full round trip on a tunneled backend
        self.pos = np.zeros((max_batch,), np.int32)
        self.tokens = np.zeros((max_batch,), np.int32)
        self.temps = np.zeros((max_batch,), np.float32)
        self.topks = np.zeros((max_batch,), np.int32)
        self.topps = np.zeros((max_batch,), np.float32)
        self._step_no = 0
        self._base_key = jax.random.PRNGKey(seed)
        self._slots: List[Optional[_Request]] = [None] * max_batch
        if policy is None:
            self._sched = Scheduler() if clock is None \
                else Scheduler(clock=clock)
        elif isinstance(policy, Scheduler):
            self._sched = policy
        elif isinstance(policy, str):
            self._sched = Scheduler(policy=policy) if clock is None \
                else Scheduler(policy=policy, clock=clock)
        else:
            raise ValueError(
                f"policy must be None, a policy name ('fifo'/'priority'/"
                f"'wfq'), or a Scheduler instance, got {policy!r}")
        self._results: Dict[int, List[int]] = {}
        self._dropped: Dict[int, str] = {}   # rid -> cancelled|expired|failed
        # per-rid wall-clock marks (submit/first-token/done) — the
        # benchmark derives TTFT and per-token latency from these
        self._req_metrics: Dict[int, Dict[str, float]] = {}
        self._wall = clock if clock is not None else time.monotonic
        # preemption / overload counters (read via sched_metrics)
        self._preemptions = 0
        self._prefill_aborts = 0
        self._resumes = 0
        self._stalls = 0
        self._stall_streak = 0
        self._idle_streak = 0
        self._next_rid = 0
        self._lora = None

        from .faults import FaultInjector, NULL_INJECTOR

        if faults is None:
            self._faults = NULL_INJECTOR
        elif isinstance(faults, FaultInjector):
            self._faults = faults
        else:
            raise ValueError(
                f"faults must be None or a FaultInjector, got {faults!r}")
        self.faults = self._faults
        if not isinstance(fault_retries, int) or fault_retries < 0:
            raise ValueError(
                f"fault_retries must be an int >= 0, got {fault_retries!r}")
        self.fault_retries = fault_retries
        # degradation-ladder state (all host ints; see _step_paged_inner)
        self._failed: Optional[str] = None      # terminal-failure reason
        self._strikes: Dict[int, int] = {}      # rid -> tick-fault strikes
        self._backoff_ticks = 0                 # ticks left to sit out
        self._degraded_ticks = 0                # pressure-response cooldown
        self._tick_faults = 0
        self._quarantined = 0

        from .telemetry import ServingTelemetry

        if telemetry is None or telemetry is False:
            self._tel = ServingTelemetry(enabled=False) if clock is None \
                else ServingTelemetry(enabled=False, clock=clock)
        elif telemetry is True:
            self._tel = ServingTelemetry(enabled=True) if clock is None \
                else ServingTelemetry(enabled=True, clock=clock)
        elif isinstance(telemetry, ServingTelemetry):
            self._tel = telemetry
        else:
            raise ValueError(
                f"telemetry must be None, a bool, or a ServingTelemetry "
                f"instance, got {telemetry!r}")
        self.telemetry = self._tel
        reg = self._tel.registry
        self._sched.attach_metrics(reg)
        # registry twins of the overload ints above: sched_metrics() reads
        # THESE (single source of truth); the ints stay in lockstep for
        # direct attribute users
        self._c_preempt = reg.counter(
            "serving_preemptions", "decoding slots swapped out to host")
        self._c_aborts = reg.counter(
            "serving_prefill_aborts",
            "prefilling slots aborted under pool pressure (recomputable)")
        self._c_resumes = reg.counter(
            "serving_resumes", "swapped requests restored into a slot")
        self._c_stalls = reg.counter(
            "serving_stalled_reservations",
            "block reservations that found no victim and no headroom")
        self._c_completed = reg.counter(
            "serving_requests_completed", "requests finished with results")
        self._c_dropped = reg.counter(
            "serving_requests_dropped",
            "requests dropped before finishing (reason label)")
        self._h_ttft = reg.histogram(
            "serving_ttft_s",
            "submit -> first token, completed requests (seconds)")
        self._h_tpot = reg.histogram(
            "serving_tpot_ms",
            "per-token latency after the first, completed requests (ms)",
            buckets=(0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                     250.0, 500.0, 1000.0, 2500.0))
        self._h_e2e = reg.histogram(
            "serving_e2e_s", "submit -> done, completed requests (seconds)")
        # fault-tolerance counters (inference/faults.py ladder)
        self._c_faults = reg.counter(
            "serving_faults_injected",
            "fault-injector firings observed by the server (site label)")
        self._c_retries = reg.counter(
            "serving_tick_retries",
            "decode trips retried after a recoverable tick fault")
        self._c_failed = reg.counter(
            "serving_requests_failed",
            "requests quarantined to terminal failed status (reason label)")
        self._c_corrupt = reg.counter(
            "serving_swap_reprefills",
            "corrupted swap payloads recovered by re-prefill")
        self._c_degrade = reg.counter(
            "serving_degrade_events",
            "watchdog-driven degradation responses (kind label)")
        # program key of the last paged trip, recorded per tick by the
        # flight recorder; the watchdog keys recompile excusal on it
        self._last_prog = "idle"

        if cache == "dense":
            self.buckets = sorted(b for b in prompt_buckets if b <= max_len)
            if not self.buckets:
                raise ValueError(
                    f"no prompt bucket fits max_len={max_len} "
                    f"(prompt_buckets={tuple(prompt_buckets)})")
            self._caches = [jnp.zeros((max_batch, max_len, kv, d), cdtype)
                            for _ in range(2 * cfg.num_hidden_layers)]
            # donate the KV pool: XLA updates the caches in place instead of
            # copying 2·L·(max_batch, max_len, KV, D) every decoded token
            self._decode = jax.jit(self._decode_fn, donate_argnums=(2,))
            self._prefills: Dict[int, object] = {}  # bucket -> jitted fn
        else:
            from .paged_cache import BlockAllocator

            bs = int(block_size)
            if bs < 1:
                raise ValueError(f"block_size must be >= 1, got {block_size}")
            self.block_size = bs
            chunk = int(prefill_chunk)
            if chunk < 1:
                raise ValueError(
                    f"prefill_chunk must be >= 1, got {prefill_chunk}")
            self.prefill_chunk = -(-chunk // bs) * bs  # round up to blocks
            entries = -(-max_len // bs)  # ceil: real table entries per slot
            self._max_entries = entries
            # slack entries (always 0 = scratch) so the chunk's table
            # dynamic_slice never clamps and window-surplus decode writes
            # past max_len land in scratch instead of a live block; the
            # speculative verify window writes k+1 positions per tick, so
            # its surplus past max_len can be wider than one chunk's
            slack = self.prefill_chunk // bs
            if self.spec is not None:
                # a fused spec trip writes up to tick_window (or turbo)
                # windows of k+1 positions past a row's last live
                # position; a gated plain trip writes gate_ticks positions
                wmax = max(self.tick_window, int(self.spec.turbo_windows))
                slack = max(slack, -(-(wmax * (int(self.spec.k) + 1)) // bs),
                            -(-int(self.spec.gate_ticks) // bs))
            self._table_width = entries + slack
            per_block = kv_block_bytes(cfg, bs, kv_quant)
            if num_blocks is None:
                if pool_bytes is not None:
                    # byte-budget sizing: this is where the int8 pool's
                    # ~2× capacity win comes from — same budget, half the
                    # bytes per block, twice the resident blocks
                    num_blocks = max(2, int(pool_bytes) // per_block)
                else:
                    num_blocks = max_batch * entries + 1  # dense parity
            self.alloc = BlockAllocator(int(num_blocks), bs,
                                        kv_quant=kv_quant,
                                        bytes_per_block=per_block,
                                        shards=self._tp)
            from .kv_offload import KVOffloadEngine

            self._offload = KVOffloadEngine(
                self.alloc, self._table_width,
                capacity_bytes=host_pool_bytes,
                warm_capacity_bytes=warm_pool_bytes)
            self._offload.telemetry = self._tel
            # cold-tier counter: prefix chains that fell off the warm
            # tier (or arrived with no cached ancestry at all) and paid
            # a fresh chunked prefill — the denominator's third leg in
            # the benchmark's tier_hit_rate
            self._cold_refills = 0
            self._prefill_tokens = 0
            self._prefill_wall_s = 0.0
            if self._faults is not NULL_INJECTOR:
                # thread the injector through the paged components (even
                # if currently disabled — a chaos harness arms the plan
                # after warmup); the default NULL_INJECTOR is never
                # wired, so the disabled path in each hook stays a plain
                # `is None` check
                self.alloc.faults = self._faults
                self._offload.faults = self._faults
            self._bt = np.zeros((max_batch, self._table_width), np.int32)
            # per-slot adapter page index into the LoRA pool; 0 = the
            # permanently-zero NULL page, so adapterless slots need no
            # branching inside the compiled programs
            self.aidx = np.zeros((max_batch,), np.int32)
            if lora is not None:
                from .lora import AdapterPool

                self._lora = AdapterPool(cfg, lora)
                self._lora.telemetry = self._tel
            # device-side mirror of (temps, topks, topps[, kcaps]): these
            # change only when a slot activates/releases, but were being
            # re-uploaded every trip (~0.1ms eager dispatch each)
            self._samp_dev = None
            # True while the slot is streaming prompt chunks; None once the
            # slot decodes (or is empty)
            self._prefilling: List[Optional[bool]] = [None] * max_batch
            # rids a prefill-class replica has finished prefilling (first
            # token sampled) and parked for the fleet router to hand off
            # to the decode class via evacuate(rids=)/admit_migrated
            self._handoff: set = set()
            if self.spec is not None:
                self.spec_k = int(self.spec.k)
                self.drafter = self.spec.build_drafter(max_len)
                if self._faults is not NULL_INJECTOR \
                        and hasattr(self.drafter, "faults"):
                    self.drafter.faults = self._faults
                # fusible drafters (in-program drafting, e.g. the n-gram
                # matcher) scan tick_window draft→verify→accept windows in
                # ONE program per host trip; host-side drafters need a
                # round trip per window
                self._spec_fused = bool(getattr(self.drafter, "fusible",
                                                False))
                if not self._spec_fused and self.tick_window != 1:
                    raise ValueError(
                        f"tick_window={tick_window} with spec= needs an "
                        f"in-program (fusible) drafter such as 'ngram'; "
                        f"drafter {type(self.drafter).__name__} proposes "
                        f"host-side and supports tick_window=1 only")
                self._spec_windows = self.tick_window if self._spec_fused \
                    else 1
                # per-slot draft budget (host-side, like pos/temps): rows
                # with kcap 0 run a plain decode tick inside the verify
                # program — idle/prefilling slots are masked this way
                self.kcaps = np.zeros((max_batch,), np.int32)
                self._spec_proposed = 0
                self._spec_accepted = 0
                # dynamic speculation gate (see SpecConfig.gate_low):
                # >0 = this many plain-decode trips before the next
                # speculative probe; turbo = long-trip tier while the
                # whole batch accepts near-k drafts per window
                self._spec_gate_off = 0
                self._spec_plain_windows = 0
                self._spec_turbo = False
            # engine/executor split: everything device-side — the KV
            # block pools, the compiled programs, and their (optional)
            # tp-mesh placement — lives in the executor; this engine
            # keeps only host scheduling state and dispatches through
            # the aliases below (inference/executor.py)
            from .executor import PagedExecutor

            self._exec = PagedExecutor(self, num_blocks=int(num_blocks),
                                       tp=self._tp, cp=self._cp)
            self._decode_paged = self._exec.decode_paged
            self._chunk_prefill = self._exec.chunk_prefill
            if self.spec is not None:
                if self._spec_fused:
                    self._spec_scan = self._exec.spec_scan
                else:
                    self._spec_verify = self._exec.spec_verify

    # ------------------------------------------------------------ compiled fns
    @property
    def _pools(self):
        """The executor's flat KV pool list — engine code reads/rotates
        it through this alias so the donation-rotation call sites are
        unchanged by the engine/executor split."""
        return self._exec.pools

    @_pools.setter
    def _pools(self, value):
        self._exec.pools = value

    @property
    def _pool_stride(self) -> int:
        return self._exec.pool_stride

    def _lora_flat(self):
        """Current adapter-pool tensors for a compiled-program call — ()
        when LoRA is off (the programs then skip the gather entirely).
        Host-side: the pool list changes identity on adapter upload but
        never shape, so churn re-runs nothing."""
        return self._lora.device_tensors() if self._lora is not None else ()

    def _head(self, h):
        from ..framework.dispatch import apply_op

        if self.cfg.tie_word_embeddings:
            return apply_op(lambda v, w: jnp.matmul(v, w.T), h,
                            self.model.model.embed_tokens.weight)
        return self.model.lm_head(h)

    def _decode_fn(self, params, tokens, flat_caches, pos, temps, topks,
                   topps, active, key):
        """``tick_window`` ticks as one compiled region: each tick advances
        every slot by one token (per-slot sampling via
        ``generation.sample_token_rows``: temp == 0 → greedy argmax;
        temp > 0 → categorical with that row's top-k/top-p filter).
        ``active`` masks position advance so idle slots don't drift their
        cache write row. Returns the (k, B) token stack + final caches."""
        model = self.model

        def one_tick(carry, k):
            toks, flat_c, p = carry
            caches = [(Tensor(flat_c[2 * i]), Tensor(flat_c[2 * i + 1]))
                      for i in range(self.cfg.num_hidden_layers)]

            def call():
                h, new = model.model.decode_step(Tensor(toks[:, None]),
                                                 caches, p)
                return self._head(h), new

            logits, new = functional_call(model, params, call_fn=call)
            flat = []
            for ck, cv in new:
                flat += [ck.value, cv.value]
            lg = logits.value[:, 0].astype(jnp.float32)   # (B, V)
            from ..models.generation import sample_token_rows

            nxt = sample_token_rows(lg, jax.random.fold_in(key, k), temps,
                                    topks, topps)
            return (nxt, flat, p + active), nxt

        if self.tick_window == 1:
            (_, flat, _), stack = one_tick((tokens, flat_caches, pos), 0)
            return stack[None], flat
        (_, flat, _), stack = jax.lax.scan(
            one_tick, (tokens, flat_caches, pos),
            jnp.arange(self.tick_window))
        return stack, flat

    def _prefill(self, bucket: int):
        """Dense-path prefill + slot scatter as ONE jitted call (donated
        pool): the per-layer eager `.at[slot].set` scatters cost 2·L
        dispatches per request otherwise — each a tunnel round trip."""
        if bucket not in self._prefills:
            model = self.model

            def fn(params, prompt, true_len, pool, slot):
                """prompt [1, bucket] right-padded; logits at true_len-1;
                the request's cache rows scatter into pool[slot]."""
                kvs = self.cfg.num_key_value_heads
                d = self.cfg.hidden_size // self.cfg.num_attention_heads
                from ..framework.dtype import convert_dtype

                cdtype = convert_dtype(self.cfg.dtype)
                caches = [(Tensor(jnp.zeros((1, self.max_len, kvs, d), cdtype)),
                           Tensor(jnp.zeros((1, self.max_len, kvs, d), cdtype)))
                          for _ in range(self.cfg.num_hidden_layers)]

                def call():
                    h, new = model.model.prefill(Tensor(prompt), caches)
                    last = jax.lax.dynamic_slice_in_dim(
                        h.value, true_len - 1, 1, 1)
                    return self._head(Tensor(last)), new

                logits, new = functional_call(model, params, call_fn=call)
                flat = []
                for ck, cv in new:
                    flat += [ck.value, cv.value]
                pool = [p.at[slot].set(row[0]) for p, row in zip(pool, flat)]
                return logits.value[:, 0].astype(jnp.float32), pool

            self._prefills[bucket] = jax.jit(fn, donate_argnums=(3,))
        return self._prefills[bucket]

    # --------------------------------------------------------------- requests
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 0.0, draft_k: Optional[int] = None,
               priority: int = PRIORITY_NORMAL, tenant: str = "default",
               ttl_s: Optional[float] = None,
               adapter: Optional[str] = None) -> int:
        """Queue one request; returns its rid. ``priority`` (lower = more
        urgent), ``tenant`` (WFQ fairness bucket), and ``ttl_s`` (max
        queue wait before the request expires unstarted) feed the
        scheduler; raises :class:`~.scheduler.AdmissionError` when a
        bounded queue is full (backpressure). ``adapter`` names a
        registered LoRA adapter (requires ``lora=``) — unknown names,
        ranks past the pool's ``max_rank``, and shape-incompatible
        adapters are rejected HERE, not at admission time.

        Raises :class:`~.faults.EngineFailedError` once the server is in
        a terminal failed state — enqueuing would silently strand the
        request behind an engine that will never tick again."""
        if self._failed is not None:
            from .faults import EngineFailedError

            raise EngineFailedError(
                f"server is in a terminal failed state ({self._failed}) — "
                f"restore a snapshot into a fresh server or rebuild")
        prompt = list(prompt)
        if not prompt:
            raise ValueError("prompt must contain at least one token id")
        for t in prompt:
            if isinstance(t, bool) or not isinstance(t, (int, np.integer)):
                raise ValueError(
                    f"prompt must be a sequence of int token ids, got "
                    f"{type(t).__name__}: {t!r}")
        prompt = [int(t) for t in prompt]
        if isinstance(max_new_tokens, bool) or \
                not isinstance(max_new_tokens, (int, np.integer)) or \
                max_new_tokens <= 0:
            raise ValueError(
                f"max_new_tokens must be a positive int, got "
                f"{max_new_tokens!r}")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_len={self.max_len}")
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if not 0.0 <= top_p <= 1.0:
            raise ValueError(f"top_p must be in [0, 1], got {top_p}")
        if draft_k is not None:
            if self.spec is None:
                raise ValueError(
                    "draft_k= requires a server built with "
                    "spec=SpecConfig(...)")
            if isinstance(draft_k, bool) or \
                    not isinstance(draft_k, (int, np.integer)) or draft_k < 0:
                raise ValueError(
                    f"draft_k must be an int >= 0, got {draft_k!r}")
            if draft_k > self.spec_k:
                raise ValueError(
                    f"draft_k ({draft_k}) exceeds spec.k ({self.spec_k}) — "
                    f"the compiled verify-window width; raise SpecConfig.k")
            draft_k = int(draft_k)
        if not isinstance(tenant, str) or not tenant:
            raise ValueError(
                f"tenant must be a non-empty string, got {tenant!r}")
        if adapter is not None:
            if self._lora is None:
                raise ValueError(
                    "adapter= requires a server built with "
                    "lora=LoRAConfig(...) on the paged path")
            # full ladder: registered? rank <= max_rank? targets/layers/
            # shapes match the pool layout? — fail at the door, not after
            # the request has queued behind a day of traffic
            self._lora.validate(adapter)
        if self.cache_mode == "dense":
            self._bucket_for(len(prompt))  # validate against buckets up front
        else:
            # feasibility gate: a request whose worst-case block need —
            # final position plus the transient decode-window (or
            # speculative-window) reservation — exceeds the pool could
            # never finish; admitting it would wedge the scheduler behind
            # an unsatisfiable reservation, so reject it at the door
            if self.spec is not None:
                wmax = max(self.tick_window, int(self.spec.turbo_windows))
                trans = max(wmax * (self.spec_k + 1),
                            int(self.spec.gate_ticks))
            else:
                trans = self.tick_window
            worst = len(prompt) + max_new_tokens - 1 + trans
            need = min(self._max_entries, -(-worst // self.block_size))
            if need > self.alloc.num_blocks - 1:
                raise ValueError(
                    f"request needs up to {need} KV blocks but the pool "
                    f"has {self.alloc.num_blocks - 1} usable — it could "
                    f"never be scheduled; raise num_blocks/pool_bytes or "
                    f"shorten the request")
        rid = self._next_rid
        self._next_rid += 1
        req = _Request(rid, prompt, int(max_new_tokens),
                       temperature=float(temperature),
                       top_k=int(top_k), top_p=float(top_p),
                       draft_k=draft_k, adapter=adapter)
        # cost = estimated total tokens: the WFQ charge a tenant pays
        req.sched = self._sched.submit(
            req, rid, priority=priority, tenant=tenant, ttl_s=ttl_s,
            cost=float(len(prompt) + max_new_tokens), adapter=adapter)
        self._req_metrics[rid] = {"submit_t": self._wall(),
                                  "tenant": tenant}
        if self._tel.enabled:
            tr = self._tel.tracer
            tr.set_meta(rid, tenant=tenant, priority=priority,
                        prompt_len=len(prompt), adapter=adapter or "")
            tr.begin(rid, "queued", priority=priority, tenant=tenant)
        return rid

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt length {n} exceeds largest bucket "
                         f"{self.buckets[-1]}")

    def _first_token(self, req: _Request, lg) -> int:
        """Sample the first generated token from prefill logits (1, V) —
        same ``next_token`` as model.generate, so temperature/top_k/top_p
        semantics match; one host sync per assignment. Greedy requests
        skip the eager sampling-op chain (fold_in + filtering, ~1ms of
        dispatch per admission) for a host argmax — same token."""
        if req.temperature == 0.0:
            return int(np.argmax(np.asarray(lg[0])))
        from ..models.generation import next_token

        key = jax.random.fold_in(self._base_key, (req.rid << 20) | 1)
        nxt, _ = next_token(lg, key, req.temperature, req.top_k, req.top_p)
        return int(nxt[0])

    def _activate_slot(self, slot: int, req: _Request, first: int) -> None:
        """Move a freshly-prefilled request into the decode phase."""
        self.pos[slot] = len(req.prompt)
        self.tokens[slot] = first
        self.temps[slot] = req.temperature
        self.topks[slot] = req.top_k
        self.topps[slot] = req.top_p
        if self.spec is not None:
            self.kcaps[slot] = (self.spec_k if req.draft_k is None
                                else req.draft_k)
        if self.cache_mode == "paged":
            self._samp_dev = None
        req.generated.append(first)
        m = self._req_metrics.get(req.rid)
        if m is not None:
            m.setdefault("first_token_t", self._wall())
        if self._tel.enabled:
            self._tel.tracer.end(req.rid, "prefill")
            self._tel.tracer.instant(req.rid, "first_token")

    def _samp_arrays(self):
        """Device copies of the per-slot sampling params (+ draft caps and
        adapter page indices), re-uploaded only after a slot transition."""
        if self._samp_dev is None:
            kc = (jnp.asarray(self.kcaps) if self.spec is not None
                  else None)
            ai = (jnp.asarray(self.aidx) if self._lora is not None
                  else None)
            self._samp_dev = (jnp.asarray(self.temps),
                              jnp.asarray(self.topks),
                              jnp.asarray(self.topps), kc, ai)
        return self._samp_dev

    def _assign(self, slot: int, req: _Request) -> None:
        n = len(req.prompt)
        bucket = self._bucket_for(n)
        prompt = np.zeros((1, bucket), np.int32)
        prompt[0, :n] = req.prompt
        if self._tel.enabled:
            self._tel.tracer.end(req.rid, "queued")
            self._tel.tracer.begin(req.rid, "prefill", bucket=bucket,
                                   prompt_len=n)
        # one compiled call: prefill + scatter into the slot's pool rows.
        # Rows beyond the true prompt length hold right-pad garbage, but
        # decode writes sequentially from pos=n, overwriting each such row
        # BEFORE the attention mask (arange <= pos) can reach it.
        lg, self._caches = self._prefill(bucket)(
            self.params, jnp.asarray(prompt), n, self._caches, slot)
        self._activate_slot(slot, req, self._first_token(req, lg))
        self._slots[slot] = req

    def _fill_free_slots(self) -> None:
        """Admit waiting requests into free slots in scheduler-policy
        order. Paged admission is gated on block headroom, with NO
        head-of-line bypass: skipping an inadmissible head for a smaller,
        later entry could starve the head forever — and strict order is
        safe because a draining pool always reopens headroom."""
        for s in range(self.max_batch):
            if self._slots[s] is not None:
                continue
            ent = self._sched.peek()
            if ent is None:
                break
            if self.cache_mode == "paged" and not self._admissible(ent):
                break
            self._sched.pop()
            ent.started = True
            if ent.swap is not None:
                if not self._resume_swapped(s, ent):
                    # headroom moved between the check and the restore
                    # (hash matches changed) — requeue, retry next step
                    self._sched.requeue(ent)
                    break
            elif self.cache_mode == "paged":
                self._admit_paged(s, ent.req)
            else:
                self._assign(s, ent.req)

    def _service_queue(self) -> None:
        """Queue maintenance at the top of every step: expire TTL'd
        waiters, fill free slots in policy order, then — paged only — if
        a strictly-more-urgent entry is stuck behind a full batch,
        preempt the least-urgent running request for it (one victim per
        step bounds preemption churn)."""
        for ent in self._sched.expire():
            self._drop_entry(ent, "expired")
        if self._lora is not None:
            # replay the queue's adapter demand (pop-priority order)
            # through the pool's LRU: high-share tenants' adapters become
            # most-recently-used and so evict LAST — WFQ shares govern
            # adapter residency, not just slot admission
            self._lora.warm(self._sched.adapter_demand())
        self._fill_free_slots()
        if self.cache_mode != "paged":
            return
        ent = self._sched.peek()
        if ent is not None and all(sl is not None for sl in self._slots):
            v = self._pick_victim(ent.priority)
            if v is not None and self._preempt_slot(v):
                self._fill_free_slots()

    def _drop_entry(self, ent: SchedEntry, reason: str) -> None:
        """A queued entry leaves without finishing: record why, stamp its
        metrics closed, release any parked host KV."""
        self._dropped[ent.rid] = reason
        self._c_dropped.inc(reason=reason)
        m = self._req_metrics.get(ent.rid)
        if m is not None:
            m["done_t"] = self._wall()
        if ent.swap is not None:
            self._offload.discard(ent.swap)
            ent.swap = None
        self._tel.tracer.close(ent.rid, reason)

    # ---------------------------------------------------------- paged path
    def _admit_paged(self, slot: int, req: _Request) -> None:
        """Claim a slot: reuse cached prefix blocks (prefix caching — the
        matched span skips prefill entirely) and start chunked prefill at
        the first uncached block boundary. A request with an adapter
        acquires its pool page here (upload on miss, warm revival on hit)
        and holds the ref until the slot releases or is preempted."""
        if self._lora is not None:
            self.aidx[slot] = (self._lora.acquire(req.adapter)
                               if req.adapter is not None else 0)
            self._samp_dev = None
        # corruption recovery re-prefills prompt+generated[:-1] (the
        # replay sequence) instead of the bare prompt — same program,
        # same per-block machinery, different token source
        seq = req.replay if req.replay is not None else req.prompt
        # tier-aware prefix match: hot chain blocks ref as before, warm
        # chain blocks swap in through the compile-once promotion
        # scatter (kv_offload.match_prefix_tiered) — either way the
        # matched span skips its chunked prefill
        req.table, self._pools, tiers = self._offload.match_prefix_tiered(
            seq, self._pools)
        req.hashes = self.alloc.chain_hashes(seq)
        req.pf_next = len(req.table) * self.block_size
        if req.pf_next < len(seq) and self._offload.warm.demoted_blocks:
            # the chain ran out of cached ancestry while a warm tier is
            # live: the remaining span re-prefills cold (replay rung or
            # plain chunked prefill — either way a cold-tier service)
            self._cold_refills += 1
        self._bt[slot, :] = 0
        self._bt[slot, :len(req.table)] = req.table
        self._prefilling[slot] = True
        self._slots[slot] = req
        if self._tel.enabled:
            tr = self._tel.tracer
            tr.end(req.rid, "queued")
            tr.begin(req.rid, "prefill", cached_blocks=len(req.table),
                     warm_blocks=tiers["warm"],
                     prompt_len=len(seq),
                     replay=req.replay is not None)

    def _ensure_blocks(self, slot: int, entries: int) -> None:
        """Grow the slot's block table to >= ``entries`` real entries
        (capped at ceil(max_len/block_size); writes past that land in
        scratch by construction)."""
        req = self._slots[slot]
        entries = min(entries, self._max_entries)
        while len(req.table) < entries:
            bid = self.alloc.alloc()
            req.table.append(bid)
            self._bt[slot, len(req.table) - 1] = bid

    # ------------------------------------------------- preemption / offload
    def _admissible(self, ent: SchedEntry) -> bool:
        """Block-headroom gate for paged admission: the entry's first
        allocation burst (whole prompt for a fresh request — conservative,
        so a long prompt can't thrash in and straight back out mid-
        prefill; parked block count for a swapped one) PLUS one spare
        block must be reclaimable right now."""
        if self._lora is not None and ent.req.adapter is not None \
                and not self._lora.can_acquire(ent.req.adapter):
            # every adapter page is held by a running slot: admitting
            # would fail the acquire — wait for a slot to release/preempt
            return False
        if ent.swap is not None:
            need = self._offload.restore_cost(ent.swap)
        else:
            seq = (ent.req.replay if ent.req.replay is not None
                   else ent.req.prompt)
            need = min(self._max_entries, -(-len(seq) // self.block_size))
            # hot prefix hits ref existing blocks instead of allocating
            # fresh ones — shrink the burst by them (hot_only: a WARM
            # hit still promotes into a freshly allocated device block,
            # so it must keep counting against headroom)
            need = max(need - self.alloc.probe_prefix(seq, hot_only=True),
                       1)
        ent.kv_need = need          # scheduler's queued-demand aggregate
        usable = self.alloc.num_blocks - 1
        # watchdog-driven admission tightening: while degraded, demand
        # extra spare blocks so admissions stop feeding the pressure that
        # tripped the finding (preemption storm / stall run)
        spare = 3 if self._degraded_ticks > 0 else 1
        headroom = min(need + spare, usable)
        return (self.alloc.blocks_free
                + self.alloc.evictable_cached) >= headroom

    def _maybe_demote(self) -> None:
        """Watermark-driven hot→warm demotion (the tier ladder's
        pressure rung): when the free fraction of usable blocks drops
        below ``tier_demote_low``, move LRU prefix-cached blocks to the
        warm tier until it reaches ``tier_demote_high`` — so long-prompt
        admission finds FREE blocks instead of silently cannibalizing
        the prefix cache (eviction loses the bytes; demotion keeps them
        promotable). Runs before admission each paged tick; a no-op
        without watermarks or without cached blocks to demote."""
        low = self.tier_demote_low
        if low is None:
            return
        a = self.alloc
        usable = a.num_blocks - 1
        if usable <= 0 or a.blocks_free / usable >= low:
            return
        want = int(self.tier_demote_high * usable) - a.blocks_free
        if want <= 0:
            return
        victims = a.coldest_cached(want)
        if victims:
            self._offload.demote(victims, self._pools)

    def _resume_swapped(self, slot: int, ent: SchedEntry) -> bool:
        """Restore a swapped-out request into ``slot`` exactly where it
        stopped: KV blocks back from host (prefix-hash hits skip the
        upload), position/next-token/sampling scalars from the request.
        Greedy continuation is token-identical to the un-preempted run —
        the round trip is bit-exact and the decode program sees the same
        state it would have seen. Returns False (entry untouched) if
        device headroom vanished."""
        req = ent.req
        res = self._offload.swap_in(ent.swap, self._pools)
        if res is None:
            return False
        if res == "corrupt":
            # degradation ladder, re-prefill rung: the parked payload
            # failed its CRC and is gone, but the request's TOKENS are
            # host-side state — rebuild its KV by replaying
            # prompt+generated[:-1] through the chunked-prefill program
            # (token-exact vs decode), then continue as if nothing
            # happened. The swap handle's n_tokens is exactly the KV
            # coverage at swap-out time.
            handle, ent.swap = ent.swap, None
            self._c_corrupt.inc()
            req.replay = (req.prompt + req.generated)[:handle.n_tokens]
            if self._tel.enabled:
                tr = self._tel.tracer
                tr.end(req.rid, "preempted", corrupt=True)
                tr.begin(req.rid, "queued", reason="swap_corrupt")
            self._admit_paged(slot, req)
            return True
        if self._lora is not None:
            # re-acquire AFTER the KV restore committed: _admissible
            # already vouched for can_acquire, and acquiring first would
            # leak the adapter ref if swap_in failed
            self.aidx[slot] = (self._lora.acquire(req.adapter)
                               if req.adapter is not None else 0)
        handle, ent.swap = ent.swap, None
        req.table, self._pools = res
        self._bt[slot, :] = 0
        self._bt[slot, :len(req.table)] = req.table
        self._prefilling[slot] = None
        self._slots[slot] = req
        self.pos[slot] = handle.n_tokens
        self.tokens[slot] = handle.last_token
        self.temps[slot] = req.temperature
        self.topks[slot] = req.top_k
        self.topps[slot] = req.top_p
        if self.spec is not None:
            self.kcaps[slot] = (self.spec_k if req.draft_k is None
                                else req.draft_k)
        self._samp_dev = None
        self._resumes += 1
        self._c_resumes.inc()
        if self._tel.enabled:
            self._tel.tracer.end(req.rid, "preempted", resumed=True)
        return True

    def _pick_victim(self, than_priority: int,
                     exclude=()) -> Optional[int]:
        """Least-urgent occupied slot STRICTLY less urgent than
        ``than_priority`` — equal-priority peers never preempt each other
        (that way lies ping-pong). Prefers prefilling victims (aborting
        them loses recomputable work only) and then the largest block
        holder (frees the most pool per preemption)."""
        best, best_key = None, None
        for s in range(self.max_batch):
            if s in exclude:
                continue
            req = self._slots[s]
            if req is None:
                continue
            pr = req.sched.priority
            if pr <= than_priority:
                continue
            key = (pr, 1 if self._prefilling[s] else 0, len(req.table))
            if best_key is None or key > best_key:
                best, best_key = s, key
        return best

    def _preempt_slot(self, s: int) -> bool:
        """Evict the request in slot ``s`` and requeue it. A slot still
        prefilling is ABORTED — its KV is recomputable, nothing is
        generated yet, and registered prompt blocks stay on the LRU so
        the re-run's prefix match skips them anyway. A decoding slot
        SWAPS: its table (truncated of speculative reservations) parks in
        host memory via the offload engine for a bit-exact resume.
        Returns False — slot untouched — when the host pool is full."""
        req = self._slots[s]
        ent = req.sched
        if self._prefilling[s]:
            for bid in req.table:
                self.alloc.free(bid)
            req.table = []
            req.pf_next = 0
            self._prefill_aborts += 1
            self._c_aborts.inc()
            if self._tel.enabled:
                tr = self._tel.tracer
                tr.end(req.rid, "prefill", aborted=True)
                tr.begin(req.rid, "queued", reason="prefill_abort")
        else:
            n = int(self.pos[s])
            req.table = self.alloc.truncate(req.table, n)
            handle = self._offload.swap_out(
                req.rid, req.table,
                req.hashes[:min(len(req.hashes), len(req.table))],
                self._pools, n_tokens=n, last_token=int(self.tokens[s]))
            if handle is None:
                return False
            req.table = []
            ent.swap = handle
            self._preemptions += 1
            self._c_preempt.inc()
            if self._tel.enabled:
                # spans the time parked on host; swap_out/swap_in spans
                # come from the offload engine itself
                self._tel.tracer.begin(req.rid, "preempted",
                                       blocks=handle.n_blocks)
        self._slots[s] = None
        self._bt[s, :] = 0
        self._prefilling[s] = None
        self.pos[s] = 0
        self.tokens[s] = 0
        self.temps[s] = 0.0
        self.topks[s] = 0
        self.topps[s] = 0.0
        if self.spec is not None:
            self.kcaps[s] = 0
        if self._lora is not None:
            # drop the victim's adapter ref: the page goes CACHED (LRU),
            # so a quick resume revives it without re-upload while a
            # different adapter under pressure may claim the page
            self._lora.release(int(self.aidx[s]))
            self.aidx[s] = 0
        self._samp_dev = None
        self._sched.requeue(ent)
        return True

    def _reserve_or_preempt(self, s: int, entries: int) -> str:
        """Grow slot ``s``'s table to ``entries``, preempting less-urgent
        slots when the pool is dry. Returns ``"ok"`` (reserved),
        ``"gone"`` (``s`` itself yielded — no victim outranked it, so it
        released its own blocks and requeued; the rest of the batch
        drains and it resumes when pressure clears), or ``"stall"``
        (nothing preemptable and the host pool refused the swap — ``s``
        keeps its state and simply sits out this trip)."""
        tried = {s}
        while True:
            try:
                self._ensure_blocks(s, entries)
                return "ok"
            except RuntimeError:
                v = self._pick_victim(self._slots[s].sched.priority,
                                      exclude=tried)
                if v is not None:
                    tried.add(v)
                    self._preempt_slot(v)
                    continue
                if self._preempt_slot(s):
                    return "gone"
                self._stalls += 1
                self._c_stalls.inc()
                return "stall"

    def _reserve_active(self, active, need_fn) -> List[int]:
        """Reserve each decoding slot's blocks for the coming trip, most
        urgent first — under pool pressure this is where swap-preemption
        fires. Returns the surviving slot list (victims dropped out of
        ``active``; stalled slots skip the trip but keep their state)."""
        out = []
        for s in sorted(active, key=lambda i: (self._slots[i].sched.priority,
                                               i)):
            if self._slots[s] is None:
                continue        # preempted as a victim earlier in the loop
            if self._reserve_or_preempt(s, need_fn(s)) == "ok":
                out.append(s)
        out.sort()
        if not out and active:
            self._stall_streak += 1
            if self._stall_streak > 256:
                raise RuntimeError(
                    "paged pool wedged: 256 consecutive trips made no "
                    "progress (every slot stalled on block reservation) — "
                    "raise num_blocks/pool_bytes or host_pool_bytes")
        else:
            self._stall_streak = 0
        return out

    def _prefill_chunk_step(self, slot: int) -> None:
        """Advance one prompt chunk for a prefilling slot; on the final
        chunk, sample the first token and flip the slot to decoding (a
        corruption-recovery replay instead resumes at its saved
        position — nothing new is sampled)."""
        req = self._slots[slot]
        seq = req.replay if req.replay is not None else req.prompt
        n = len(seq)
        bs = self.block_size
        C = self.prefill_chunk
        start = req.pf_next
        end = min(start + C, n)
        if self._reserve_or_preempt(slot, -(-end // bs)) != "ok":
            return      # aborted as its own victim, or stalled — no chunk
        chunk = np.zeros((1, C), np.int32)
        chunk[0, :end - start] = seq[start:end]
        last_idx = (n - 1 - start) if end == n else 0
        aidx = (jnp.asarray(self.aidx[slot:slot + 1])
                if self._lora is not None else None)
        tel = self._tel
        _t0 = tel.clock() if tel.enabled else 0.0
        _w0 = self._wall()
        lg, self._pools = self._chunk_prefill(
            self.params, jnp.asarray(chunk), self._pools,
            jnp.asarray(self._bt[slot]), jnp.int32(start),
            jnp.int32(last_idx), aidx, self._lora_flat())
        # per-chip prefill throughput ledger (tools/serving_benchmark.py
        # divides by tp*cp): real prompt tokens only, not chunk padding
        self._prefill_tokens += end - start
        self._prefill_wall_s += self._wall() - _w0
        if tel.enabled:
            tel.tracer.complete(req.rid, "prefill_chunk", _t0, tel.clock(),
                                start=start, tokens=end - start)
        # publish the prompt blocks this chunk completed for prefix reuse
        # (a freshly prefilled hash supersedes any stale warm copy)
        for i in range(start // bs, end // bs):
            self.alloc.register(req.table[i], req.hashes[i])
            self._offload.forget_warm(req.hashes[i])
        req.pf_next = start + C
        if end == n:
            if req.replay is not None:
                self._activate_replayed(slot, req)
            else:
                self._activate_slot(slot, req, self._first_token(req, lg))
            self._prefilling[slot] = None
            if self.role == "prefill" and self._slots[slot] is req:
                # prefill-class replica: the request now holds exactly
                # the KV + first token a decode replica resumes from —
                # park it for the router's evacuate(rids=)/admit_migrated
                # handoff instead of decoding here (replays park too:
                # their decode phase belongs to the decode class)
                self._handoff.add(req.rid)

    def _activate_replayed(self, slot: int, req: _Request) -> None:
        """Flip a corruption-recovery replay straight back to decoding.

        The chunked prefill just rebuilt KV for ``prompt +
        generated[:-1]`` (token-exact vs the decode path — the PR 1
        guarantee), and the next decode input is the last token already
        generated, whose KV is deliberately not written yet (decode
        writes it) — exactly the invariant a swap-in restore lands on.
        Nothing is sampled here; greedy continuation is token-identical
        to the uncorrupted run."""
        n = len(req.replay)
        req.replay = None
        self.pos[slot] = n
        self.tokens[slot] = req.generated[-1]
        self.temps[slot] = req.temperature
        self.topks[slot] = req.top_k
        self.topps[slot] = req.top_p
        if self.spec is not None:
            self.kcaps[slot] = (self.spec_k if req.draft_k is None
                                else req.draft_k)
        self._samp_dev = None
        if self._tel.enabled:
            self._tel.tracer.end(req.rid, "prefill", replayed=True)

    def _all_greedy(self, rows) -> bool:
        """True iff every listed slot decodes at temperature 0 — the
        STATIC specialization key for the decode/verify programs (temp 0
        rows ignore top-k/top-p, so temps alone decides). Flipping the
        flag costs one extra compile, then both variants are cached."""
        return all(float(self.temps[s]) == 0.0 for s in rows)

    def _step_paged(self) -> int:
        tel = self._tel
        if not tel.enabled:
            return self._step_paged_inner()
        # flight recording wraps the whole tick: counter/allocator deltas
        # plus the backend-compile delta (recompile_guard's jax.monitoring
        # listener) keyed by the program the tick dispatched
        from ..analysis.recompile_guard import compile_count

        a = self.alloc
        t0 = tel.clock()
        c0 = compile_count()
        pre = (self._preemptions, self._prefill_aborts, self._resumes,
               self._stalls, a.fresh_allocs, a.evictions,
               a.swap_out_blocks, a.swap_in_blocks,
               a.demoted_blocks, a.promoted_blocks)
        sp0, sa0 = ((self._spec_proposed, self._spec_accepted)
                    if self.spec is not None else (0, 0))
        remaining = self._step_paged_inner()
        rec = {
            "t_wall_s": tel.clock() - t0,
            "prog": self._last_prog,
            "decoding": sum(1 for s in range(self.max_batch)
                            if self._slots[s] is not None
                            and not self._prefilling[s]),
            "prefilling": sum(1 for s in range(self.max_batch)
                              if self._prefilling[s]),
            "queue_depth": len(self._sched),
            "blocks_in_use": a.blocks_in_use,
            "blocks_allocated": a.fresh_allocs - pre[4],
            "evictions": a.evictions - pre[5],
            "preemptions": self._preemptions - pre[0],
            "prefill_aborts": self._prefill_aborts - pre[1],
            "resumes": self._resumes - pre[2],
            "stalls": self._stalls - pre[3],
            "swap_out_blocks": a.swap_out_blocks - pre[6],
            "swap_in_blocks": a.swap_in_blocks - pre[7],
            "swap_bytes": (a.swap_out_blocks - pre[6]
                           + a.swap_in_blocks - pre[7]) * a.bytes_per_block,
            "host_bytes": self._offload.host.bytes_in_use,
            "demotions": a.demoted_blocks - pre[8],
            "promotions": a.promoted_blocks - pre[9],
            "warm_bytes": self._offload.warm.bytes_in_use,
            "recompiles": compile_count() - c0,
        }
        if self.spec is not None:
            rec["spec_proposed"] = self._spec_proposed - sp0
            rec["spec_accepted"] = self._spec_accepted - sa0
        tel.flight.record(**rec)
        # pressure response: every 32 recorded ticks, run the watchdog
        # over the RECENT window; a preemption storm or stall run flips
        # the server degraded for a cooldown — speculation forced off and
        # admission tightened (see _dispatch_trips / _admissible) —
        # instead of letting the pressure feed itself
        if tel.flight.total % 32 == 0:
            from .telemetry import watchdog as _watchdog

            finds = [f for f in _watchdog(tel.flight.dump()[-64:])
                     if f["kind"] in ("preemption_storm",
                                      "pool_pressure_stall")]
            if finds:
                if self._degraded_ticks == 0:
                    for f in finds:
                        self._c_degrade.inc(kind=f["kind"])
                self._degraded_ticks = 64
        return remaining

    def _step_paged_inner(self) -> int:
        tel_on = self._tel.enabled
        if tel_on:
            self._last_prog = "idle"
        # demote BEFORE admission: freed blocks feed _service_queue's
        # headroom gate this same tick
        self._maybe_demote()
        self._service_queue()
        # chunked prefill interleaves with decode: ONE chunk per prefilling
        # slot per step, so a long prompt never blocks slots mid-decode
        # (no head-of-line blocking) and short requests keep streaming out
        did_prefill = False
        for s in range(self.max_batch):
            if self._slots[s] is not None and self._prefilling[s]:
                self._prefill_chunk_step(s)
                did_prefill = True
        active = [s for s in range(self.max_batch)
                  if self._slots[s] is not None and not self._prefilling[s]
                  and self._slots[s].rid not in self._handoff]
        if self._degraded_ticks > 0:
            self._degraded_ticks -= 1
        if active:
            self._step_no += 1
            if self._backoff_ticks > 0:
                # degradation ladder, backoff rung: a recent tick fault
                # left state untouched (faults fire before dispatch), so
                # sitting out a few ticks lets a transient failure domain
                # clear before the identical trip is retried
                self._backoff_ticks -= 1
                if tel_on:
                    self._last_prog = "backoff"
            else:
                rids = [self._slots[s].rid for s in active]
                try:
                    self._dispatch_trips(active)
                except Exception as e:
                    from .faults import TickFault

                    if isinstance(e, TickFault):
                        self._on_tick_fault(rids, e)
                    else:
                        # an exception AFTER compiled dispatch may have
                        # consumed donated pool buffers — no further trip
                        # is safe; flag terminal failure (submit() now
                        # refuses) and propagate
                        self._failed = f"{type(e).__name__}: {e}"
                        raise
                else:
                    # a clean trip clears its participants' strikes: the
                    # fault domain that struck them was transient
                    for r in rids:
                        self._strikes.pop(r, None)
        if tel_on and did_prefill:
            # prefill-bearing ticks get their own program-key suffix: the
            # chunk program's (and first-token sampling's) one-time
            # compiles must not read as steady-state recompiles of an
            # already-warm decode program
            self._last_prog += "+pf"
        occupied = sum(sl is not None for sl in self._slots)
        if occupied == 0 and len(self._sched) > 0:
            # every slot empty yet entries wait: admission must succeed
            # against an idle pool, so a persistent streak means state
            # corruption (e.g. leaked pins) — fail loudly, don't spin
            self._idle_streak += 1
            if self._idle_streak > 64:
                self._failed = ("scheduler wedged: 64 steps with empty "
                                "slots and a non-empty queue")
                raise RuntimeError(
                    "scheduler wedged: 64 steps with empty slots and a "
                    "non-empty queue — allocator headroom never recovered")
        else:
            self._idle_streak = 0
        return occupied + len(self._sched)

    def _dispatch_trips(self, active) -> None:
        """Dispatch the step's decode work for ``active`` slots — the one
        place a tick fault can fire, and it fires BEFORE any compiled
        call, so the caller may retry the trip verbatim (donated pools
        are still intact). A drafter failure degrades to the always-warm
        plain program and holds the speculation gate off."""
        if self._faults.enabled:
            spec = self._faults.fire("tick")
            if spec is not None:
                self._c_faults.inc(site="tick")
                if spec.kind == "fatal":
                    raise RuntimeError("injected fatal engine fault")
                from .faults import TickFault

                raise TickFault(rid=spec.rid)
        if self.spec is not None:
            # dynamic speculation gate: while recent acceptance is below
            # spec.gate_low, drafts are a net loss (a verify window costs
            # ~(k+1)x a decode tick but advances 1 token when all drafts
            # miss) — run the plain decode program for spec.gate_cooldown
            # trips, then probe again. Both programs compile during
            # warmup; switching is free. A degraded server (watchdog
            # pressure finding) forces the plain program the same way.
            if self._spec_gate_off > 0 or self._degraded_ticks > 0:
                if self._spec_gate_off > 0:
                    self._spec_gate_off -= 1
                self._spec_plain_windows += self.spec.gate_ticks
                self._plain_decode_trip(active, self.spec.gate_ticks)
            else:
                from .speculative import DrafterFault

                try:
                    self._spec_tick(active)
                except DrafterFault:
                    # the drafter is an accelerator, not a correctness
                    # dependency: emit this trip through the plain
                    # program and keep speculation off for a cooldown
                    self._c_faults.inc(site="drafter")
                    self._spec_gate_off = max(
                        int(self.spec.gate_cooldown) or 0, 4)
                    self._spec_turbo = False
                    self._spec_plain_windows += self.spec.gate_ticks
                    self._plain_decode_trip(active, self.spec.gate_ticks)
        else:
            self._plain_decode_trip(active)

    def _on_tick_fault(self, rids, fault) -> None:
        """Degradation ladder, strike rung: attribute the fault (to its
        named rid when the plan says so, else to every participant),
        back off exponentially, and quarantine any request that has
        exhausted its retries — one poison request must never take the
        engine down."""
        self._tick_faults += 1
        self._c_retries.inc()
        targets = rids
        rid = getattr(fault, "rid", None)
        if rid is not None and rid in rids:
            targets = [rid]
        worst = 0
        for r in targets:
            self._strikes[r] = self._strikes.get(r, 0) + 1
            worst = max(worst, self._strikes[r])
        # 1, 2, 4, 8 ticks — capped so a noisy plan can't idle the engine
        self._backoff_ticks = min(1 << max(worst - 1, 0), 8)
        for r in list(targets):
            if self._strikes.get(r, 0) > self.fault_retries:
                self._quarantine_rid(r, "tick_fault_retries_exhausted")

    def _quarantine_rid(self, rid: int, reason: str) -> None:
        """Terminal ``failed`` status for one request: release its slot,
        blocks, and adapter ref; record why. The engine itself keeps
        serving — that is the entire point of the quarantine rung."""
        self._strikes.pop(rid, None)
        self._quarantined += 1
        self._dropped[rid] = "failed"
        self._c_failed.inc(reason=reason)
        self._c_dropped.inc(reason="failed")
        m = self._req_metrics.get(rid)
        if m is not None:
            m["done_t"] = self._wall()
        for s in range(self.max_batch):
            req = self._slots[s]
            if req is not None and req.rid == rid:
                req.table = self.alloc.truncate(req.table, 0)
                self._tel.tracer.close(rid, "failed")
                self._release_slot(s)
                return
        ent = self._sched.remove(rid)
        if ent is not None:
            if ent.swap is not None:
                self._offload.discard(ent.swap)
                ent.swap = None
            self._tel.tracer.close(rid, "failed")

    def _plain_decode_trip(self, active, ticks=None) -> None:
        """One plain (non-speculative) decode trip: ``ticks`` (default
        ``tick_window``) ticks in one compiled program across the listed
        slots."""
        k = self.tick_window if ticks is None else ticks
        tel = self._tel
        active = self._reserve_active(
            active, lambda s: -(-(int(self.pos[s]) + k) // self.block_size))
        if not active:
            if tel.enabled:
                self._last_prog = "stalled"
            return
        if tel.enabled:
            # program key: tick count + greedy specialization are the
            # static jit-cache axes of the plain decode program
            self._last_prog = (f"plain:t{'w' if ticks is None else ticks}"
                               f":g{int(self._all_greedy(active))}")
            _t0 = tel.clock()
            _rids = [self._slots[s].rid for s in active]
        # the greedy-specialized programs never read the key — skip the
        # per-step eager fold_in dispatch (~0.4ms) for it
        key = (self._base_key if self._all_greedy(active)
               else jax.random.fold_in(self._base_key, self._step_no))
        active_mask = np.zeros((self.max_batch,), np.int32)
        active_mask[active] = 1
        # idle/prefilling rows run masked: zeroed table + pos 0 routes
        # their (discarded) cache writes to the scratch block
        bt = np.where(active_mask[:, None] > 0, self._bt, 0)
        posv = self.pos * active_mask
        temps, topks, topps, _, aidx = self._samp_arrays()
        stack, self._pools = self._decode_paged(
            self.params, jnp.asarray(self.tokens), self._pools,
            jnp.asarray(bt), jnp.asarray(posv), temps, topks, topps,
            jnp.asarray(active_mask), key, aidx, self._lora_flat(),
            self._all_greedy(active), ticks)
        self._harvest_window(np.asarray(stack), active, active_mask)
        if tel.enabled:
            # retroactive: one shared device trip advanced every listed
            # row, so each request gets the same-walled span (the host
            # sync happened inside the harvest's np.asarray)
            _t1 = tel.clock()
            for rid in _rids:
                tel.tracer.complete(rid, "decode_window", _t0, _t1, ticks=k)

    # ----------------------------------------------------------- speculative
    def _spec_tick(self, active) -> None:
        """One speculative server tick: draft k tokens per decoding slot,
        verify all k+1 window positions in one fused program, accept/reject
        exactly — emitting 1..k+1 tokens per slot per window with the same
        compiled shapes every tick regardless of acceptance. Fusible
        drafters scan ``tick_window`` whole windows on device per host
        round trip; host-side drafters run one window per trip."""
        if self._spec_fused and self._faults.enabled \
                and self._faults.fire("drafter") is not None:
            # a fused drafter proposes IN-program, so its host propose()
            # hook never runs — the injector consults the site here,
            # before any reservation or dispatch
            from .speculative import DrafterFault

            raise DrafterFault("injected drafter failure (fused path)")
        k = self.spec_k
        S = self._spec_windows
        if self._spec_turbo and self.spec.turbo_windows > S:
            S = self.spec.turbo_windows
        # reserve blocks for every window of the trip up front (speculative
        # append); rejected-draft tail entries are truncated back in harvest
        tel = self._tel
        active = self._reserve_active(
            active, lambda s: -(-(int(self.pos[s]) + S * (k + 1)) //
                                self.block_size))
        if not active:
            if tel.enabled:
                self._last_prog = "stalled"
            return
        if tel.enabled:
            self._last_prog = (f"spec:w{S}"
                               f":g{int(self._all_greedy(active))}")
            _t0 = tel.clock()
            _rids = [(s, self._slots[s].rid) for s in active]
            _kc = {s: int(self.kcaps[s]) for s in active}
        key = (self._base_key if self._all_greedy(active)
               else jax.random.fold_in(self._base_key, self._step_no))
        active_mask = np.zeros((self.max_batch,), np.int32)
        active_mask[active] = 1
        bt = np.where(active_mask[:, None] > 0, self._bt, 0)
        posv = self.pos * active_mask
        # nonzero kcaps exist only on activated, unreleased slots — exactly
        # the active set — so the cached device kcaps already carries the
        # idle/prefilling row masking
        temps, topks, topps, kcaps, aidx = self._samp_arrays()
        if self._spec_fused:
            ctx = np.zeros((self.max_batch, self.max_len), np.int32)
            for s in active:
                req = self._slots[s]
                toks = req.prompt + req.generated
                ctx[s, :len(toks)] = toks
            outs, accs, self._pools = self._spec_scan(
                self.params, jnp.asarray(ctx), self._pools,
                jnp.asarray(bt), jnp.asarray(posv), temps, topks, topps,
                kcaps, jnp.asarray(active_mask), key, aidx,
                self._lora_flat(), self._all_greedy(active), S)
        else:
            contexts: List[Optional[List[int]]] = [None] * self.max_batch
            for s in active:
                req = self._slots[s]
                contexts[s] = req.prompt + req.generated
            proposals, qprobs = self.drafter.propose(
                contexts, k, temps=self.temps,
                key=jax.random.fold_in(key, 1))
            out, acc, self._pools = self._spec_verify(
                self.params, jnp.asarray(self.tokens),
                jnp.asarray(proposals), self._pools, jnp.asarray(bt),
                jnp.asarray(posv), temps, topks, topps,
                kcaps, jax.random.fold_in(key, 2),
                None if qprobs is None else jnp.asarray(qprobs),
                aidx, self._lora_flat(), self._all_greedy(active))
            outs, accs = np.asarray(out)[None], np.asarray(acc)[None]
        accs = np.asarray(accs)
        self._harvest_spec(np.asarray(outs), accs, active)
        if tel.enabled:
            _t1 = tel.clock()
            for s, rid in _rids:
                tel.tracer.complete(
                    rid, "spec_window", _t0, _t1,
                    windows=int(accs.shape[0]),
                    accepted=int(accs[:, s].sum()),
                    proposed=int(accs.shape[0]) * _kc[s])
        if self.spec.gate_cooldown:
            m = float(accs[:, active].mean())
            # below gate_low mean accepted drafts/window, drafting is a
            # net loss — fall back to plain decode, probe again later
            if m < self.spec.gate_low:
                self._spec_gate_off = self.spec.gate_cooldown
                self._spec_turbo = False
            else:
                # near-k acceptance across the batch: switch to long
                # trips (turbo_windows per program) so the host round
                # trip amortizes over many more emitted tokens
                self._spec_turbo = (self._spec_fused
                                    and self.spec.turbo_windows > 0
                                    and m >= self.spec_k - 1)

    def _harvest_spec(self, outs, accs, active) -> None:
        """Fold a trip's verify windows into per-request state. Window w of
        row ``s`` emits ``outs[w, s, :accs[w, s]+1]`` (accepted drafts,
        then one correction/bonus) — appended under the exact same
        eos/max-new/max-len walk as :meth:`_harvest_window`, so an eos
        inside a window truncates the bonus token and later drafts (and
        any later windows) and final results match the plain server token
        for token. Surviving slots advance ``pos`` by the emitted count
        and give back the blocks reserved for rejected drafts
        (``BlockAllocator.truncate`` — refcount-safe rollback; the
        rejected positions' stale K/V is overwritten by the next window
        before any query can attend it)."""
        S = outs.shape[0]
        for s in active:
            req = self._slots[s]
            kcap = int(self.kcaps[s])
            new_pos = int(self.pos[s])
            last_tok = int(self.tokens[s])
            done = False
            if self.eos is None:
                # no-eos fast path: the only stop conditions are budget
                # counters, so each window's emission is a slice — skips
                # the per-token python walk (~1ms/trip at bench shapes)
                gen = req.generated
                for w in range(S):
                    a = int(accs[w, s])
                    self._spec_proposed += kcap
                    self._spec_accepted += a
                    limit = min(req.max_new_tokens - len(gen),
                                self.max_len - 1 - new_pos)
                    take = a + 1
                    if take >= limit:
                        take = limit
                        done = True
                    # outs is host numpy by the time harvest runs — the
                    # one sync already happened in _spec_tick
                    gen.extend(outs[w, s, :take].tolist())  # graftlint: noqa[host-sync]
                    new_pos += take
                    if done:
                        break
                if done:
                    self._emit_result(req)
                    self._release_slot(s)
                else:
                    self.pos[s] = new_pos
                    self.tokens[s] = gen[-1]
                    req.table = self.alloc.truncate(req.table, new_pos)
                    self._bt[s, len(req.table):] = 0
                continue
            for w in range(S):
                a = int(accs[w, s])
                self._spec_proposed += kcap
                self._spec_accepted += a
                for j in range(a + 1):
                    tok = int(outs[w, s, j])
                    finished_last = (self.eos is not None and
                                     req.generated[-1] == self.eos)
                    if not finished_last:
                        req.generated.append(tok)
                    pos_t = new_pos + j + 1
                    if (finished_last
                            or len(req.generated) >= req.max_new_tokens
                            or pos_t >= self.max_len - 1):
                        done = True
                        break
                if done:
                    break
                new_pos += a + 1
                last_tok = int(outs[w, s, a])
            if done:
                self._emit_result(req)
                self._release_slot(s)
            else:
                self.pos[s] = new_pos
                self.tokens[s] = last_tok
                req.table = self.alloc.truncate(req.table, new_pos)
                self._bt[s, len(req.table):] = 0

    def spec_metrics(self) -> Dict[str, float]:
        """Draft/accept counters for the speculative path (empty when
        spec is off). ``acceptance_rate`` = accepted / proposed drafts."""
        if self.spec is None:
            return {}
        prop = self._spec_proposed
        return {"draft_tokens_proposed": prop,
                "draft_tokens_accepted": self._spec_accepted,
                "acceptance_rate":
                    (self._spec_accepted / prop) if prop else 0.0,
                "gated_plain_windows": self._spec_plain_windows}

    def _emit_result(self, req: _Request) -> None:
        """A request finished: publish its tokens, close its metrics —
        TTFT/TPOT are observed HERE (at completion) into the registry
        histograms, making the tenant breakdown and the benchmark's
        percentiles two views of the same samples."""
        self._results[req.rid] = req.prompt + req.generated[
            :req.max_new_tokens]
        m = self._req_metrics.get(req.rid)
        if m is not None:
            m["done_t"] = self._wall()
            m["n_generated"] = min(len(req.generated), req.max_new_tokens)
            tenant = m.get("tenant", "default")
            pr = (req.sched.priority if req.sched is not None
                  else PRIORITY_NORMAL)
            self._c_completed.inc(tenant=tenant)
            if "first_token_t" in m:
                self._h_ttft.observe(m["first_token_t"] - m["submit_t"],
                                     tenant=tenant, priority=pr)
                self._h_e2e.observe(m["done_t"] - m["submit_t"],
                                    tenant=tenant)
                n = int(m["n_generated"])
                if n > 1:
                    self._h_tpot.observe(
                        (m["done_t"] - m["first_token_t"]) / (n - 1) * 1e3,
                        tenant=tenant)
        self._tel.tracer.close(req.rid, "complete")

    # ---------------------------------------------------- request lifecycle
    def cancel(self, rid: int) -> bool:
        """Cooperative cancel, effective immediately at the host level: a
        waiting (or swapped-out) request leaves the queue and any parked
        host KV is discarded; a running request's blocks — including the
        speculative-window tail reservation — roll back through the same
        refcount-safe ``BlockAllocator.truncate`` path that speculative
        rejection uses, returning the allocator to its pre-submit
        occupancy. Returns False for unknown or already-finished rids;
        cancelled requests never appear in results (``status(rid)`` says
        ``"cancelled"``)."""
        ent = self._sched.cancel(rid)
        if ent is not None:
            self._drop_entry(ent, "cancelled")
            return True
        for s in range(self.max_batch):
            req = self._slots[s]
            if req is not None and req.rid == rid:
                if self.cache_mode == "paged":
                    req.table = self.alloc.truncate(req.table, 0)
                self._dropped[rid] = "cancelled"
                self._c_dropped.inc(reason="cancelled")
                m = self._req_metrics.get(rid)
                if m is not None:
                    m["done_t"] = self._wall()
                self._tel.tracer.close(rid, "cancelled")
                self._release_slot(s)
                return True
        return False

    def status(self, rid: int) -> str:
        """One of ``done / cancelled / expired / failed / running /
        prefilling / swapped / preempted / queued / unknown``
        (``failed`` = quarantined after exhausting its fault-retry
        budget; terminal, with a telemetry record)."""
        if rid in self._results:
            return "done"
        if rid in self._dropped:
            return self._dropped[rid]
        for s in range(self.max_batch):
            req = self._slots[s]
            if req is not None and req.rid == rid:
                return "prefilling" if (self.cache_mode == "paged"
                                        and self._prefilling[s]) \
                    else "running"
        for ent in self._sched.waiting():
            if ent.rid == rid:
                if ent.swap is not None:
                    return "swapped"
                return "preempted" if ent.preempted else "queued"
        return "unknown"

    def sched_metrics(self) -> Dict[str, Any]:
        """Scheduler + preemption counters (all cache modes; swap fields
        appear on the paged path only; adapter-pool fields and the
        per-tenant TTFT/TPOT breakdown when ``lora=`` is configured)."""
        # thin view over the metrics registry: the counters below ARE the
        # values the registry exposes via to_json()/to_prometheus() — the
        # dict shape is the stable public contract, the registry is the
        # store (attach_metrics seeds scheduler history, so totals always
        # match the legacy int attributes)
        reg = self._tel.registry
        m = {"policy": self._sched.policy,
             "queue_depth": len(self._sched),
             "submitted": int(reg.counter(
                 "sched_requests_submitted").total()),
             "expired": int(reg.counter("sched_requests_expired").total()),
             "cancelled": int(self._c_dropped.total(
                 where={"reason": "cancelled"})),
             "preemptions": int(self._c_preempt.total()),
             "prefill_aborts": int(self._c_aborts.total()),
             "resumes": int(self._c_resumes.total()),
             "stalled_reservations": int(self._c_stalls.total())}
        if self.cache_mode == "paged":
            m["host_bytes_in_use"] = self._offload.host.bytes_in_use
            m["host_bytes_peak"] = self._offload.host.bytes_peak
            m["swapped_waiting"] = sum(
                1 for e in self._sched.waiting() if e.swap is not None)
        m["tenants"] = self._tenant_breakdown()
        if self._lora is not None:
            m.update(self._lora.stats())
        return m

    def _tenant_breakdown(self) -> Dict[str, Dict[str, float]]:
        """Per-tenant latency percentiles over COMPLETED requests: TTFT
        (submit → first token) and TPOT (per-token after the first) p50 /
        p95 — the multi-tenant fairness view the benchmark reports. A
        thin view over the registry's ``serving_ttft_s`` /
        ``serving_tpot_ms`` histograms (observed at completion in
        ``_emit_result``), so these numbers and the exposition formats
        can never drift apart."""
        out: Dict[str, Dict[str, float]] = {}
        for t in self._h_ttft.label_values("tenant"):
            xs = self._h_ttft.samples({"tenant": t})
            row = {"completed": float(len(xs))}
            if xs:
                row["ttft_p50_ms"] = float(np.percentile(xs, 50) * 1e3)
                row["ttft_p95_ms"] = float(np.percentile(xs, 95) * 1e3)
            tp = self._h_tpot.samples({"tenant": t})
            if tp:
                row["tpot_p50_ms"] = float(np.percentile(tp, 50))
                row["tpot_p95_ms"] = float(np.percentile(tp, 95))
            out[t] = row
        return out

    def request_metrics(self) -> Dict[int, Dict[str, float]]:
        """Per-rid wall-clock marks — ``submit_t``, ``first_token_t``,
        ``done_t``, ``n_generated`` (plus the request's ``tenant``) —
        from which TTFT and per-token latency are derived
        (tools/serving_benchmark.py)."""
        return self._req_metrics

    def _release_slot(self, slot: int) -> None:
        req = self._slots[slot]
        self._slots[slot] = None
        if self.cache_mode == "paged":
            for bid in req.table:
                self.alloc.free(bid)
            req.table = []
            self._bt[slot, :] = 0
            self._prefilling[slot] = None
            self.pos[slot] = 0
            self.tokens[slot] = 0
            self.temps[slot] = 0.0
            self.topks[slot] = 0
            self.topps[slot] = 0.0
            if self.spec is not None:
                self.kcaps[slot] = 0
            if self._lora is not None:
                self._lora.release(int(self.aidx[slot]))
                self.aidx[slot] = 0
            self._samp_dev = None

    def kv_stats(self) -> Dict[str, int]:
        """Paged-pool occupancy/prefix-cache counters, merged with the
        warm-tier ledger (``warm_*`` keys) and the cold-refill count
        (empty for dense)."""
        if self.cache_mode != "paged":
            return {}
        out = self.alloc.stats()
        out.update(self._offload.tier_stats())
        out["cold_refills"] = self._cold_refills
        return out

    # ------------------------------------------------------ fault tolerance
    def assert_conserved(self) -> Dict[str, int]:
        """Pool conservation invariants — raises AssertionError on a leak.

        Checked between steps (the chaos tests call this after EVERY
        tick, so a leak surfaces at the faulting tick, not at teardown):

        - block identity: ``in_use + cached + free == num_blocks - 1``
          (block 0 is scratch) and no block is left pinned;
        - refcount audit: the allocator's live refcounts equal the
          multiset of block-table entries across occupied slots;
        - host-pool audit: parked bytes equal the sum over waiting
          swapped entries, in BOTH byte ledgers (pool and allocator);
        - adapter-pool audit (when ``lora=``): same identity over pages,
          and page refs equal the occupied slots holding each page.

        Returns the audited numbers (handy for test output). Dense-cache
        servers have no pools to audit and return ``{}``."""
        if self.cache_mode != "paged":
            return {}
        from collections import Counter

        a = self.alloc
        errs: List[str] = []
        usable = a.num_blocks - 1
        if a.blocks_in_use + a.blocks_cached + a.blocks_free != usable:
            errs.append(
                f"block identity broken: in_use={a.blocks_in_use} + "
                f"cached={a.blocks_cached} + free={a.blocks_free} != "
                f"usable={usable}")
        if a.pinned_blocks != 0:
            errs.append(f"{a.pinned_blocks} blocks left pinned between "
                        f"steps (pins must be copy-scoped)")
        expect: Counter = Counter()
        for s in range(self.max_batch):
            req = self._slots[s]
            if req is not None:
                expect.update(req.table)
        refs = a.ref_counts()
        if dict(expect) != refs:
            extra = {b: n for b, n in refs.items() if expect.get(b) != n}
            missing = {b: n for b, n in expect.items() if refs.get(b) != n}
            errs.append(f"refcount audit failed: allocator-only={extra} "
                        f"tables-only={missing}")
        swapped = [e for e in self._sched.waiting() if e.swap is not None]
        parked = sum(e.swap.nbytes for e in swapped)
        if self._offload.host.bytes_in_use != parked:
            errs.append(f"host pool ledger {self._offload.host.bytes_in_use}"
                        f" != sum of waiting swap handles {parked}")
        if a.host_bytes_in_use != parked:
            errs.append(f"allocator host ledger {a.host_bytes_in_use} != "
                        f"sum of waiting swap handles {parked}")
        if len(self._offload.host) != len(swapped):
            errs.append(f"host pool parks {len(self._offload.host)} "
                        f"payloads but {len(swapped)} entries are swapped")
        warm = self._offload.warm
        warm_bytes = sum(nb for _, _, nb, _ in warm.entries())
        if warm_bytes != warm.bytes_in_use:
            errs.append(f"warm tier ledger {warm.bytes_in_use} != sum of "
                        f"parked entries {warm_bytes}")
        dual = [h for h, _, _, _ in warm.entries()
                if a.contains_hash(h)]
        if dual:
            # promotion takes the warm copy and demotion unregisters the
            # hot block — a hash resident in BOTH tiers means one of
            # those handoffs half-finished
            errs.append(f"{len(dual)} chain hashes resident in both the "
                        f"hot prefix cache and the warm tier")
        if self._lora is not None:
            la = self._lora.alloc
            lu = la.num_blocks - 1
            if la.blocks_in_use + la.blocks_cached + la.blocks_free != lu:
                errs.append(
                    f"adapter page identity broken: in_use="
                    f"{la.blocks_in_use} + cached={la.blocks_cached} + "
                    f"free={la.blocks_free} != usable={lu}")
            pexp: Counter = Counter()
            for s in range(self.max_batch):
                if self._slots[s] is not None and int(self.aidx[s]) > 0:
                    pexp[int(self.aidx[s])] += 1
            if dict(pexp) != la.ref_counts():
                errs.append(f"adapter page refs {la.ref_counts()} != "
                            f"slot aidx multiset {dict(pexp)}")
        if errs:
            raise AssertionError("; ".join(errs))
        out = {"blocks_in_use": a.blocks_in_use,
               "blocks_cached": a.blocks_cached,
               "blocks_free": a.blocks_free,
               "host_bytes_in_use": parked,
               "warm_blocks": len(warm),
               "warm_bytes_in_use": warm.bytes_in_use,
               "swapped_waiting": len(swapped)}
        # per-shard pool audit (tp executors): donation must rotate the
        # pool buffers without ever resharding them — raises on a lost
        # tp layout, and reports the per-shard accounting alongside
        out.update(self._exec.shard_audit())
        return out

    def _snapshot_fingerprint(self) -> Dict[str, Any]:
        """Shape-critical configuration a snapshot can only restore into:
        these fields decide the compiled programs' shapes and the KV
        payloads' fixed gather width."""
        return {"cache": self.cache_mode,
                "block_size": self.block_size,
                "max_len": self.max_len,
                "max_batch": self.max_batch,
                "kv_quant": self.kv_quant,
                "tick_window": self.tick_window,
                "table_width": self._table_width,
                "num_blocks": self.alloc.num_blocks,
                "spec_k": self.spec_k if self.spec is not None else None,
                "lora": self._lora is not None,
                "kernels": self.kernels,
                "mk_geometry": (self.mk_geometry.asdict()
                                if self.mk_geometry is not None else None),
                # resolved per-layer kernel geometry (non-default ops
                # only; None when everything runs the default schedule,
                # which keeps pre-geometry snapshots restorable)
                "kernel_geometry": ({op: g.asdict()
                                     for op, (g, src)
                                     in self.kernel_geometry.items()
                                     if src != "default"} or None),
                "mesh": self._exec.mesh_fingerprint}

    def _req_state(self, req: _Request) -> Dict[str, Any]:
        return {"rid": req.rid, "prompt": list(req.prompt),
                "max_new_tokens": req.max_new_tokens,
                "temperature": req.temperature, "top_k": req.top_k,
                "top_p": req.top_p, "generated": list(req.generated),
                "draft_k": req.draft_k, "adapter": req.adapter,
                "replay": (list(req.replay) if req.replay is not None
                           else None),
                "hashes": list(req.hashes)}

    def _sched_state(self, ent: SchedEntry) -> Dict[str, Any]:
        now = self._sched.now()
        return {"priority": ent.priority, "tenant": ent.tenant,
                "ttl_remaining": (None if ent.deadline is None
                                  else max(ent.deadline - now, 0.0)),
                "seq": ent.seq, "cost": ent.cost, "vtag": ent.vtag,
                "preempted": ent.preempted, "started": ent.started}

    def snapshot(self, *, trust_kv: bool = True) -> Dict[str, Any]:
        """Crash-safe capture of the full in-flight engine state — the
        drain/migrate primitive (ROADMAP 5): every queued, prefilling,
        decoding, and swapped request, with enough state that
        :meth:`restore` on a FRESH server continues each one with
        greedy-token-identical output.

        Decoding slots' KV rides the offload engine's compile-once
        fixed-width gather (non-destructive — the captured server keeps
        serving); already-swapped entries copy their parked host arrays;
        prefilling/queued work is recomputable and restores as queued.
        Per-payload CRC checksums ride along, so a payload corrupted in
        transit degrades to re-prefill on the restoring side instead of
        wrong tokens. Host-only: zero compiled programs on a warm
        server, zero device state mutated. Paged servers only.

        ``trust_kv=False`` captures decoding slots as replay-queued work
        (prompt + generated so far, re-prefilled token-exactly on the
        restoring side) instead of gathering their device KV — the
        salvage mode for an engine whose device state can no longer be
        trusted (a failed replica): host-side request state is always
        consistent at the last completed harvest, the device pools may
        not be. Already-swapped entries keep their KV payloads either
        way — those live in host RAM behind a CRC, not on the device."""
        if self.cache_mode != "paged":
            raise ValueError("snapshot() requires cache='paged' — the "
                             "dense slab has no per-request KV capture")
        if self._failed is not None and trust_kv:
            raise ValueError(
                f"server failed ({self._failed}): device KV is untrusted "
                f"after a post-dispatch failure — capture with "
                f"snapshot(trust_kv=False) to salvage from host state")
        from .kv_offload import payload_checksum

        reqs: List[Dict[str, Any]] = []
        for s in range(self.max_batch):
            req = self._slots[s]
            if req is None:
                continue
            d = self._req_state(req)
            d["sched"] = self._sched_state(req.sched)
            if self._prefilling[s]:
                # prefill is recomputable (and must be: its KV covers an
                # unfinished chunk boundary) — restore re-queues it
                d["phase"] = "queued"
            elif not trust_kv:
                # salvage: re-enter through the corruption-recovery replay
                # rung — re-prefill prompt+generated[:-1], resume decode at
                # the saved position with the last generated token as the
                # next input; token-identical by the same argument as the
                # CRC-mismatch fallback
                d["phase"] = "queued"
                d["replay"] = (list(req.prompt)
                               + list(req.generated))[:int(self.pos[s])]
            else:
                arrays = self._offload.gather_payload(req.table,
                                                      self._pools)
                d["phase"] = "kv"
                d["kv"] = {
                    "arrays": arrays,
                    "n_tokens": int(self.pos[s]),
                    "last_token": int(self.tokens[s]),
                    "n_blocks": len(req.table),
                    "hashes": list(
                        req.hashes[:min(len(req.hashes), len(req.table))]),
                    "nbytes": len(req.table) * self.alloc.bytes_per_block,
                    "checksum": payload_checksum(arrays)}
            reqs.append(d)
        for ent in self._sched.waiting():
            d = self._req_state(ent.req)
            d["sched"] = self._sched_state(ent)
            if ent.swap is not None:
                h = ent.swap
                arrays = [np.array(a)
                          for a in self._offload.host.peek(h.rid)]
                d["phase"] = "kv"
                d["kv"] = {"arrays": arrays, "n_tokens": h.n_tokens,
                           "last_token": h.last_token,
                           "n_blocks": h.n_blocks,
                           "hashes": list(h.hashes), "nbytes": h.nbytes,
                           "checksum": h.checksum}
            else:
                d["phase"] = "queued"
            reqs.append(d)
        snap: Dict[str, Any] = {
            "format": 1,
            "config": self._snapshot_fingerprint(),
            "rng_key": np.asarray(self._base_key),
            "step_no": self._step_no,
            "next_rid": self._next_rid,
            "sched": {"vnow": self._sched._vnow,
                      "tenant_tag": dict(self._sched._tenant_tag)},
            "requests": reqs,
            "results": {r: list(t) for r, t in self._results.items()},
            "dropped": dict(self._dropped),
            # the warm tier rides along in BOTH modes: its payloads are
            # host RAM behind per-block CRCs (like swapped entries), so
            # an untrusted device never taints them; the restoring side
            # adopts them via adopt_warm (CRC-verified, best-effort)
            "warm_tier": [
                {"hash": h, "arrays": [np.array(x) for x in arrs],
                 "nbytes": nb, "checksum": crc}
                for h, arrs, nb, crc in self._offload.warm.entries()],
        }
        if self.spec is not None:
            snap["spec_state"] = {
                "gate_off": self._spec_gate_off,
                "plain_windows": self._spec_plain_windows,
                "turbo": self._spec_turbo,
                "proposed": self._spec_proposed,
                "accepted": self._spec_accepted}
        return snap

    def restore(self, snap: Dict[str, Any]) -> int:
        """Rebuild a :meth:`snapshot` into THIS (idle, freshly built)
        server; returns the number of requests restored.

        Every request re-enters through the normal machinery — KV-bearing
        requests become swapped queue entries whose payload is adopted
        into the host pool and restored by the compile-once, CRC-verified
        swap-in path at the next step; queued/prefilling work re-queues.
        Greedy continuation is token-identical to the captured server's
        because resume is the same bit-exact path preemption already
        proves out, and the sampling key + step counter come along."""
        if self.cache_mode != "paged":
            raise ValueError("restore() requires cache='paged'")
        if self._failed is not None:
            raise ValueError(f"cannot restore into a failed server "
                             f"({self._failed}) — build a fresh one")
        if any(sl is not None for sl in self._slots) or len(self._sched):
            raise ValueError("restore() needs an idle server: slots and "
                             "queue must be empty")
        if snap.get("format") != 1:
            raise ValueError(f"unknown snapshot format "
                             f"{snap.get('format')!r}")
        self._check_snapshot_config(snap["config"])
        # pre-flight the per-request ladder BEFORE mutating anything: a
        # mid-loop rejection (unknown adapter) must leave this server
        # exactly as it was — a partial restore would be corruption, not
        # an error
        for d in snap["requests"]:
            self._validate_snapshot_request(d)
        self._base_key = jnp.asarray(np.asarray(snap["rng_key"]))
        self._step_no = int(snap["step_no"])
        self._next_rid = max(self._next_rid, int(snap["next_rid"]))
        self._sched.restore_state(snap["sched"]["vnow"],
                                  snap["sched"]["tenant_tag"])
        if self.spec is not None and "spec_state" in snap:
            st = snap["spec_state"]
            self._spec_gate_off = int(st["gate_off"])
            self._spec_plain_windows = int(st["plain_windows"])
            self._spec_turbo = bool(st["turbo"])
            self._spec_proposed = int(st["proposed"])
            self._spec_accepted = int(st["accepted"])
        self._results.update(
            {int(r): list(t) for r, t in snap["results"].items()})
        self._dropped.update(snap["dropped"])
        now = self._sched.now()
        restored = 0
        for d in sorted(snap["requests"], key=lambda d: d["sched"]["seq"]):
            self._admit_snapshot_request(d, now)
            restored += 1
        self.adopt_warm(snap.get("warm_tier", ()))
        return restored

    def _check_snapshot_config(self, want: Dict[str, Any]) -> None:
        """Validate a snapshot's config fingerprint against this server's
        (shared by :meth:`restore` and :meth:`admit_migrated`)."""
        have = self._snapshot_fingerprint()
        for k, hv in have.items():
            wv = want.get(k)
            if k == "mesh":
                # provenance stamp, not a gate: snapshot KV payloads are
                # full-width host gathers, so any tp restores into any tp
                # (fleet homogeneity still compares it — replicas must
                # agree — but restore/migration across layouts is legal)
                continue
            if k == "num_blocks":
                if hv < wv:
                    raise ValueError(
                        f"restoring pool has {hv} blocks but the snapshot "
                        f"was taken with {wv} — a smaller pool cannot "
                        f"guarantee the captured requests stay feasible")
            elif hv != wv:
                raise ValueError(
                    f"snapshot/server config mismatch on {k!r}: snapshot "
                    f"has {wv!r}, this server has {hv!r}")

    def _validate_snapshot_request(self, d: Dict[str, Any]) -> None:
        """Reject-at-the-door checks for one snapshot request dict —
        must run before ANY server state mutates."""
        if self.role == "prefill" and (d.get("phase") == "kv"
                                       or d.get("generated")):
            # the prefill class runs chunked prefill ONLY: decode-phase
            # work (a KV payload, or any request that already generated
            # tokens and would resume decoding) belongs to the decode
            # class — admitting it here would wedge it parked forever
            raise ValueError(
                f"prefill-class replica cannot admit decode-phase "
                f"request {d['rid']} (phase={d.get('phase')!r}, "
                f"{len(d.get('generated') or ())} generated tokens) — "
                f"route it to the decode class")
        if d["adapter"] is not None:
            if self._lora is None:
                raise ValueError(
                    f"request {d['rid']} names adapter "
                    f"{d['adapter']!r} but this server has no lora=")
            self._lora.validate(d["adapter"])

    def _admit_snapshot_request(self, d: Dict[str, Any],
                                now: float) -> None:
        """Re-admit one validated snapshot request dict through the
        normal machinery: KV payloads are adopted into the host pool and
        re-enter via the CRC-verified swap-in path; queued/replay work
        re-queues. The per-request half of :meth:`restore`, shared with
        :meth:`admit_migrated`."""
        from .kv_offload import SwapHandle

        req = _Request(int(d["rid"]), list(d["prompt"]),
                       int(d["max_new_tokens"]),
                       temperature=float(d["temperature"]),
                       top_k=int(d["top_k"]), top_p=float(d["top_p"]),
                       draft_k=d["draft_k"], adapter=d["adapter"])
        req.generated = list(d["generated"])
        req.replay = (list(d["replay"]) if d["replay"] is not None
                      else None)
        req.hashes = list(d["hashes"])
        sd = d["sched"]
        ent = SchedEntry(req=req, rid=req.rid,
                         priority=int(sd["priority"]),
                         tenant=sd["tenant"],
                         deadline=(None if sd["ttl_remaining"] is None
                                   else now + sd["ttl_remaining"]),
                         seq=int(sd["seq"]), cost=float(sd["cost"]),
                         vtag=float(sd["vtag"]),
                         preempted=bool(sd["preempted"]),
                         started=bool(sd["started"]),
                         adapter=req.adapter)
        req.sched = ent
        if d["phase"] == "kv":
            kv = d["kv"]
            handle = SwapHandle(
                rid=req.rid, n_tokens=int(kv["n_tokens"]),
                last_token=int(kv["last_token"]),
                n_blocks=int(kv["n_blocks"]),
                hashes=list(kv["hashes"]), nbytes=int(kv["nbytes"]),
                checksum=int(kv["checksum"]))
            self._offload.adopt(
                handle, [np.asarray(a) for a in kv["arrays"]])
            ent.swap = handle
        self._sched.restore_entry(ent)
        # fresh wall-clock marks: the captured server's monotonic
        # clock does not transfer across processes, and mixing the
        # two would observe negative latencies
        m: Dict[str, Any] = {"submit_t": self._wall(),
                             "tenant": ent.tenant}
        if req.generated:
            m["first_token_t"] = m["submit_t"]
        self._req_metrics[req.rid] = m
        if self._tel.enabled:
            tr = self._tel.tracer
            tr.set_meta(req.rid, tenant=ent.tenant,
                        priority=ent.priority,
                        prompt_len=len(req.prompt),
                        adapter=req.adapter or "")
            tr.begin(req.rid, "queued", restored=True)

    def admit_migrated(self, d: Dict[str, Any], *,
                       source_config: Optional[Dict[str, Any]] = None
                       ) -> int:
        """Admit ONE snapshot request dict into this — possibly busy —
        server: the fleet migration primitive. Unlike :meth:`restore`
        (whole-snapshot, idle target only) this re-admits a single
        request through the same validated path while the target keeps
        serving its own traffic; KV payloads adopt into the host pool
        and resume via the compile-once, CRC-verified swap-in program,
        so a payload corrupted in transit degrades to re-prefill.

        ``source_config`` (the snapshot's ``config`` fingerprint) is
        checked when given — fleet replicas are homogeneous, so the
        router passes it once per migration. The caller guarantees rid
        uniqueness across engines (``FleetRouter`` assigns replicas
        disjoint rid spaces). Returns the admitted rid."""
        if self.cache_mode != "paged":
            raise ValueError("admit_migrated() requires cache='paged'")
        if self._failed is not None:
            from .faults import EngineFailedError

            raise EngineFailedError(
                f"cannot migrate into a failed server ({self._failed})")
        if source_config is not None:
            self._check_snapshot_config(source_config)
        self._validate_snapshot_request(d)
        self._admit_snapshot_request(d, self._sched.now())
        return int(d["rid"])

    def adopt_warm(self, entries: Sequence[Dict[str, Any]]) -> int:
        """Adopt a peer's warm-tier entries (a snapshot's ``warm_tier``
        list) into this server's warm tier — the fleet-wide prefix-cache
        half of a migration: a shared prompt prefilled once on the dying
        replica stays promotable on the survivor. Best-effort and
        CRC-verified per entry: a corrupt payload is dropped (a cache
        may always miss), a hash already hot here is skipped (cross-tier
        exclusivity), and the warm pool's own capacity/LRU rules apply.
        Returns the number of entries adopted."""
        if self.cache_mode != "paged":
            raise ValueError("adopt_warm() requires cache='paged'")
        from .kv_offload import payload_checksum

        adopted = 0
        for d in entries:
            h = int(d["hash"])
            if self.alloc.contains_hash(h) or h in self._offload.warm:
                continue
            arrays = [np.asarray(a) for a in d["arrays"]]
            if payload_checksum(arrays) != int(d["checksum"]):
                self._c_corrupt.inc()
                continue
            if self._offload.warm.put(h, arrays, int(d["nbytes"]),
                                      int(d["checksum"])):
                adopted += 1
        return adopted

    def evacuate(self, *, trust_kv: bool = True,
                 rids: Optional[Sequence[int]] = None) -> Dict[str, Any]:
        """Capture a :meth:`snapshot` and then RELEASE every in-flight
        request from this server — the drain half of a fleet migration:
        the caller re-admits the returned snapshot's requests elsewhere,
        and this engine ends empty (slots free, queue empty, host pool
        drained) so :meth:`assert_conserved` holds trivially afterwards.
        Completed results and dropped markers stay readable on this
        server (and ride the snapshot). ``trust_kv=False`` salvages a
        failed engine from host state only.

        ``rids=``: evacuate ONLY the listed requests (the snapshot's
        ``requests`` list is filtered to them and only they release) —
        the disaggregated prefill→decode handoff primitive: a
        prefill-class replica keeps streaming its other prompts while
        its finished ones (:meth:`handoff_ready`) move to the decode
        class over this same CRC-verified snapshot path."""
        snap = self.snapshot(trust_kv=trust_kv)
        if rids is not None:
            keep = set(int(r) for r in rids)
            snap["requests"] = [d for d in snap["requests"]
                                if d["rid"] in keep]
        else:
            keep = None
        for s in range(self.max_batch):
            req = self._slots[s]
            if req is None or (keep is not None and req.rid not in keep):
                continue
            self._handoff.discard(req.rid)
            req.table = self.alloc.truncate(req.table, 0)
            self._tel.tracer.close(req.rid, "migrated")
            self._release_slot(s)
        for ent in list(self._sched.waiting()):
            if keep is not None and ent.rid not in keep:
                continue
            self._handoff.discard(ent.rid)
            self._sched.remove(ent.rid)
            if ent.swap is not None:
                self._offload.discard(ent.swap)
            self._tel.tracer.close(ent.rid, "migrated")
        if keep is None:
            self._handoff.clear()
            # full drain: the warm entries moved with the snapshot (the
            # router offers them to a survivor via adopt_warm) — drop
            # the local copies so this engine truly ends empty
            self._offload.warm.clear()
        return snap

    def handoff_ready(self) -> List[int]:
        """Rids a prefill-class replica has finished prefilling and
        parked for the decode class — the fleet router's per-step
        handoff sweep passes them straight to
        ``evacuate(trust_kv=True, rids=...)``. Pruned lazily against the
        live request set (a parked request can still be cancelled or
        quarantined out from under the set)."""
        live = {r.rid for r in self._slots if r is not None}
        live.update(e.rid for e in self._sched.waiting())
        self._handoff &= live
        return sorted(self._handoff)

    def take_results(self) -> Dict[int, List[int]]:
        """Pop and return every completed result accumulated so far —
        the incremental-harvest form of :meth:`run`'s return value (the
        fleet router collects per step instead of at drain)."""
        out, self._results = self._results, {}
        return out

    @property
    def steps(self) -> int:
        """Completed engine steps — the fleet router's tick-progress
        heartbeat signal (a replica wedged with queued work but no
        active slot holds work without advancing this)."""
        return self._step_no

    def fail(self, reason: str) -> None:
        """Mark this engine terminally failed (idempotent — the first
        reason sticks). ``submit``/``restore``/``admit_migrated`` refuse
        afterwards; the fleet router uses this to poison a replica the
        chaos plan killed so nothing re-enters it behind the salvage."""
        if self._failed is None:
            self._failed = str(reason)

    def load_metrics(self) -> Dict[str, int]:
        """O(1) load signals for routing decisions — the cheap subset of
        :meth:`sched_metrics` (which builds per-tenant percentile tables
        and is priced for end-of-run reporting, not per-submission
        scoring) plus the allocator's admission headroom."""
        m = {"queue_depth": len(self._sched),
             "slots_occupied": sum(sl is not None for sl in self._slots),
             "slots_total": self.max_batch}
        if self.cache_mode == "paged":
            m["blocks_headroom"] = (self.alloc.blocks_free
                                    + self.alloc.evictable_cached)
            m["queued_kv_demand"] = self._sched.kv_demand()
        return m

    def set_rid_base(self, base: int) -> None:
        """Start this server's rid counter at ``base`` — only valid on a
        fresh server (nothing submitted yet). The fleet router assigns
        each replica a disjoint rid space so migrated requests can never
        collide with a peer's own."""
        if (self._next_rid != 0 or len(self._sched)
                or any(sl is not None for sl in self._slots)
                or self._results or self._dropped):
            raise ValueError("set_rid_base() requires a fresh server — "
                             "rids already handed out would collide")
        if not isinstance(base, int) or isinstance(base, bool) or base < 0:
            raise ValueError(f"rid base must be an int >= 0, got {base!r}")
        self._next_rid = base

    # ------------------------------------------------------- router surface
    # Everything the fleet router needs, as methods rather than attribute
    # walks (``srv.alloc...``, ``srv.telemetry.registry...``), so a remote
    # ReplicaHandle can answer the same questions over one RPC each.
    def probe_prefix(self, prompt: Sequence[int]) -> int:
        """Cached-prefix blocks this server could reuse for ``prompt`` —
        the router's routing-affinity signal. Read-only (takes no refs);
        0 on the dense path, which has no content-addressed cache."""
        if self.cache_mode != "paged":
            return 0
        return self.alloc.probe_prefix(list(prompt))

    def watchdog_findings(self) -> List[Dict[str, Any]]:
        """The flight-recorder watchdog's cumulative findings — the
        router's periodic health probe (see
        :meth:`~paddle_tpu.telemetry.ServingTelemetry.watchdog`)."""
        return self._tel.watchdog()

    def slo_observations(self) -> Dict[str, Dict[str, List[float]]]:
        """Per-tenant latency samples for the fleet SLO roll-up:
        ``{"ttft": {tenant: [seconds...]}, "tpot": {tenant: [ms...]}}``
        read from this server's tenant-labeled histograms. The router
        merges these across replicas instead of reaching into each
        replica's registry — the one shape a remote handle can ship."""
        out: Dict[str, Dict[str, List[float]]] = {"ttft": {}, "tpot": {}}
        for hname, key in (("serving_ttft_s", "ttft"),
                           ("serving_tpot_ms", "tpot")):
            h = self._tel.registry.get(hname)
            if h is None:
                continue
            for tenant in h.label_values("tenant"):
                out[key][tenant] = list(h.samples({"tenant": tenant}))
        return out

    # ------------------------------------------------------------ telemetry
    def telemetry_snapshot(self) -> Dict[str, Any]:
        """Sync point-in-time gauges (pool occupancy, adapter pool, spec
        counters, queue depth) into the registry, then return the full
        telemetry blob: registry JSON (histograms carry computed
        p50/p95), watchdog findings over the flight ring, and the serving
        configuration the numbers were produced under."""
        reg = self._tel.registry
        reg.gauge("serving_queue_depth").set(float(len(self._sched)))
        reg.gauge("serving_slots_occupied").set(
            float(sum(sl is not None for sl in self._slots)))
        reg.gauge("serving_slots_total").set(float(self.max_batch))
        if self.cache_mode == "paged":
            self.alloc.publish(reg)
            for k, v in self._offload.host.stats().items():
                reg.gauge(f"serving_host_pool_{k}").set(float(v))
            for k, v in self._offload.tier_stats().items():
                reg.gauge(f"serving_tier_{k}").set(float(v))
            reg.gauge("serving_tier_cold_refills").set(
                float(self._cold_refills))
        if self._lora is not None:
            for k, v in self._lora.stats().items():
                reg.gauge(f"serving_{k}").set(float(v))
        for k, v in self.spec_metrics().items():
            reg.gauge(f"serving_spec_{k}").set(float(v))
        # info gauge: which per-layer kernel schedule actually ran —
        # value 1.0, identity in the labels (op + default/profile/swept)
        for op, (_, src) in self.kernel_geometry.items():
            reg.gauge("serving_kernel_geometry").set(1.0, op=op, source=src)
        snap = self._tel.snapshot()
        snap["config"] = {"cache": self.cache_mode,
                          "max_batch": self.max_batch,
                          "max_len": self.max_len,
                          "tick_window": self.tick_window,
                          "kv_quant": self.kv_quant,
                          "policy": self._sched.policy}
        if self.spec is not None:
            snap["config"]["spec"] = self.spec.describe()
        return snap

    def export_chrome_trace(self, path: str) -> str:
        """Write the span tracer's chrome trace (one timeline row per
        request — queued/prefill/decode/spec/preempt/swap spans). Open in
        chrome://tracing or Perfetto; empty when telemetry is disabled."""
        return self._tel.export_chrome_trace(path)

    # ------------------------------------------------------------- stepping
    def _harvest_window(self, nxt_host, active, active_mask) -> None:
        """Fold one decode window's (k, B) token stack into the per-request
        state: append tokens, detect eos/max-new/max-len completion (window
        surplus past completion is discarded — tick_window semantics) and
        free finished slots for next window's refill."""
        k = nxt_host.shape[0]
        self.pos = self.pos + active_mask * k
        self.tokens = np.where(active_mask > 0, nxt_host[-1],
                               self.tokens).astype(np.int32)
        pos_after = self.pos
        for s in active:
            req = self._slots[s]
            done = False
            if self.eos is None:
                # no-eos fast path (see _harvest_spec): emission is one
                # slice per window instead of a per-token python walk
                gen = req.generated
                limit = min(req.max_new_tokens - len(gen),
                            self.max_len - 1 - (int(pos_after[s]) - k))
                take = k
                if take >= limit:
                    take = limit
                    done = True
                # nxt_host is host numpy — the window's one sync is done
                gen.extend(nxt_host[:take, s].tolist())  # graftlint: noqa[host-sync]
                if done:
                    self._emit_result(req)
                    self._release_slot(s)
                continue
            for t in range(k):
                tok = int(nxt_host[t, s])
                finished_last = (self.eos is not None and
                                 req.generated[-1] == self.eos)
                if not finished_last:
                    req.generated.append(tok)
                pos_t = int(pos_after[s]) - k + t + 1
                if (finished_last
                        or len(req.generated) >= req.max_new_tokens
                        or pos_t >= self.max_len - 1):
                    done = True
                    break
            if done:
                self._emit_result(req)
                self._release_slot(s)

    def step(self) -> int:
        """One server step: admit queued requests, advance one prefill
        chunk per prefilling slot (paged), then one decode window
        (``tick_window`` ticks) across decoding slots; returns #remaining
        (occupied slots + queued)."""
        if self.cache_mode == "paged":
            return self._step_paged()
        tel = self._tel
        if tel.enabled:
            from ..analysis.recompile_guard import compile_count
            _tt0 = tel.clock()
            _c0 = compile_count()
        self._service_queue()
        active = [s for s in range(self.max_batch)
                  if self._slots[s] is not None]
        if not active:
            return 0
        self._step_no += 1
        key = jax.random.fold_in(self._base_key, self._step_no)
        active_mask = np.zeros((self.max_batch,), np.int32)
        active_mask[active] = 1
        if tel.enabled:
            _t0 = tel.clock()
            _rids = [self._slots[s].rid for s in active]
        # only occupied slots advance — idle slots must not drift their
        # write position (their garbage scatters would eventually go OOB)
        stack, self._caches = self._decode(
            self.params, jnp.asarray(self.tokens), self._caches,
            jnp.asarray(self.pos), jnp.asarray(self.temps),
            jnp.asarray(self.topks), jnp.asarray(self.topps),
            jnp.asarray(active_mask), key)
        self._harvest_window(np.asarray(stack), active, active_mask)
        if tel.enabled:
            _t1 = tel.clock()
            for rid in _rids:
                tel.tracer.complete(rid, "decode_window", _t0, _t1,
                                    ticks=self.tick_window)
            tel.flight.record(t_wall_s=_t1 - _tt0, prog="dense",
                              decoding=len(active),
                              queue_depth=len(self._sched),
                              recompiles=compile_count() - _c0)
        return sum(sl is not None for sl in self._slots) + len(self._sched)

    def run(self) -> Dict[int, List[int]]:
        """Drain the queue; returns {rid: prompt+generated token ids}."""
        while self.step():
            pass
        out, self._results = self._results, {}
        return out
