"""Continuous-batching generation server — the TPU serving engine.

Ref capability: the reference serves models through AnalysisPredictor /
DistModel (inference/api/, fleet_executor/dist_model.cc) with request-level
batching. The TPU-native redesign follows modern LLM serving: a FIXED pool
of ``max_batch`` slots, each with its own KV-cache rows and position; ONE
compiled decode step advances every active slot per tick (static shapes —
compiled exactly once), and finished slots are freed and refilled mid-flight
so throughput is never quantized by batch boundaries (continuous batching).

Prefill runs per request at bucketed prompt lengths (one compile per
bucket), producing cache rows that are scattered into the slot. The decode
step uses the model's vector-position path (`LlamaAttention.decode` with
``pos [B]``): every slot attends at its own depth.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..jit import functional_call, state_values


@dataclass
class _Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    generated: List[int] = field(default_factory=list)
    done: bool = False


class GenerationServer:
    """Continuous-batching decode server for a ``LlamaForCausalLM`` —
    greedy by default, per-request temperature sampling via
    ``submit(..., temperature=...)``.

    Usage::

        srv = GenerationServer(model, max_batch=4, max_len=256)
        rid = srv.submit([1, 5, 9], max_new_tokens=16)
        out = srv.run()          # drain all pending requests
        tokens = out[rid]        # prompt + generated ids
    """

    def __init__(self, model, max_batch: int = 4, max_len: int = 256,
                 prompt_buckets: Sequence[int] = (32, 64, 128),
                 eos_token_id: Optional[int] = None, seed: int = 0):
        cfg = model.cfg
        assert max_len <= cfg.max_position_embeddings
        self.model = model
        self.cfg = cfg
        self.max_batch = max_batch
        self.max_len = max_len
        self.buckets = sorted(b for b in prompt_buckets if b <= max_len)
        if not self.buckets:
            raise ValueError(
                f"no prompt bucket fits max_len={max_len} "
                f"(prompt_buckets={tuple(prompt_buckets)})")
        self.eos = eos_token_id
        self.params = state_values(model)

        from ..framework.dtype import convert_dtype

        kv = cfg.num_key_value_heads
        d = cfg.hidden_size // cfg.num_attention_heads
        cdtype = convert_dtype(cfg.dtype)
        self._caches = [jnp.zeros((max_batch, max_len, kv, d), cdtype)
                        for _ in range(2 * cfg.num_hidden_layers)]
        self.pos = jnp.zeros((max_batch,), jnp.int32)
        self.tokens = jnp.zeros((max_batch,), jnp.int32)
        self.temps = jnp.zeros((max_batch,), jnp.float32)
        self._step_no = 0
        self._base_key = jax.random.PRNGKey(seed)
        self._slots: List[Optional[_Request]] = [None] * max_batch
        self._queue: deque = deque()
        self._results: Dict[int, List[int]] = {}
        self._next_rid = 0
        # donate the KV pool: XLA updates the caches in place instead of
        # copying 2·L·(max_batch, max_len, KV, D) every decoded token
        self._decode = jax.jit(self._decode_fn, donate_argnums=(2,))
        self._prefills: Dict[int, object] = {}  # bucket -> jitted fn

    # ------------------------------------------------------------ compiled fns
    def _head(self, h):
        from ..framework.dispatch import apply_op

        if self.cfg.tie_word_embeddings:
            return apply_op(lambda v, w: jnp.matmul(v, w.T), h,
                            self.model.model.embed_tokens.weight)
        return self.model.lm_head(h)

    def _decode_fn(self, params, tokens, flat_caches, pos, temps, key):
        """One tick: advance every slot by one token. Per-slot temperature:
        temp == 0 → greedy argmax; temp > 0 → categorical sample at that
        temperature (each slot draws from its own key)."""
        model = self.model
        caches = [(Tensor(flat_caches[2 * i]), Tensor(flat_caches[2 * i + 1]))
                  for i in range(self.cfg.num_hidden_layers)]

        def call():
            h, new = model.model.decode_step(Tensor(tokens[:, None]), caches,
                                             pos)
            return self._head(h), new

        logits, new = functional_call(model, params, call_fn=call)
        flat = []
        for ck, cv in new:
            flat += [ck.value, cv.value]
        lg = logits.value[:, 0].astype(jnp.float32)       # (B, V)
        greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        # categorical draws independent samples per row with one key
        sampled = jax.random.categorical(
            key, lg / jnp.maximum(temps, 1e-6)[:, None]).astype(jnp.int32)
        return jnp.where(temps > 0, sampled, greedy), flat

    def _prefill(self, bucket: int):
        if bucket not in self._prefills:
            model = self.model

            def fn(params, prompt, true_len):
                """prompt [1, bucket] right-padded; logits at true_len-1."""
                kvs = self.cfg.num_key_value_heads
                d = self.cfg.hidden_size // self.cfg.num_attention_heads
                from ..framework.dtype import convert_dtype

                cdtype = convert_dtype(self.cfg.dtype)
                caches = [(Tensor(jnp.zeros((1, self.max_len, kvs, d), cdtype)),
                           Tensor(jnp.zeros((1, self.max_len, kvs, d), cdtype)))
                          for _ in range(self.cfg.num_hidden_layers)]

                def call():
                    h, new = model.model.prefill(Tensor(prompt), caches)
                    last = jax.lax.dynamic_slice_in_dim(
                        h.value, true_len - 1, 1, 1)
                    return self._head(Tensor(last)), new

                logits, new = functional_call(model, params, call_fn=call)
                flat = []
                for ck, cv in new:
                    flat += [ck.value, cv.value]
                return logits.value[:, 0].astype(jnp.float32), flat

            self._prefills[bucket] = jax.jit(fn)
        return self._prefills[bucket]

    # --------------------------------------------------------------- requests
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               temperature: float = 0.0) -> int:
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_len={self.max_len}")
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        self._bucket_for(len(prompt))  # validate against buckets up front
        rid = self._next_rid
        self._next_rid += 1
        self._queue.append(_Request(rid, list(prompt), max_new_tokens,
                                    temperature=float(temperature)))
        return rid

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt length {n} exceeds largest bucket "
                         f"{self.buckets[-1]}")

    def _assign(self, slot: int, req: _Request) -> None:
        n = len(req.prompt)
        bucket = self._bucket_for(n)
        prompt = np.zeros((1, bucket), np.int32)
        prompt[0, :n] = req.prompt
        lg, flat = self._prefill(bucket)(self.params, jnp.asarray(prompt), n)
        # the FIRST generated token honors the request temperature too
        if req.temperature > 0:
            k = jax.random.fold_in(self._base_key, (req.rid << 20) | 1)
            first = jax.random.categorical(
                k, lg / max(req.temperature, 1e-6)).astype(jnp.int32)
        else:
            first = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        # scatter this request's cache rows into the slot. Rows beyond the
        # true prompt length hold right-pad garbage, but decode writes
        # sequentially from pos=n, overwriting each such row BEFORE the
        # attention mask (arange <= pos) can reach it — never attended.
        for i in range(len(self._caches)):
            self._caches[i] = self._caches[i].at[slot, :self.max_len].set(
                flat[i][0])
        self.pos = self.pos.at[slot].set(n)
        self.tokens = self.tokens.at[slot].set(int(first[0]))
        self.temps = self.temps.at[slot].set(req.temperature)
        req.generated.append(int(first[0]))
        self._slots[slot] = req

    def _fill_free_slots(self) -> None:
        for s in range(self.max_batch):
            if self._slots[s] is None and self._queue:
                self._assign(s, self._queue.popleft())

    def step(self) -> int:
        """One decode tick across all occupied slots; returns #active."""
        self._fill_free_slots()
        active = [s for s in range(self.max_batch)
                  if self._slots[s] is not None]
        if not active:
            return 0
        self._step_no += 1
        key = jax.random.fold_in(self._base_key, self._step_no)
        nxt, self._caches = self._decode(self.params, self.tokens,
                                         self._caches, self.pos, self.temps,
                                         key)
        active_mask = np.zeros((self.max_batch,), np.int32)
        active_mask[active] = 1
        # only occupied slots advance — idle slots must not drift their
        # write position (their garbage scatters would eventually go OOB)
        self.pos = self.pos + jnp.asarray(active_mask)
        self.tokens = nxt
        nxt_host = np.asarray(nxt)
        pos_host = np.asarray(self.pos)
        for s in active:
            req = self._slots[s]
            tok = int(nxt_host[s])
            finished_last = (self.eos is not None and
                             req.generated[-1] == self.eos)
            if not finished_last:
                req.generated.append(tok)
            if (finished_last or len(req.generated) >= req.max_new_tokens
                    or int(pos_host[s]) >= self.max_len - 1):
                self._results[req.rid] = req.prompt + req.generated[
                    :req.max_new_tokens]
                self._slots[s] = None  # freed: refilled next tick
        return sum(sl is not None for sl in self._slots) + len(self._queue)

    def run(self) -> Dict[int, List[int]]:
        """Drain the queue; returns {rid: prompt+generated token ids}."""
        while self.step():
            pass
        out, self._results = self._results, {}
        return out
