"""Continuous-batching generation server — the TPU serving engine.

Ref capability: the reference serves models through AnalysisPredictor /
DistModel (inference/api/, fleet_executor/dist_model.cc) with request-level
batching. The TPU-native redesign follows modern LLM serving: a FIXED pool
of ``max_batch`` slots, each with its own KV-cache rows and position; ONE
compiled decode step advances every active slot per tick (static shapes —
compiled exactly once), and finished slots are freed and refilled mid-flight
so throughput is never quantized by batch boundaries (continuous batching).

Two KV-cache backends share the slot machinery (``cache=`` ctor arg):

- ``"dense"`` (the reference oracle): a ``2·L·(max_batch, max_len, KV, D)``
  slab, one cache row span per slot. Prefill runs per request at bucketed
  prompt lengths (one compile per bucket) and scatters into the slot.
- ``"paged"``: a shared pool of fixed-size blocks + per-slot block tables
  (ops/paged_attention.py, inference/paged_cache.py). HBM is proportional
  to ACTIVE tokens instead of ``max_batch · max_len``; prompts stream
  through ONE compiled fixed-chunk prefill program (chunked prefill — no
  per-bucket compile family, no head-of-line blocking: each server step
  advances one chunk per prefilling slot, then runs the decode tick for
  the slots already decoding); full prompt blocks are content-hashed and
  refcount-shared, so a repeated prefix (shared system prompt) prefills
  once (prefix caching). Greedy outputs are token-exact vs the dense
  server. See docs/serving.md.

The decode step uses the model's vector-position path (``pos [B]``): every
slot attends at its own depth. Sampling routes through
``models/generation.py`` (``sample_token_rows`` in the compiled tick,
``next_token`` for the prefill-produced first token) so per-request
``temperature``/``top_k``/``top_p`` match ``model.generate`` semantics.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..jit import functional_call, state_values


@dataclass
class _Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    generated: List[int] = field(default_factory=list)
    done: bool = False
    # paged-path state
    table: List[int] = field(default_factory=list)   # block ids, in order
    hashes: List[int] = field(default_factory=list)  # chain hash per full blk
    pf_next: int = 0                                 # next prefill position


class GenerationServer:
    """Continuous-batching decode server for a ``LlamaForCausalLM`` —
    greedy by default, per-request sampling via
    ``submit(..., temperature=, top_k=, top_p=)``.

    Usage::

        srv = GenerationServer(model, max_batch=4, max_len=256)
        rid = srv.submit([1, 5, 9], max_new_tokens=16)
        out = srv.run()          # drain all pending requests
        tokens = out[rid]        # prompt + generated ids
    """

    def __init__(self, model, max_batch: int = 4, max_len: int = 256,
                 prompt_buckets: Sequence[int] = (32, 64, 128),
                 eos_token_id: Optional[int] = None, seed: int = 0,
                 tick_window: int = 1, cache: str = "dense",
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 prefill_chunk: int = 32):
        """``tick_window``: decode ticks per host round trip. 1 = exact
        per-token semantics. k>1 runs k ticks as ONE compiled lax.scan
        before the host sees the tokens — eos detection and slot refill lag
        by up to k-1 tokens (the surplus is discarded), in exchange for
        amortizing the device→host sync: on a tunneled backend the
        round-trip dominates a decode tick by ~100×, and even on a local
        host it bounds tick-rate. The serving analogue of generate()'s
        fully-compiled scan loop.

        ``cache="paged"``: block-table KV pool. ``block_size`` tokens per
        block; ``num_blocks`` bounds total KV memory (default: dense
        parity, ``max_batch·ceil(max_len/block_size)+1``); prompts prefill
        in fixed ``prefill_chunk``-token chunks (rounded up to a block
        multiple). ``prompt_buckets`` is ignored on the paged path."""
        cfg = model.cfg
        assert max_len <= cfg.max_position_embeddings
        if cache not in ("dense", "paged"):
            raise ValueError(f"cache must be 'dense' or 'paged', got {cache!r}")
        self.model = model
        self.cfg = cfg
        self.cache_mode = cache
        self.max_batch = max_batch
        self.max_len = max_len
        self.eos = eos_token_id
        if tick_window < 1:
            raise ValueError(f"tick_window must be >= 1, got {tick_window}")
        self.tick_window = int(tick_window)
        self.params = state_values(model)

        from ..framework.dtype import convert_dtype

        kv = cfg.num_key_value_heads
        d = cfg.hidden_size // cfg.num_attention_heads
        cdtype = convert_dtype(cfg.dtype)
        # per-slot scalars live HOST-side (numpy): slot assignment would
        # otherwise cost one eager device dispatch per field per request —
        # each a full round trip on a tunneled backend
        self.pos = np.zeros((max_batch,), np.int32)
        self.tokens = np.zeros((max_batch,), np.int32)
        self.temps = np.zeros((max_batch,), np.float32)
        self.topks = np.zeros((max_batch,), np.int32)
        self.topps = np.zeros((max_batch,), np.float32)
        self._step_no = 0
        self._base_key = jax.random.PRNGKey(seed)
        self._slots: List[Optional[_Request]] = [None] * max_batch
        self._queue: deque = deque()
        self._results: Dict[int, List[int]] = {}
        self._next_rid = 0

        if cache == "dense":
            self.buckets = sorted(b for b in prompt_buckets if b <= max_len)
            if not self.buckets:
                raise ValueError(
                    f"no prompt bucket fits max_len={max_len} "
                    f"(prompt_buckets={tuple(prompt_buckets)})")
            self._caches = [jnp.zeros((max_batch, max_len, kv, d), cdtype)
                            for _ in range(2 * cfg.num_hidden_layers)]
            # donate the KV pool: XLA updates the caches in place instead of
            # copying 2·L·(max_batch, max_len, KV, D) every decoded token
            self._decode = jax.jit(self._decode_fn, donate_argnums=(2,))
            self._prefills: Dict[int, object] = {}  # bucket -> jitted fn
        else:
            from .paged_cache import BlockAllocator

            bs = int(block_size)
            if bs < 1:
                raise ValueError(f"block_size must be >= 1, got {block_size}")
            self.block_size = bs
            chunk = int(prefill_chunk)
            if chunk < 1:
                raise ValueError(
                    f"prefill_chunk must be >= 1, got {prefill_chunk}")
            self.prefill_chunk = -(-chunk // bs) * bs  # round up to blocks
            entries = -(-max_len // bs)  # ceil: real table entries per slot
            self._max_entries = entries
            # slack entries (always 0 = scratch) so the chunk's table
            # dynamic_slice never clamps and window-surplus decode writes
            # past max_len land in scratch instead of a live block
            self._table_width = entries + self.prefill_chunk // bs
            if num_blocks is None:
                num_blocks = max_batch * entries + 1  # dense parity + scratch
            self.alloc = BlockAllocator(int(num_blocks), bs)
            self._pools = [jnp.zeros((int(num_blocks), bs, kv, d), cdtype)
                           for _ in range(2 * cfg.num_hidden_layers)]
            self._bt = np.zeros((max_batch, self._table_width), np.int32)
            # True while the slot is streaming prompt chunks; None once the
            # slot decodes (or is empty)
            self._prefilling: List[Optional[bool]] = [None] * max_batch
            self._decode_paged = jax.jit(self._decode_paged_fn,
                                         donate_argnums=(2,))
            self._chunk_prefill = jax.jit(self._chunk_prefill_fn,
                                          donate_argnums=(2,))

    # ------------------------------------------------------------ compiled fns
    def _head(self, h):
        from ..framework.dispatch import apply_op

        if self.cfg.tie_word_embeddings:
            return apply_op(lambda v, w: jnp.matmul(v, w.T), h,
                            self.model.model.embed_tokens.weight)
        return self.model.lm_head(h)

    def _decode_fn(self, params, tokens, flat_caches, pos, temps, topks,
                   topps, active, key):
        """``tick_window`` ticks as one compiled region: each tick advances
        every slot by one token (per-slot sampling via
        ``generation.sample_token_rows``: temp == 0 → greedy argmax;
        temp > 0 → categorical with that row's top-k/top-p filter).
        ``active`` masks position advance so idle slots don't drift their
        cache write row. Returns the (k, B) token stack + final caches."""
        model = self.model

        def one_tick(carry, k):
            toks, flat_c, p = carry
            caches = [(Tensor(flat_c[2 * i]), Tensor(flat_c[2 * i + 1]))
                      for i in range(self.cfg.num_hidden_layers)]

            def call():
                h, new = model.model.decode_step(Tensor(toks[:, None]),
                                                 caches, p)
                return self._head(h), new

            logits, new = functional_call(model, params, call_fn=call)
            flat = []
            for ck, cv in new:
                flat += [ck.value, cv.value]
            lg = logits.value[:, 0].astype(jnp.float32)   # (B, V)
            from ..models.generation import sample_token_rows

            nxt = sample_token_rows(lg, jax.random.fold_in(key, k), temps,
                                    topks, topps)
            return (nxt, flat, p + active), nxt

        if self.tick_window == 1:
            (_, flat, _), stack = one_tick((tokens, flat_caches, pos), 0)
            return stack[None], flat
        (_, flat, _), stack = jax.lax.scan(
            one_tick, (tokens, flat_caches, pos),
            jnp.arange(self.tick_window))
        return stack, flat

    def _decode_paged_fn(self, params, tokens, flat_pools, tables, pos,
                         temps, topks, topps, active, key):
        """Paged twin of :meth:`_decode_fn`: K/V reads/writes go through
        per-slot block tables into the shared pool. ``tables``: int32
        (B, table_width) — the server zeroes rows of idle/prefilling slots
        so their masked ticks write only the scratch block."""
        model = self.model

        def one_tick(carry, k):
            toks, flat_p, p = carry
            pools = [(Tensor(flat_p[2 * i]), Tensor(flat_p[2 * i + 1]))
                     for i in range(self.cfg.num_hidden_layers)]

            def call():
                h, new = model.model.paged_decode_step(Tensor(toks[:, None]),
                                                       pools, tables, p)
                return self._head(h), new

            logits, new = functional_call(model, params, call_fn=call)
            flat = []
            for kp, vp in new:
                flat += [kp.value, vp.value]
            lg = logits.value[:, 0].astype(jnp.float32)   # (B, V)
            from ..models.generation import sample_token_rows

            nxt = sample_token_rows(lg, jax.random.fold_in(key, k), temps,
                                    topks, topps)
            return (nxt, flat, p + active), nxt

        if self.tick_window == 1:
            (_, flat, _), stack = one_tick((tokens, flat_pools, pos), 0)
            return stack[None], flat
        (_, flat, _), stack = jax.lax.scan(
            one_tick, (tokens, flat_pools, pos),
            jnp.arange(self.tick_window))
        return stack, flat

    def _chunk_prefill_fn(self, params, chunk, flat_pools, table, start,
                          last_idx):
        """ONE compiled program for every prefill chunk of every prompt
        length: chunk (1, C) right-padded; K/V scatter into the slot's
        block table at block-aligned ``start``; returns fp32 logits at
        local index ``last_idx`` (the last real prompt token on the final
        chunk; ignored on earlier chunks) + updated pools."""
        model = self.model
        pools = [(Tensor(flat_pools[2 * i]), Tensor(flat_pools[2 * i + 1]))
                 for i in range(self.cfg.num_hidden_layers)]

        def call():
            h, new = model.model.paged_prefill_chunk(Tensor(chunk), pools,
                                                     table, start)
            last = jax.lax.dynamic_slice_in_dim(h.value, last_idx, 1, 1)
            return self._head(Tensor(last)), new

        logits, new = functional_call(model, params, call_fn=call)
        flat = []
        for kp, vp in new:
            flat += [kp.value, vp.value]
        return logits.value[:, 0].astype(jnp.float32), flat

    def _prefill(self, bucket: int):
        """Dense-path prefill + slot scatter as ONE jitted call (donated
        pool): the per-layer eager `.at[slot].set` scatters cost 2·L
        dispatches per request otherwise — each a tunnel round trip."""
        if bucket not in self._prefills:
            model = self.model

            def fn(params, prompt, true_len, pool, slot):
                """prompt [1, bucket] right-padded; logits at true_len-1;
                the request's cache rows scatter into pool[slot]."""
                kvs = self.cfg.num_key_value_heads
                d = self.cfg.hidden_size // self.cfg.num_attention_heads
                from ..framework.dtype import convert_dtype

                cdtype = convert_dtype(self.cfg.dtype)
                caches = [(Tensor(jnp.zeros((1, self.max_len, kvs, d), cdtype)),
                           Tensor(jnp.zeros((1, self.max_len, kvs, d), cdtype)))
                          for _ in range(self.cfg.num_hidden_layers)]

                def call():
                    h, new = model.model.prefill(Tensor(prompt), caches)
                    last = jax.lax.dynamic_slice_in_dim(
                        h.value, true_len - 1, 1, 1)
                    return self._head(Tensor(last)), new

                logits, new = functional_call(model, params, call_fn=call)
                flat = []
                for ck, cv in new:
                    flat += [ck.value, cv.value]
                pool = [p.at[slot].set(row[0]) for p, row in zip(pool, flat)]
                return logits.value[:, 0].astype(jnp.float32), pool

            self._prefills[bucket] = jax.jit(fn, donate_argnums=(3,))
        return self._prefills[bucket]

    # --------------------------------------------------------------- requests
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 0.0) -> int:
        prompt = list(prompt)
        if not prompt:
            raise ValueError("prompt must contain at least one token id")
        for t in prompt:
            if isinstance(t, bool) or not isinstance(t, (int, np.integer)):
                raise ValueError(
                    f"prompt must be a sequence of int token ids, got "
                    f"{type(t).__name__}: {t!r}")
        prompt = [int(t) for t in prompt]
        if isinstance(max_new_tokens, bool) or \
                not isinstance(max_new_tokens, (int, np.integer)) or \
                max_new_tokens <= 0:
            raise ValueError(
                f"max_new_tokens must be a positive int, got "
                f"{max_new_tokens!r}")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_len={self.max_len}")
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if not 0.0 <= top_p <= 1.0:
            raise ValueError(f"top_p must be in [0, 1], got {top_p}")
        if self.cache_mode == "dense":
            self._bucket_for(len(prompt))  # validate against buckets up front
        rid = self._next_rid
        self._next_rid += 1
        self._queue.append(_Request(rid, prompt, int(max_new_tokens),
                                    temperature=float(temperature),
                                    top_k=int(top_k), top_p=float(top_p)))
        return rid

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt length {n} exceeds largest bucket "
                         f"{self.buckets[-1]}")

    def _first_token(self, req: _Request, lg) -> int:
        """Sample the first generated token from prefill logits (1, V) —
        same ``next_token`` as model.generate, so temperature/top_k/top_p
        semantics match; one host sync per assignment."""
        from ..models.generation import next_token

        key = jax.random.fold_in(self._base_key, (req.rid << 20) | 1)
        nxt, _ = next_token(lg, key, req.temperature, req.top_k, req.top_p)
        return int(nxt[0])

    def _activate_slot(self, slot: int, req: _Request, first: int) -> None:
        """Move a freshly-prefilled request into the decode phase."""
        self.pos[slot] = len(req.prompt)
        self.tokens[slot] = first
        self.temps[slot] = req.temperature
        self.topks[slot] = req.top_k
        self.topps[slot] = req.top_p
        req.generated.append(first)

    def _assign(self, slot: int, req: _Request) -> None:
        n = len(req.prompt)
        bucket = self._bucket_for(n)
        prompt = np.zeros((1, bucket), np.int32)
        prompt[0, :n] = req.prompt
        # one compiled call: prefill + scatter into the slot's pool rows.
        # Rows beyond the true prompt length hold right-pad garbage, but
        # decode writes sequentially from pos=n, overwriting each such row
        # BEFORE the attention mask (arange <= pos) can reach it.
        lg, self._caches = self._prefill(bucket)(
            self.params, jnp.asarray(prompt), n, self._caches, slot)
        self._activate_slot(slot, req, self._first_token(req, lg))
        self._slots[slot] = req

    def _fill_free_slots(self) -> None:
        for s in range(self.max_batch):
            if self._slots[s] is None and self._queue:
                req = self._queue.popleft()
                if self.cache_mode == "paged":
                    self._admit_paged(s, req)
                else:
                    self._assign(s, req)

    # ---------------------------------------------------------- paged path
    def _admit_paged(self, slot: int, req: _Request) -> None:
        """Claim a slot: reuse cached prefix blocks (prefix caching — the
        matched span skips prefill entirely) and start chunked prefill at
        the first uncached block boundary."""
        req.table = self.alloc.match_prefix(req.prompt)
        req.hashes = self.alloc.chain_hashes(req.prompt)
        req.pf_next = len(req.table) * self.block_size
        self._bt[slot, :] = 0
        self._bt[slot, :len(req.table)] = req.table
        self._prefilling[slot] = True
        self._slots[slot] = req

    def _ensure_blocks(self, slot: int, entries: int) -> None:
        """Grow the slot's block table to >= ``entries`` real entries
        (capped at ceil(max_len/block_size); writes past that land in
        scratch by construction)."""
        req = self._slots[slot]
        entries = min(entries, self._max_entries)
        while len(req.table) < entries:
            bid = self.alloc.alloc()
            req.table.append(bid)
            self._bt[slot, len(req.table) - 1] = bid

    def _prefill_chunk_step(self, slot: int) -> None:
        """Advance one prompt chunk for a prefilling slot; on the final
        chunk, sample the first token and flip the slot to decoding."""
        req = self._slots[slot]
        n = len(req.prompt)
        bs = self.block_size
        C = self.prefill_chunk
        start = req.pf_next
        end = min(start + C, n)
        self._ensure_blocks(slot, -(-end // bs))
        chunk = np.zeros((1, C), np.int32)
        chunk[0, :end - start] = req.prompt[start:end]
        last_idx = (n - 1 - start) if end == n else 0
        lg, self._pools = self._chunk_prefill(
            self.params, jnp.asarray(chunk), self._pools,
            jnp.asarray(self._bt[slot]), jnp.int32(start),
            jnp.int32(last_idx))
        # publish the prompt blocks this chunk completed for prefix reuse
        for i in range(start // bs, end // bs):
            self.alloc.register(req.table[i], req.hashes[i])
        req.pf_next = start + C
        if end == n:
            self._activate_slot(slot, req, self._first_token(req, lg))
            self._prefilling[slot] = None

    def _step_paged(self) -> int:
        self._fill_free_slots()
        # chunked prefill interleaves with decode: ONE chunk per prefilling
        # slot per step, so a long prompt never blocks slots mid-decode
        # (no head-of-line blocking) and short requests keep streaming out
        for s in range(self.max_batch):
            if self._slots[s] is not None and self._prefilling[s]:
                self._prefill_chunk_step(s)
        active = [s for s in range(self.max_batch)
                  if self._slots[s] is not None and not self._prefilling[s]]
        if active:
            self._step_no += 1
            key = jax.random.fold_in(self._base_key, self._step_no)
            k = self.tick_window
            for s in active:
                self._ensure_blocks(s, -(-(int(self.pos[s]) + k) //
                                         self.block_size))
            active_mask = np.zeros((self.max_batch,), np.int32)
            active_mask[active] = 1
            # idle/prefilling rows run masked: zeroed table + pos 0 routes
            # their (discarded) cache writes to the scratch block
            bt = np.where(active_mask[:, None] > 0, self._bt, 0)
            posv = self.pos * active_mask
            stack, self._pools = self._decode_paged(
                self.params, jnp.asarray(self.tokens), self._pools,
                jnp.asarray(bt), jnp.asarray(posv), jnp.asarray(self.temps),
                jnp.asarray(self.topks), jnp.asarray(self.topps),
                jnp.asarray(active_mask), key)
            self._harvest_window(np.asarray(stack), active, active_mask)
        return sum(sl is not None for sl in self._slots) + len(self._queue)

    def _release_slot(self, slot: int) -> None:
        req = self._slots[slot]
        self._slots[slot] = None
        if self.cache_mode == "paged":
            for bid in req.table:
                self.alloc.free(bid)
            req.table = []
            self._bt[slot, :] = 0
            self._prefilling[slot] = None
            self.pos[slot] = 0
            self.tokens[slot] = 0
            self.temps[slot] = 0.0
            self.topks[slot] = 0
            self.topps[slot] = 0.0

    def kv_stats(self) -> Dict[str, int]:
        """Paged-pool occupancy/prefix-cache counters (empty for dense)."""
        if self.cache_mode != "paged":
            return {}
        return self.alloc.stats()

    # ------------------------------------------------------------- stepping
    def _harvest_window(self, nxt_host, active, active_mask) -> None:
        """Fold one decode window's (k, B) token stack into the per-request
        state: append tokens, detect eos/max-new/max-len completion (window
        surplus past completion is discarded — tick_window semantics) and
        free finished slots for next window's refill."""
        k = nxt_host.shape[0]
        self.pos = self.pos + active_mask * k
        self.tokens = np.where(active_mask > 0, nxt_host[-1],
                               self.tokens).astype(np.int32)
        pos_after = self.pos
        for s in active:
            req = self._slots[s]
            done = False
            for t in range(k):
                tok = int(nxt_host[t, s])
                finished_last = (self.eos is not None and
                                 req.generated[-1] == self.eos)
                if not finished_last:
                    req.generated.append(tok)
                pos_t = int(pos_after[s]) - k + t + 1
                if (finished_last
                        or len(req.generated) >= req.max_new_tokens
                        or pos_t >= self.max_len - 1):
                    done = True
                    break
            if done:
                self._results[req.rid] = req.prompt + req.generated[
                    :req.max_new_tokens]
                self._release_slot(s)

    def step(self) -> int:
        """One server step: admit queued requests, advance one prefill
        chunk per prefilling slot (paged), then one decode window
        (``tick_window`` ticks) across decoding slots; returns #remaining
        (occupied slots + queued)."""
        if self.cache_mode == "paged":
            return self._step_paged()
        self._fill_free_slots()
        active = [s for s in range(self.max_batch)
                  if self._slots[s] is not None]
        if not active:
            return 0
        self._step_no += 1
        key = jax.random.fold_in(self._base_key, self._step_no)
        active_mask = np.zeros((self.max_batch,), np.int32)
        active_mask[active] = 1
        # only occupied slots advance — idle slots must not drift their
        # write position (their garbage scatters would eventually go OOB)
        stack, self._caches = self._decode(
            self.params, jnp.asarray(self.tokens), self._caches,
            jnp.asarray(self.pos), jnp.asarray(self.temps),
            jnp.asarray(self.topks), jnp.asarray(self.topps),
            jnp.asarray(active_mask), key)
        self._harvest_window(np.asarray(stack), active, active_mask)
        return sum(sl is not None for sl in self._slots) + len(self._queue)

    def run(self) -> Dict[int, List[int]]:
        """Drain the queue; returns {rid: prompt+generated token ids}."""
        while self.step():
            pass
        out, self._results = self._results, {}
        return out
