"""Serving telemetry — re-export shim.

The telemetry substrate (MetricsRegistry / SpanTracer / FlightRecorder /
watchdog + the ServingTelemetry facade) was promoted to the shared
top-level :mod:`paddle_tpu.telemetry` when the training tier
(TrainTelemetry, goodput accounting) started building on the same
primitives — the same promotion ``faults.py`` got when training gained
fault injection. Serving code keeps importing from here; everything is
re-exported unchanged.
"""
from ..telemetry import (DEFAULT_BUCKETS, NULL_FLIGHT,  # noqa: F401
                         NULL_TRACER, TRAIN_RID, Counter, FlightRecorder,
                         Gauge, GoodputLedger, Histogram, MetricsRegistry,
                         ServingTelemetry, SpanTracer, TrainTelemetry,
                         train_watchdog, watchdog)

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "SpanTracer", "FlightRecorder", "ServingTelemetry", "watchdog",
           "DEFAULT_BUCKETS", "NULL_TRACER", "NULL_FLIGHT"]
