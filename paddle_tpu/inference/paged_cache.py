"""Paged KV-cache block allocator — free list, refcounts, prefix caching.

Host-side bookkeeping for the paged serving path (the device pools live in
``GenerationServer``; ops in ``ops/paged_attention.py``). One block =
``block_size`` consecutive token positions of K/V across every layer.

Design (vLLM's block manager, trimmed to what the TPU server needs):

- **free list**: blocks are handed out one at a time; block id 0 is the
  reserved SCRATCH block — never allocated, it absorbs writes from idle /
  prefilling slot rows inside the compiled decode step so stale table
  entries can never corrupt a live block.
- **refcounts**: prompt-prefix blocks can be shared by many requests;
  a block returns to circulation only when its last user releases it.
- **prefix caching**: every FULL prompt block gets a chained content hash
  ``h_i = hash((h_{i-1}, tokens[i*bs:(i+1)*bs]))`` — chaining means a hit
  on block i implies blocks 0..i-1 matched too, so lookup is a simple
  walk. Released blocks that carry a hash are RETAINED on an LRU list
  instead of freed; a later request with the same prefix re-refs them and
  skips prefill for those tokens entirely (shared system prompts prefill
  once). Fresh allocation prefers truly-free blocks and only then evicts
  the coldest cached block.
- **last-token rule**: matching is capped at ``(n-1)//bs`` blocks so at
  least the final prompt token is always recomputed — its logits seed the
  first generated token (a full-cache hit would otherwise leave nothing
  to sample from).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Set, Tuple

SCRATCH_BLOCK = 0


class BlockAllocator:
    """Refcounted fixed-size KV block allocator with prefix caching."""

    def __init__(self, num_blocks: int, block_size: int,
                 kv_quant: str = "none", bytes_per_block: int = 0,
                 shards: int = 1):
        if num_blocks < 2:
            raise ValueError(f"num_blocks must be >= 2 (one scratch + one "
                             f"usable), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # TP serving shards the kv-head axis of every pool tensor, so each
        # of `shards` devices holds a 1/shards slice of EVERY block: one
        # allocator (one free list, one block table) spans all shards, and
        # bytes_per_block stays the FULL-width block footprint while
        # bytes_per_block_shard below is what each device actually pays
        self.shards = int(shards)
        # the quant mode seeds the hash chain: int8 and fp pools store
        # different bits for the same tokens, so their prefix blocks must
        # never alias even if allocator state ever crossed server instances
        self.kv_quant = kv_quant
        self.bytes_per_block = int(bytes_per_block)
        # LIFO free list over ids 1..N-1 (0 = scratch)
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._ref: Dict[int, int] = {}
        self._hash_of: Dict[int, int] = {}   # bid -> chain hash
        self._by_hash: Dict[int, int] = {}   # chain hash -> bid
        self._lru: "OrderedDict[int, None]" = OrderedDict()  # cached, ref==0
        # pinned blocks (live or cached) are frozen: never LRU-evicted —
        # the swap engine pins a victim's blocks for the device→host copy
        # so prefix reclaim can't recycle one mid-swap
        self._pinned: Set[int] = set()
        # stats
        self.peak_in_use = 0
        self.fresh_allocs = 0
        self.prefix_hit_blocks = 0
        self.prefix_lookup_blocks = 0
        self.evictions = 0
        # swap bookkeeping (inference/kv_offload.py drives these)
        self.swap_out_blocks = 0
        self.swap_in_blocks = 0
        self.host_bytes_in_use = 0
        self.host_bytes_peak = 0
        # tier bookkeeping: blocks demoted to the warm tier under LRU
        # pressure and promoted back on a cross-tier prefix hit
        # (inference/kv_offload.py drives both)
        self.demoted_blocks = 0
        self.promoted_blocks = 0
        # optional read-only membership probe into the warm tier
        # (chain_hash -> bool): KVOffloadEngine wires its WarmTier here so
        # probe_prefix — and through it the fleet router's prefix scoring —
        # sees warm-resident blocks without any side effect
        self.warm_probe = None
        # optional FaultInjector (inference/faults.py); the server wires
        # this so chaos plans can script pool exhaustion deterministically
        self.faults = None

    # ----------------------------------------------------------------- stats
    @property
    def blocks_in_use(self) -> int:
        """Blocks referenced by live requests (excludes cached + free)."""
        return len(self._ref)

    @property
    def blocks_cached(self) -> int:
        return len(self._lru)

    @property
    def blocks_free(self) -> int:
        return len(self._free)

    @property
    def pinned_blocks(self) -> int:
        return len(self._pinned)

    @property
    def evictable_cached(self) -> int:
        """Cached blocks eviction may actually reclaim (unpinned)."""
        return sum(1 for bid in self._lru if bid not in self._pinned)

    def ref_counts(self) -> Dict[int, int]:
        """Copy of the live refcount map (bid → refs) — the conservation
        checker (``GenerationServer.assert_conserved``) compares this
        against the multiset of block-table entries every chaos tick."""
        return dict(self._ref)

    def stats(self) -> Dict[str, int]:
        looked = self.prefix_lookup_blocks
        return {"num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "blocks_in_use": self.blocks_in_use,
                "blocks_cached": self.blocks_cached,
                "blocks_free": self.blocks_free,
                "peak_blocks_in_use": self.peak_in_use,
                "fresh_allocs": self.fresh_allocs,
                "prefix_hit_blocks": self.prefix_hit_blocks,
                "prefix_lookup_blocks": looked,
                "prefix_hit_rate":
                    (self.prefix_hit_blocks / looked) if looked else 0.0,
                "evictions": self.evictions,
                "kv_quant": self.kv_quant,
                "bytes_per_block": self.bytes_per_block,
                "bytes_in_use": self.bytes_per_block * self.blocks_in_use,
                "shards": self.shards,
                "bytes_per_block_shard": self.bytes_per_block // self.shards,
                "bytes_in_use_shard":
                    (self.bytes_per_block // self.shards)
                    * self.blocks_in_use,
                "pinned_blocks": self.pinned_blocks,
                "swap_out_blocks": self.swap_out_blocks,
                "swap_in_blocks": self.swap_in_blocks,
                "host_bytes_in_use": self.host_bytes_in_use,
                "host_bytes_peak": self.host_bytes_peak,
                "demoted_blocks": self.demoted_blocks,
                "promoted_blocks": self.promoted_blocks}

    def publish(self, registry) -> None:
        """Mirror :meth:`stats` into a
        :class:`~.telemetry.MetricsRegistry` as ``kv_pool_*`` gauges
        (numeric fields only) — called at snapshot time, never per tick."""
        for k, v in self.stats().items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                registry.gauge(f"kv_pool_{k}").set(float(v))

    # ------------------------------------------------------- swap bookkeeping
    def note_swap_out(self, nblocks: int, nbytes: int) -> None:
        """Record ``nblocks`` parked to host (``nbytes`` of host pool)."""
        self.swap_out_blocks += nblocks
        self.host_bytes_in_use += nbytes
        self.host_bytes_peak = max(self.host_bytes_peak,
                                   self.host_bytes_in_use)

    def note_swap_in(self, nblocks: int, nbytes: int) -> None:
        """Record ``nblocks`` restored from host (releasing ``nbytes``)."""
        self.swap_in_blocks += nblocks
        self.host_bytes_in_use -= nbytes

    def note_host_release(self, nbytes: int) -> None:
        """Record a parked copy discarded without restore (cancel)."""
        self.host_bytes_in_use -= nbytes

    def note_promote(self, nblocks: int) -> None:
        """Record ``nblocks`` promoted back from the warm tier."""
        self.promoted_blocks += nblocks

    def _note_use(self):
        self.peak_in_use = max(self.peak_in_use, self.blocks_in_use)

    # ------------------------------------------------------------ allocation
    def alloc(self) -> int:
        """Hand out one private block (ref=1, no hash). Prefers the free
        list; falls back to evicting the coldest cached prefix block."""
        if self.faults is not None and self.faults.fire("alloc") is not None:
            # same exception (and message shape) as a genuinely dry pool,
            # so injected exhaustion exercises the real preempt/stall path
            raise RuntimeError(
                f"paged KV pool exhausted (injected fault): all "
                f"{self.num_blocks - 1} usable blocks unavailable")
        bid = None
        if self._free:
            bid = self._free.pop()
        else:
            # oldest UNPINNED cached block; pinned ones are mid-swap (or
            # otherwise frozen) and must survive reclaim
            for cand in self._lru:
                if cand not in self._pinned:
                    bid = cand
                    break
            if bid is None:
                raise RuntimeError(
                    f"paged KV pool exhausted: all {self.num_blocks - 1} "
                    f"usable blocks are referenced by live requests or "
                    f"pinned — raise num_blocks or lower max_batch/max_len")
            del self._lru[bid]
            h = self._hash_of.pop(bid)
            self._by_hash.pop(h, None)
            self.evictions += 1
        self._ref[bid] = 1
        self.fresh_allocs += 1
        self._note_use()
        return bid

    def ref(self, bid: int) -> None:
        """Take an additional reference on a live or cached block."""
        if bid in self._ref:
            self._ref[bid] += 1
        elif bid in self._lru:
            del self._lru[bid]
            self._ref[bid] = 1
        else:
            raise KeyError(f"block {bid} is neither live nor cached")
        self._note_use()

    def free(self, bid: int) -> None:
        """Drop one reference; at zero the block is retained on the LRU
        list when it carries a prefix hash, else returned to the free
        list."""
        n = self._ref.get(bid)
        if n is None:
            raise KeyError(f"block {bid} is not live")
        if n > 1:
            self._ref[bid] = n - 1
            return
        del self._ref[bid]
        if bid in self._hash_of:
            self._lru[bid] = None
            self._lru.move_to_end(bid)
        else:
            self._free.append(bid)

    # --------------------------------------------------------------- pinning
    def pin(self, bid: int) -> None:
        """Freeze a live or cached block against LRU eviction. Refcounts
        are untouched — pinning is orthogonal to sharing, which is what
        keeps swap, prefix reclaim, and speculative rollback from fighting
        over the same counter. Idempotent."""
        if bid not in self._ref and bid not in self._lru:
            raise KeyError(f"block {bid} is neither live nor cached")
        self._pinned.add(bid)

    def unpin(self, bid: int) -> None:
        """Release a pin (idempotent; unknown bids are a no-op so teardown
        paths can unpin unconditionally)."""
        self._pinned.discard(bid)

    def coldest_cached(self, n: int) -> List[Tuple[int, int]]:
        """Up to ``n`` demotion candidates ``[(bid, chain_hash), ...]`` in
        LRU order (coldest first): cached ref==0 blocks that carry a
        prefix hash and are not pinned. Read-only — the tier driver
        copies them to host first and only then calls
        :meth:`evict_cached` on each."""
        out: List[Tuple[int, int]] = []
        for bid in self._lru:
            if len(out) >= n:
                break
            if bid in self._pinned:
                continue
            out.append((bid, self._hash_of[bid]))
        return out

    def evict_cached(self, bid: int) -> None:
        """Remove one cached (ref==0) block from the prefix cache and
        return it to the free list — the demotion commit. Counted as
        ``demoted_blocks``, NOT ``evictions``: the contents survive in
        the warm tier, they are not lost."""
        if bid not in self._lru:
            raise KeyError(f"block {bid} is not cached")
        if bid in self._pinned:
            raise KeyError(f"block {bid} is pinned — cannot demote")
        del self._lru[bid]
        h = self._hash_of.pop(bid)
        self._by_hash.pop(h, None)
        self._free.append(bid)
        self.demoted_blocks += 1

    def contains_hash(self, chain_hash: int) -> bool:
        """Read-only: is this chain hash hot-resident (live or cached)?"""
        return chain_hash in self._by_hash

    def ref_hash(self, chain_hash: int) -> Optional[int]:
        """Re-ref the hot-resident block carrying ``chain_hash`` and
        return its id, or None on a miss — the per-hash twin of
        :meth:`match_prefix` that the cross-tier walk interleaves with
        warm-tier promotion."""
        bid = self._by_hash.get(chain_hash)
        if bid is None:
            return None
        self.ref(bid)
        return bid

    def touch(self, bid: int) -> None:
        """Refresh a CACHED block's LRU position (most-recently-used) so
        eviction reaches it last. Live or unknown blocks are a no-op —
        callers use this to keep blocks with queued demand warm (the
        adapter pool replays WFQ order through it) without taking a ref."""
        if bid in self._lru:
            self._lru.move_to_end(bid)

    def truncate(self, table: List[int], n_tokens: int) -> List[int]:
        """Refcount-safely release the tail of ``table`` so it covers only
        ``n_tokens`` positions — the speculative ROLLBACK primitive: blocks
        reserved for drafted tokens that the verify step rejected go back
        through :meth:`free` (shared prefix blocks just drop a ref; hashed
        blocks land on the LRU). Returns the kept prefix of ``table``."""
        keep = -(-n_tokens // self.block_size)  # ceil; 0 tokens keeps none
        for bid in table[keep:]:
            self.free(bid)
        return list(table[:keep])

    # --------------------------------------------------------- prefix caching
    def chain_hashes(self, tokens: Sequence[int]) -> List[int]:
        """Chained content hash per FULL block of ``tokens``."""
        bs = self.block_size
        out: List[int] = []
        h = hash(("kv_quant", self.kv_quant))
        for i in range(len(tokens) // bs):
            h = hash((h, tuple(tokens[i * bs:(i + 1) * bs])))
            out.append(h)
        return out

    def match_prefix(self, tokens: Sequence[int]) -> List[int]:
        """Longest cached prefix of ``tokens`` as a list of block ids —
        each returned block is re-ref'd for the caller. Capped at
        ``(n-1)//bs`` blocks (last-token rule)."""
        n = len(tokens)
        limit = max((n - 1) // self.block_size, 0)
        hashes = self.chain_hashes(tokens)[:limit]
        out: List[int] = []
        for h in hashes:
            bid = self._by_hash.get(h)
            if bid is None:
                break
            self.ref(bid)
            out.append(bid)
        self.prefix_lookup_blocks += len(hashes)
        self.prefix_hit_blocks += len(out)
        return out

    def probe_prefix(self, tokens: Sequence[int],
                     hot_only: bool = False) -> int:
        """Read-only routing probe: how many leading full prompt blocks of
        ``tokens`` are currently resident, capped by the last-token rule
        like :meth:`match_prefix`. Takes NO references, triggers NO
        swap-ins, leaves the LRU order and every hit/lookup counter
        untouched — a fleet router scores many replicas per submission,
        and a probe that perturbed the cache would make routing
        observe-and-destroy. Hashes are chained lazily so a miss stops
        the walk early.

        "Resident" is tier-aware: hot (live or cached in HBM) OR warm
        (demoted to host, via the ``warm_probe`` membership hook) — a
        replica holding a prompt's prefix warm is still a far better
        routing target than one that must re-prefill it. ``hot_only``
        restricts the walk to HBM residency; the admission path uses it
        because warm hits still cost fresh device blocks to promote."""
        n = len(tokens)
        limit = max((n - 1) // self.block_size, 0)
        bs = self.block_size
        warm = None if hot_only else self.warm_probe
        h = hash(("kv_quant", self.kv_quant))
        hits = 0
        for i in range(limit):
            h = hash((h, tuple(tokens[i * bs:(i + 1) * bs])))
            if h not in self._by_hash and not (warm is not None
                                               and warm(h)):
                break
            hits += 1
        return hits

    def match_hashes(self, hashes: Sequence[int]) -> List[int]:
        """Longest still-resident prefix of an explicit chain-hash list,
        re-ref'd for the caller — the swap-in fast path: every hit is a
        block restored without an upload. Unlike :meth:`match_prefix`
        this takes hashes (a :class:`~.kv_offload.SwapHandle` carries
        them), not tokens, and doesn't touch the prefix-hit counters —
        resume reuse and prefill-skip reuse are different economics."""
        out: List[int] = []
        for h in hashes:
            bid = self._by_hash.get(h)
            if bid is None:
                break
            self.ref(bid)
            out.append(bid)
        return out

    def register(self, bid: int, chain_hash: int) -> None:
        """Publish a fully-prefilled prompt block under its chain hash so
        later requests can reuse it. First writer wins; a block already
        carrying a hash keeps it."""
        if chain_hash in self._by_hash or bid in self._hash_of:
            return
        if bid not in self._ref:
            raise KeyError(f"block {bid} is not live")
        self._by_hash[chain_hash] = bid
        self._hash_of[bid] = chain_hash
