"""Deterministic fault injection for the serving stack.

The substrate (``FaultInjector``/``FaultPlan``/``FaultSpec``, the
scripted-site contract, and the shared exception types) now lives in
:mod:`paddle_tpu.faults`, where the training stack
(``parallel/engine.py``, ``distributed/train_checkpoint.py``, the
elastic chaos harness) shares it. This module re-exports the serving
surface so every existing import path keeps working — the hook-site
table, the host-only contract (graftlint GL011), and the
fire-before-dispatch ordering rule are documented there.
"""
from __future__ import annotations

from ..faults import (NULL_INJECTOR, SITES, DataFeedFault,  # noqa: F401
                      EngineFailedError, FaultInjector, FaultPlan,
                      FaultSpec, SimulatedKill, StepFault, TickFault)

__all__ = [
    "SITES", "TickFault", "StepFault", "DataFeedFault", "SimulatedKill",
    "EngineFailedError", "FaultSpec", "FaultPlan", "FaultInjector",
    "NULL_INJECTOR",
]
