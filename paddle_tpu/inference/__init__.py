"""Inference deployment (ref: paddle/fluid/inference/ — AnalysisPredictor
api/analysis_predictor.cc:929 Run, AnalysisConfig, pass pipeline :1315).

TPU-native redesign: the IR-pass pipeline (ir_analysis_pass, memory-optimize,
TensorRT subgraphs) is XLA's job. What remains of the capability:
- Config: predictor configuration surface (API parity),
- Predictor: AOT-compiled callable (jax.jit lowered+compiled once at load),
- export/load via jax.export StableHLO serialization — the deployable
  artifact (the analogue of the serialized inference program + params).
"""
from __future__ import annotations

import os
import pickle
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..jit import functional_call, state_values


class Config:
    """AnalysisConfig parity (the GPU/TensorRT/MKLDNN knobs become no-ops —
    XLA owns those decisions on TPU)."""

    def __init__(self, model_dir: Optional[str] = None,
                 prog_file: Optional[str] = None, params_file: Optional[str] = None):
        self.model_dir = model_dir
        self.prog_file = prog_file
        self.params_file = params_file
        self._use_tpu = True
        self._memory_optim = True
        self._ir_optim = True

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        pass

    def disable_gpu(self):
        pass

    def enable_memory_optim(self):
        self._memory_optim = True

    def switch_ir_optim(self, x=True):
        self._ir_optim = x

    def enable_tensorrt_engine(self, *a, **k):
        raise NotImplementedError("TensorRT is CUDA-only; XLA compiles on TPU")

    def set_cpu_math_library_num_threads(self, n):
        pass


class Predictor:
    """AnalysisPredictor parity: compiled forward with named input/output
    handles (ref analysis_predictor.cc Run :929)."""

    def __init__(self, fn, params, input_names: Sequence[str],
                 example_inputs: Sequence[Any]):
        self._params = params
        self._input_names = list(input_names)
        self._inputs: Dict[str, Any] = {}
        self._outputs: List[Any] = []
        self._compiled = jax.jit(fn)
        # warm compile with example inputs
        if example_inputs:
            out = self._compiled(params, *example_inputs)
            jax.block_until_ready(out)

    @classmethod
    def from_layer(cls, layer, example_inputs: Sequence[Any],
                   input_names: Optional[Sequence[str]] = None):
        params = state_values(layer)
        layer.eval()

        def fn(params, *args):
            out = functional_call(layer, params, *[Tensor(a) for a in args])
            return jax.tree_util.tree_map(
                lambda t: t.value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))

        names = list(input_names) if input_names else \
            [f"input_{i}" for i in range(len(example_inputs))]
        ex = [a.value if isinstance(a, Tensor) else jnp.asarray(a)
              for a in example_inputs]
        return cls(fn, params, names, ex)

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name):
        pred = self

        class _Handle:
            def copy_from_cpu(self, arr):
                pred._inputs[name] = jnp.asarray(arr)

            def reshape(self, shape):
                pass

        return _Handle()

    def get_output_names(self):
        return [f"output_{i}" for i in range(len(self._outputs))]

    def get_output_handle(self, name):
        idx = int(name.split("_")[-1])
        pred = self

        class _Handle:
            def copy_to_cpu(self):
                return np.asarray(pred._outputs[idx])

        return _Handle()

    def run(self, inputs: Optional[Sequence[Any]] = None):
        if inputs is None:
            inputs = [self._inputs[n] for n in self._input_names]
        else:
            inputs = [i.value if isinstance(i, Tensor) else jnp.asarray(i)
                      for i in inputs]
        out = self._compiled(self._params, *inputs)
        self._outputs = list(out) if isinstance(out, (list, tuple)) else [out]
        return [np.asarray(o) for o in self._outputs]

    __call__ = run


def create_predictor(config_or_layer, example_inputs=None, **kw) -> Predictor:
    if isinstance(config_or_layer, Config):
        return load_predictor(config_or_layer.model_dir)
    return Predictor.from_layer(config_or_layer, example_inputs or [], **kw)


# --------------------------------------------------------------------------- #
# AOT export (StableHLO) — the deployable artifact
# --------------------------------------------------------------------------- #


def _unwrap_out(out):
    return jax.tree_util.tree_map(
        lambda t: t.value if isinstance(t, Tensor) else t, out,
        is_leaf=lambda t: isinstance(t, Tensor))


def _write_artifact(fn, params, example_inputs, path, meta_extra=None):
    """Trace fn(params, *inputs), serialize StableHLO + params + meta —
    the one artifact format load_predictor consumes."""
    from jax import export as jexport

    ex = [a.value if isinstance(a, Tensor) else jnp.asarray(a)
          for a in example_inputs]
    exported = jexport.export(jax.jit(fn))(
        jax.tree_util.tree_map(
            lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), params),
        *[jax.ShapeDtypeStruct(e.shape, e.dtype) for e in ex])
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "model.stablehlo"), "wb") as f:
        f.write(exported.serialize())
    with open(os.path.join(path, "params.pkl"), "wb") as f:
        pickle.dump(jax.tree_util.tree_map(np.asarray, params), f)
    with open(os.path.join(path, "meta.pkl"), "wb") as f:
        pickle.dump({"n_inputs": len(ex), **(meta_extra or {})}, f)
    return path


def export_model(layer, example_inputs: Sequence[Any], path: str):
    """Serialize weights + StableHLO of the jitted forward (ref: the saved
    inference program; jax.export replaces ProgramDesc+params files)."""
    layer.eval()
    params = state_values(layer)

    def fn(params, *args):
        return _unwrap_out(
            functional_call(layer, params, *[Tensor(a) for a in args]))

    return _write_artifact(fn, params, example_inputs, path)


def export_quantized_model(layer, example_inputs: Sequence[Any], path: str,
                           quantizable=None, skip_patterns=None):
    """Quantized-program export (the reference's int8 quantizer pipeline,
    ref inference/api/mkldnn_quantizer.cc, done the TPU way): serialized
    params are per-output-channel INT8 weights, and the traced StableHLO
    program dequantizes in-graph — int8 weights live in HBM (half the
    artifact/transfer of bf16, quarter of fp32) and XLA fuses the dequant
    into the consuming matmul (the weight-only int8 serving path that gives
    1.55x decode throughput, BASELINE.md). Loads with the same
    :func:`load_predictor`."""
    from jax import export as jexport

    from ..static.quantization import (channelwise_quant_int8,
                                       select_quantizable)

    layer.eval()
    params = state_values(layer)
    np_params = {n: np.asarray(v) for n, v in params.items()}
    # scope: >=2D floating parameters (not buffers), embedding-family names
    # excluded by default — mirror of quant_post_static's quantizable_op_type
    # contract; override with quantizable=/skip_patterns=
    to_quant = select_quantizable(
        np_params, quantizable=quantizable, skip_patterns=skip_patterns,
        param_names={n for n, _ in layer.named_parameters()})
    qparams: Dict[str, Any] = {}
    scales: Dict[str, Any] = {}
    for name, arr in np_params.items():
        if name in to_quant:
            q, sc, bshape = channelwise_quant_int8(
                arr.astype(np.float32) if arr.dtype != np.float32 else arr)
            qparams[name] = q
            scales[name] = (jnp.asarray(sc.reshape(bshape)), arr.dtype)
        else:
            qparams[name] = arr
    assert scales, (
        "no quantizable weights: every >=2D floating parameter was excluded "
        "by the default scope (embedding-family names and buffers are "
        "skipped) — pass quantizable=[names]/predicate or skip_patterns=() "
        "to widen it")

    def fn(qp, *args):
        deq = {}
        for name, v in qp.items():
            if name in scales:
                sc, dt = scales[name]  # scales are program constants
                deq[name] = (v.astype(jnp.float32) * sc).astype(dt)
            else:
                deq[name] = v
        return _unwrap_out(
            functional_call(layer, deq, *[Tensor(a) for a in args]))

    return _write_artifact(fn, qparams, example_inputs, path,
                           meta_extra={"quantized": "int8-weight-only"})


def load_predictor(path: str) -> Predictor:
    from jax import export as jexport

    with open(os.path.join(path, "model.stablehlo"), "rb") as f:
        exported = jexport.deserialize(f.read())
    with open(os.path.join(path, "params.pkl"), "rb") as f:
        params = pickle.load(f)
    with open(os.path.join(path, "meta.pkl"), "rb") as f:
        meta = pickle.load(f)

    def fn(params, *args):
        return exported.call(params, *args)

    names = [f"input_{i}" for i in range(meta["n_inputs"])]
    return Predictor(fn, params, names, [])


from .autoscale import (AutoscalePolicy, ElasticAutoscaler,  # noqa: E402,F401
                        FleetAutoscaler, ScaleDecision, verify_replay)
from .faults import (NULL_INJECTOR, EngineFailedError,  # noqa: E402,F401
                     FaultInjector, FaultPlan, FaultSpec, TickFault)
from .fleet import (REPLICA_DEAD, REPLICA_DEGRADED,  # noqa: E402,F401
                    REPLICA_DRAINING, REPLICA_LIVE, RID_STRIDE,
                    FleetRouter, ReplicaInfo)
from .kv_offload import (HostKVPool, KVOffloadEngine,  # noqa: E402,F401
                         SwapHandle, payload_checksum)
from .lora import (Adapter, AdapterPool, AdapterRegistry,  # noqa: E402,F401
                   LoRAConfig, adapter_page_bytes)
from .paged_cache import BlockAllocator  # noqa: E402,F401
from .scheduler import (PRIORITY_HIGH, PRIORITY_LOW,  # noqa: E402,F401
                        PRIORITY_NORMAL, AdmissionError, SchedEntry,
                        Scheduler)
from .serving import GenerationServer  # noqa: E402,F401
from .speculative import (DrafterFault, DraftModelDrafter,  # noqa: E402,F401
                          NgramDrafter, SpecConfig)
from .telemetry import (FlightRecorder, MetricsRegistry,  # noqa: E402,F401
                        ServingTelemetry, SpanTracer, watchdog)
from .transport import (InProcessReplica, RemoteReplicaError,  # noqa: E402,F401
                        ReplicaHandle, ReplicaTransportError,
                        SubprocessReplica)
