"""Replica worker: one GenerationServer behind a socketpair fd.

Spawned by :class:`~.transport.SubprocessReplica` as ``python -m
paddle_tpu.inference.replica_worker --fd N`` with one end of a
``socket.socketpair()`` passed as an inherited file descriptor — no
listener, no filesystem socket, no port to collide on. The protocol is
the frame codec from ``transport.py``:

1. the first frame is a hello carrying the build ``spec``; the worker
   constructs its model deterministically from ``(config kwargs, seed)``
   — identical weights to any peer built from the same spec, which is
   what makes cross-process fleets migration-homogeneous — and replies
   with the engine's snapshot fingerprint;
2. every subsequent frame names one allowlisted engine op (the
   router-facing surface, nothing else) and is answered by exactly one
   correlated reply; engine exceptions travel back as ``(type, msg)``
   and re-raise on the client side — the worker never dies on one;
3. every reply piggybacks the engine's step counter plus a monotone
   reply sequence number — the fleet heartbeat's freshness signal;
4. a ``shutdown`` op (or the parent closing its end) exits the loop.

The engine's time base is injectable like everywhere else:
``spec["server"]["clock"] = "counting"`` builds a
:class:`~.transport.CountingClock` so per-request latency metrics are
byte-deterministic across runs; the default leaves the server's own
default clock in place. The worker itself never sleeps and never reads
the wall clock (GL012/GL015).
"""
from __future__ import annotations

import argparse
import socket
import sys
from typing import Any, Dict, Optional

from .transport import (PASSTHROUGH_OPS, CountingClock,
                        ReplicaTransportError, recv_frame, send_frame)


def build_server(spec: Dict[str, Any]):
    """Construct the worker's engine from a build spec (see module
    docstring). Imports live here so the subprocess pays them once."""
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    from .serving import GenerationServer

    model_spec = dict(spec.get("model") or {})
    cfg = LlamaConfig(**dict(model_spec.get("config") or {}))
    paddle.seed(int(model_spec.get("seed", 0)))
    model = LlamaForCausalLM(cfg)

    server_kw = dict(spec.get("server") or {})
    clock = server_kw.pop("clock", None)
    if clock == "counting":
        server_kw["clock"] = CountingClock(
            float(server_kw.pop("clock_dt", 0.001)))
    elif clock is not None:
        raise ValueError(f"unknown worker clock {clock!r} — "
                         f"only 'counting' crosses the process boundary")
    return GenerationServer(model, **server_kw)


def _dispatch(server: Any, op: str, args: tuple, kw: Dict[str, Any]) -> Any:
    if op == "ping":
        return None
    if op == "steps":
        return server.steps
    if op == "telemetry_reset":
        return server.telemetry.reset(**kw)
    if op in PASSTHROUGH_OPS:
        return getattr(server, op)(*args, **kw)
    raise ValueError(f"unknown replica op {op!r}")


def serve(sock: socket.socket) -> int:
    """Run the hello + dispatch loop until shutdown or a dead peer."""
    seq = 0
    server = None

    def reply(mid: int, **body: Any) -> None:
        nonlocal seq
        seq += 1
        body.update(id=mid, seq=seq,
                    steps=(server.steps if server is not None else 0))
        send_frame(sock, body)

    try:
        hello = recv_frame(sock)
    except ReplicaTransportError:
        return 1
    if hello.get("op") != "__hello__":
        reply(hello.get("id", 0), ok=False,
              error={"type": "ValueError",
                     "msg": f"expected hello, got {hello.get('op')!r}"})
        return 1
    try:
        server = build_server(hello.get("spec") or {})
    except Exception as e:
        reply(hello.get("id", 0), ok=False,
              error={"type": type(e).__name__, "msg": str(e)})
        return 1
    reply(hello.get("id", 0), ok=True,
          value={"fingerprint": server._snapshot_fingerprint(),
                 "cache_mode": server.cache_mode,
                 "block_size": server.block_size,
                 "role": server.role})

    while True:
        try:
            msg = recv_frame(sock)
        except ReplicaTransportError:
            return 0          # parent went away — nothing left to serve
        mid = msg.get("id", -1)
        op = msg.get("op", "")
        if op == "shutdown":
            try:
                reply(mid, ok=True, value=None)
            except ReplicaTransportError:
                pass
            return 0
        try:
            value = _dispatch(server, op,
                              tuple(msg.get("args") or ()),
                              dict(msg.get("kw") or {}))
        except Exception as e:
            try:
                reply(mid, ok=False,
                      error={"type": type(e).__name__, "msg": str(e)})
            except ReplicaTransportError:
                return 1
            continue
        try:
            reply(mid, ok=True, value=value)
        except ReplicaTransportError:
            return 1


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--fd", type=int, required=True,
                    help="inherited socketpair fd connected to the "
                         "SubprocessReplica handle")
    args = ap.parse_args(argv)
    sock = socket.socket(fileno=args.fd)
    try:
        return serve(sock)
    finally:
        try:
            sock.close()
        except OSError:
            pass


if __name__ == "__main__":
    sys.exit(main())
