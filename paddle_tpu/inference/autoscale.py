"""SLO-driven elastic autoscaling for the replica fleet.

Two halves, deliberately separated:

- :class:`ElasticAutoscaler` is the *pure decision engine*: given one
  observation row — live replica count, observed token demand, the cost
  model's forecast of demand ahead of the diurnal curve, and the worst
  per-tenant SLO burn rate from the PR 13 roll-up — it returns a
  :class:`ScaleDecision`. It holds no clock and draws no randomness, so
  the same observations always produce the same decisions (the
  determinism contract the traffic simulator's byte-identical runs lean
  on), and every decision is journaled with its full input row so a
  recorded run can be *replayed* and audited (:func:`verify_replay`).

- :class:`FleetAutoscaler` binds those decisions to a live
  :class:`~.fleet.FleetRouter`: scale-up calls a caller-supplied
  ``spawn()`` factory and :meth:`~.fleet.FleetRouter.add_replica`;
  scale-down picks a victim (degraded first, then least loaded, newest
  first) and rides the router's token-exact
  :meth:`~.fleet.FleetRouter.drain` — the same snapshot/swap-in path
  every other migration uses, so elasticity never invents a new
  correctness path.

Sizing logic: desired capacity covers ``max(observed demand, forecast)``
with each replica loaded to at most ``target_utilization`` of the cost
model's predicted per-replica capacity
(:meth:`~paddle_tpu.autotune.cost.ServingCostModel.capacity_tok_s`).
That makes the *forecast* the proactive half — capacity arrives before
the diurnal peak does — while a burn rate above ``burn_up`` forces a
reactive scale-up even when the model disagrees (the model is a sizing
device, the SLO is the contract). Scale-down is deliberately timid:
blocked while any tenant still burns above ``burn_down``, rate-limited
by ``down_cooldown_s``, one replica per decision, and it refuses to
drain the last live replica no matter what the arithmetic says.

All decisions land in telemetry as ``fleet_autoscale_*`` counters and
gauges.
"""
from __future__ import annotations

import math
from dataclasses import asdict, dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = [
    "AutoscalePolicy", "ElasticAutoscaler", "FleetAutoscaler",
    "ScaleDecision", "verify_replay",
]


@dataclass(frozen=True)
class AutoscalePolicy:
    """Knobs for the decision engine. Defaults suit a diurnal day-scale
    sim; real deployments tune them like any other SLO parameter."""

    #: hard floor/ceiling on live replicas — the floor is also the
    #: "never drain the last replica" guarantee (min 1 enforced)
    min_replicas: int = 1
    max_replicas: int = 8
    #: plan each replica to at most this fraction of predicted capacity
    #: — the headroom that absorbs forecast error and burst
    target_utilization: float = 0.75
    #: any tenant burning above this forces a reactive scale-up
    burn_up: float = 1.0
    #: scale-down is blocked while any tenant burns above this
    burn_down: float = 0.25
    #: seconds between consecutive scale-ups / scale-downs
    up_cooldown_s: float = 60.0
    down_cooldown_s: float = 600.0
    #: most replicas added per decision (downs are always one at a time)
    max_step_up: int = 2

    def __post_init__(self) -> None:
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1 — a fleet with "
                             "zero replicas can serve nothing")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) < min_replicas "
                f"({self.min_replicas})")
        if not 0.0 < self.target_utilization <= 1.0:
            raise ValueError(
                f"target_utilization must be in (0, 1], got "
                f"{self.target_utilization!r}")


@dataclass(frozen=True)
class ScaleDecision:
    """One journaled decision: the full observation row plus the
    outcome, so a trace replays bit-identically (:func:`verify_replay`)."""

    t: float
    action: str                 # "up" | "down" | "hold"
    count: int                  # replicas added/removed (0 on hold)
    desired: int                # post-clamp desired replica count
    live: int                   # live replicas when observed
    demand_tok_s: float
    forecast_tok_s: float
    burn_rate: float
    reason: str

    def as_dict(self) -> Dict[str, Any]:
        return asdict(self)


class ElasticAutoscaler:
    """Pure, clock-free, journaling decision engine (see module doc)."""

    def __init__(self, capacity_tok_s: float, *,
                 policy: Optional[AutoscalePolicy] = None,
                 registry=None):
        if capacity_tok_s <= 0:
            raise ValueError(
                f"capacity_tok_s must be > 0, got {capacity_tok_s!r}")
        self.capacity_tok_s = float(capacity_tok_s)
        self.policy = policy or AutoscalePolicy()
        self.events: List[ScaleDecision] = []
        self._last_up_t: Optional[float] = None
        self._last_down_t: Optional[float] = None
        if registry is None:
            from .telemetry import MetricsRegistry

            registry = MetricsRegistry()
        self.registry = registry
        self._c_decisions = registry.counter(
            "fleet_autoscale_decisions",
            "autoscaler control decisions (action label)")
        self._c_blocked = registry.counter(
            "fleet_autoscale_blocked",
            "desired!=live decisions held back (reason label: "
            "cooldown/burn_gate/last_replica/ceiling)")
        self._g_desired = registry.gauge(
            "fleet_autoscale_desired_replicas",
            "replica count the sizing arithmetic wants")
        self._g_live = registry.gauge(
            "fleet_autoscale_live_replicas",
            "live replicas at the last decision")
        self._g_demand = registry.gauge(
            "fleet_autoscale_demand_tok_s",
            "observed token demand at the last decision")
        self._g_forecast = registry.gauge(
            "fleet_autoscale_forecast_tok_s",
            "cost-model demand forecast at the last decision")
        self._g_burn = registry.gauge(
            "fleet_autoscale_burn_rate",
            "worst per-tenant SLO burn rate at the last decision")

    # ------------------------------------------------------------ decisions
    def _raw_want(self, demand_tok_s: float,
                  forecast_tok_s: float) -> int:
        """Unclamped sizing: replicas to cover the larger of observed
        demand and forecast at ``target_utilization``. Zero planning
        load wants zero replicas — the [min, max] clamp (and the
        last-replica refusal in :meth:`decide`) is policy, and keeping
        it OUT of the arithmetic is what lets the decision journal
        distinguish "held at the floor" from "sized to the floor"."""
        p = self.policy
        planning = max(float(demand_tok_s), float(forecast_tok_s), 0.0)
        cap = self.capacity_tok_s * p.target_utilization
        return int(math.ceil(planning / cap)) if planning > 0 else 0

    def desired_replicas(self, demand_tok_s: float,
                         forecast_tok_s: float = 0.0) -> int:
        """Pure sizing arithmetic: replicas to cover the larger of
        observed demand and forecast at ``target_utilization``, clamped
        to the policy's [min, max]."""
        p = self.policy
        want = self._raw_want(demand_tok_s, forecast_tok_s)
        return max(p.min_replicas, min(p.max_replicas, want))

    def decide(self, now: float, *, live: int, demand_tok_s: float,
               forecast_tok_s: float = 0.0,
               burn_rate: float = 0.0) -> ScaleDecision:
        """One control decision from one observation row. ``now`` is
        the CALLER's clock (virtual in the simulator, the router's
        injected clock in a live fleet) — the engine never reads time
        itself."""
        p = self.policy
        live = int(live)
        want = self._raw_want(demand_tok_s, forecast_tok_s)
        desired = max(p.min_replicas, min(p.max_replicas, want))
        reason = ("forecast" if forecast_tok_s > demand_tok_s
                  else "demand")
        if burn_rate > p.burn_up and desired <= live:
            # the SLO is the contract: budget burning faster than the
            # model predicted means the model is wrong, not the tenants
            desired = min(p.max_replicas, live + 1)
            reason = "burn_rate"

        action, count = "hold", 0
        if desired > live or want > live >= p.max_replicas:
            # second disjunct: the arithmetic wants MORE than the
            # ceiling allows while the fleet already sits at it — the
            # clamp hides that from `desired`, but pinned-at-ceiling is
            # an auditable decision (capacity is being refused), not
            # steady state
            if live >= p.max_replicas:
                reason = "ceiling"
                self._c_blocked.inc(reason="ceiling")
            elif (self._last_up_t is not None
                    and now - self._last_up_t < p.up_cooldown_s):
                reason = "up_cooldown"
                self._c_blocked.inc(reason="cooldown")
            else:
                action = "up"
                count = min(desired - live, p.max_step_up,
                            p.max_replicas - live)
                self._last_up_t = now
        elif desired < live or want < live <= max(1, p.min_replicas):
            # the second disjunct is the arithmetic *wanting* to go
            # below the floor (want < min <= live): the clamp hides it
            # from `desired`, but the refusal must still be journaled —
            # "held at the floor" is an auditable decision, not silence
            if live <= max(1, p.min_replicas):
                # never drain the last live replica — even a policy
                # misconfiguration must not scale the fleet to zero
                reason = "last_replica"
                self._c_blocked.inc(reason="last_replica")
            elif burn_rate > p.burn_down:
                reason = "burn_gate"
                self._c_blocked.inc(reason="burn_gate")
            elif (self._last_down_t is not None
                  and now - self._last_down_t < p.down_cooldown_s):
                reason = "down_cooldown"
                self._c_blocked.inc(reason="cooldown")
            else:
                action, count = "down", 1
                self._last_down_t = now
        else:
            reason = "steady"

        d = ScaleDecision(t=float(now), action=action, count=count,
                          desired=desired, live=live,
                          demand_tok_s=float(demand_tok_s),
                          forecast_tok_s=float(forecast_tok_s),
                          burn_rate=float(burn_rate), reason=reason)
        self.events.append(d)
        self._c_decisions.inc(action=action)
        self._g_desired.set(float(desired))
        self._g_live.set(float(live))
        self._g_demand.set(float(demand_tok_s))
        self._g_forecast.set(float(forecast_tok_s))
        self._g_burn.set(float(burn_rate))
        return d


def verify_replay(events: Sequence[Dict[str, Any]],
                  capacity_tok_s: float, *,
                  policy: Optional[AutoscalePolicy] = None) -> bool:
    """Re-run every journaled observation row through a FRESH engine and
    check it reproduces the recorded decisions exactly — the audit that
    a sim trace's ``autoscale_events`` really are a replayable record
    (determinism contract) rather than a log of accidents. Raises
    ``AssertionError`` naming the first diverging event."""
    engine = ElasticAutoscaler(capacity_tok_s, policy=policy)
    for i, ev in enumerate(events):
        d = engine.decide(ev["t"], live=ev["live"],
                          demand_tok_s=ev["demand_tok_s"],
                          forecast_tok_s=ev["forecast_tok_s"],
                          burn_rate=ev["burn_rate"])
        got = d.as_dict()
        for k in ("action", "count", "desired", "reason"):
            if got[k] != ev[k]:
                raise AssertionError(
                    f"autoscale replay diverged at event {i}: "
                    f"{k}={got[k]!r}, recorded {ev[k]!r}")
    return True


class FleetAutoscaler:
    """Bind an :class:`ElasticAutoscaler` to a live
    :class:`~.fleet.FleetRouter`: each :meth:`control` call turns one
    decision into real spawns (``spawn()`` factory + ``add_replica``)
    or one token-exact ``drain``."""

    def __init__(self, fleet: Any, engine: ElasticAutoscaler,
                 spawn: Callable[[], Any]):
        self.fleet = fleet
        self.engine = engine
        self.spawn = spawn
        #: (decision, [replica indices added/drained]) pairs, in order
        self.applied: List[Any] = []

    def worst_burn_rate(self) -> float:
        """Max burn rate across tenants and both objectives, from the
        router's PR 13 SLO roll-up."""
        worst = 0.0
        for row in self.fleet.slo_rollup().values():
            for key in ("ttft", "tpot"):
                worst = max(worst, float(row[key]["burn_rate"]))
        return worst

    def _drain_victim(self) -> int:
        """Degraded first (shed flaky capacity), then least loaded,
        then newest — replica 0 retires last."""
        from .fleet import REPLICA_DEGRADED

        def score(idx: int):
            rep = self.fleet._replicas[idx]
            lm = rep.server.load_metrics()
            return (0 if rep.state == REPLICA_DEGRADED else 1,
                    lm["queue_depth"] + lm["slots_occupied"], -idx)

        return min(self.fleet.live_indices(), key=score)

    def control(self, now: float, *, demand_tok_s: float,
                forecast_tok_s: float = 0.0):
        """One control-loop tick: observe, decide, apply. Returns the
        :class:`ScaleDecision` (with replicas spawned/drained recorded
        in :attr:`applied`)."""
        live = len(self.fleet.live_indices())
        d = self.engine.decide(now, live=live,
                               demand_tok_s=demand_tok_s,
                               forecast_tok_s=forecast_tok_s,
                               burn_rate=self.worst_burn_rate())
        touched: List[int] = []
        if d.action == "up":
            for _ in range(d.count):
                touched.append(self.fleet.add_replica(self.spawn()))
        elif d.action == "down":
            victim = self._drain_victim()
            self.fleet.drain(victim)
            touched.append(victim)
        self.applied.append((d, touched))
        return d
