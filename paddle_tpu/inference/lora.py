"""Multi-tenant LoRA serving: adapter registry + paged adapter-weight pool.

Hundreds of per-customer fine-tuned adapters served over ONE base model.
The device footprint is bounded by a fixed page count, not the adapter
population:

- :class:`AdapterRegistry` is the host tier — every registered adapter
  keeps its f32 A/B factors in host memory (numpy), so "offload" for a
  cold adapter is simply dropping its device page; re-activation is an
  upload, never a recompute.
- :class:`AdapterPool` is the device tier — ``max_live_adapters`` fixed-
  size pages inside per-target stacked tensors, padded to a static
  ``max_rank``. Page lifecycle (refcounts, LRU retention of released
  pages, pin/unpin, eviction of the coldest unpinned page) reuses
  :class:`~.paged_cache.BlockAllocator` verbatim — an adapter page is a
  block of rank-padded factors instead of a block of K/V. Page 0 is the
  permanently-zero NULL adapter (the analogue of the KV scratch block):
  rows without an adapter gather page 0 and get an exact zero delta, so
  the decode program needs no branching on "has adapter".

The batched heterogeneous-adapter delta (BGMV style): every compiled
serving program takes the flat pool tensors plus a per-row int32 page
index; :meth:`AdapterPool.gather_rows` gathers per-row A/B factors and
``nn.lora.bgmv`` applies ``y += (x @ A) @ B * (alpha/r)`` as two skinny
f32 matmuls. All shapes are static — registering, evicting, or swapping
adapters only changes pool *values* (functional ``.at[page].set``
uploads), so adapter churn causes zero steady-state recompiles.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .paged_cache import BlockAllocator

# (layer, target) addressing: the seven Llama projection sites. Order is
# load-bearing — it fixes the flat pool tensor layout.
LORA_TARGETS = ("q", "k", "v", "o", "gate", "up", "down")

NULL_PAGE = 0

# module-path suffix -> short target key (parses nn.lora export dicts)
_PATH_TARGETS = {"q_proj": "q", "k_proj": "k", "v_proj": "v", "o_proj": "o",
                 "gate_proj": "gate", "up_proj": "up", "down_proj": "down"}


def target_dims(cfg) -> Dict[str, Tuple[int, int]]:
    """(in, out) dims per target for a Llama-family config."""
    h = cfg.hidden_size
    hd = h // cfg.num_attention_heads
    q_out = cfg.num_attention_heads * hd
    kv_out = cfg.num_key_value_heads * hd
    return {"q": (h, q_out), "k": (h, kv_out), "v": (h, kv_out),
            "o": (q_out, h),
            "gate": (h, cfg.intermediate_size),
            "up": (h, cfg.intermediate_size),
            "down": (cfg.intermediate_size, h)}


def adapter_page_bytes(cfg, max_rank: int,
                       targets: Sequence[str] = LORA_TARGETS) -> int:
    """f32 bytes of ONE rank-padded adapter page across all layers/targets
    (the adapter analogue of ``serving.kv_block_bytes``)."""
    dims = target_dims(cfg)
    L = cfg.num_hidden_layers
    n = 0
    for t in targets:
        i, o = dims[t]
        n += L * (i * max_rank + max_rank * o)
    return 4 * n + 4  # + the page's scale slot


@dataclasses.dataclass
class Adapter:
    """One registered adapter: host-resident f32 factors keyed by
    (layer_idx, target). ``uid`` is unique per registration so a pool can
    tell a re-registered name from a warm cached page."""
    name: str
    rank: int
    alpha: float
    weights: Dict[Tuple[int, str], Tuple[np.ndarray, np.ndarray]]
    uid: int = 0

    @property
    def scale(self) -> float:
        return self.alpha / self.rank

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes + b.nbytes for a, b in self.weights.values())


def _parse_path_key(key: str) -> Optional[Tuple[int, str]]:
    """'model.layers.3.self_attn.q_proj' -> (3, 'q'); None if unparseable."""
    parts = key.split(".")
    tname = _PATH_TARGETS.get(parts[-1])
    if tname is None:
        return None
    for i, p in enumerate(parts):
        if p == "layers" and i + 1 < len(parts) and parts[i + 1].isdigit():
            return int(parts[i + 1]), tname
    return None


class AdapterRegistry:
    """Host-side adapter store (the cold/offload tier). Registration
    normalizes factors to f32 numpy keyed by (layer_idx, target); the
    factors stay resident for the adapter's lifetime so an evicted device
    page can always be re-uploaded."""

    def __init__(self):
        self._adapters: Dict[str, Adapter] = {}
        self._next_uid = 1

    def register(self, name: str, weights: Dict, rank: Optional[int] = None,
                 alpha: Optional[float] = None) -> Adapter:
        """``weights``: either {(layer_idx, target): (A, B)} with short
        target keys from :data:`LORA_TARGETS`, or an ``nn.lora`` export
        dict keyed by module path (its ``__meta__`` supplies rank/alpha)."""
        if name in self._adapters:
            raise ValueError(f"adapter {name!r} already registered")
        norm: Dict[Tuple[int, str], Tuple[np.ndarray, np.ndarray]] = {}
        meta = weights.get("__meta__") if isinstance(weights, dict) else None
        for key, ab in weights.items():
            if key == "__meta__":
                continue
            if isinstance(key, str):
                parsed = _parse_path_key(key)
                if parsed is None:
                    raise ValueError(
                        f"adapter {name!r}: unrecognized module path {key!r}")
                lk = parsed
                a, b = ab["A"], ab["B"]
            else:
                lk = (int(key[0]), str(key[1]))
                a, b = ab
            norm[lk] = (np.asarray(a, dtype=np.float32),
                        np.asarray(b, dtype=np.float32))
        if not norm:
            raise ValueError(f"adapter {name!r} has no weights")
        if meta is not None:
            rank = rank if rank is not None else int(meta["rank"])
            alpha = alpha if alpha is not None else float(meta["alpha"])
        ranks = {a.shape[1] for a, _ in norm.values()}
        if rank is None:
            if len(ranks) != 1:
                raise ValueError(f"adapter {name!r}: mixed ranks {ranks} "
                                 f"need an explicit rank=")
            rank = ranks.pop()
        for (l, t), (a, b) in norm.items():
            if a.shape[1] != rank or b.shape[0] != rank:
                raise ValueError(
                    f"adapter {name!r} ({l}, {t}): factor rank "
                    f"{a.shape[1]}/{b.shape[0]} != declared rank {rank}")
        ad = Adapter(name=name, rank=int(rank),
                     alpha=float(alpha if alpha is not None else rank),
                     weights=norm, uid=self._next_uid)
        self._next_uid += 1
        self._adapters[name] = ad
        return ad

    def unregister(self, name: str) -> None:
        del self._adapters[name]

    def get(self, name: str) -> Adapter:
        return self._adapters[name]

    def __contains__(self, name: str) -> bool:
        return name in self._adapters

    def __len__(self) -> int:
        return len(self._adapters)

    def names(self) -> List[str]:
        return list(self._adapters)

    @property
    def host_bytes(self) -> int:
        return sum(a.nbytes for a in self._adapters.values())


@dataclasses.dataclass
class LoRAConfig:
    """Serving-side pool shape — fixed at server construction so every
    compiled program's adapter arguments are static."""
    registry: AdapterRegistry
    max_live_adapters: int = 8
    max_rank: int = 8
    targets: Tuple[str, ...] = LORA_TARGETS

    def validate(self):
        if self.max_live_adapters < 1:
            raise ValueError(f"max_live_adapters must be >= 1, got "
                             f"{self.max_live_adapters}")
        if self.max_rank < 1:
            raise ValueError(f"max_rank must be >= 1, got {self.max_rank}")
        bad = [t for t in self.targets if t not in LORA_TARGETS]
        if bad:
            raise ValueError(f"unknown LoRA targets {bad}; "
                             f"valid: {LORA_TARGETS}")


class AdapterPool:
    """Device-resident paged pool of rank-padded adapter factors.

    Layout: per target ``t`` two stacked tensors ``A_t`` of shape
    (pages, L, in_t, max_rank) and ``B_t`` (pages, L, max_rank, out_t),
    plus one (pages,) f32 scale vector with alpha/r pre-baked — flat list
    ``[A_t0, B_t0, A_t1, B_t1, ..., scale]`` handed to the compiled
    programs. ``pages = max_live_adapters + 1``; page 0 is the null
    adapter (all-zero factors, scale 0).

    Residency reuses :class:`BlockAllocator` over page ids: acquire()
    refs a resident page or allocates one (evicting the coldest unpinned
    released page) and uploads from the registry; release() drops the ref
    but RETAINS the page on the LRU so the next request for the same
    adapter is a hit, not an upload. True ranks < max_rank upload into
    zero-padded columns, which keeps the batched delta exact per adapter.
    """

    def __init__(self, model_cfg, cfg: LoRAConfig):
        cfg.validate()
        self.registry = cfg.registry
        self.max_live_adapters = cfg.max_live_adapters
        self.max_rank = cfg.max_rank
        self.targets = tuple(cfg.targets)
        self.num_layers = model_cfg.num_hidden_layers
        self._dims = target_dims(model_cfg)
        self.page_bytes = adapter_page_bytes(model_cfg, self.max_rank,
                                             self.targets)
        pages = self.max_live_adapters + 1
        self.alloc = BlockAllocator(pages, 1, kv_quant="none",
                                    bytes_per_block=self.page_bytes)
        L, R = self.num_layers, self.max_rank
        flat = []
        for t in self.targets:
            i, o = self._dims[t]
            flat.append(jnp.zeros((pages, L, i, R), jnp.float32))
            flat.append(jnp.zeros((pages, L, R, o), jnp.float32))
        flat.append(jnp.zeros((pages,), jnp.float32))
        self._flat = flat
        self._resident: Dict[str, int] = {}    # name -> page (live or cached)
        self._page_name: Dict[int, str] = {}
        self._page_uid: Dict[int, int] = {}    # page -> registration uid
        self._validated: Dict[str, int] = {}   # name -> validated uid
        # stats
        self.hits = 0
        self.uploads = 0
        # optional ServingTelemetry (inference/telemetry.py), set by the
        # owning server: adapter uploads then feed the
        # serving_lora_upload_s histogram
        self.telemetry = None

    # ------------------------------------------------------------- validation
    def validate(self, name: str) -> Adapter:
        """Submit-time feasibility gate: the adapter must exist, fit the
        pool's rank budget, and its factors must match the model's
        projection shapes. Raises ValueError with an actionable message."""
        try:
            ad = self.registry.get(name)
        except KeyError:
            raise ValueError(f"unknown adapter {name!r} — register it "
                             f"before submit") from None
        if self._validated.get(name) == ad.uid:
            return ad
        if ad.rank > self.max_rank:
            raise ValueError(
                f"adapter {name!r} rank {ad.rank} exceeds the pool's "
                f"max_rank {self.max_rank} — it cannot fit an adapter page")
        for (l, t), (a, b) in ad.weights.items():
            if t not in self.targets:
                raise ValueError(f"adapter {name!r} targets {t!r} which this "
                                 f"pool does not serve ({self.targets})")
            if l < 0 or l >= self.num_layers:
                raise ValueError(f"adapter {name!r} addresses layer {l} of a "
                                 f"{self.num_layers}-layer model")
            i, o = self._dims[t]
            if a.shape != (i, ad.rank) or b.shape != (ad.rank, o):
                raise ValueError(
                    f"adapter {name!r} ({l}, {t}): factor shapes "
                    f"{a.shape}/{b.shape} do not match model dims "
                    f"({i}, r)/(r, {o})")
        self._validated[name] = ad.uid
        return ad

    # -------------------------------------------------------------- residency
    def is_resident(self, name: str) -> bool:
        return name in self._resident

    def can_acquire(self, name: str) -> bool:
        """Admission headroom check — True when acquire() cannot fail."""
        try:
            ad = self.registry.get(name)
        except KeyError:
            return False
        page = self._resident.get(name)
        if page is not None and self._page_uid.get(page) == ad.uid:
            return True
        return self.alloc.blocks_free + self.alloc.evictable_cached >= 1

    def acquire(self, name: str) -> int:
        """Take a ref on the adapter's device page, uploading (and possibly
        evicting the coldest released adapter) on a miss. Returns the page
        id the request's slot carries into the decode program."""
        ad = self.validate(name)
        page = self._resident.get(name)
        if page is not None and self._page_uid.get(page) != ad.uid:
            # re-registered under the same name: the cached page holds the
            # OLD factors. Orphan it (normal LRU pressure reclaims it) and
            # fall through to a fresh upload.
            self._resident.pop(name, None)
            self._page_name.pop(page, None)
            self._page_uid.pop(page, None)
            page = None
        if page is not None:
            self.alloc.ref(page)
            self.hits += 1
            return page
        page = self.alloc.alloc()  # may raise: every page refed or pinned
        old = self._page_name.pop(page, None)
        if old is not None:
            self._resident.pop(old, None)
        self._page_uid.pop(page, None)
        # a hash makes free() retain the page on the allocator's LRU, which
        # is exactly the warm-adapter cache; uid-keyed so a re-registered
        # name can never collide with its own stale page
        self.alloc.register(page, hash(("adapter", name, ad.uid)))
        tel = self.telemetry
        if tel is not None and tel.enabled:
            _t0 = tel.clock()
            self._upload(page, ad)
            tel.registry.histogram(
                "serving_lora_upload_s",
                "adapter factor upload wall time").observe(
                    tel.clock() - _t0, adapter=name)
        else:
            self._upload(page, ad)
        self.uploads += 1
        self._resident[name] = page
        self._page_name[page] = name
        self._page_uid[page] = ad.uid
        return page

    def release(self, page: int) -> None:
        """Drop one ref on a page (LRU-retained for warm reuse)."""
        if page == NULL_PAGE:
            return
        self.alloc.free(page)

    def pin(self, name: str) -> None:
        self.alloc.pin(self._resident[name])

    def unpin(self, name: str) -> None:
        page = self._resident.get(name)
        if page is not None:
            self.alloc.unpin(page)

    def warm(self, names: Iterable[str]) -> None:
        """Replay queued-demand order (most urgent FIRST) into the page
        LRU so eviction under pressure reclaims the adapter whose tenants
        hold the least scheduler share last-to-first. This is how WFQ
        shares govern adapter residency: the scheduler ranks waiting
        adapters, the pool keeps that ranking warm."""
        for name in reversed(list(names)):
            page = self._resident.get(name)
            if page is not None:
                self.alloc.touch(page)

    # ---------------------------------------------------------------- device
    def device_tensors(self) -> List:
        """The flat pool list for a compiled-program call."""
        return list(self._flat)

    def place_device_tensors(self, place_fn) -> None:
        """Re-place the stacked pool tensors (the tp executor shards A/B
        pages onto its serving mesh at construction —
        parallel/serving_mesh.py). ``place_fn(flat) -> flat`` must keep
        every shape/dtype; later page uploads are functional ``.at[]``
        updates, which preserve whatever placement lives here."""
        new = list(place_fn(list(self._flat)))
        if len(new) != len(self._flat) or any(
                a.shape != b.shape or a.dtype != b.dtype
                for a, b in zip(new, self._flat)):
            raise ValueError("place_fn must preserve the pool's tensor "
                             "shapes and dtypes")
        self._flat = new

    def _upload(self, page: int, ad: Adapter) -> None:
        """Write one adapter's rank-padded factors into ``page`` via
        functional updates — pool shapes never change, so uploads are
        eager device stores, not recompiles."""
        L, R = self.num_layers, self.max_rank
        for ti, t in enumerate(self.targets):
            i, o = self._dims[t]
            a_stack = np.zeros((L, i, R), np.float32)
            b_stack = np.zeros((L, R, o), np.float32)
            for (l, lt), (a, b) in ad.weights.items():
                if lt != t:
                    continue
                a_stack[l, :, :ad.rank] = a
                b_stack[l, :ad.rank, :] = b
            self._flat[2 * ti] = self._flat[2 * ti].at[page].set(
                jnp.asarray(a_stack))
            self._flat[2 * ti + 1] = self._flat[2 * ti + 1].at[page].set(
                jnp.asarray(b_stack))
        self._flat[-1] = self._flat[-1].at[page].set(ad.scale)

    def gather_rows(self, flat: Sequence, idx) -> List[Dict[str, Tuple]]:
        """Inside a traced program: gather per-row factors for page index
        vector ``idx`` (B,) int32. Returns a per-layer list of
        {target: (A (B, in, R), B (B, R, out), scale (B,))} raw jnp —
        the shape ``models/llama.py`` threads to ``nn.lora.bgmv``. The
        static python loops unroll at trace time; nothing here branches
        on adapter values."""
        scale = flat[-1][idx]
        out: List[Dict[str, Tuple]] = [dict() for _ in range(self.num_layers)]
        for ti, t in enumerate(self.targets):
            ag = flat[2 * ti][idx]       # (B, L, in, R)
            bg = flat[2 * ti + 1][idx]   # (B, L, R, out)
            for l in range(self.num_layers):
                out[l][t] = (ag[:, l], bg[:, l], scale)
        return out

    # ----------------------------------------------------------------- stats
    @property
    def pool_bytes(self) -> int:
        return self.page_bytes * (self.max_live_adapters + 1)

    @property
    def hit_rate(self) -> float:
        n = self.hits + self.uploads
        return self.hits / n if n else 0.0

    def stats(self) -> Dict:
        return {"adapter_pages": self.max_live_adapters,
                "adapter_page_bytes": self.page_bytes,
                "adapter_pool_bytes": self.pool_bytes,
                "adapters_registered": len(self.registry),
                "adapters_resident": len(self._resident),
                "adapter_hits": self.hits,
                "adapter_uploads": self.uploads,
                "adapter_hit_rate": self.hit_rate,
                "adapter_evictions": self.alloc.evictions,
                "adapter_host_bytes": self.registry.host_bytes}
