"""Request scheduling for ``GenerationServer`` — priorities, deadlines,
fair queuing, admission control, cancellation.

The MPK split (PAPERS.md, arXiv:2512.22219) keeps the compiled decode /
prefill / verify programs FIXED-SHAPE and pushes every scheduling dynamic
to the host runtime. This module is that host runtime's policy half: it
owns the waiting-request queue and decides, at each server step, which
request is admitted to a slot next. The mechanism half — preempting a
running request by swapping its KV blocks to host memory and restoring
them later — lives in ``inference/kv_offload.py``; the two meet in
``GenerationServer._step_paged``.

Design constraints, in order:

- **No device work.** Everything here is pure host Python over small
  lists — a pop is O(queue depth) with tiny constants. Policy never
  touches compiled-program shapes, so switching policies (or preempting
  and resuming a request) triggers zero recompiles.
- **Overload is a policy outcome, not a stall.** The pre-scheduler server
  had one behavior under pressure: queued requests waited forever behind
  whatever held the pool. With a scheduler, overload becomes: low
  priority work is preempted (KV swapped to host), TTL'd queue entries
  expire, and admission pushes back (``AdmissionError``) once the queue
  passes ``max_queue`` — all measurable via counters.
- **Cooperative cancellation.** The server is single-threaded; a cancel
  takes effect at the next step boundary, where the request's blocks are
  rolled back through the same refcount-safe ``truncate`` path the
  speculative rollback uses.

Three built-in policies (``GenerationServer(policy=...)``):

- ``"fifo"`` (default): submission order. Exactly the pre-scheduler
  behavior when nothing else (priority/TTL/cancel) is used.
- ``"priority"``: strict priority classes (lower value = more urgent),
  FIFO within a class; entries carrying a deadline order ahead of
  no-deadline peers, earliest first (EDF within the class).
- ``"wfq"``: weighted fair queuing ACROSS TENANTS within each priority
  class. Classic virtual-time WFQ: tenant ``t`` with weight ``w_t``
  charges each request ``cost / w_t`` of virtual time past the tenant's
  previous finish tag, and pops are lowest-tag-first — a tenant's share
  of admissions converges to ``w_t / sum(w)`` regardless of how fast it
  submits, so one chatty tenant cannot starve the rest.

Preempted requests re-enter the queue with their original ``seq`` and a
``preempted`` flag that orders them ahead of waiting peers in the same
class: they hold host-pool bytes (or lost prefill work), so draining them
first bounds both swap residency and resume latency.

TTLs bound QUEUE WAIT, not execution: an entry whose deadline passes
while still waiting (never admitted) is dropped by ``expire()`` and the
server reports it ``"expired"``. Once a request has run at all —
including a preempted-and-requeued one — it is never expired, only
cancelled explicitly.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

__all__ = ["AdmissionError", "SchedEntry", "Scheduler",
           "PRIORITY_HIGH", "PRIORITY_NORMAL", "PRIORITY_LOW"]

# Priority classes: plain ints, lower = more urgent. Any int >= 0 works
# (the three names are conventional anchors, not an enum cage).
PRIORITY_HIGH = 0
PRIORITY_NORMAL = 1
PRIORITY_LOW = 2

_POLICIES = ("fifo", "priority", "wfq")


class AdmissionError(RuntimeError):
    """Backpressure: the queue is at ``max_queue`` — the caller should
    shed load or retry later, not silently deepen the backlog."""


@dataclass
class SchedEntry:
    """One waiting (or preempted) request as the scheduler sees it. The
    payload ``req`` is opaque — the scheduler never reads token ids."""

    req: Any
    rid: int
    priority: int = PRIORITY_NORMAL
    tenant: str = "default"
    deadline: Optional[float] = None    # absolute clock time; None = no TTL
    seq: int = 0                        # admission order, stable across requeue
    cost: float = 1.0                   # WFQ charge (est. total tokens)
    vtag: float = 0.0                   # WFQ finish tag, set at submit
    preempted: bool = False             # requeued after losing its slot
    started: bool = False               # was admitted at least once
    swap: Any = None                    # kv_offload.SwapHandle when swapped out
    adapter: Optional[str] = None       # LoRA adapter name (None = base model)
    # estimated FRESH device blocks the entry's first allocation burst
    # needs, annotated by the server's admission gate each time it runs —
    # tier-aware: hot prefix hits are subtracted (they re-ref resident
    # blocks), warm-tier hits still count (promotion fills a fresh
    # block). None until the gate has looked at the entry.
    kv_need: Optional[int] = None


class Scheduler:
    """Policy-ordered waiting queue with admission control and TTLs.

    ``clock`` is injectable (default ``time.monotonic``) so deadline
    behavior is deterministic under test. ``weights`` maps tenant name to
    WFQ weight (default 1.0; ignored by fifo/priority).
    """

    def __init__(self, policy: str = "fifo",
                 max_queue: Optional[int] = None,
                 default_ttl_s: Optional[float] = None,
                 weights: Optional[Dict[str, float]] = None,
                 clock: Callable[[], float] = time.monotonic):
        if policy not in _POLICIES:
            raise ValueError(f"policy must be one of {_POLICIES}, "
                             f"got {policy!r}")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if default_ttl_s is not None and not default_ttl_s > 0:
            raise ValueError(
                f"default_ttl_s must be > 0, got {default_ttl_s}")
        self.policy = policy
        self.max_queue = max_queue
        self.default_ttl_s = default_ttl_s
        self.weights = dict(weights or {})
        for t, w in self.weights.items():
            if not w > 0:
                raise ValueError(f"tenant {t!r} weight must be > 0, got {w}")
        self._clock = clock
        # monotonic clamp high-water mark (see now()); -inf until first read
        self._last_now = float("-inf")
        self._q: List[SchedEntry] = []
        self._seq = 0
        # WFQ virtual time: advances to each popped entry's finish tag;
        # per-tenant last tag keeps a tenant's backlog serialized
        self._vnow = 0.0
        self._tenant_tag: Dict[str, float] = {}
        # counters (read by GenerationServer.sched_metrics)
        self.submitted = 0
        self.expired = 0
        self.cancelled = 0
        # registry twins (inference/telemetry.py) — None until a server
        # calls attach_metrics; the ints above stay authoritative for
        # direct Scheduler users with no registry
        self._m_submitted = None
        self._m_expired = None
        self._m_cancelled = None

    def attach_metrics(self, registry) -> None:
        """Mirror the intake counters into a
        :class:`~.telemetry.MetricsRegistry` (``sched_requests_*``).
        Pre-attach history is seeded in so registry totals always equal
        the ints; ``submitted`` is additionally labeled by tenant."""
        self._m_submitted = registry.counter(
            "sched_requests_submitted", "requests admitted to the queue")
        self._m_expired = registry.counter(
            "sched_requests_expired", "queued requests dropped by TTL")
        self._m_cancelled = registry.counter(
            "sched_requests_cancelled", "queued requests cancelled")
        for c, n in ((self._m_submitted, self.submitted),
                     (self._m_expired, self.expired),
                     (self._m_cancelled, self.cancelled)):
            if n:
                c.inc(n)

    # ------------------------------------------------------------------- clock
    def now(self) -> float:
        """Monotonically-clamped read of the injectable clock.

        The clock is injectable for tests and chaos plans, which means it
        can stall or jump backwards; an unclamped backwards jump would
        compute negative TTL remainders and make deadlines granted after
        the jump expire before deadlines granted before it. Clamping to
        the high-water mark keeps every timestamp ordering monotone: a
        stalled/backwards clock degrades to "time stands still", which
        TTL logic tolerates (nothing new expires), instead of corrupting
        the ordering invariants."""
        t = self._clock()
        if t > self._last_now:
            self._last_now = t
        return self._last_now

    # ------------------------------------------------------------------ intake
    def submit(self, req: Any, rid: int, *, priority: int = PRIORITY_NORMAL,
               tenant: str = "default", ttl_s: Optional[float] = None,
               cost: float = 1.0,
               adapter: Optional[str] = None) -> SchedEntry:
        """Admit one request to the queue; raises :class:`AdmissionError`
        when the queue is full (backpressure — shed, don't bury)."""
        if isinstance(priority, bool) or not isinstance(priority, int) \
                or priority < 0:
            raise ValueError(f"priority must be an int >= 0 "
                             f"(0 = most urgent), got {priority!r}")
        if ttl_s is not None and not ttl_s > 0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s!r}")
        if self.max_queue is not None and len(self._q) >= self.max_queue:
            raise AdmissionError(
                f"queue full ({len(self._q)}/{self.max_queue} waiting) — "
                f"backpressure: retry later or raise max_queue")
        ttl = ttl_s if ttl_s is not None else self.default_ttl_s
        now = self.now()
        w = self.weights.get(tenant, 1.0)
        tag = max(self._vnow, self._tenant_tag.get(tenant, 0.0)) \
            + float(cost) / w
        self._tenant_tag[tenant] = tag
        ent = SchedEntry(req=req, rid=rid, priority=priority, tenant=tenant,
                         deadline=(now + ttl) if ttl is not None else None,
                         seq=self._seq, cost=float(cost), vtag=tag,
                         adapter=adapter)
        self._seq += 1
        self._q.append(ent)
        self.submitted += 1
        if self._m_submitted is not None:
            self._m_submitted.inc(tenant=tenant)
        return ent

    def requeue(self, ent: SchedEntry) -> None:
        """Return a preempted entry to the queue. Never subject to
        admission control (it was already admitted once); its original
        ``seq``/``vtag`` plus the ``preempted`` flag order it ahead of
        waiting peers in its class."""
        ent.preempted = True
        ent.started = True
        self._q.append(ent)

    # ------------------------------------------------------------------ order
    def _key(self, ent: SchedEntry):
        head = (ent.priority, 0 if ent.preempted else 1)
        if self.policy == "fifo":
            return (0 if ent.preempted else 1, ent.seq)
        if self.policy == "priority":
            dl = ent.deadline if ent.deadline is not None else float("inf")
            return head + (dl, ent.seq)
        return head + (ent.vtag, ent.seq)            # wfq

    def peek(self) -> Optional[SchedEntry]:
        if not self._q:
            return None
        return min(self._q, key=self._key)

    def pop(self) -> Optional[SchedEntry]:
        ent = self.peek()
        if ent is None:
            return None
        self._q.remove(ent)
        if self.policy == "wfq":
            self._vnow = max(self._vnow, ent.vtag)
        return ent

    # ---------------------------------------------------------------- restore
    def restore_entry(self, ent: SchedEntry) -> None:
        """Re-enqueue an entry rebuilt from a ``GenerationServer``
        snapshot. Bypasses admission control (the request was admitted on
        the captured server) and preserves its ``seq``/``vtag``/flags so
        pop order survives the migration; the internal seq counter is
        bumped past it so new submissions order after restored work."""
        self._q.append(ent)
        self._seq = max(self._seq, ent.seq + 1)
        self.submitted += 1
        if self._m_submitted is not None:
            self._m_submitted.inc(tenant=ent.tenant)

    def restore_state(self, vnow: float,
                      tenant_tag: Dict[str, float]) -> None:
        """Adopt a snapshot's WFQ virtual time so restored tenants keep
        the fair-share debt they had accrued on the captured server."""
        self._vnow = max(self._vnow, float(vnow))
        for t, tag in tenant_tag.items():
            self._tenant_tag[t] = max(self._tenant_tag.get(t, 0.0),
                                      float(tag))

    # --------------------------------------------------------------- removal
    def remove(self, rid: int) -> Optional[SchedEntry]:
        """Remove a waiting entry by rid without touching the cancelled
        counter — the quarantine path uses this (a quarantined request is
        ``failed``, not ``cancelled``, and the metrics must not lie)."""
        for ent in self._q:
            if ent.rid == rid:
                self._q.remove(ent)
                return ent
        return None

    def cancel(self, rid: int) -> Optional[SchedEntry]:
        """Remove a waiting entry by rid; returns it (or None if the rid
        is not queued — it may be running, finished, or unknown)."""
        ent = self.remove(rid)
        if ent is not None:
            self.cancelled += 1
            if self._m_cancelled is not None:
                self._m_cancelled.inc()
        return ent

    def expire(self) -> List[SchedEntry]:
        """Drop and return every never-started entry whose deadline has
        passed. Preempted entries are exempt: their work (host-side KV,
        or a partial prefill) is already paid for — kill those with
        :meth:`cancel`, not a timer."""
        now = self.now()
        out = [e for e in self._q
               if e.deadline is not None and e.deadline <= now
               and not e.started]
        for e in out:
            self._q.remove(e)
        self.expired += len(out)
        if out and self._m_expired is not None:
            self._m_expired.inc(len(out))
        return out

    def __len__(self) -> int:
        return len(self._q)

    def waiting(self) -> List[SchedEntry]:
        """Current queue in pop order (for introspection/tests)."""
        return sorted(self._q, key=self._key)

    def kv_demand(self) -> int:
        """Aggregate fresh-block demand of the waiting queue — the sum of
        every annotated ``SchedEntry.kv_need``. The admission gate
        refreshes annotations as it scans, so this tracks the tier-aware
        cost of draining the backlog (fleet routing reads it through
        ``GenerationServer.load_metrics`` as ``queued_kv_demand``);
        entries the gate has not seen yet contribute 0."""
        return sum(e.kv_need for e in self._q if e.kv_need is not None)

    def adapter_demand(self) -> List[str]:
        """Distinct adapter names the queue wants, in pop-priority order —
        the policy's view of adapter residency pressure. The server replays
        this through ``AdapterPool.warm`` so that under WFQ the adapters of
        high-share tenants stay most-recently-used in the pool's LRU and
        evict last."""
        out: List[str] = []
        seen = set()
        for ent in self.waiting():
            a = ent.adapter
            if a is not None and a not in seen:
                seen.add(a)
                out.append(a)
        return out
