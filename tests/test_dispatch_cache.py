"""Cached eager autograd (framework/dispatch.py): closure-free op functions
compile their vjp once per (code, structure, kwargs); impure ops (PRNG
readers) and closures are excluded."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.framework.dispatch as D
from paddle_tpu.framework.dispatch import apply_op


def _op_static_scale(v, w, *, scale=2.0):
    return (v * w) * scale


def test_cache_hits_for_per_call_defs():
    """Functions with identical code defined per call share one cache entry."""
    x = paddle.to_tensor(np.ones((2, 3), "float32"))
    x.stop_gradient = False
    before = len(D._FWD_JIT_CACHE)

    outs = []
    for _ in range(4):
        def f(v, w):  # same code object every iteration
            return v * w + 1.0

        outs.append(apply_op(f, x, x, op_name="t"))
    assert len(D._FWD_JIT_CACHE) == before + 1
    outs[-1].sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad.value), 2 * np.ones((2, 3)))


def test_kwdefaults_distinguish_entries():
    x = paddle.to_tensor(np.ones((2,), "float32"))
    x.stop_gradient = False
    o1 = apply_op(_op_static_scale, x, x, op_name="s")
    o2 = apply_op(_op_static_scale, x, x, op_name="s", scale=5.0)
    np.testing.assert_allclose(np.asarray(o1.value), [2.0, 2.0])
    np.testing.assert_allclose(np.asarray(o2.value), [5.0, 5.0])


def test_impure_rng_ops_not_frozen():
    paddle.seed(0)
    p = paddle.to_tensor(np.full((8,), 0.5, "float32"))
    p.stop_gradient = False  # grad-enabled → record path
    draws = {tuple(np.asarray(paddle.bernoulli(p).value)) for _ in range(4)}
    assert len(draws) > 1, "bernoulli draws frozen by the vjp cache"


def test_closure_fns_excluded():
    x = paddle.to_tensor(np.ones((2,), "float32"))
    x.stop_gradient = False
    before = len(D._FWD_JIT_CACHE)
    for k in (1.0, 2.0, 3.0):
        def f(v, _k=None):  # closure over k
            return v * k

        out = apply_op(f, x, op_name="c")
        np.testing.assert_allclose(np.asarray(out.value), [k, k])
    assert len(D._FWD_JIT_CACHE) == before  # none cached


def test_backward_through_cached_conv():
    from paddle_tpu import nn

    paddle.seed(0)
    m = nn.Conv2D(3, 4, 3, padding=1)
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 3, 8, 8)
                         .astype("float32"))
    y = m(x)
    (y * y).sum().backward()
    g1 = np.asarray(m.weight.grad.value).copy()
    m.clear_gradients()
    y = m(x)  # second call: cache hit
    (y * y).sum().backward()
    np.testing.assert_allclose(np.asarray(m.weight.grad.value), g1,
                               rtol=1e-6, atol=1e-7)
