"""Unit tests for paddle_tpu.utils.bench_timing — the dispatch-chain
differencing harness every benchmark tool times through.

The TPU-tunnel failure modes this module exists for (async
block_until_ready, seconds-scale jitter) are simulated with fakes; the
real-backend behavior is exercised by the benchmark tools themselves on
hardware (BASELINE.md round-3 on-hardware table).
"""
import threading
import time

import jax.numpy as jnp
import pytest

from paddle_tpu.utils import bench_timing as bt


def test_pull_scalar_jax_array_and_tensor():
    import paddle_tpu as paddle

    assert bt.pull_scalar(jnp.arange(4.0)) == 0.0
    assert bt.pull_scalar(paddle.to_tensor([3.0, 1.0])) == 3.0
    # pytrees: first non-None leaf wins
    assert bt.pull_scalar({"a": None, "b": jnp.full((2, 2), 7.0)}) == 7.0


def test_device_time_ms_measures_a_known_busy_wait():
    target_s = 0.004

    def fn():
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < target_s:
            pass
        return jnp.zeros(())

    ms = bt.device_time_ms(fn, reps=8, repeats=2)
    # busy-wait is the per-call cost; allow generous slack for CI hosts
    assert 0.5 * target_s * 1e3 <= ms <= 3.0 * target_s * 1e3


def test_device_time_ms_raises_unstable_on_pure_jitter(monkeypatch):
    # a "backend" where every chain takes the same time regardless of n
    # (zero signal) but with spread: must raise, never return ~0
    calls = iter([0.5, 0.9] * 50)

    def fake_chain(fn, n, repeats):
        a, b = next(calls), next(calls)
        return min(a, b), max(a, b)

    monkeypatch.setattr(bt, "_chain_stats", fake_chain)
    with pytest.raises(bt.UnstableMeasurement):
        bt.device_time_ms(lambda: jnp.zeros(()), reps=4, max_reps=16)


def test_unstable_is_not_a_generic_runtime_error_catchall():
    # callers catch UnstableMeasurement specifically; a raw RuntimeError
    # (e.g. an XLA OOM) must NOT be an instance of it
    assert issubclass(bt.UnstableMeasurement, RuntimeError)
    assert not isinstance(RuntimeError("boom"), bt.UnstableMeasurement)


def test_adaptive_floor_scales_with_observed_spread(monkeypatch):
    # quiet backend: tiny spread -> small reps suffice even for a fast fn
    def fake_chain(fn, n, repeats):
        base = 0.001 * n + 0.050  # 1 ms/call + 50 ms fixed cost, no jitter
        return base, base + 0.0001

    monkeypatch.setattr(bt, "_chain_stats", fake_chain)
    ms = bt.device_time_ms(lambda: jnp.zeros(()), reps=16)
    assert ms == pytest.approx(1.0, rel=0.05)


def test_tpu_lock_times_out_and_proceeds(tmp_path, capsys):
    lock_path = str(tmp_path / "l")
    entered = threading.Event()
    release = threading.Event()

    def holder():
        with bt.tpu_lock(lock_path):
            entered.set()
            release.wait(10)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert entered.wait(5)
    t0 = time.monotonic()
    with bt.tpu_lock(lock_path, timeout_s=1.5):
        waited = time.monotonic() - t0
    release.set()
    t.join(5)
    assert 1.0 <= waited <= 6.0  # waited for the timeout, then proceeded


def test_tpu_lock_serializes_two_holders(tmp_path):
    lock_path = str(tmp_path / "l")
    order = []

    def worker(tag, hold_s):
        with bt.tpu_lock(lock_path):
            order.append(("in", tag))
            time.sleep(hold_s)
            order.append(("out", tag))

    t1 = threading.Thread(target=worker, args=("a", 0.3))
    t1.start()
    time.sleep(0.1)
    t2 = threading.Thread(target=worker, args=("b", 0.0))
    t2.start()
    t1.join(5)
    t2.join(5)
    assert order == [("in", "a"), ("out", "a"), ("in", "b"), ("out", "b")]
