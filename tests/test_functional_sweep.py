"""nn.functional sweep + surface-completeness gate (the op_test.py pattern
applied to the functional surface: numpy reference per op, or a tight
mathematical property where a numpy oracle is impractical; the gate fails
when a functional op is neither swept nor exempted-with-reason)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

RNG = np.random.RandomState(5)
X = RNG.randn(3, 7).astype("float32")
POSX = np.abs(X) + 0.1
Y = RNG.randn(3, 7).astype("float32")
IMG = RNG.randn(2, 4, 8, 8).astype("float32")  # NCHW


def t(x):
    return paddle.to_tensor(x)


def npv(o):
    return np.asarray(o.value)


def _sig(x):
    return 1 / (1 + np.exp(-x))


# --------------------------------------------------------------------------
# activations: (name, input, numpy reference)
# --------------------------------------------------------------------------

ACTS = [
    ("relu", X, lambda x: np.maximum(x, 0)),
    ("relu6", X * 4, lambda x: np.clip(x, 0, 6)),
    ("elu", X, lambda x: np.where(x > 0, x, np.expm1(x))),
    ("celu", X, lambda x: np.where(x > 0, x, np.expm1(x))),
    ("selu", X, lambda x: 1.0507009873554805 * np.where(
        x > 0, x, 1.6732632423543772 * np.expm1(x))),
    ("gelu", X, lambda x: x * 0.5 * (1 + np.vectorize(_erf)(x / np.sqrt(2)))),
    ("silu", X, lambda x: x * _sig(x)),
    ("swish", X, lambda x: x * _sig(x)),
    ("mish", X, lambda x: x * np.tanh(np.log1p(np.exp(x)))),
    ("hardtanh", X * 3, lambda x: np.clip(x, -1, 1)),
    ("hardsigmoid", X * 4, lambda x: np.clip(x / 6 + 0.5, 0, 1)),
    ("hardswish", X * 4, lambda x: x * np.clip(x / 6 + 0.5, 0, 1)),
    ("hardshrink", X, lambda x: np.where(np.abs(x) > 0.5, x, 0)),
    ("softshrink", X, lambda x: np.where(x > 0.5, x - 0.5,
                                         np.where(x < -0.5, x + 0.5, 0))),
    ("tanhshrink", X, lambda x: x - np.tanh(x)),
    ("thresholded_relu", X, lambda x: np.where(x > 1.0, x, 0)),
    ("leaky_relu", X, lambda x: np.where(x > 0, x, 0.01 * x)),
    ("log_sigmoid", X, lambda x: np.log(_sig(x))),
    ("softplus", X, lambda x: np.log1p(np.exp(x))),
    ("softsign", X, lambda x: x / (1 + np.abs(x))),
    ("sigmoid", X, _sig),
    ("tanh", X, np.tanh),
    ("softmax", X, lambda x: np.exp(x) / np.exp(x).sum(-1, keepdims=True)),
    ("log_softmax", X,
     lambda x: x - x.max(-1, keepdims=True) -
     np.log(np.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True))),
]


def _erf(v):
    import math

    return math.erf(v)


@pytest.mark.parametrize("name,x,ref", ACTS, ids=[a[0] for a in ACTS])
def test_activation_forward(name, x, ref):
    out = npv(getattr(F, name)(t(x)))
    np.testing.assert_allclose(out, ref(x), rtol=1e-4, atol=1e-5, err_msg=name)


def test_prelu_rrelu_maxout_glu_gumbel():
    w = np.full((7,), 0.2, "float32")
    np.testing.assert_allclose(npv(F.prelu(t(X), t(w))),
                               np.where(X > 0, X, 0.2 * X), rtol=1e-5)
    # rrelu in eval mode uses the fixed mean slope
    lo, hi = 1 / 8.0, 1 / 3.0
    np.testing.assert_allclose(
        npv(F.rrelu(t(X), lower=lo, upper=hi, training=False)),
        np.where(X > 0, X, (lo + hi) / 2 * X), rtol=1e-5)
    # maxout over channel groups
    xm = RNG.randn(2, 4, 3, 3).astype("float32")
    out = npv(F.maxout(t(xm), groups=2))
    np.testing.assert_allclose(out, xm.reshape(2, 2, 2, 3, 3).max(2),
                               rtol=1e-6)
    # glu: first half * sigmoid(second half)
    g = npv(F.glu(t(X[:, :6]), axis=-1))
    np.testing.assert_allclose(g, X[:, :3] * _sig(X[:, 3:6]), rtol=1e-5)
    # gumbel_softmax: rows sum to 1; hard=True is one-hot
    gs = npv(F.gumbel_softmax(t(X), temperature=0.5))
    np.testing.assert_allclose(gs.sum(-1), np.ones(3), rtol=1e-5)
    hard = npv(F.gumbel_softmax(t(X), hard=True))
    assert set(np.unique(hard)) <= {0.0, 1.0} and hard.sum() == 3


# --------------------------------------------------------------------------
# losses
# --------------------------------------------------------------------------


def test_regression_losses():
    x, y = X, Y
    np.testing.assert_allclose(npv(F.l1_loss(t(x), t(y))),
                               np.abs(x - y).mean(), rtol=1e-5)
    np.testing.assert_allclose(npv(F.mse_loss(t(x), t(y))),
                               ((x - y) ** 2).mean(), rtol=1e-5)
    np.testing.assert_allclose(npv(F.square_error_cost(t(x), t(y))),
                               (x - y) ** 2, rtol=1e-5)
    d = x - y
    sl1 = np.where(np.abs(d) < 1, 0.5 * d * d, np.abs(d) - 0.5).mean()
    np.testing.assert_allclose(npv(F.smooth_l1_loss(t(x), t(y))), sl1,
                               rtol=1e-5)
    hub = np.where(np.abs(d) <= 1, 0.5 * d * d, np.abs(d) - 0.5).mean()
    np.testing.assert_allclose(npv(F.huber_loss(t(x), t(y))), hub, rtol=1e-5)


def test_classification_losses():
    logits = X
    probs = _sig(logits)
    labels01 = (Y > 0).astype("float32")
    bce = -(labels01 * np.log(np.clip(probs, 1e-7, 1)) +
            (1 - labels01) * np.log(np.clip(1 - probs, 1e-7, 1))).mean()
    np.testing.assert_allclose(
        npv(F.binary_cross_entropy(t(probs), t(labels01))), bce, rtol=1e-4)
    np.testing.assert_allclose(
        npv(F.binary_cross_entropy_with_logits(t(logits), t(labels01))),
        bce, rtol=1e-4)
    # nll_loss over log-probabilities
    lp = npv(F.log_softmax(t(logits)))
    idx = RNG.randint(0, 7, (3,)).astype("int64")
    np.testing.assert_allclose(
        npv(F.nll_loss(t(lp), t(idx))),
        -lp[np.arange(3), idx].mean(), rtol=1e-5)
    # softmax CE == nll(log_softmax)
    np.testing.assert_allclose(
        npv(F.cross_entropy(t(logits), t(idx))),
        -lp[np.arange(3), idx].mean(), rtol=1e-5)
    swce = npv(F.softmax_with_cross_entropy(t(logits), t(idx[:, None])))
    np.testing.assert_allclose(swce.reshape(-1),
                               -lp[np.arange(3), idx], rtol=1e-5)
    # kl_div (mean over batch: paddle 'mean' divides by numel)
    q = np.exp(lp)
    p_target = np.abs(Y) / np.abs(Y).sum(-1, keepdims=True)
    kl = (p_target * (np.log(p_target + 1e-12) - lp)).sum()
    np.testing.assert_allclose(
        npv(F.kl_div(t(lp), t(p_target), reduction="sum")), kl, rtol=1e-4)
    # label smoothing
    oh = np.eye(7, dtype="float32")[idx]
    np.testing.assert_allclose(npv(F.label_smooth(t(oh), epsilon=0.1)),
                               oh * 0.9 + 0.1 / 7, rtol=1e-5)


def test_margin_and_embedding_losses():
    a, b = X, Y
    lab = np.sign(RNG.randn(3)).astype("float32")
    mr = np.maximum(0, -lab[:, None] * (a - b) + 0.0).mean()
    np.testing.assert_allclose(
        npv(F.margin_ranking_loss(t(a), t(b), t(lab[:, None]))), mr,
        rtol=1e-4)
    # hinge embedding: y=1 -> x; y=-1 -> max(0, margin-x)
    he = np.where(lab[:, None] > 0, a, np.maximum(0, 1.0 - a)).mean()
    np.testing.assert_allclose(
        npv(F.hinge_embedding_loss(t(a), t(np.broadcast_to(
            lab[:, None], a.shape).copy()))), he, rtol=1e-4, atol=1e-6)
    # soft margin
    sm = np.log1p(np.exp(-lab[:, None] * a)).mean()
    np.testing.assert_allclose(
        npv(F.soft_margin_loss(t(a), t(np.broadcast_to(
            lab[:, None], a.shape).copy()))), sm, rtol=1e-4)
    # cosine embedding
    y1 = np.array([1, -1], "float32")
    u = RNG.randn(2, 5).astype("float32")
    v = RNG.randn(2, 5).astype("float32")
    cossim = (u * v).sum(-1) / (np.linalg.norm(u, axis=-1) *
                                np.linalg.norm(v, axis=-1))
    ce = np.where(y1 > 0, 1 - cossim, np.maximum(0, cossim - 0.0)).mean()
    np.testing.assert_allclose(
        npv(F.cosine_embedding_loss(t(u), t(v), t(y1))), ce, rtol=1e-4)
    # triplet margin
    anc, pos, neg = (RNG.randn(4, 6).astype("float32") for _ in range(3))
    dp = np.linalg.norm(anc - pos, axis=-1)
    dn = np.linalg.norm(anc - neg, axis=-1)
    tm = np.maximum(0, dp - dn + 1.0).mean()
    np.testing.assert_allclose(
        npv(F.triplet_margin_loss(t(anc), t(pos), t(neg))), tm, rtol=1e-4)
    np.testing.assert_allclose(
        npv(F.triplet_margin_with_distance_loss(t(anc), t(pos), t(neg))),
        tm, rtol=1e-4)


def test_misc_losses_finite_and_formula():
    logits = X
    labels01 = (Y > 0).astype("float32")
    # sigmoid focal (gamma=2, alpha=.25): formula
    p = _sig(logits)
    ce = -(labels01 * np.log(p) + (1 - labels01) * np.log(1 - p))
    pt = labels01 * p + (1 - labels01) * (1 - p)
    alpha_t = labels01 * 0.25 + (1 - labels01) * 0.75
    focal = (alpha_t * (1 - pt) ** 2 * ce).sum() / 3  # normalizer=batch
    got = npv(F.sigmoid_focal_loss(t(logits), t(labels01),
                                   normalizer=t(np.float32(3.0))))
    np.testing.assert_allclose(got, focal, rtol=1e-3)
    # dice loss
    pr = _sig(RNG.randn(2, 5, 1).astype("float32"))
    lb = RNG.randint(0, 2, (2, 5, 1)).astype("int64")
    assert np.isfinite(npv(F.dice_loss(t(pr), t(lb)))).all()
    # log_loss
    eps = 1e-4
    inp = np.clip(_sig(X), 0.01, 0.99)
    ll = -(labels01 * np.log(inp + eps) +
           (1 - labels01) * np.log(1 - inp + eps))
    np.testing.assert_allclose(npv(F.log_loss(t(inp), t(labels01))), ll,
                               rtol=1e-4)
    # poisson nll (log_input=True): exp(x) - y*x
    pn = (np.exp(X) - Y * X).mean()
    np.testing.assert_allclose(npv(F.poisson_nll_loss(t(X), t(Y))), pn,
                               rtol=1e-4)
    # gaussian nll
    var = POSX
    gn = 0.5 * (np.log(np.maximum(var, 1e-6)) + (X - Y) ** 2 / var).mean()
    np.testing.assert_allclose(
        npv(F.gaussian_nll_loss(t(X), t(Y), t(var))), gn, rtol=1e-3)
    # multi-label soft margin
    ml = -(labels01 * np.log(_sig(X)) +
           (1 - labels01) * np.log(_sig(-X))).mean()
    np.testing.assert_allclose(
        npv(F.multi_label_soft_margin_loss(t(X), t(labels01))), ml,
        rtol=1e-4)
    # multi margin
    idx = RNG.randint(0, 7, (3,)).astype("int64")
    corr = X[np.arange(3), idx][:, None]
    mm = np.maximum(0, 1 - corr + X)
    mm[np.arange(3), idx] = 0
    np.testing.assert_allclose(npv(F.multi_margin_loss(t(X), t(idx))),
                               (mm.sum(-1) / 7).mean(), rtol=1e-4)
    # npair: finite
    anc, pos = (RNG.randn(4, 6).astype("float32") for _ in range(2))
    lbl = np.arange(4).astype("int64")
    assert np.isfinite(npv(F.npair_loss(t(anc), t(pos), t(lbl)))).all()
    # ctc / rnnt: finite on a tiny case
    lp = npv(F.log_softmax(t(RNG.randn(6, 2, 5).astype("float32"))))
    labels = np.array([[1, 2], [2, 3]], "int32")
    ilen = np.array([6, 6], "int64")
    llen = np.array([2, 2], "int64")
    ctc = npv(F.ctc_loss(t(lp), t(labels), t(ilen), t(llen)))
    assert np.isfinite(ctc).all()
    # hsigmoid: finite
    feat = RNG.randn(3, 4).astype("float32")
    w = RNG.randn(6, 4).astype("float32")
    lab = RNG.randint(0, 7, (3, 1)).astype("int64")
    out = F.hsigmoid_loss(t(feat), t(lab), 7, t(w))
    assert np.isfinite(npv(out)).all()


# --------------------------------------------------------------------------
# structural / shape ops
# --------------------------------------------------------------------------


def test_geometry_and_shuffle_ops():
    # pixel (un)shuffle roundtrip
    x = RNG.randn(2, 8, 4, 4).astype("float32")
    ps = F.pixel_shuffle(t(x), 2)
    assert npv(ps).shape == (2, 2, 8, 8)
    back = npv(F.pixel_unshuffle(ps, 2))
    np.testing.assert_allclose(back, x, rtol=1e-6)
    # channel shuffle is a permutation
    cs = npv(F.channel_shuffle(t(x), 4))
    np.testing.assert_allclose(np.sort(cs.ravel()), np.sort(x.ravel()))
    # zeropad2d
    zp = npv(F.zeropad2d(t(x), [1, 2, 3, 4]))
    assert zp.shape == (2, 8, 4 + 3 + 4, 4 + 1 + 2)
    np.testing.assert_allclose(zp[:, :, 3:7, 1:5], x)
    # temporal shift: (N*T, C, H, W) with T=seg_num; shape preserved
    ts = npv(F.temporal_shift(t(IMG), seg_num=2, shift_ratio=0.25))
    assert ts.shape == IMG.shape
    np.testing.assert_allclose(np.sort(np.abs(ts).ravel())[-10:],
                               np.sort(np.abs(ts).ravel())[-10:])
    # diag_embed
    de = npv(F.diag_embed(t(X)))
    assert de.shape == (3, 7, 7)
    np.testing.assert_allclose(de[1].diagonal(), X[1])
    # one_hot
    oh = npv(F.one_hot(t(np.array([0, 2], "int64")), 4))
    np.testing.assert_allclose(oh, np.eye(4, dtype="float32")[[0, 2]])
    # sequence_mask
    m = npv(F.sequence_mask(t(np.array([2, 0], "int64")), maxlen=3))
    np.testing.assert_array_equal(m, [[1, 1, 0], [0, 0, 0]])


def test_similarity_ops():
    u, v = X, Y
    cs = (u * v).sum(-1) / (np.linalg.norm(u, axis=-1) *
                            np.linalg.norm(v, axis=-1))
    np.testing.assert_allclose(npv(F.cosine_similarity(t(u), t(v))), cs,
                               rtol=1e-5)
    pd = np.linalg.norm(u - v, axis=-1)
    np.testing.assert_allclose(npv(F.pairwise_distance(t(u), t(v))), pd,
                               rtol=1e-5)
    nn = u / np.linalg.norm(u, axis=-1, keepdims=True)
    np.testing.assert_allclose(npv(F.normalize(t(u))), nn, rtol=1e-5)
    # bilinear: x1 W x2^T + b
    w = RNG.randn(3, 7, 7).astype("float32")
    bl = npv(F.bilinear(t(u), t(v), t(w)))
    want = np.einsum("bi,oij,bj->bo", u, w, v)
    np.testing.assert_allclose(bl, want, rtol=1e-4)
    # linear
    wl = RNG.randn(7, 4).astype("float32")
    np.testing.assert_allclose(npv(F.linear(t(u), t(wl))), u @ wl, rtol=1e-4)
    # embedding
    table = RNG.randn(10, 4).astype("float32")
    ids = np.array([[1, 3], [0, 9]], "int64")
    np.testing.assert_allclose(npv(F.embedding(t(ids), t(table))),
                               table[ids], rtol=1e-6)


def test_conv_variants_against_conv2d():
    # conv1d == conv2d with a height-1 image
    x = RNG.randn(2, 3, 10).astype("float32")
    w = RNG.randn(5, 3, 3).astype("float32")
    o1 = npv(F.conv1d(t(x), t(w), padding=1))
    o2 = npv(F.conv2d(t(x[:, :, None, :]), t(w[:, :, None, :]),
                      padding=[0, 1]))[:, :, 0, :]
    np.testing.assert_allclose(o1, o2, rtol=1e-4, atol=1e-5)
    # conv3d on a depth-1 volume == conv2d
    x3 = RNG.randn(2, 3, 1, 6, 6).astype("float32")
    w3 = RNG.randn(4, 3, 1, 3, 3).astype("float32")
    o3 = npv(F.conv3d(t(x3), t(w3), padding=[0, 1, 1]))[:, :, 0]
    o2d = npv(F.conv2d(t(x3[:, :, 0]), t(w3[:, :, 0]), padding=1))
    np.testing.assert_allclose(o3, o2d, rtol=1e-4, atol=1e-5)
    # transpose convs invert stride-2 shape
    xt = RNG.randn(1, 4, 5).astype("float32")
    wt = RNG.randn(4, 2, 3).astype("float32")
    assert npv(F.conv1d_transpose(t(xt), t(wt), stride=2)).shape == (1, 2, 11)
    xt2 = RNG.randn(1, 4, 5, 5).astype("float32")
    wt2 = RNG.randn(4, 2, 3, 3).astype("float32")
    assert npv(F.conv2d_transpose(t(xt2), t(wt2), stride=2)).shape == \
        (1, 2, 11, 11)
    xt3 = RNG.randn(1, 4, 2, 5, 5).astype("float32")
    wt3 = RNG.randn(4, 2, 1, 3, 3).astype("float32")
    assert npv(F.conv3d_transpose(t(xt3), t(wt3))).shape == (1, 2, 2, 7, 7)


def test_pool_variants():
    x = RNG.randn(2, 3, 8).astype("float32")
    mp = npv(F.max_pool1d(t(x), 2, stride=2))
    np.testing.assert_allclose(mp, x.reshape(2, 3, 4, 2).max(-1), rtol=1e-6)
    ap = npv(F.avg_pool1d(t(x), 2, stride=2))
    np.testing.assert_allclose(ap, x.reshape(2, 3, 4, 2).mean(-1), rtol=1e-6)
    import itertools

    x3 = RNG.randn(1, 2, 4, 4, 4).astype("float32")
    mp3 = npv(F.max_pool3d(t(x3), 2, stride=2))
    brute = np.zeros((1, 2, 2, 2, 2), "float32")
    for d, h, w in itertools.product(range(2), range(2), range(2)):
        brute[0, :, d, h, w] = x3[0, :, 2 * d:2 * d + 2, 2 * h:2 * h + 2,
                                  2 * w:2 * w + 2].reshape(2, -1).max(-1)
    np.testing.assert_allclose(mp3, brute, rtol=1e-6)
    ap3 = npv(F.avg_pool3d(t(x3), 2, stride=2))
    assert ap3.shape == (1, 2, 2, 2, 2)
    # adaptive pools at divisible sizes equal plain pools
    a2 = npv(F.adaptive_avg_pool2d(t(IMG), 4))
    p2 = npv(F.avg_pool2d(t(IMG), 2, stride=2))
    np.testing.assert_allclose(a2, p2, rtol=1e-5, atol=1e-6)
    am2 = npv(F.adaptive_max_pool2d(t(IMG), 4))
    pm2 = npv(F.max_pool2d(t(IMG), 2, stride=2))
    np.testing.assert_allclose(am2, pm2, rtol=1e-5, atol=1e-6)
    a1 = npv(F.adaptive_avg_pool1d(t(x), 4))
    np.testing.assert_allclose(a1, x.reshape(2, 3, 4, 2).mean(-1), rtol=1e-6)
    am1 = npv(F.adaptive_max_pool1d(t(x), 4))
    np.testing.assert_allclose(am1, x.reshape(2, 3, 4, 2).max(-1), rtol=1e-6)
    a3 = npv(F.adaptive_avg_pool3d(t(x3), 2))
    assert a3.shape == (1, 2, 2, 2, 2)
    am3 = npv(F.adaptive_max_pool3d(t(x3), 2))
    np.testing.assert_allclose(am3, brute, rtol=1e-6)


def test_unpool_roundtrip():
    x = RNG.randn(1, 2, 6).astype("float32")
    out, idx = F.max_pool1d(t(x), 2, stride=2, return_mask=True)
    restored = npv(F.max_unpool1d(out, idx, 2, stride=2))
    got = npv(out)
    # every pooled max must reappear at its argmax position
    assert restored.shape == (1, 2, 6)
    assert np.isin(got.ravel(), restored.ravel()).all()
    x2 = RNG.randn(1, 2, 4, 4).astype("float32")
    out2, idx2 = F.max_pool2d(t(x2), 2, stride=2, return_mask=True)
    r2 = npv(F.max_unpool2d(out2, idx2, 2, stride=2))
    assert r2.shape == (1, 2, 4, 4)
    assert np.isin(npv(out2).ravel(), r2.ravel()).all()
    x3 = RNG.randn(1, 1, 2, 4, 4).astype("float32")
    out3, idx3 = F.max_pool3d(t(x3), 2, stride=2, return_mask=True)
    r3 = npv(F.max_unpool3d(out3, idx3, 2, stride=2))
    assert r3.shape == (1, 1, 2, 4, 4)


def test_norm_functionals():
    x = IMG
    # layer_norm over last dims
    w = np.ones((8,), "float32")
    b = np.zeros((8,), "float32")
    ln = npv(F.layer_norm(t(x), (8,), weight=t(w), bias=t(b)))
    mu = x.mean(-1, keepdims=True)
    sd = x.std(-1, keepdims=True)
    np.testing.assert_allclose(ln, (x - mu) / np.sqrt(sd ** 2 + 1e-5),
                               rtol=1e-3, atol=1e-3)
    # instance_norm: per (N, C) over HW
    inn = npv(F.instance_norm(t(x)))
    mu = x.mean((2, 3), keepdims=True)
    var = x.var((2, 3), keepdims=True)
    np.testing.assert_allclose(inn, (x - mu) / np.sqrt(var + 1e-5),
                               rtol=1e-3, atol=1e-3)
    # group_norm with groups == channels == instance norm
    gw = np.ones((4,), "float32")
    gb = np.zeros((4,), "float32")
    gn = npv(F.group_norm(t(x), 4, weight=t(gw), bias=t(gb)))
    np.testing.assert_allclose(gn, inn, rtol=1e-3, atol=1e-3)
    # batch_norm in eval mode with given stats
    rm = x.mean((0, 2, 3))
    rv = x.var((0, 2, 3))
    bn = npv(F.batch_norm(t(x), t(rm), t(rv), training=False))
    np.testing.assert_allclose(
        bn, (x - rm[None, :, None, None]) /
        np.sqrt(rv[None, :, None, None] + 1e-5), rtol=1e-3, atol=1e-3)
    # rms_norm
    rw = np.ones((8,), "float32")
    rms = npv(F.rms_norm(t(x), t(rw)))
    np.testing.assert_allclose(
        rms, x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6),
        rtol=1e-3, atol=1e-3)
    # local_response_norm: finite + shape
    lrn = npv(F.local_response_norm(t(x), size=3))
    assert lrn.shape == x.shape and np.isfinite(lrn).all()
    # spectral_norm: largest singular value of the output is ~1
    wmat = RNG.randn(6, 4).astype("float32")
    sn = npv(F.spectral_norm(t(wmat), power_iters=50))
    assert abs(np.linalg.svd(sn, compute_uv=False)[0] - 1.0) < 0.05


def test_dropout_family():
    # F.alpha_dropout( / F.dropout( eval-mode identity
    for fn in (F.dropout, F.alpha_dropout):
        out = npv(fn(t(X), 0.5, training=False))
        np.testing.assert_allclose(out, X)
    np.testing.assert_allclose(npv(F.dropout2d(t(IMG), 0.4, training=False)),
                               IMG)
    x3 = RNG.randn(1, 2, 2, 4, 4).astype("float32")
    np.testing.assert_allclose(npv(F.dropout3d(t(x3), 0.4, training=False)),
                               x3)
    paddle.seed(0)
    tr = npv(F.dropout(t(np.ones((100, 100), "float32")), 0.5, training=True))
    assert abs(tr.mean() - 1.0) < 0.1  # inverted scaling keeps expectation
    assert (tr == 0).mean() > 0.3


def test_resize_pad_fold_grid():
    up = npv(F.interpolate(t(IMG), scale_factor=2, mode="nearest"))
    np.testing.assert_allclose(up, IMG.repeat(2, -1).repeat(2, -2), rtol=1e-6)
    np.testing.assert_allclose(
        npv(F.upsample(t(IMG), scale_factor=2, mode="nearest")), up)
    pd = npv(F.pad(t(X), [1, 1], value=9.0))
    np.testing.assert_allclose(pd[:, 0], np.full(3, 9.0))
    # unfold/fold roundtrip (non-overlapping patches sum back exactly)
    u = F.unfold(t(IMG), kernel_sizes=2, strides=2)
    assert npv(u).shape == (2, 4 * 2 * 2, 16)
    back = npv(F.fold(u, output_sizes=[8, 8], kernel_sizes=2, strides=2))
    np.testing.assert_allclose(back, IMG, rtol=1e-6)
    # identity affine grid samples the input back
    theta = np.tile(np.array([[[1, 0, 0], [0, 1, 0]]], "float32"), (2, 1, 1))
    grid = F.affine_grid(t(theta), [2, 4, 8, 8])
    samp = npv(F.grid_sample(t(IMG), grid))
    np.testing.assert_allclose(samp, IMG, rtol=1e-3, atol=1e-3)


def test_attention_and_misc():
    q = RNG.randn(2, 4, 2, 8).astype("float32")  # B S H D
    k = RNG.randn(2, 4, 2, 8).astype("float32")
    v = RNG.randn(2, 4, 2, 8).astype("float32")
    out = npv(F.scaled_dot_product_attention(t(q), t(k), t(v)))
    qt, kt, vt = (a.transpose(0, 2, 1, 3) for a in (q, k, v))
    sc = qt @ kt.transpose(0, 1, 3, 2) / np.sqrt(8)
    p = np.exp(sc) / np.exp(sc).sum(-1, keepdims=True)
    want = (p @ vt).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)
    # gather_tree: simple 2-step beam
    ids = np.array([[[1, 2]], [[3, 4]]], "int64")  # (T=2, B=1, beam=2)
    parents = np.array([[[0, 0]], [[1, 0]]], "int64")
    gt = npv(F.gather_tree(t(ids), t(parents)))
    assert gt.shape == (2, 1, 2)
    np.testing.assert_array_equal(gt[:, 0, 0], [2, 3])  # backtracks parent 1


def test_newly_implemented_ops():
    """sparse_attention / rnnt_loss / class_center_sample were stubs until
    this sweep forced real implementations."""
    # sparse_attention with a full CSR layout == dense attention
    B, H, S, D = 1, 2, 4, 8
    q, k, v = (RNG.randn(B, H, S, D).astype("float32") for _ in range(3))
    offs = np.tile(np.arange(0, S * S + 1, S, dtype="int32"), (B, H, 1))
    cols = np.tile(np.tile(np.arange(S, dtype="int32"), S), (B, H, 1))
    sp = npv(F.sparse_attention(t(q), t(k), t(v), t(offs), t(cols)))
    sc = q @ k.transpose(0, 1, 3, 2) / np.sqrt(D)
    p = np.exp(sc) / np.exp(sc).sum(-1, keepdims=True)
    np.testing.assert_allclose(sp, p @ v, rtol=1e-4, atol=1e-5)
    # banded layout: masked-out column contributes nothing
    offs2 = np.tile(np.arange(0, S + 1, dtype="int32"), (B, H, 1))
    cols2 = np.tile(np.arange(S, dtype="int32"), (B, H, 1))  # diagonal only
    spd = npv(F.sparse_attention(t(q), t(k), t(v), t(offs2), t(cols2)))
    np.testing.assert_allclose(spd, v, rtol=1e-4, atol=1e-5)  # softmax of 1

    # rnnt_loss: T=1, U=0 lattice reduces to -log P(blank at (0,0))
    V = 3
    logits = RNG.randn(1, 1, 1, V).astype("float32")
    lp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    loss = npv(F.rnnt_loss(t(logits), t(np.zeros((1, 0), "int32")),
                           t(np.array([1], "int64")),
                           t(np.array([0], "int64"))))
    np.testing.assert_allclose(loss, -lp[0, 0, 0, 0], rtol=1e-4)
    # bigger lattice with per-sample lengths vs a brute-force log-semiring DP
    def _brute(lg1, lb1, T_, U_):
        lp = lg1 - np.log(np.exp(lg1).sum(-1, keepdims=True))
        alpha = np.full((T_, U_ + 1), -np.inf)
        alpha[0, 0] = 0.0
        for t_ in range(T_):
            for u_ in range(U_ + 1):
                if t_ == 0 and u_ == 0:
                    continue
                c = []
                if t_ > 0:
                    c.append(alpha[t_ - 1, u_] + lp[t_ - 1, u_, 0])
                if u_ > 0:
                    c.append(alpha[t_, u_ - 1] + lp[t_, u_ - 1, lb1[u_ - 1]])
                alpha[t_, u_] = np.logaddexp.reduce(c)
        return -(alpha[T_ - 1, U_] + lp[T_ - 1, U_, 0])

    lg = RNG.randn(2, 5, 3, 4).astype("float32")
    lb = RNG.randint(1, 4, (2, 2)).astype("int32")
    tl = np.array([5, 4], "int64")
    ul = np.array([2, 1], "int64")
    got = npv(F.rnnt_loss(t(lg), t(lb), t(tl), t(ul), reduction="none"))
    want = [_brute(lg[b], lb[b], int(tl[b]), int(ul[b])) for b in range(2)]
    np.testing.assert_allclose(got, want, rtol=1e-4)

    # class_center_sample: all positives present, remap consistent
    lab = np.array([3, 9, 3, 7], "int64")
    remapped, sampled = F.class_center_sample(t(lab), 20, 6)
    sam = npv(sampled)
    rem = npv(remapped)
    assert len(sam) == 6 and {3, 7, 9} <= set(sam.tolist())
    np.testing.assert_array_equal(sam[rem], lab)


# --------------------------------------------------------------------------
# surface completeness gate
# --------------------------------------------------------------------------

EXEMPT = {
    "elu_": "in-place alias of elu",
    "relu_": "in-place alias of relu",
    "tanh_": "in-place alias of tanh",
    "softmax_": "in-place alias of softmax",
    "margin_cross_entropy": "TP loss — covered in test_distributed.py ParallelCrossEntropy suite",
}


def test_functional_surface_is_covered():
    import ast
    import os

    src = open(os.path.abspath(__file__)).read()
    surface = {n for n in dir(F) if not n.startswith("_")
               and callable(getattr(F, n))}
    covered = {a[0] for a in ACTS}
    covered |= {n for n in surface if f"F.{n}(" in src}
    missing = surface - covered - set(EXEMPT)
    assert not missing, f"functional ops never swept: {sorted(missing)}"
