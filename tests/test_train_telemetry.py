"""Training telemetry (paddle_tpu/telemetry.py training tier + engine/
checkpointer/chaos wiring): per-step spans AROUND the compiled dispatch,
flight-ring step records, goodput accounting (exactly 1.0 fault-free,
< 1.0 under seeded kills), train_watchdog findings, and the one-timeline
acceptance — training spans and serving request spans on one shared
chrome trace. Quick tier on CPU."""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.parallel.engine import ParallelEngine
from paddle_tpu.telemetry import (TRAIN_RID, GoodputLedger, ServingTelemetry,
                                  SpanTracer, TrainTelemetry, train_watchdog)


def make_batch(cursor):
    rng = np.random.RandomState(100 + cursor)
    return (rng.randn(8, 4).astype("float32"),
            rng.randn(8, 2).astype("float32"))


def make_engine(injector=None, telemetry=None, seed=5):
    paddle.seed(seed)
    m = nn.Linear(4, 2)
    o = optimizer.AdamW(learning_rate=0.05, parameters=m.parameters())
    return ParallelEngine(m, o, loss_fn=nn.functional.mse_loss, donate=False,
                          injector=injector, telemetry=telemetry)


def run_steps(eng, n, start=0):
    for i in range(start, start + n):
        X, y = make_batch(i)
        eng.train_batch(paddle.to_tensor(X), paddle.to_tensor(y))


# --------------------------------------------------------------------------
# Engine wiring
# --------------------------------------------------------------------------

class TestEngineInstrumentation:
    def test_spans_flight_gauges_and_unit_goodput(self):
        tel = TrainTelemetry()
        eng = make_engine(telemetry=tel)
        run_steps(eng, 5)
        names = sorted({s["name"] for s in tel.tracer.spans(TRAIN_RID)})
        assert names == ["device_wait", "dispatch", "host_to_device",
                         "train_step"]
        assert len([s for s in tel.tracer.spans(TRAIN_RID)
                    if s["name"] == "train_step"]) == 5
        ticks = tel.flight.dump()
        assert [t["step"] for t in ticks] == list(range(5))
        assert ticks[0]["prog"] == "train:8x4;8x2"
        # first step compiles; steady state must not
        assert ticks[0]["recompiles"] >= 1
        assert all(t["recompiles"] == 0 for t in ticks[1:])
        reg = tel.registry
        assert reg.counter("train_steps").total() == 5
        assert reg.counter("train_tokens_total").total() == 5 * 8 * 4
        assert reg.gauge("train_tokens_per_s").value() > 0
        assert reg.histogram("train_step_time_s").count() == 5
        assert tel.goodput.ratio() == 1.0
        assert reg.gauge("train_goodput_ratio").value() == 1.0
        assert tel.watchdog() == []
        assert tel.model_params == 4 * 2 + 2

    def test_mfu_gauge_needs_peak_flops(self):
        tel = TrainTelemetry()                       # PT_PEAK_TFLOPS unset
        eng = make_engine(telemetry=tel)
        run_steps(eng, 2)
        assert tel.registry.get("train_mfu") is None or \
            tel.registry.gauge("train_mfu").value() == 0
        tel2 = TrainTelemetry(peak_flops=1e12)
        eng2 = make_engine(telemetry=tel2)
        run_steps(eng2, 2)
        assert tel2.registry.gauge("train_mfu").value() > 0

    def test_no_telemetry_records_nothing(self):
        eng = make_engine(telemetry=None)
        run_steps(eng, 3)
        assert eng.telemetry is None

    def test_snapshot_is_json_serializable(self):
        tel = TrainTelemetry()
        eng = make_engine(telemetry=tel)
        run_steps(eng, 3)
        blob = tel.snapshot()
        json.dumps(blob)
        assert blob["goodput"]["ratio"] == 1.0
        assert blob["flight_ticks"] == 3


class TestFeedAndCheckpointSpans:
    def test_data_feed_and_ckpt_spans_share_the_train_row(self, tmp_path):
        from paddle_tpu.distributed.train_checkpoint import (
            CheckpointableDataFeed, TrainCheckpointer)

        tel = TrainTelemetry()
        eng = make_engine(telemetry=tel)
        feed = CheckpointableDataFeed(make_batch, telemetry=tel)
        ck = TrainCheckpointer(str(tmp_path / "ck"), telemetry=tel)
        for i in range(3):
            X, y = feed.next_batch()
            eng.train_batch(paddle.to_tensor(X), paddle.to_tensor(y))
            ck.save(i, engine=eng, data_feed=feed)
        names = [s["name"] for s in tel.tracer.spans(TRAIN_RID)]
        assert names.count("data_feed") == 3
        assert names.count("ckpt_save") == 3
        assert tel.registry.histogram("train_data_feed_s").count() == 3
        assert tel.registry.histogram("train_ckpt_save_s").count() == 3
        # the feed wall also folds into the NEXT step's flight record
        assert all(t["data_feed_s"] > 0 for t in tel.flight.dump())

        # restore emits its span too
        eng2 = make_engine(telemetry=tel, seed=6)
        feed2 = CheckpointableDataFeed(make_batch, telemetry=tel)
        ck2 = TrainCheckpointer(str(tmp_path / "ck"), telemetry=tel)
        host = ck2.restore(engine=eng2, data_feed=feed2)
        assert host["step"] == 2
        assert [s["name"] for s in tel.tracer.spans(TRAIN_RID)
                ].count("ckpt_restore") == 1
        assert tel.registry.histogram("train_ckpt_restore_s").count() == 1


# --------------------------------------------------------------------------
# Goodput ledger
# --------------------------------------------------------------------------

class TestGoodputLedger:
    def test_fault_free_is_exactly_one(self):
        g = GoodputLedger()
        for i in range(50):
            g.step(i, 0.001 * (i + 1))
        assert g.ratio() == 1.0                     # no float residue
        assert g.snapshot()["lost_steps"] == 0

    def test_replayed_index_books_lost_work(self):
        g = GoodputLedger()
        g.step(0, 2.0)
        g.step(1, 3.0)
        g.step(1, 5.0)                              # replay after rollback
        s = g.snapshot()
        assert s["lost_s"] == 3.0 and s["lost_steps"] == 1
        assert s["total_s"] == 10.0 and s["productive_s"] == 7.0
        assert g.ratio() == pytest.approx(0.7)

    def test_recovery_books_outage_wall(self):
        g = GoodputLedger()
        g.step(0, 6.0)
        g.recovery(2.0)
        s = g.snapshot()
        assert s["recoveries"] == 1 and s["recovery_s"] == 2.0
        assert g.ratio() == pytest.approx(6.0 / 8.0)


# --------------------------------------------------------------------------
# train_watchdog
# --------------------------------------------------------------------------

def _steps(n, wall=0.01, **extra):
    return [dict({"step": i, "seq": i, "prog": "train:8x4;8x2",
                  "t_wall_s": wall, "data_feed_s": 0.0, "recompiles": 0,
                  "ckpt_backoffs": 0}, **extra) for i in range(n)]


class TestTrainWatchdog:
    def test_quiet_run(self):
        recs = _steps(40)
        recs[0]["recompiles"] = 1                   # the warmup compile
        assert train_watchdog(recs) == []

    def test_steady_state_recompile(self):
        recs = _steps(40)
        recs[20]["recompiles"] = 1
        (f,) = train_watchdog(recs)
        assert f["kind"] == "steady_state_recompile" and f["seq"] == 20

    def test_warm_prog_recompile_flagged_at_step_zero(self):
        recs = _steps(6)
        recs[0]["recompiles"] = 1
        (f,) = train_watchdog(recs, warm_progs={"train:8x4;8x2"})
        assert f["kind"] == "steady_state_recompile" and f["seq"] == 0

    def test_step_time_regression(self):
        recs = _steps(30)
        for r in recs[-8:]:
            r["t_wall_s"] = 0.05                    # 5x the 0.01 baseline
        (f,) = train_watchdog(recs)
        assert f["kind"] == "step_time_regression"
        assert f["factor"] == pytest.approx(5.0)

    def test_data_feed_stall(self):
        recs = _steps(32)
        for r in recs[8:24]:
            r["data_feed_s"] = 0.02                 # feed > step wall
        kinds = [f["kind"] for f in train_watchdog(recs)]
        assert kinds == ["data_feed_stall"]

    def test_ckpt_backoff_storm(self):
        recs = _steps(40)
        for i in (10, 12, 14, 16):
            recs[i]["ckpt_backoffs"] = 1
        kinds = [f["kind"] for f in train_watchdog(recs)]
        assert kinds == ["ckpt_backoff_storm"]


# --------------------------------------------------------------------------
# Chaos-harness goodput attribution
# --------------------------------------------------------------------------

class TestChaosGoodput:
    def test_kill_dips_goodput_below_one(self, tmp_path):
        from paddle_tpu.distributed.fleet.chaos import ElasticChaosHarness
        from paddle_tpu.distributed.train_checkpoint import (
            CheckpointableDataFeed, TrainCheckpointer)
        from paddle_tpu.faults import FaultInjector, FaultPlan, FaultSpec

        tel = TrainTelemetry()
        plan = FaultPlan(specs=[FaultSpec("kill", at=3)], seed=3)
        injector = FaultInjector(plan)

        class Run:
            def __init__(self, inj):
                self.eng = make_engine(injector=inj, telemetry=tel)
                self.feed = CheckpointableDataFeed(make_batch, injector=inj,
                                                   telemetry=tel)
                self.ck = TrainCheckpointer(str(tmp_path / "chaos"),
                                            injector=inj, telemetry=tel)

            def restore(self):
                host = self.ck.restore(engine=self.eng, data_feed=self.feed)
                return (host["step"] + 1) if host else 0

            def step(self, i):
                X, y = self.feed.next_batch()
                return float(np.asarray(self.eng.train_batch(
                    paddle.to_tensor(X), paddle.to_tensor(y)).value))

            def save(self, i):
                self.ck.save(i, engine=self.eng, data_feed=self.feed)

        harness = ElasticChaosHarness(
            Run, total_steps=6, injector=injector, max_restarts=2,
            heartbeat_interval=0.05, lease_ttl=0.3, telemetry=tel)
        report = harness.run()
        assert report.completed and report.restarts == 1

        g = tel.goodput.snapshot()
        assert tel.goodput.ratio() < 1.0
        assert g["recoveries"] == 1 and g["recovery_s"] > 0
        # the kill at step 3 rolled back to the step-2 save -> step 3 ran
        # twice; its first run is the lost work
        assert g["lost_steps"] >= 1
        assert tel.registry.gauge("train_goodput_ratio").value() == \
            tel.goodput.ratio()
        assert tel.registry.counter("train_recoveries").total() == 1
        names = [s["name"] for s in tel.tracer.spans(TRAIN_RID)]
        assert names.count("recovery") == 1
        # fresh incarnation recompiled the same prog: the watchdog must
        # SAY so — chaos runs are exactly what the finding is for
        kinds = [f["kind"] for f in tel.watchdog()]
        assert "steady_state_recompile" in kinds

    def test_fault_free_twin_stays_at_one(self):
        tel = TrainTelemetry()
        eng = make_engine(telemetry=tel)
        run_steps(eng, 6)
        assert tel.goodput.ratio() == 1.0
        assert tel.goodput.snapshot()["recoveries"] == 0


# --------------------------------------------------------------------------
# One timeline: train spans + serving request spans in one chrome trace
# --------------------------------------------------------------------------

def test_train_and_serving_spans_share_one_timeline(tmp_path):
    from paddle_tpu.inference.serving import GenerationServer
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    tracer = SpanTracer()
    train_tel = TrainTelemetry(tracer=tracer)
    serve_tel = ServingTelemetry(tracer=tracer)

    eng = make_engine(telemetry=train_tel)
    run_steps(eng, 4)

    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=160,
                      dtype="float32", use_flash_attention=False)
    paddle.seed(7)
    model = LlamaForCausalLM(cfg)
    srv = GenerationServer(model, max_batch=2, max_len=96, cache="paged",
                           block_size=8, prefill_chunk=16,
                           telemetry=serve_tel)
    rng = np.random.RandomState(0)
    rids = [srv.submit(rng.randint(1, 127, size=n).tolist(),
                       max_new_tokens=6) for n in (9, 14)]
    srv.run()

    path = str(tmp_path / "whole_stack.trace.json")
    tracer.export_chrome_trace(path)
    ev = json.load(open(path))
    ev = ev["traceEvents"] if isinstance(ev, dict) else ev
    by_tid = {}
    for e in ev:
        if e.get("ph") == "X":
            by_tid.setdefault(e["tid"], set()).add(e["name"])
    # the reserved train row carries the step phases...
    assert {"train_step", "device_wait"} <= by_tid[TRAIN_RID]
    # ...and request rows carry serving lifecycles on the SAME timeline
    req_rows = [tid for tid in by_tid if tid != TRAIN_RID]
    assert len(req_rows) >= len(rids)
    assert any("decode" in n or "prefill" in n
               for tid in req_rows for n in by_tid[tid])
    # the train row is labeled for humans
    labels = {e["args"]["name"] for e in ev
              if e.get("ph") == "M" and e.get("name") == "thread_name"}
    assert "train loop" in labels
