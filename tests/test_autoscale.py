"""Elastic autoscaler decision engine in isolation.

Every test drives :class:`ElasticAutoscaler.decide` with explicit
observation rows — no fleet, no engine — because the engine's contract
is exactly that: a pure function of (observations, policy, decision
history). The counting-clock test is the determinism keystone the
fleet simulator's byte-identical reports stand on.
"""
import pytest

from paddle_tpu.inference.autoscale import (AutoscalePolicy,
                                            ElasticAutoscaler,
                                            verify_replay)
from paddle_tpu.inference.transport import CountingClock

CAP = 1000.0  # tokens/s per replica


def _engine(**kw):
    kw.setdefault("max_replicas", 8)
    kw.setdefault("target_utilization", 0.8)
    kw.setdefault("up_cooldown_s", 0.0)
    kw.setdefault("down_cooldown_s", 0.0)
    return ElasticAutoscaler(CAP, policy=AutoscalePolicy(**kw))


class TestSizing:
    def test_desired_covers_demand_at_target_utilization(self):
        eng = _engine()
        # 2000 tok/s over 800 effective tok/s per replica -> 3
        assert eng.desired_replicas(2000.0) == 3

    def test_desired_takes_max_of_demand_and_forecast(self):
        eng = _engine()
        assert eng.desired_replicas(100.0, forecast_tok_s=4000.0) == 5

    def test_desired_clamps_to_policy_bounds(self):
        eng = _engine(min_replicas=2, max_replicas=4)
        assert eng.desired_replicas(0.0) == 2
        assert eng.desired_replicas(1e9) == 4

    def test_invalid_policy_rejected(self):
        with pytest.raises(ValueError):
            AutoscalePolicy(min_replicas=0)
        with pytest.raises(ValueError):
            AutoscalePolicy(min_replicas=4, max_replicas=2)
        with pytest.raises(ValueError):
            AutoscalePolicy(target_utilization=1.5)
        with pytest.raises(ValueError):
            ElasticAutoscaler(0.0)


class TestDecisions:
    def test_burn_above_threshold_forces_reactive_up(self):
        # sizing says live is plenty — but a tenant is burning budget,
        # so the SLO overrides the model
        eng = _engine(burn_up=1.0)
        d = eng.decide(0.0, live=2, demand_tok_s=100.0, burn_rate=1.5)
        assert d.action == "up" and d.count == 1
        assert d.reason == "burn_rate"

    def test_burn_below_threshold_defers_to_sizing(self):
        eng = _engine(burn_up=1.0)
        d = eng.decide(0.0, live=2, demand_tok_s=100.0, burn_rate=0.5)
        assert d.action == "hold"

    def test_forecast_leads_the_arrival_curve(self):
        # observed demand fits one replica; the diurnal forecast says
        # the peak is coming — capacity must arrive BEFORE the load
        eng = _engine()
        d = eng.decide(0.0, live=1, demand_tok_s=500.0,
                       forecast_tok_s=3000.0)
        assert d.action == "up" and d.reason == "forecast"
        assert d.desired == 4

    def test_scale_up_respects_max_step(self):
        eng = _engine(max_step_up=2)
        d = eng.decide(0.0, live=1, demand_tok_s=6000.0)
        assert d.action == "up" and d.count == 2

    def test_scale_up_blocked_by_cooldown(self):
        eng = _engine(up_cooldown_s=60.0)
        assert eng.decide(0.0, live=1, demand_tok_s=3000.0).action == "up"
        d = eng.decide(10.0, live=2, demand_tok_s=6000.0)
        assert d.action == "hold" and d.reason == "up_cooldown"
        assert eng.decide(70.0, live=2,
                          demand_tok_s=6000.0).action == "up"

    def test_refuses_to_drain_last_live_replica(self):
        # demand collapses to zero: the arithmetic wants zero replicas,
        # the engine journals the refusal instead of complying
        eng = _engine()
        d = eng.decide(0.0, live=1, demand_tok_s=0.0)
        assert d.action == "hold" and d.reason == "last_replica"

    def test_scale_down_blocked_while_burning(self):
        eng = _engine(burn_down=0.25)
        d = eng.decide(0.0, live=3, demand_tok_s=100.0, burn_rate=0.5)
        assert d.action == "hold" and d.reason == "burn_gate"

    def test_scale_down_one_at_a_time_when_clear(self):
        eng = _engine(burn_down=0.25)
        d = eng.decide(0.0, live=3, demand_tok_s=100.0, burn_rate=0.0)
        assert d.action == "down" and d.count == 1

    def test_scale_down_blocked_by_cooldown(self):
        eng = _engine(down_cooldown_s=600.0)
        assert eng.decide(0.0, live=4,
                          demand_tok_s=100.0).action == "down"
        d = eng.decide(60.0, live=3, demand_tok_s=100.0)
        assert d.action == "hold" and d.reason == "down_cooldown"

    def test_ceiling_blocks_and_is_journaled(self):
        eng = _engine(max_replicas=2)
        d = eng.decide(0.0, live=2, demand_tok_s=1e6)
        assert d.action == "hold" and d.reason == "ceiling"


class TestDeterminism:
    def _drive(self, clock):
        eng = _engine(up_cooldown_s=2.0, down_cooldown_s=5.0)
        rows = [(1, 3000.0, 0.0, 0.0), (2, 3000.0, 0.0, 0.0),
                (3, 6000.0, 0.0, 1.4), (4, 100.0, 0.0, 0.5),
                (5, 100.0, 0.0, 0.0), (6, 100.0, 0.0, 0.0)]
        live = 1
        for _, demand, forecast, burn in rows:
            d = eng.decide(clock(), live=live, demand_tok_s=demand,
                           forecast_tok_s=forecast, burn_rate=burn)
            live += d.count if d.action == "up" else \
                (-d.count if d.action == "down" else 0)
        return [d.as_dict() for d in eng.events]

    def test_identical_decisions_under_counting_clock(self):
        # two fresh engines, two fresh clocks, same observation rows
        # -> identical journals: the whole byte-identical-sim contract
        a = self._drive(CountingClock(dt=1.0))
        b = self._drive(CountingClock(dt=1.0))
        assert a == b
        assert any(d["action"] == "up" for d in a)

    def test_verify_replay_accepts_own_journal(self):
        events = self._drive(CountingClock(dt=1.0))
        assert verify_replay(
            events, CAP,
            policy=AutoscalePolicy(max_replicas=8,
                                   target_utilization=0.8,
                                   up_cooldown_s=2.0,
                                   down_cooldown_s=5.0))

    def test_verify_replay_rejects_tampered_journal(self):
        events = self._drive(CountingClock(dt=1.0))
        events[0]["action"] = "down"
        with pytest.raises(AssertionError):
            verify_replay(
                events, CAP,
                policy=AutoscalePolicy(max_replicas=8,
                                       target_utilization=0.8,
                                       up_cooldown_s=2.0,
                                       down_cooldown_s=5.0))


class TestTelemetry:
    def test_decisions_and_blocks_counted(self):
        eng = _engine(max_replicas=2)
        eng.decide(0.0, live=1, demand_tok_s=3000.0)      # up
        eng.decide(1.0, live=2, demand_tok_s=1e6)          # ceiling
        eng.decide(2.0, live=1, demand_tok_s=0.0)          # last_replica
        dec = eng.registry.get("fleet_autoscale_decisions")
        blocked = eng.registry.get("fleet_autoscale_blocked")
        assert dec.value(action="up") == 1
        assert dec.value(action="hold") == 2
        assert blocked.value(reason="ceiling") == 1
        assert blocked.value(reason="last_replica") == 1
        assert eng.registry.get(
            "fleet_autoscale_desired_replicas").value() == 1.0

    def test_gauges_track_last_observation(self):
        eng = _engine()
        eng.decide(5.0, live=3, demand_tok_s=1234.0,
                   forecast_tok_s=2500.0, burn_rate=0.125)
        reg = eng.registry
        assert reg.get("fleet_autoscale_live_replicas").value() == 3.0
        assert reg.get("fleet_autoscale_demand_tok_s").value() == 1234.0
        assert reg.get(
            "fleet_autoscale_forecast_tok_s").value() == 2500.0
        assert reg.get("fleet_autoscale_burn_rate").value() == 0.125
