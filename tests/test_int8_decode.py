"""Weight-only int8 decode (ops/int8.py, nn.quant.Int8Linear,
LlamaForCausalLM.quantize_int8; ref fused_multi_transformer_int8 weight-only
path)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM


class TestW8Matmul:
    def test_quantize_roundtrip_error(self):
        import jax.numpy as jnp

        from paddle_tpu.ops.int8 import quantize_per_channel

        rng = np.random.RandomState(0)
        w = rng.randn(64, 32).astype("float32")
        w_q, scale = quantize_per_channel(w)
        assert w_q.dtype == jnp.int8 and scale.shape == (32,)
        deq = np.asarray(w_q, np.float32) * np.asarray(scale)[None, :]
        # absmax symmetric per channel: max error bounded by scale/2
        err = np.abs(deq - w)
        assert (err <= np.asarray(scale)[None, :] * 0.5 + 1e-6).all()

    def test_w8_matmul_matches_dequant_reference(self):
        from paddle_tpu.ops.int8 import quantize_per_channel, w8_matmul

        rng = np.random.RandomState(1)
        x = rng.randn(4, 7, 64).astype("float32")
        w = rng.randn(64, 128).astype("float32")
        w_q, scale = quantize_per_channel(w)
        out = np.asarray(w8_matmul(x, w_q, scale))
        ref = x @ (np.asarray(w_q, np.float32) * np.asarray(scale)[None, :])
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


class TestInt8Llama:
    def _model(self):
        cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=96,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, max_position_embeddings=64,
                          dtype="float32", use_flash_attention=False,
                          tie_word_embeddings=False)
        paddle.seed(0)
        return LlamaForCausalLM(cfg)

    def test_quantized_logits_close_to_full(self):
        m = self._model()
        ids = paddle.to_tensor(np.arange(12, dtype="int32").reshape(1, 12) % 128)
        full = np.asarray(m(ids).value)
        m.quantize_int8()
        quant = np.asarray(m(ids).value)
        # int8 weight-only: logits track the full model closely
        denom = np.maximum(np.abs(full).max(), 1e-6)
        assert np.abs(quant - full).max() / denom < 0.05

    def test_quantized_generate_runs_greedy(self):
        m = self._model()
        ids = paddle.to_tensor(np.array([[5, 7, 11]], dtype="int32"))
        ref = np.asarray(m.generate(ids, max_new_tokens=6).value)
        m.quantize_int8()
        out = np.asarray(m.generate(ids, max_new_tokens=6).value)
        assert out.shape == (1, 9)
        np.testing.assert_array_equal(out[:, :3], ref[:, :3])  # prompt kept
        assert (out >= 0).all() and (out < 128).all()

    def test_int8_state_is_int8(self):
        import jax.numpy as jnp

        from paddle_tpu.jit import state_values

        m = self._model().quantize_int8()
        sv = state_values(m)
        q_keys = [k for k in sv if k.endswith("weight_q")]
        assert len(q_keys) == 2 * 7 + 1  # 7 projections per layer + lm_head
        assert all(sv[k].dtype == jnp.int8 for k in q_keys)
        # float projection weights are gone from the state
        assert not any(k.endswith("q_proj.weight") for k in sv)

    def test_params_bytes_halved(self):
        m = self._model()
        def nbytes(model):
            from paddle_tpu.jit import state_values

            return sum(np.asarray(v).nbytes for k, v in state_values(model).items()
                       if "embed" not in k)
        before = nbytes(m)
        m.quantize_int8()
        after = nbytes(m)
        assert after < before * 0.5 * 1.2  # int8 + f32 scales ≈ quarter of f32


def test_w8_pallas_kernel_interpreted_matches_jnp():
    """The Pallas w8 kernel logic itself (BlockSpec maps, scale layout) via
    interpret mode — the path the TPU tier compiles with Mosaic."""
    import os

    import jax.numpy as jnp

    from paddle_tpu.ops.int8 import _w8_matmul_pallas, quantize_per_channel

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 256).astype("float32")).astype(jnp.bfloat16)
    w = jnp.asarray(rng.randn(256, 512).astype("float32") * 0.05)
    wq, scale = quantize_per_channel(w)
    os.environ["PT_FLASH_INTERPRET"] = "1"
    try:
        got = _w8_matmul_pallas(x, wq, scale, jnp.float32)
    finally:
        os.environ.pop("PT_FLASH_INTERPRET", None)
    want = (x.astype(jnp.float32) @
            (wq.astype(jnp.float32) * scale[None, :]))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-2, atol=1e-2)


def test_fused_qkv_matches_unfused(monkeypatch):
    """PT_W8_FUSED_QKV=1 concatenates q/k/v into one int8 matmul; greedy
    generation must match the unfused int8 path exactly (per-channel scales
    are column-independent, so the quantization is identical)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=64,
                      dtype="float32", use_flash_attention=False)
    rng = np.random.RandomState(3)
    ids = paddle.to_tensor(rng.randint(1, 128, (2, 12)).astype("int32"))

    paddle.seed(5)
    m1 = LlamaForCausalLM(cfg)
    sd = {k: np.array(np.asarray(v.value)) for k, v in m1.state_dict().items()}
    monkeypatch.delenv("PT_W8_FUSED_QKV", raising=False)
    out1 = np.asarray(m1.quantize_int8().generate(ids, max_new_tokens=8).value)

    paddle.seed(5)
    m2 = LlamaForCausalLM(cfg)
    m2.set_state_dict({k: paddle.to_tensor(v) for k, v in sd.items()})
    monkeypatch.setenv("PT_W8_FUSED_QKV", "1")
    out2 = np.asarray(m2.quantize_int8().generate(ids, max_new_tokens=8).value)
    np.testing.assert_array_equal(out1, out2)
    # the bf16 projections are really gone (no double weight stream):
    # check the PARAMETER store, where the dropped Linears lived
    att = m2.model.layers[0].self_attn
    pnames = [n for n, _ in att.named_parameters()]
    assert not any(p in n for n in pnames
                   for p in ("q_proj", "k_proj", "v_proj")), pnames
    bnames = [n for n, _ in att.named_buffers()]
    assert any("qkv_fused" in n for n in bnames), bnames


class TestW8PathHeuristic:
    """Pin WHICH program w8_matmul picks per shape — the M<=16 reuse gate
    (ops/int8.py:106-114): single-token decode batches stream int8 weights
    through the Pallas kernel; prefill/training shapes (M large, each
    weight block reused M times) must take the dequantize-once XLA path."""

    def _spy(self, monkeypatch):
        from paddle_tpu.ops import int8 as int8_mod

        calls = []
        real = int8_mod._w8_matmul_pallas

        def spy(x2, w_q, scale, out_dtype, block_n=0):
            calls.append(x2.shape)
            return real(x2, w_q, scale, out_dtype, block_n)

        monkeypatch.setattr(int8_mod, "_w8_matmul_pallas", spy)
        return calls

    def _run(self, M, K, N):
        from paddle_tpu.ops.int8 import quantize_per_channel, w8_matmul

        rng = np.random.RandomState(0)
        x = rng.randn(M, K).astype("float32")
        w_q, scale = quantize_per_channel(rng.randn(K, N).astype("float32"))
        out = np.asarray(w8_matmul(x, w_q, scale))
        ref = x @ (np.asarray(w_q, np.float32) * np.asarray(scale)[None, :])
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3)

    def test_decode_shape_streams(self, monkeypatch):
        # M<=16, aligned K/N: the weight-read-bound regime → Pallas path
        monkeypatch.setenv("PT_FLASH_INTERPRET", "1")
        calls = self._spy(monkeypatch)
        self._run(16, 128, 128)
        assert calls == [(16, 128)]

    def test_prefill_shape_dequantizes_once(self, monkeypatch):
        # M>16 (prefill/training: weights reused M times) → XLA dequant
        monkeypatch.setenv("PT_FLASH_INTERPRET", "1")
        calls = self._spy(monkeypatch)
        self._run(32, 128, 128)
        assert calls == []

    def test_unaligned_k_falls_back(self, monkeypatch):
        # K not a lane multiple can't tile the MXU → XLA dequant
        monkeypatch.setenv("PT_FLASH_INTERPRET", "1")
        calls = self._spy(monkeypatch)
        self._run(8, 96, 128)
        assert calls == []

    def test_cpu_without_interpret_dequantizes(self, monkeypatch):
        # no TPU and no interpret flag: _use_pallas() is False even at
        # decode shapes — the gate must consult the backend, not just M
        monkeypatch.delenv("PT_FLASH_INTERPRET", raising=False)
        calls = self._spy(monkeypatch)
        self._run(8, 128, 128)
        assert calls == []
