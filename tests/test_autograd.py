"""Autograd engine tests (ref: eager backward semantics, numeric grad checks
à la op_test.py check_grad)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def numeric_grad(f, x, eps=1e-3):
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        xp = x.copy()
        xp[idx] += eps
        xm = x.copy()
        xm[idx] -= eps
        g[idx] = (f(xp) - f(xm)) / (2 * eps)
        it.iternext()
    return g


class TestBackward:
    def test_simple_chain(self):
        x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
        y = (x * x + x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0, 7.0], rtol=1e-5)

    def test_numeric_grad_check(self):
        a = np.random.randn(3, 3).astype(np.float32)

        def f(x):
            return float(np.sum(np.tanh(x @ x.T)))

        t = paddle.to_tensor(a, stop_gradient=False)
        out = paddle.tanh(paddle.matmul(t, t, transpose_y=True)).sum()
        out.backward()
        ref = numeric_grad(f, a.astype(np.float64))
        np.testing.assert_allclose(t.grad.numpy(), ref, rtol=1e-2, atol=1e-3)

    def test_grad_accumulation(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        (x * 2).backward()
        (x * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0])
        x.clear_grad()
        assert x.grad is None

    def test_stop_gradient(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = paddle.to_tensor([2.0], stop_gradient=True)
        (x * y).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])
        assert y.grad is None

    def test_detach(self):
        x = paddle.to_tensor([3.0], stop_gradient=False)
        y = x * 2
        z = y.detach() * x
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0])

    def test_multi_output_op(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                             stop_gradient=False)
        parts = paddle.split(x, 3, axis=1)
        loss = parts[0].sum() + 2 * parts[2].sum()
        loss.backward()
        ref = np.array([[1, 0, 2], [1, 0, 2]], np.float32)
        np.testing.assert_allclose(x.grad.numpy(), ref)

    def test_retain_graph(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * x
        y.backward(retain_graph=True)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [8.0])

    def test_double_backward_raises(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * x
        y.backward()
        with pytest.raises(RuntimeError):
            y.backward()

    def test_hooks(self):
        x = paddle.to_tensor([1.0, 1.0], stop_gradient=False)
        seen = []

        def hook(g):
            seen.append(g.numpy().copy())
            return g * 2

        x.register_hook(hook)
        (x * 3).sum().backward()
        assert len(seen) == 1
        np.testing.assert_allclose(x.grad.numpy(), [6.0, 6.0])

    def test_paddle_grad_api(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * x * x
        (g,) = paddle.grad(y, x)
        np.testing.assert_allclose(g.numpy(), [12.0], rtol=1e-5)
        assert x.grad is None  # paddle.grad must not pollute .grad slots

    def test_no_grad(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        with paddle.no_grad():
            y = x * 2
        assert y.stop_gradient

    def test_backward_nonscalar_with_grad_tensor(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = x * 3
        y.backward(paddle.to_tensor([1.0, 10.0]))
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 30.0])


class TestPyLayer:
    def test_custom_forward_backward(self):
        from paddle_tpu.autograd import PyLayer

        class Cube(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x * x

            @staticmethod
            def backward(ctx, grad):
                (x,) = ctx.saved_tensor
                return grad * 3 * x * x

        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = Cube.apply(x)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [12.0], rtol=1e-5)

    def test_recompute(self):
        from paddle_tpu.distributed.fleet import recompute

        x = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32),
                             stop_gradient=False)

        def block(v):
            return paddle.tanh(paddle.matmul(v, v)).sum()

        y = recompute(block, x)
        y.backward()
        g1 = x.grad.numpy().copy()

        x2 = paddle.to_tensor(x.numpy(), stop_gradient=False)
        block(x2).backward()
        np.testing.assert_allclose(g1, x2.grad.numpy(), rtol=1e-4, atol=1e-5)
