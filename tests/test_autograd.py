"""Autograd engine tests (ref: eager backward semantics, numeric grad checks
à la op_test.py check_grad)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def numeric_grad(f, x, eps=1e-3):
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        xp = x.copy()
        xp[idx] += eps
        xm = x.copy()
        xm[idx] -= eps
        g[idx] = (f(xp) - f(xm)) / (2 * eps)
        it.iternext()
    return g


class TestBackward:
    def test_simple_chain(self):
        x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
        y = (x * x + x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0, 7.0], rtol=1e-5)

    def test_numeric_grad_check(self):
        a = np.random.randn(3, 3).astype(np.float32)

        def f(x):
            return float(np.sum(np.tanh(x @ x.T)))

        t = paddle.to_tensor(a, stop_gradient=False)
        out = paddle.tanh(paddle.matmul(t, t, transpose_y=True)).sum()
        out.backward()
        ref = numeric_grad(f, a.astype(np.float64))
        np.testing.assert_allclose(t.grad.numpy(), ref, rtol=1e-2, atol=1e-3)

    def test_grad_accumulation(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        (x * 2).backward()
        (x * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0])
        x.clear_grad()
        assert x.grad is None

    def test_stop_gradient(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = paddle.to_tensor([2.0], stop_gradient=True)
        (x * y).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])
        assert y.grad is None

    def test_detach(self):
        x = paddle.to_tensor([3.0], stop_gradient=False)
        y = x * 2
        z = y.detach() * x
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0])

    def test_multi_output_op(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                             stop_gradient=False)
        parts = paddle.split(x, 3, axis=1)
        loss = parts[0].sum() + 2 * parts[2].sum()
        loss.backward()
        ref = np.array([[1, 0, 2], [1, 0, 2]], np.float32)
        np.testing.assert_allclose(x.grad.numpy(), ref)

    def test_retain_graph(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * x
        y.backward(retain_graph=True)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [8.0])

    def test_double_backward_raises(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * x
        y.backward()
        with pytest.raises(RuntimeError):
            y.backward()

    def test_hooks(self):
        x = paddle.to_tensor([1.0, 1.0], stop_gradient=False)
        seen = []

        def hook(g):
            seen.append(g.numpy().copy())
            return g * 2

        x.register_hook(hook)
        (x * 3).sum().backward()
        assert len(seen) == 1
        np.testing.assert_allclose(x.grad.numpy(), [6.0, 6.0])

    def test_paddle_grad_api(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * x * x
        (g,) = paddle.grad(y, x)
        np.testing.assert_allclose(g.numpy(), [12.0], rtol=1e-5)
        assert x.grad is None  # paddle.grad must not pollute .grad slots

    def test_no_grad(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        with paddle.no_grad():
            y = x * 2
        assert y.stop_gradient

    def test_backward_nonscalar_with_grad_tensor(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = x * 3
        y.backward(paddle.to_tensor([1.0, 10.0]))
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 30.0])


class TestPyLayer:
    def test_custom_forward_backward(self):
        from paddle_tpu.autograd import PyLayer

        class Cube(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x * x

            @staticmethod
            def backward(ctx, grad):
                (x,) = ctx.saved_tensor
                return grad * 3 * x * x

        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = Cube.apply(x)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [12.0], rtol=1e-5)

    def test_recompute(self):
        from paddle_tpu.distributed.fleet import recompute

        x = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32),
                             stop_gradient=False)

        def block(v):
            return paddle.tanh(paddle.matmul(v, v)).sum()

        y = recompute(block, x)
        y.backward()
        g1 = x.grad.numpy().copy()

        x2 = paddle.to_tensor(x.numpy(), stop_gradient=False)
        block(x2).backward()
        np.testing.assert_allclose(g1, x2.grad.numpy(), rtol=1e-4, atol=1e-5)


class TestCreateGraph:
    """Higher-order autograd: paddle.grad(create_graph=True) records the
    backward pass on the tape (each vjp re-linearized through dispatch), so
    grads are differentiable — ref eager GeneralGrad double-grad tests."""

    def test_double_grad_polynomial(self):
        x = paddle.to_tensor(np.array([2.0, 3.0], "float32"),
                             stop_gradient=False)
        y = paddle.sum(x ** 3)
        (g,) = paddle.grad(y, [x], create_graph=True)
        np.testing.assert_allclose(np.asarray(g.value), [12.0, 27.0],
                                   rtol=1e-6)
        z = paddle.sum(g * g)  # sum(9 x^4)
        (gg,) = paddle.grad(z, [x])
        np.testing.assert_allclose(np.asarray(gg.value), [288.0, 972.0],
                                   rtol=1e-5)

    def test_double_grad_matches_jax_on_mlp(self):
        import jax
        import jax.numpy as jnp

        rng = np.random.RandomState(0)
        w0 = rng.randn(4, 8).astype("float32")
        x0 = rng.randn(2, 4).astype("float32")

        w = paddle.to_tensor(w0, stop_gradient=False)
        x = paddle.to_tensor(x0, stop_gradient=False)
        y = paddle.sum(paddle.tanh(x.matmul(w)) ** 2)
        (gw,) = paddle.grad(y, [w], create_graph=True)
        z = paddle.sum(gw ** 2)
        (ggw,) = paddle.grad(z, [w])

        def inner(wv):
            return jnp.sum(jnp.tanh(jnp.asarray(x0) @ wv) ** 2)

        ref = jax.grad(lambda wv: jnp.sum(jax.grad(inner)(wv) ** 2))(
            jnp.asarray(w0))
        np.testing.assert_allclose(np.asarray(ggw.value), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_gradient_penalty_trains(self):
        """WGAN-GP-style use: the grad-norm penalty participates in a
        backward pass end to end."""
        import paddle_tpu.nn as nn
        from paddle_tpu.optimizer import SGD

        paddle.seed(0)
        m = nn.Linear(4, 1)
        opt = SGD(learning_rate=0.1, parameters=m.parameters())
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 4)
                             .astype("float32"), stop_gradient=False)
        out = paddle.sum(m(x))
        (gx,) = paddle.grad(out, [x], create_graph=True)
        gp = paddle.mean((paddle.sqrt(paddle.sum(gx ** 2, axis=1)) - 1) ** 2)
        gp.backward()
        assert m.weight.grad is not None
        g = np.asarray(m.weight.grad.value)
        assert np.isfinite(g).all() and np.abs(g).sum() > 0
        opt.step()

    def test_without_create_graph_still_fails_cleanly(self):
        x = paddle.to_tensor(np.array([2.0], "float32"), stop_gradient=False)
        y = paddle.sum(x ** 3)
        (g,) = paddle.grad(y, [x])  # no create_graph: grad is detached
        with pytest.raises(RuntimeError):
            paddle.grad(paddle.sum(g * g), [x])
