"""Model-level MoE (LlamaConfig.moe_num_experts > 0): the EP axis gets the
same model-integrated treatment CP/Ulysses got — Mixtral-style SwiGLU
experts slotted into the decoder FFN, GShard top-k routing, aux loss folded
into the LM loss, expert weights sharded over the 'expert' mesh axis.

Ref: incubate moe_layer.py primitives (already covered) composed into the
flagship model family; the reference has no in-tree MoE transformer."""
import jax
import numpy as np
import pytest
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.optimizer import AdamW
from paddle_tpu.parallel import ParallelEngine


def _cfg(**kw):
    return LlamaConfig(**{**dict(
        vocab_size=128, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, dtype="float32",
        use_flash_attention=False, tie_word_embeddings=False,
        moe_num_experts=4, moe_top_k=2), **kw})


def _batches(cfg, n=4, B=4, S=16):
    rng = np.random.RandomState(0)
    return [(rng.randint(0, cfg.vocab_size, (B, S)).astype("int32"),
             rng.randint(0, cfg.vocab_size, (B, S)).astype("int64"))
            for _ in range(n)]


def _train(cfg, mesh, batches):
    paddle.seed(11)
    model = LlamaForCausalLM(cfg)
    opt = AdamW(learning_rate=5e-3, parameters=model.parameters())
    eng = ParallelEngine(model, optimizer=opt, loss_fn=None, mesh=mesh)
    losses = [float(np.asarray(eng.train_batch(x, y).value))
              for x, y in batches]
    eng.sync_to_model()
    return losses, {k: np.asarray(v.value)
                    for k, v in model.state_dict().items()}


def test_moe_llama_trains_fused_ce_with_aux():
    cfg = _cfg(fused_lm_head_ce=True)
    x, y = _batches(cfg, n=1)[0]
    losses, w = _train(cfg, None, [( x, y)] * 6)
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    # expert weights exist and are the Mixtral SwiGLU shape
    names = [k for k in w if ".moe.experts.w3" in k]
    assert len(names) == cfg.num_hidden_layers


def test_moe_aux_loss_reaches_training():
    """With a huge aux coefficient the loss must move measurably — proves
    the gate loss is actually wired into the LM objective."""
    cfg_small = _cfg(moe_aux_coeff=0.0)
    cfg_big = _cfg(moe_aux_coeff=100.0)
    (x, y) = _batches(cfg_small, n=1)[0]
    paddle.seed(3)
    m1 = LlamaForCausalLM(cfg_small)
    paddle.seed(3)
    m2 = LlamaForCausalLM(cfg_big)
    l1 = float(np.asarray(m1(paddle.to_tensor(x), paddle.to_tensor(y)).value))
    l2 = float(np.asarray(m2(paddle.to_tensor(x), paddle.to_tensor(y)).value))
    assert l2 > l1 + 1.0, (l1, l2)


def test_moe_llama_ep_mesh_parity():
    """data2 × expert2: expert-sharded training must match single-device on
    values (the dispatch math is identical; GSPMD only moves it)."""
    cfg = _cfg()
    batches = _batches(cfg)
    ref_l, ref_w = _train(cfg, None, batches)
    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("data", "expert"))
    ep_l, ep_w = _train(cfg, mesh, batches)
    np.testing.assert_allclose(ep_l, ref_l, rtol=1e-4, atol=1e-5)
    for k in ref_w:
        np.testing.assert_allclose(ep_w[k], ref_w[k], rtol=1e-3, atol=2e-5,
                                   err_msg=k)


def test_moe_every_interleaves_dense_layers():
    cfg = _cfg(num_hidden_layers=4, moe_every=2)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    kinds = ["moe" if hasattr(layer.mlp, "moe") else "dense"
             for layer in model.model.layers]
    assert kinds == ["moe", "dense", "moe", "dense"], kinds


def test_moe_llama_generate_smoke():
    cfg = _cfg()
    paddle.seed(5)
    model = LlamaForCausalLM(cfg)
    rng = np.random.RandomState(0)
    prompt = paddle.to_tensor(rng.randint(0, 128, (2, 8)).astype("int32"))
    out = model.generate(prompt, max_new_tokens=4)
    assert np.asarray(out.value).shape == (2, 12)


def test_loss_fn_path_includes_aux():
    """ParallelEngine(loss_fn=model.loss_fn) must train the router too:
    loss_fn folds the recorded gate aux in (review r5 finding)."""
    cfg = _cfg(moe_aux_coeff=100.0, fused_lm_head_ce=False)
    (x, y) = _batches(cfg, n=1)[0]
    paddle.seed(3)
    m = LlamaForCausalLM(cfg)
    logits = m(paddle.to_tensor(x))
    with_aux = float(np.asarray(m.loss_fn(
        logits, paddle.to_tensor(y)).value))
    m.cfg.moe_aux_coeff = 0.0
    without = float(np.asarray(m.loss_fn(
        logits, paddle.to_tensor(y)).value))
    assert with_aux > without + 1.0, (with_aux, without)


def test_moe_rejects_eager_recompute():
    with pytest.raises(ValueError, match="recompute"):
        LlamaForCausalLM(_cfg(recompute=True))
