"""Tests for fleet static meta-optimizers, fleet dataset/data_generator, and
the new incubate modules (autotune / auto_checkpoint / multiprocessing),
plus sysconfig/onnx surfaces (SURVEY §2 inventory items)."""
import os
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.distributed import fleet


# --------------------------------------------------------------------------- #
# fleet static meta-optimizers
# --------------------------------------------------------------------------- #


@pytest.fixture
def _static_mode():
    paddle.enable_static()
    static.reset_default_programs()
    yield
    paddle.disable_static()


def test_fleet_static_meta_optimizers_apply_and_train(_static_mode):
    strat = fleet.DistributedStrategy()
    strat.amp = True
    strat.recompute = True
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
    fleet.init(is_collective=True, strategy=strat)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 8], "float32")
        loss = paddle.mean(static.nn.fc(x, 4) ** 2)
        opt = fleet.distributed_optimizer(paddle.optimizer.SGD(0.1))
        opt.minimize(loss)
    assert opt.applied_meta_optimizers == ["amp", "recompute", "gradient_merge"]
    exe = static.Executor()
    exe.run(startup)
    xs = np.random.RandomState(0).randn(8, 8).astype("float32")
    losses = [float(exe.run(main, feed={"x": xs}, fetch_list=[loss])[0])
              for _ in range(6)]
    assert losses[4] < losses[0]  # optimization proceeds through the stack
    assert losses[0] == pytest.approx(losses[1])  # k=2 merge: step parity


# --------------------------------------------------------------------------- #
# fleet data_generator / dataset
# --------------------------------------------------------------------------- #


from paddle_tpu.distributed.fleet.data_generator import MultiSlotDataGenerator


class _SlotGen(MultiSlotDataGenerator):
    def generate_sample(self, line):
        def it():
            if line is None:
                return
            vals = [float(x) for x in line.split()]
            yield [("x", vals[:-1]), ("y", vals[-1:])]
        return it


def _write_slot_file(tmp_path, n=10, width=4):
    fn = tmp_path / "slots.txt"
    with open(fn, "w") as f:
        for i in range(n):
            f.write(" ".join(str(i + j) for j in range(width)) + f" {i}\n")
    return str(fn)


def test_inmemory_dataset_load_shuffle_iterate(tmp_path):
    fn = _write_slot_file(tmp_path)
    ds = fleet.InMemoryDataset()
    ds.init(batch_size=4, use_var=["x", "y"])
    ds.set_filelist([fn])
    ds.set_generator(_SlotGen())
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 10
    batches = list(ds)
    assert [b["x"].shape for b in batches] == [(4, 4), (4, 4), (2, 4)]
    first_before = batches[0]["y"][:, 0].tolist()
    ds.local_shuffle(seed=7)
    shuffled = list(ds)[0]["y"][:, 0].tolist()
    assert sorted(first_before) != shuffled or first_before != shuffled
    ds.release_memory()
    assert ds.get_memory_data_size() == 0


def test_queue_dataset_streams_without_materializing(tmp_path):
    fn = _write_slot_file(tmp_path, n=6)
    ds = fleet.QueueDataset()
    ds.init(batch_size=3, use_var=["x", "y"])
    ds.set_filelist([fn])
    ds.set_generator(_SlotGen())
    batches = list(ds)
    assert len(batches) == 2
    np.testing.assert_allclose(batches[0]["y"][:, 0], [0, 1, 2])


def test_data_generator_gen_str_protocol():
    g = _SlotGen()
    s = g._gen_str([("x", [1.0, 2.0]), ("y", [3.0])])
    assert s == "2 1.0 2.0 1 3.0\n"


# --------------------------------------------------------------------------- #
# incubate.autotune / checkpoint / multiprocessing
# --------------------------------------------------------------------------- #


def test_autotune_set_get_config(tmp_path):
    from paddle_tpu.incubate import autotune

    autotune.set_config({"dataloader": {"enable": True, "tuning_steps": 99}})
    cfg = autotune.get_config()
    assert cfg["dataloader"]["tuning_steps"] == 99
    with pytest.raises(ValueError):
        autotune.set_config({"nonsense": {}})
    p = tmp_path / "cfg.json"
    p.write_text('{"kernel": {"enable": false}}')
    autotune.set_config(str(p))
    assert autotune.get_config()["kernel"]["enable"] is False


def test_auto_checkpoint_epoch_resume(tmp_path, monkeypatch):
    import paddle_tpu.incubate.checkpoint.auto_checkpoint as acp

    monkeypatch.setenv("PADDLE_CHECKPOINT_DIR", str(tmp_path))
    acp.g_checker = None
    done = []
    for e in acp.train_epoch_range(5, name="job"):
        done.append(e)
        if e == 2:
            break  # crash mid-epoch-2
    acp.g_checker = None
    resumed = list(acp.train_epoch_range(5, name="job"))
    # epochs 0,1 completed; epoch 2 was interrupted before bookkeeping -> re-run
    assert done == [0, 1, 2]
    assert resumed == [2, 3, 4]


def test_auto_checkpoint_save_restore_fns(tmp_path, monkeypatch):
    import paddle_tpu.incubate.checkpoint.auto_checkpoint as acp

    monkeypatch.setenv("PADDLE_CHECKPOINT_DIR", str(tmp_path))
    acp.g_checker = None
    state = {"w": 0}
    saved = {}

    def save_fn(path):
        os.makedirs(path, exist_ok=True)
        saved.update(state)

    def restore_fn(path):
        state.update(saved)

    for e in acp.train_epoch_range(3, name="j2", save_checkpoint_inter=0,
                                   save_fn=save_fn, restore_fn=restore_fn):
        state["w"] = e + 1
    assert saved["w"] == 3  # final forced snapshot saw the last epoch's state


def test_multiprocessing_shm_reduction_roundtrip():
    from multiprocessing.reduction import ForkingPickler

    import paddle_tpu.incubate.multiprocessing  # noqa: F401 (registers)

    t = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    buf = ForkingPickler.dumps(t)
    t2 = pickle.loads(buf)
    np.testing.assert_allclose(t2.numpy(), t.numpy())
    assert bool(t2.stop_gradient) == bool(t.stop_gradient)


# --------------------------------------------------------------------------- #
# sysconfig / onnx
# --------------------------------------------------------------------------- #


def test_sysconfig_paths():
    inc, lib = paddle.sysconfig.get_include(), paddle.sysconfig.get_lib()
    assert os.path.isdir(inc) and os.path.isdir(lib)


def test_onnx_export_gated_without_onnx_pkg():
    try:
        import onnx  # noqa: F401
        pytest.skip("onnx installed; gating not applicable")
    except ImportError:
        pass
    layer = paddle.nn.Linear(4, 2)
    with pytest.raises(ImportError, match="jit.save"):
        paddle.onnx.export(layer, "/tmp/should_not_exist")


def test_distributed_fused_lamb_steps():
    """ref incubate/optimizer/distributed_fused_lamb.py — LAMB math with
    state sharding delegated to the engine's GSPMD layout."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.incubate import DistributedFusedLamb

    paddle.seed(0)
    m = nn.Linear(4, 3)
    opt = DistributedFusedLamb(learning_rate=0.05,
                               parameters=m.parameters())
    before = np.array(m.weight.numpy())
    x = paddle.to_tensor(np.random.RandomState(0).rand(8, 4).astype("float32"))
    for _ in range(3):
        loss = paddle.mean(paddle.square(m(x)))
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert not np.allclose(before, m.weight.numpy())
    assert float(loss) < 1.0
