"""Tests for fleet static meta-optimizers, fleet dataset/data_generator, and
the new incubate modules (autotune / auto_checkpoint / multiprocessing),
plus sysconfig/onnx surfaces (SURVEY §2 inventory items)."""
import os
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.distributed import fleet


# --------------------------------------------------------------------------- #
# fleet static meta-optimizers
# --------------------------------------------------------------------------- #


@pytest.fixture
def _static_mode():
    paddle.enable_static()
    static.reset_default_programs()
    yield
    paddle.disable_static()


def test_fleet_static_meta_optimizers_apply_and_train(_static_mode):
    strat = fleet.DistributedStrategy()
    strat.amp = True
    strat.recompute = True
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
    fleet.init(is_collective=True, strategy=strat)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 8], "float32")
        loss = paddle.mean(static.nn.fc(x, 4) ** 2)
        opt = fleet.distributed_optimizer(paddle.optimizer.SGD(0.1))
        opt.minimize(loss)
    assert opt.applied_meta_optimizers == ["amp", "recompute", "gradient_merge"]
    exe = static.Executor()
    exe.run(startup)
    xs = np.random.RandomState(0).randn(8, 8).astype("float32")
    losses = [float(exe.run(main, feed={"x": xs}, fetch_list=[loss])[0])
              for _ in range(6)]
    assert losses[4] < losses[0]  # optimization proceeds through the stack
    assert losses[0] == pytest.approx(losses[1])  # k=2 merge: step parity


# --------------------------------------------------------------------------- #
# fleet data_generator / dataset
# --------------------------------------------------------------------------- #


from paddle_tpu.distributed.fleet.data_generator import MultiSlotDataGenerator


class _SlotGen(MultiSlotDataGenerator):
    def generate_sample(self, line):
        def it():
            if line is None:
                return
            vals = [float(x) for x in line.split()]
            yield [("x", vals[:-1]), ("y", vals[-1:])]
        return it


def _write_slot_file(tmp_path, n=10, width=4):
    fn = tmp_path / "slots.txt"
    with open(fn, "w") as f:
        for i in range(n):
            f.write(" ".join(str(i + j) for j in range(width)) + f" {i}\n")
    return str(fn)


def test_inmemory_dataset_load_shuffle_iterate(tmp_path):
    fn = _write_slot_file(tmp_path)
    ds = fleet.InMemoryDataset()
    ds.init(batch_size=4, use_var=["x", "y"])
    ds.set_filelist([fn])
    ds.set_generator(_SlotGen())
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 10
    batches = list(ds)
    assert [b["x"].shape for b in batches] == [(4, 4), (4, 4), (2, 4)]
    first_before = batches[0]["y"][:, 0].tolist()
    ds.local_shuffle(seed=7)
    shuffled = list(ds)[0]["y"][:, 0].tolist()
    assert sorted(first_before) != shuffled or first_before != shuffled
    ds.release_memory()
    assert ds.get_memory_data_size() == 0


def test_queue_dataset_streams_without_materializing(tmp_path):
    fn = _write_slot_file(tmp_path, n=6)
    ds = fleet.QueueDataset()
    ds.init(batch_size=3, use_var=["x", "y"])
    ds.set_filelist([fn])
    ds.set_generator(_SlotGen())
    batches = list(ds)
    assert len(batches) == 2
    np.testing.assert_allclose(batches[0]["y"][:, 0], [0, 1, 2])


def test_data_generator_gen_str_protocol():
    g = _SlotGen()
    s = g._gen_str([("x", [1.0, 2.0]), ("y", [3.0])])
    assert s == "2 1.0 2.0 1 3.0\n"


# --------------------------------------------------------------------------- #
# incubate.autotune / checkpoint / multiprocessing
# --------------------------------------------------------------------------- #


def test_autotune_set_get_config(tmp_path):
    from paddle_tpu.incubate import autotune

    autotune.set_config({"dataloader": {"enable": True, "tuning_steps": 99}})
    cfg = autotune.get_config()
    assert cfg["dataloader"]["tuning_steps"] == 99
    with pytest.raises(ValueError):
        autotune.set_config({"nonsense": {}})
    p = tmp_path / "cfg.json"
    p.write_text('{"kernel": {"enable": false}}')
    autotune.set_config(str(p))
    assert autotune.get_config()["kernel"]["enable"] is False


def test_auto_checkpoint_epoch_resume(tmp_path, monkeypatch):
    import paddle_tpu.incubate.checkpoint.auto_checkpoint as acp

    monkeypatch.setenv("PADDLE_CHECKPOINT_DIR", str(tmp_path))
    acp.g_checker = None
    done = []
    for e in acp.train_epoch_range(5, name="job"):
        done.append(e)
        if e == 2:
            break  # crash mid-epoch-2
    acp.g_checker = None
    resumed = list(acp.train_epoch_range(5, name="job"))
    # epochs 0,1 completed; epoch 2 was interrupted before bookkeeping -> re-run
    assert done == [0, 1, 2]
    assert resumed == [2, 3, 4]


def test_auto_checkpoint_save_restore_fns(tmp_path, monkeypatch):
    import paddle_tpu.incubate.checkpoint.auto_checkpoint as acp

    monkeypatch.setenv("PADDLE_CHECKPOINT_DIR", str(tmp_path))
    acp.g_checker = None
    state = {"w": 0}
    saved = {}

    def save_fn(path):
        os.makedirs(path, exist_ok=True)
        saved.update(state)

    def restore_fn(path):
        state.update(saved)

    for e in acp.train_epoch_range(3, name="j2", save_checkpoint_inter=0,
                                   save_fn=save_fn, restore_fn=restore_fn):
        state["w"] = e + 1
    assert saved["w"] == 3  # final forced snapshot saw the last epoch's state


def test_multiprocessing_shm_reduction_roundtrip():
    from multiprocessing.reduction import ForkingPickler

    import paddle_tpu.incubate.multiprocessing  # noqa: F401 (registers)

    t = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    buf = ForkingPickler.dumps(t)
    t2 = pickle.loads(buf)
    np.testing.assert_allclose(t2.numpy(), t.numpy())
    assert bool(t2.stop_gradient) == bool(t.stop_gradient)


# --------------------------------------------------------------------------- #
# sysconfig / onnx
# --------------------------------------------------------------------------- #


def test_sysconfig_paths():
    inc, lib = paddle.sysconfig.get_include(), paddle.sysconfig.get_lib()
    assert os.path.isdir(inc) and os.path.isdir(lib)


def test_onnx_export_is_documented_nongoal():
    """paddle.onnx.export keeps the reference's API surface but is a
    documented non-goal (README): always raises pointing at jit.save's
    StableHLO path."""
    layer = paddle.nn.Linear(4, 2)
    with pytest.raises(NotImplementedError, match="jit.save"):
        paddle.onnx.export(layer, "/tmp/should_not_exist")


def test_distributed_fused_lamb_steps():
    """ref incubate/optimizer/distributed_fused_lamb.py — LAMB math with
    state sharding delegated to the engine's GSPMD layout."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.incubate import DistributedFusedLamb

    paddle.seed(0)
    m = nn.Linear(4, 3)
    opt = DistributedFusedLamb(learning_rate=0.05,
                               parameters=m.parameters())
    before = np.array(m.weight.numpy())
    x = paddle.to_tensor(np.random.RandomState(0).rand(8, 4).astype("float32"))
    for _ in range(3):
        loss = paddle.mean(paddle.square(m(x)))
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert not np.allclose(before, m.weight.numpy())
    assert float(loss) < 1.0


class TestMetaOptimizerRewrites:
    """lamb/lars/localsgd meta-optimizers swap the inner optimizer
    (ref meta_optimizers/lamb_optimizer.py, lars_optimizer.py,
    localsgd_optimizer.py); dgc warns as a documented non-goal."""

    def _strategy(self, **flags):
        from paddle_tpu.distributed.fleet import DistributedStrategy

        s = DistributedStrategy()
        for k, v in flags.items():
            setattr(s, k, v)
        return s

    def test_lamb_swap(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.fleet.meta_optimizers import \
            rewrite_inner_optimizer
        from paddle_tpu.optimizer import Lamb, Momentum

        m = nn.Linear(4, 4)
        inner = Momentum(learning_rate=0.1, parameters=m.parameters())
        out = rewrite_inner_optimizer(inner, self._strategy(lamb=True))
        assert isinstance(out, Lamb)

    def test_lars_swap(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.fleet.meta_optimizers import \
            rewrite_inner_optimizer
        from paddle_tpu.optimizer import Lars, Momentum

        m = nn.Linear(4, 4)
        inner = Momentum(learning_rate=0.1, parameters=m.parameters())
        out = rewrite_inner_optimizer(inner, self._strategy(lars=True))
        assert isinstance(out, Lars)

    def test_localsgd_steps_and_averages(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.fleet.meta_optimizers import \
            rewrite_inner_optimizer
        from paddle_tpu.optimizer import SGD

        m = nn.Linear(2, 2)
        inner = SGD(learning_rate=0.1, parameters=m.parameters())
        s = self._strategy(localsgd=True)
        s.localsgd_configs = {"k_steps": 2}
        opt = rewrite_inner_optimizer(inner, s)
        x = paddle.to_tensor(np.ones((1, 2), "float32"))
        for _ in range(3):
            loss = paddle.mean(m(x) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert opt._t == 3  # stepped through the wrapper

    def test_dgc_warns_nongoal(self):
        import warnings

        import paddle_tpu.nn as nn
        from paddle_tpu.distributed.fleet.meta_optimizers import \
            rewrite_inner_optimizer
        from paddle_tpu.optimizer import Momentum

        m = nn.Linear(2, 2)
        inner = Momentum(learning_rate=0.1, parameters=m.parameters())
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out = rewrite_inner_optimizer(inner, self._strategy(dgc=True))
        assert out is inner
        assert any("non-goal" in str(x.message) for x in w)


class TestQuantPostStatic:
    """Real quant_post_static export (was a NotImplementedError stub):
    per-channel int8 weights + scales + activation calibration."""

    def test_weight_only_from_saved_model(self, tmp_path):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.static.quantization import (load_quantized_state,
                                                    quant_post_static)

        paddle.seed(3)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        src = str(tmp_path / "model")
        paddle.jit.save(m, src)
        dst = str(tmp_path / "model_int8")
        quant_post_static(model_dir=src, quantize_model_path=dst)
        state, acts = load_quantized_state(dst)
        ref = {k: np.asarray(v.value) for k, v in m.state_dict().items()}
        assert set(state) == set(ref)
        for k in ref:
            if ref[k].ndim >= 2:
                err = np.abs(state[k] - ref[k]).max()
                assert err <= np.abs(ref[k]).max() / 127 + 1e-6, (k, err)
            else:
                np.testing.assert_array_equal(state[k], ref[k])

    def test_ptq_with_calibration(self, tmp_path):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.static.quantization import (load_quantized_state,
                                                    quant_post_static)

        paddle.seed(3)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        rng = np.random.RandomState(0)
        batches = [paddle.to_tensor(rng.randn(4, 8).astype("float32"))
                   for _ in range(4)]
        dst = str(tmp_path / "ptq_int8")
        quant_post_static(model=m, sample_generator=iter(batches),
                          quantize_model_path=dst, batch_nums=4)
        state, acts = load_quantized_state(dst)
        assert len(acts) > 0  # activation ranges were calibrated
        assert all(v > 0 for v in acts.values())
