"""Crash-safe training: complete-state checkpoints, bit-exact resume,
degradation ladder, and the elastic-restart chaos harness.

The training analogue of ``test_serving.py``'s snapshot/chaos tier:

- manifest + atomic-commit primitives (CRC detection of bit rot and
  truncation, stale-staging sweep, ``AutoCheckpoint`` torn-dir immunity);
- ``load_state_dict`` shardings keyed by TREE PATH (the ``id()``-keyed
  scheme this replaces dropped every sharding under tree transforms);
- ``TrainCheckpointer``: the headline **bit-exact resume** guarantee —
  kill at step k, restore, steps k+1..n produce losses and final
  params/opt-state identical to an unkilled twin — for the fp32 engine
  path and the eager AMP path (live loss-scaler mid-backoff), plus
  GSPMD reshard-on-load onto a different n=8 mesh layout;
- the degradation ladder: torn write → retry → (exhausted) drop-and-
  continue; corrupt read → CRC detection → previous-generation fallback
  → ``CheckpointCorruptError`` only when nothing valid remains;
- the elastic chaos harness + ``tools/train_chaos.py`` twin gate.
"""
import importlib.util
import os
import pickle
import sys
import tempfile
from pathlib import Path

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.checkpoint import (AutoCheckpoint, load_state_dict,
                                               read_manifest, replace_dir,
                                               save_state_dict, staging_path,
                                               sweep_stale_staging,
                                               tree_path_key, verify_manifest,
                                               write_manifest)
from paddle_tpu.distributed.train_checkpoint import (CheckpointableDataFeed,
                                                     CheckpointCorruptError,
                                                     TrainCheckpointer,
                                                     config_fingerprint)
from paddle_tpu.faults import (NULL_INJECTOR, DataFeedFault, FaultInjector,
                               FaultPlan, FaultSpec, StepFault)
from paddle_tpu.parallel.engine import ParallelEngine

REPO = Path(__file__).resolve().parents[1]


def npt(t):
    return np.asarray(t.value)


def make_batch(cursor):
    rng = np.random.RandomState(100 + cursor)
    return (rng.randn(8, 4).astype("float32"),
            rng.randn(8, 2).astype("float32"))


def make_engine(injector=None, mesh=None, fsdp=False, seed=5):
    paddle.seed(seed)
    m = nn.Linear(4, 2)
    o = optimizer.AdamW(learning_rate=0.05, parameters=m.parameters())
    return ParallelEngine(m, o, loss_fn=nn.functional.mse_loss, donate=False,
                          mesh=mesh, fsdp=fsdp,
                          injector=injector or NULL_INJECTOR)


# --------------------------------------------------------------------------- #
# Manifest + atomic commit primitives
# --------------------------------------------------------------------------- #


class TestManifest:
    def _write_gen(self, d):
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "shard_0.bin"), "wb") as f:
            f.write(b"\x01\x02" * 512)
        with open(os.path.join(d, "meta.json"), "wb") as f:
            f.write(b'{"step": 1}')
        return write_manifest(d, step=1, fingerprint="fp")

    def test_roundtrip_and_verify_clean(self, tmp_path):
        d = str(tmp_path / "gen")
        mf = self._write_gen(d)
        assert set(mf["files"]) == {"shard_0.bin", "meta.json"}
        assert read_manifest(d)["fingerprint"] == "fp"
        assert verify_manifest(d) == []

    def test_bit_flip_detected(self, tmp_path):
        d = str(tmp_path / "gen")
        self._write_gen(d)
        with open(os.path.join(d, "shard_0.bin"), "r+b") as f:
            f.seek(100)
            b = f.read(1)
            f.seek(100)
            f.write(bytes([b[0] ^ 0x10]))
        problems = verify_manifest(d)
        assert problems and "crc mismatch" in problems[0]

    def test_truncation_and_missing_shard_detected(self, tmp_path):
        d = str(tmp_path / "gen")
        self._write_gen(d)
        with open(os.path.join(d, "shard_0.bin"), "r+b") as f:
            f.truncate(17)
        assert any("size mismatch" in p for p in verify_manifest(d))
        os.remove(os.path.join(d, "shard_0.bin"))
        assert any("missing shard" in p for p in verify_manifest(d))

    def test_injector_corrupt_file_is_caught(self, tmp_path):
        d = str(tmp_path / "gen")
        self._write_gen(d)
        inj = FaultInjector(FaultPlan(specs=[FaultSpec("ckpt_read")], seed=9))
        off = inj.corrupt_file(os.path.join(d, "shard_0.bin"))
        assert off >= 0
        assert verify_manifest(d) != []

    def test_replace_dir_commit_and_resave(self, tmp_path):
        final = str(tmp_path / "step_1")
        for token in (b"old", b"new"):
            tmp = staging_path(final)
            os.makedirs(tmp)
            with open(os.path.join(tmp, "payload"), "wb") as f:
                f.write(token)
            replace_dir(tmp, final)
        with open(os.path.join(final, "payload"), "rb") as f:
            assert f.read() == b"new"
        assert not os.path.exists(staging_path(final))
        assert not os.path.exists(staging_path(final) + ".old")

    def test_sweep_stale_staging(self, tmp_path):
        os.makedirs(tmp_path / ".tmp-step_7")
        os.makedirs(tmp_path / "step_1")
        assert sweep_stale_staging(str(tmp_path)) == 1
        assert (tmp_path / "step_1").exists()
        assert not (tmp_path / ".tmp-step_7").exists()


# --------------------------------------------------------------------------- #
# Satellite 1: shardings keyed by tree path
# --------------------------------------------------------------------------- #


class TestPathKeyedShardings:
    def test_tree_path_key_forms(self):
        tree = {"model": {"weight": np.zeros(2)}, "seq": [np.zeros(2)]}
        keys = []
        jax.tree_util.tree_map_with_path(
            lambda p, x: keys.append(tree_path_key(p)), tree)
        assert sorted(keys) == ["model/weight", "seq/0"]

    def test_load_with_path_keyed_shardings(self, tmp_path):
        devs = np.array(jax.devices()[:8])
        mesh = Mesh(devs.reshape(8), ("data",))
        state = {"model": {"w": np.arange(16, dtype=np.float32),
                           "b": np.ones(4, np.float32)}}
        save_state_dict(state, str(tmp_path / "ckpt"))
        sh = NamedSharding(mesh, P("data"))
        out = load_state_dict(str(tmp_path / "ckpt"), target=state,
                              shardings={"model/w": sh})
        w = out["model"]["w"].value
        assert w.sharding == sh  # the path-keyed entry landed
        np.testing.assert_array_equal(np.asarray(w), state["model"]["w"])
        # leaves without an entry still restore (unsharded)
        np.testing.assert_array_equal(np.asarray(out["model"]["b"].value),
                                      state["model"]["b"])


# --------------------------------------------------------------------------- #
# Satellite 2: AutoCheckpoint atomic commit
# --------------------------------------------------------------------------- #


class TestAutoCheckpointAtomic:
    def test_commit_is_manifested_and_staging_free(self, tmp_path):
        paddle.seed(3)
        m = nn.Linear(4, 2)
        ac = AutoCheckpoint(str(tmp_path), every_n_steps=1)
        tag = ac.step(model=m)
        assert verify_manifest(tag) == []
        assert not [d for d in os.listdir(tmp_path) if d.startswith(".tmp-")]

    def test_torn_dir_is_skipped_by_latest(self, tmp_path):
        paddle.seed(3)
        m = nn.Linear(4, 2)
        ac = AutoCheckpoint(str(tmp_path), every_n_steps=1)
        good = ac.step(model=m)
        # a kill mid-save leaves a higher-step dir with no manifest —
        # latest() must fall back to the verified generation
        torn = tmp_path / "step_99"
        os.makedirs(torn)
        (torn / "garbage").write_bytes(b"\x00" * 64)
        assert ac.latest() == good

    def test_stale_staging_swept_on_init(self, tmp_path):
        os.makedirs(tmp_path / ".tmp-step_5")
        AutoCheckpoint(str(tmp_path))
        assert not (tmp_path / ".tmp-step_5").exists()

    def test_resume_roundtrip(self, tmp_path):
        paddle.seed(3)
        m = nn.Linear(4, 2)
        ac = AutoCheckpoint(str(tmp_path), every_n_steps=2)
        ac.step(model=m)
        assert ac.step(model=m) is not None
        m2 = nn.Linear(4, 2)
        ac2 = AutoCheckpoint(str(tmp_path), every_n_steps=2)
        assert ac2.resume(model=m2) == 2
        np.testing.assert_array_equal(npt(m.weight), npt(m2.weight))


# --------------------------------------------------------------------------- #
# TrainCheckpointer: complete state + bit-exact resume
# --------------------------------------------------------------------------- #


def run_engine(ckpt_dir, *, n=6, kill_at=None, save_every=2, injector=None,
               metrics=None):
    """One (possibly killed) incarnation against a shared checkpoint dir."""
    eng = make_engine(injector)
    feed = CheckpointableDataFeed(make_batch,
                                  injector=injector or NULL_INJECTOR)
    ck = TrainCheckpointer(ckpt_dir, injector=injector or NULL_INJECTOR,
                           metrics=metrics, save_retries=2, backoff_s=0.005)
    losses = {}
    host = ck.restore(engine=eng, data_feed=feed)
    start = (host["step"] + 1) if host else 0
    for i in range(start, n):
        X, y = feed.next_batch()
        losses[i] = float(np.asarray(eng.train_batch(
            paddle.to_tensor(X), paddle.to_tensor(y)).value))
        if kill_at is not None and i == kill_at:
            return losses, None, ck
        if (i + 1) % save_every == 0:
            ck.save(i, engine=eng, data_feed=feed)
    return losses, eng.engine_state_dict(), ck


class TestBitExactResume:
    def test_fp32_kill_restore_twin(self, tmp_path):
        twin_losses, twin_state, _ = run_engine(str(tmp_path / "twin"))
        pre, _, _ = run_engine(str(tmp_path / "run"), kill_at=3)
        post, state, _ = run_engine(str(tmp_path / "run"))
        # replayed + continued steps are bit-identical to the unkilled twin
        assert pre[3] == twin_losses[3]
        for i, v in post.items():
            assert v == twin_losses[i], (i, v, twin_losses[i])
        for nm in twin_state["params"]:
            np.testing.assert_array_equal(twin_state["params"][nm],
                                          state["params"][nm])
        for nm in twin_state["opt_state"]:
            for k in twin_state["opt_state"][nm]:
                np.testing.assert_array_equal(twin_state["opt_state"][nm][k],
                                              state["opt_state"][nm][k])
        assert state["step"] == twin_state["step"]

    def test_save_does_not_perturb_training(self, tmp_path):
        # the twin above never checkpoints; a run that checkpoints every
        # step must produce the identical trajectory (capture is read-only)
        a, sa, _ = run_engine(str(tmp_path / "a"), save_every=1)
        b, sb, _ = run_engine(str(tmp_path / "b"), save_every=10**6)
        assert a == b
        for nm in sa["params"]:
            np.testing.assert_array_equal(sa["params"][nm], sb["params"][nm])

    def test_rng_and_feed_cursor_roundtrip(self, tmp_path):
        eng = make_engine()
        feed = CheckpointableDataFeed(make_batch, cursor=7)
        paddle.seed(77)
        key_before = np.asarray(paddle.framework.random.get_rng_state())
        ck = TrainCheckpointer(str(tmp_path))
        ck.save(7, engine=eng, data_feed=feed, extra={"note": "x"})
        paddle.seed(1)  # clobber
        eng2 = make_engine(seed=6)
        feed2 = CheckpointableDataFeed(make_batch)
        host = ck.restore(engine=eng2, data_feed=feed2)
        assert host["step"] == 7 and host["extra"] == {"note": "x"}
        assert feed2.cursor == 7
        np.testing.assert_array_equal(
            np.asarray(paddle.framework.random.get_rng_state()), key_before)

    def test_fingerprint_mismatch_refuses_resume(self, tmp_path):
        fp = config_fingerprint({"lr": 0.05, "width": 4})
        ck = TrainCheckpointer(str(tmp_path), fingerprint=fp)
        ck.save(0, engine=make_engine())
        ck2 = TrainCheckpointer(
            str(tmp_path), fingerprint=config_fingerprint({"lr": 0.1}))
        with pytest.raises(ValueError, match="fingerprint mismatch"):
            ck2.restore(engine=make_engine())

    def test_async_save_commits_off_step_path(self, tmp_path):
        ck = TrainCheckpointer(str(tmp_path), async_save=True)
        eng = make_engine()
        path = ck.save(3, engine=eng)
        ck.wait()
        assert verify_manifest(path) == []
        assert ck.latest_valid()[0] == 3

    def test_keep_last_prunes_old_generations(self, tmp_path):
        ck = TrainCheckpointer(str(tmp_path), keep_last=2)
        eng = make_engine()
        for i in range(4):
            ck.save(i, engine=eng)
        assert [s for s, _ in ck.generations()] == [2, 3]


class TestAMPResume:
    """Satellite 3: kill/restore with a live loss-scaler mid-backoff."""

    N = 6
    INF_STEPS = {2, 3}  # scripted overflow steps (via the data stream)

    @classmethod
    def _amp_batch(cls, cursor):
        X, y = make_batch(cursor)
        if cursor in cls.INF_STEPS:
            X = X.copy()
            X[0, 0] = 1e30  # mse squares it → inf loss → inf grads
        return X, y

    def _run(self, ckpt_dir, *, kill_at=None):
        paddle.seed(11)
        m = nn.Linear(4, 2)
        sched = optimizer.lr.StepDecay(learning_rate=0.05, step_size=2)
        o = optimizer.AdamW(learning_rate=sched, parameters=m.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=32.0,
                                       incr_every_n_steps=3,
                                       decr_every_n_nan_or_inf=2)
        feed = CheckpointableDataFeed(self._amp_batch)
        ck = TrainCheckpointer(ckpt_dir)
        host = ck.restore(model=m, optimizer=o, scaler=scaler,
                          data_feed=feed)
        start = (host["step"] + 1) if host else 0
        losses = {}
        for i in range(start, self.N):
            X, y = feed.next_batch()
            loss = nn.functional.mse_loss(m(paddle.to_tensor(X)),
                                          paddle.to_tensor(y))
            scaler.scale(loss).backward()
            scaler.step(o)
            scaler.update()
            o.clear_grad()
            sched.step()
            losses[i] = float(np.asarray(loss.value))
            if kill_at is not None and i == kill_at:
                return losses, m, scaler, sched, None
            ck.save(i, model=m, optimizer=o, scaler=scaler, data_feed=feed)
        return losses, m, scaler, sched, ck

    def test_scaler_mid_backoff_roundtrips_bit_exactly(self, tmp_path):
        twin_losses, twin_m, twin_scaler, twin_sched, _ = self._run(
            str(tmp_path / "twin"))
        pre, _, scaler_at_kill, _, _ = self._run(str(tmp_path / "run"),
                                                 kill_at=2)
        # the kill lands mid-backoff: one bad step seen, scale not yet cut
        assert scaler_at_kill.state_dict()["decr_count"] == 1
        assert scaler_at_kill.state_dict()["scale"] == 32.0
        post, m2, scaler2, sched2, _ = self._run(str(tmp_path / "run"))
        # scaler state (scale + growth/backoff counters) is bit-exact at
        # every comparison point, through the second bad step's scale cut
        assert scaler2.state_dict() == twin_scaler.state_dict()
        assert twin_scaler.state_dict()["scale"] == 16.0  # 2 bad → halved
        assert sched2.state_dict() == twin_sched.state_dict()
        for i, v in post.items():
            assert v == twin_losses[i] or (
                np.isinf(v) and np.isinf(twin_losses[i])), (i, v)
        for (n1, p1), (n2, p2) in zip(twin_m.named_parameters(),
                                      m2.named_parameters()):
            assert n1 == n2
            np.testing.assert_array_equal(npt(p1), npt(p2))


class TestReshardOnLoad:
    """n=8 dryrun: checkpoint written on one mesh layout restores onto a
    different one via GSPMD reshard-on-load (orbax target shardings)."""

    WIDTH = 64  # big enough that the fsdp auto-shard policy shards it

    @staticmethod
    def _wide_batch(cursor):
        rng = np.random.RandomState(100 + cursor)
        return (rng.randn(8, TestReshardOnLoad.WIDTH).astype("float32"),
                rng.randn(8, TestReshardOnLoad.WIDTH).astype("float32"))

    def _spmd_engine(self, layout):
        devs = np.array(jax.devices()[:8])
        paddle.seed(5)
        m = nn.Linear(self.WIDTH, self.WIDTH)
        o = optimizer.AdamW(learning_rate=0.05, parameters=m.parameters())
        if layout == "dp8":
            mesh = Mesh(devs.reshape(8), ("data",))
            return ParallelEngine(m, o, loss_fn=nn.functional.mse_loss,
                                  donate=False, mesh=mesh)
        mesh = Mesh(devs.reshape(2, 4), ("data", "sharding"))
        return ParallelEngine(m, o, loss_fn=nn.functional.mse_loss,
                              donate=False, mesh=mesh, fsdp=True)

    def test_cross_mesh_restore_is_state_exact_and_trains(self, tmp_path):
        eng_a = self._spmd_engine("dp8")
        feed = CheckpointableDataFeed(self._wide_batch)
        for i in range(2):
            X, y = feed.next_batch()
            eng_a.train_batch(paddle.to_tensor(X), paddle.to_tensor(y))
        ck = TrainCheckpointer(str(tmp_path))
        ck.save(1, engine=eng_a, data_feed=feed)
        saved = eng_a.engine_state_dict()

        eng_b = self._spmd_engine("fsdp2x4")
        feed_b = CheckpointableDataFeed(self._wide_batch)
        host = ck.restore(engine=eng_b, data_feed=feed_b)
        assert host["step"] == 1 and feed_b.cursor == 2
        restored = eng_b.engine_state_dict()
        # resharded, not altered: gathered state is byte-identical
        for nm in saved["params"]:
            np.testing.assert_array_equal(saved["params"][nm],
                                          restored["params"][nm])
        for nm in saved["opt_state"]:
            for k in saved["opt_state"][nm]:
                np.testing.assert_array_equal(saved["opt_state"][nm][k],
                                              restored["opt_state"][nm][k])
        assert restored["step"] == saved["step"]
        # the params actually landed sharded over the new mesh axis
        w = eng_b.params["weight"]
        assert "sharding" in str(w.sharding.spec)
        # and training continues on the new layout
        X, y = feed_b.next_batch()
        loss = eng_b.train_batch(paddle.to_tensor(X), paddle.to_tensor(y))
        assert np.isfinite(float(np.asarray(loss.value)))


# --------------------------------------------------------------------------- #
# Degradation ladder
# --------------------------------------------------------------------------- #


class TestDegradationLadder:
    def test_torn_write_retry_succeeds(self, tmp_path):
        inj = FaultInjector(FaultPlan(
            specs=[FaultSpec("ckpt_write", at=0, kind="torn")], seed=1))
        ck = TrainCheckpointer(str(tmp_path), injector=inj, save_retries=2,
                               backoff_s=0.005)
        path = ck.save(0, engine=make_engine())
        assert path is not None and verify_manifest(path) == []
        m = ck.metrics
        assert m.counter("train_checkpoint_save_retries", "").total() == 1
        assert m.counter("train_checkpoint_save_failures", "").total() == 0

    def test_torn_write_exhausted_drops_save_never_raises(self, tmp_path):
        eng = make_engine()
        good = TrainCheckpointer(str(tmp_path))
        good.save(0, engine=eng)
        inj = FaultInjector(FaultPlan(
            specs=[FaultSpec("ckpt_write", at=0, count=2, kind="torn")],
            seed=1))
        ck = TrainCheckpointer(str(tmp_path), injector=inj, save_retries=1,
                               backoff_s=0.005)
        assert ck.save(1, engine=eng) is None  # dropped, not raised
        assert ck.metrics.counter(
            "train_checkpoint_save_failures", "").total() == 1
        # the step loop continues against the last valid generation
        assert ck.latest_valid()[0] == 0
        # no staging husk leaks from the failed attempts
        assert not [d for d in os.listdir(tmp_path) if d.startswith(".tmp-")]
        # and the NEXT save (plan spent) commits normally
        assert ck.save(2, engine=eng) is not None
        assert ck.latest_valid()[0] == 2

    def test_corrupt_read_falls_back_a_generation(self, tmp_path):
        eng = make_engine()
        writer = TrainCheckpointer(str(tmp_path))
        writer.save(0, engine=eng)
        eng.train_batch(*map(paddle.to_tensor, make_batch(0)))
        writer.save(1, engine=eng)
        inj = FaultInjector(FaultPlan(
            specs=[FaultSpec("ckpt_read", at=0)], seed=4))
        ck = TrainCheckpointer(str(tmp_path), injector=inj)
        host = ck.restore(engine=make_engine(seed=6))
        assert host["step"] == 0  # newest gen corrupted on read → fell back
        m = ck.metrics
        assert m.counter("train_checkpoint_corrupt_reads", "").total() == 1
        assert m.counter(
            "train_checkpoint_generation_fallbacks", "").total() == 1

    def test_all_generations_corrupt_raises(self, tmp_path):
        eng = make_engine()
        ck = TrainCheckpointer(str(tmp_path))
        for i in range(2):
            ck.save(i, engine=eng)
        for _step, path in ck.generations():
            mf = read_manifest(path)
            rel = sorted(mf["files"])[0]
            with open(os.path.join(path, rel), "r+b") as f:
                f.seek(0)
                b = f.read(1)
                f.seek(0)
                f.write(bytes([b[0] ^ 0xFF]))
        with pytest.raises(CheckpointCorruptError):
            ck.restore(engine=make_engine(seed=6))

    def test_empty_dir_restores_fresh(self, tmp_path):
        ck = TrainCheckpointer(str(tmp_path))
        assert ck.restore(engine=make_engine()) is None


# --------------------------------------------------------------------------- #
# Training fault sites
# --------------------------------------------------------------------------- #


class TestTrainingFaultSites:
    def test_train_step_fault_fires_before_dispatch_and_retries(self):
        inj = FaultInjector(FaultPlan(
            specs=[FaultSpec("train_step", at=1)], seed=1))
        eng = make_engine(injector=inj)
        clean = make_engine()
        feed = CheckpointableDataFeed(make_batch)
        losses, ref = [], []
        for i in range(3):
            X, y = feed.next_batch()
            for attempt in range(3):
                try:
                    losses.append(float(np.asarray(eng.train_batch(
                        paddle.to_tensor(X), paddle.to_tensor(y)).value)))
                    break
                except StepFault:
                    assert attempt < 2
            ref.append(float(np.asarray(clean.train_batch(
                paddle.to_tensor(X), paddle.to_tensor(y)).value)))
        # state untouched by the fault: the retried run tracks the clean twin
        assert losses == ref
        assert ("train_step", 1) in inj.fired

    def test_fatal_train_step_fault_is_plain_runtime_error(self):
        inj = FaultInjector(FaultPlan(
            specs=[FaultSpec("train_step", at=0, kind="fatal")], seed=1))
        eng = make_engine(injector=inj)
        X, y = make_batch(0)
        with pytest.raises(RuntimeError, match="fatal"):
            eng.train_batch(paddle.to_tensor(X), paddle.to_tensor(y))

    def test_data_feed_fault_does_not_advance_cursor(self):
        inj = FaultInjector(FaultPlan(
            specs=[FaultSpec("data_feed", at=1)], seed=1))
        feed = CheckpointableDataFeed(make_batch, injector=inj)
        clean = CheckpointableDataFeed(make_batch)
        out = []
        for _ in range(3):
            while True:
                try:
                    out.append(feed.next_batch())
                    break
                except DataFeedFault:
                    pass
            ref = clean.next_batch()
            np.testing.assert_array_equal(out[-1][0], ref[0])
        assert feed.cursor == clean.cursor == 3

    def test_serving_reexports_are_the_shared_substrate(self):
        import paddle_tpu.faults as shared
        from paddle_tpu.inference import faults as serving

        assert serving.FaultInjector is shared.FaultInjector
        assert serving.FaultPlan is shared.FaultPlan
        assert serving.NULL_INJECTOR is shared.NULL_INJECTOR

    def test_train_chaos_plan_is_seeded_and_covers_sites(self):
        p1 = FaultPlan.train_chaos(3, horizon=12, kills=2)
        p2 = FaultPlan.train_chaos(3, horizon=12, kills=2)
        assert p1 == p2  # same seed → same plan
        sites = {s.site for s in p1.specs}
        assert sites == {"train_step", "data_feed", "ckpt_write",
                         "ckpt_read", "kill"}
        kill_ats = [s.at for s in p1.specs if s.site == "kill"]
        assert len(set(kill_ats)) == 2  # distinct ordinals, both fire


# --------------------------------------------------------------------------- #
# Elastic chaos harness + the stage-8 gate
# --------------------------------------------------------------------------- #


class TestElasticChaos:
    def test_harness_kill_detect_restore_continue(self, tmp_path):
        from paddle_tpu.distributed.fleet.chaos import ElasticChaosHarness

        twin_losses, twin_state, _ = run_engine(str(tmp_path / "twin"), n=6)

        plan = FaultPlan(specs=[FaultSpec("kill", at=3)], seed=3)
        injector = FaultInjector(plan)
        state = {}

        class Run:
            def __init__(self, inj):
                self.eng = make_engine(injector=inj)
                self.feed = CheckpointableDataFeed(make_batch, injector=inj)
                self.ck = TrainCheckpointer(str(tmp_path / "chaos"),
                                            injector=inj)
                state["engine"] = self.eng

            def restore(self):
                host = self.ck.restore(engine=self.eng, data_feed=self.feed)
                return (host["step"] + 1) if host else 0

            def step(self, i):
                X, y = self.feed.next_batch()
                return float(np.asarray(self.eng.train_batch(
                    paddle.to_tensor(X), paddle.to_tensor(y)).value))

            def save(self, i):
                self.ck.save(i, engine=self.eng, data_feed=self.feed)

        harness = ElasticChaosHarness(
            Run, total_steps=6, injector=injector, max_restarts=2,
            heartbeat_interval=0.05, lease_ttl=0.3)
        report = harness.run()
        assert report.completed and report.restarts == 1
        assert report.detected_kills == 1  # observed via lease expiry
        for i, v in report.losses.items():
            assert v == twin_losses[i], (i, v)
        final = state["engine"].engine_state_dict()
        for nm in twin_state["params"]:
            np.testing.assert_array_equal(twin_state["params"][nm],
                                          final["params"][nm])

    @pytest.mark.slow
    def test_train_chaos_tool_gate(self):
        """The stage-8 gate end to end, in-process (small config)."""
        spec = importlib.util.spec_from_file_location(
            "train_chaos_tool", REPO / "tools" / "train_chaos.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rc = mod.main(["--steps", "10", "--kills", "2", "--seed", "3",
                       "--json"])
        assert rc == 0
