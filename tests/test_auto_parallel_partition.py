"""auto_parallel Completer/Partitioner/Resharder/Converter (ref
auto_parallel completion.py/partitioner.py/reshard.py/converter.py): assert
on sharding artifacts without N real devices — the reference's
program-text-test pattern (SURVEY §4) on jaxpr/HLO instead."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.distributed.auto_parallel import (Cluster, Completer,
                                                  Converter, Partitioner,
                                                  Resharder)


def _mesh():
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    return Mesh(devs, ("dp", "mp"))


class TestCompleter:
    def test_hlo_carries_shardings(self):
        mesh = _mesh()

        def fn(x, w):
            return x @ w

        x = jnp.ones((16, 32))
        w = jnp.ones((32, 64))
        prog = Completer(mesh).complete(fn, x, w,
                                        in_specs=[P("dp", None), P(None, "mp")])
        assert "sharding" in prog.hlo_text  # GSPMD annotations present
        assert len(prog.input_shardings()) == 2

    def test_output_shardings_propagated(self):
        mesh = _mesh()
        prog = Completer(mesh).complete(lambda x: x * 2, jnp.ones((8, 8)),
                                        in_specs=[P("dp", None)])
        (out,) = prog.output_shardings()
        # elementwise op: the dp row sharding must propagate to the output
        assert out.spec == P("dp") or out.spec == P("dp", None)


class TestPartitioner:
    def test_local_shapes(self):
        mesh = _mesh()
        part = Partitioner(mesh)
        assert part.local_shape((16, 64), P("dp", "mp")) == (4, 32)
        assert part.local_shape((16, 64), P(None, "mp")) == (16, 32)
        assert part.local_shape((16, 64), None) == (16, 64)

    def test_partition_state(self):
        mesh = _mesh()
        state = {"w": np.zeros((8, 8)), "b": np.zeros((8,))}
        shapes = Partitioner(mesh).partition_state(
            state, {"w": P(None, "mp"), "b": None})
        assert shapes == {"w": (8, 4), "b": (8,)}


class TestReshardConvert:
    def test_reshard_changes_layout(self):
        mesh = _mesh()
        x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
        y = Resharder(mesh).reshard(x, P("dp", None))
        assert y.sharding.spec == P("dp", None)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))

    def test_converter_checkpoint_reshard(self):
        """Params saved replicated load back mp-sharded with identical
        values — the strategy-change resume flow (ref converter.py)."""
        mesh = _mesh()
        sd = {"w": np.arange(32, dtype=np.float32).reshape(4, 8)}
        out = Converter(sd).convert(mesh, {"w": P(None, "mp")})
        assert out["w"].sharding.spec == P(None, "mp")
        np.testing.assert_array_equal(np.asarray(out["w"]), sd["w"])


class TestEnginePredict:
    def test_predict_uses_trained_weights(self):
        """fit() trains inside the ParallelEngine's donated buffers; predict
        must see those weights, not the Layer's initial ones."""
        import paddle_tpu as paddle
        from paddle_tpu import nn, optimizer
        from paddle_tpu.distributed.auto_parallel import Engine
        from paddle_tpu.io import Dataset

        class DS(Dataset):
            def __init__(self):
                rng = np.random.RandomState(0)
                self.x = rng.rand(32, 4).astype("float32")
                self.y = self.x.sum(1, keepdims=True).astype("float32")

            def __len__(self):
                return 32

            def __getitem__(self, i):
                return self.x[i], self.y[i]

        model = nn.Linear(4, 1)
        opt = optimizer.Adam(learning_rate=0.05,
                             parameters=model.parameters())
        eng = Engine(model=model, loss=nn.functional.mse_loss, optimizer=opt)
        before = np.array(model.weight.numpy())
        eng.fit(DS(), epochs=3, batch_size=8, verbose=0)
        preds = eng.predict(DS(), batch_size=8)
        # weights must have left their initial values in the Layer itself
        assert not np.allclose(before, model.weight.numpy())
        ds = DS()
        mse = float(np.mean((np.concatenate(
            [np.asarray(p) for p in preds]) - ds.y) ** 2))
        init_mse = float(np.mean((ds.x @ before + 0 - ds.y) ** 2))
        assert mse < init_mse  # predictions reflect training


class TestCluster:
    def test_cluster_describes_devices(self):
        c = Cluster()
        assert c.device_count >= 8
        assert c.machine_count() >= 1
        assert len(c.devices) == c.device_count
        assert c.device_kinds()


class TestCostModelSearch:
    """Cost-model-driven strategy search (ref auto_parallel/cost_model.py +
    tuner search loop): rankings must reflect the roofline structure."""

    def _model(self, n_params=8e9, layers=32, heads=32):
        from paddle_tpu.distributed.auto_parallel import ModelDesc

        return ModelDesc(n_params=int(n_params), hidden_size=4096,
                         num_layers=layers, num_attention_heads=heads,
                         seq_len=4096)

    def test_small_model_prefers_pure_dp(self):
        from paddle_tpu.distributed.auto_parallel import ClusterDesc, search

        m = self._model(n_params=5e8)
        best = search(m, ClusterDesc(n_devices=8), global_batch=32)
        s = best["strategy"]
        assert s.tensor == 1 and s.pipe == 1, s.degrees()
        assert s.dp * s.sharding == 8

    def test_large_model_needs_sharding_axes(self):
        from paddle_tpu.distributed.auto_parallel import ClusterDesc, search

        m = self._model(n_params=70e9, layers=80, heads=64)
        # v5p-class HBM: 70B state (1.12TB at 16B/param) needs >=13 chips of
        # coverage; on 16GB v5e-64 it genuinely does NOT fit (1TB total) —
        # a correct infeasibility the model reports
        best = search(m, ClusterDesc(n_devices=64, hbm_bytes=95 << 30),
                      global_batch=64)
        s = best["strategy"]
        assert best["cost"].feasible
        assert s.tensor * s.sharding * s.pipe >= 16, s.degrees()

    def test_infeasible_strategies_are_rejected(self):
        from paddle_tpu.distributed.auto_parallel import (ClusterDesc,
                                                          TunedStrategy,
                                                          estimate_step_time)

        m = self._model(n_params=70e9)
        replicated = TunedStrategy(dp=8)
        cost = estimate_step_time(m, ClusterDesc(n_devices=8), replicated)
        assert not cost.feasible

    def test_pp_bubble_penalizes_step_time(self):
        from paddle_tpu.distributed.auto_parallel import (ClusterDesc,
                                                          TunedStrategy,
                                                          estimate_step_time)

        m = self._model()
        c = ClusterDesc(n_devices=8, hbm_bytes=95 << 30)  # all configs fit
        t_dp = estimate_step_time(m, c, TunedStrategy(dp=8), 32)
        t_pp = estimate_step_time(m, c, TunedStrategy(pipe=8), 32,
                                  num_micro=8)
        assert t_pp.pp_bubble_frac > 0 and t_dp.pp_bubble_frac == 0
        assert t_pp.step_s > t_dp.compute_s
