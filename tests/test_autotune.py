"""Autotuner tests (paddle_tpu/autotune/): config space validity and
seeded sampling, analytic cost-model sanity (monotonicity, the PR 3
speculative break-even, calibration), workload draw determinism and
warmup-stream disjointness, end-to-end search byte-determinism under a
counting clock, the hard reject gates (watchdog findings, token
fingerprint mismatch), tuned-profile round-trip/tamper detection, and
the serving_benchmark traffic-decoupling regression (two configs at one
seed must see byte-identical traffic)."""
import dataclasses
import json
import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.autotune.cost import (ACCEPT_P_RANDOM, ServingCostModel,
                                      expected_acceptance)
from paddle_tpu.autotune.features import FeatureVector
from paddle_tpu.autotune.profile import (TunedProfile, config_server_kwargs,
                                         resolve_profile)
from paddle_tpu.autotune.search import TrialRunner, autotune
from paddle_tpu.autotune.space import ALL_KNOBS, ConfigSpace, engine_space
from paddle_tpu.autotune.workload import (WorkloadSpec, draw_traffic,
                                          warmup_traffic)
from paddle_tpu.cost_model import (REF_DECODING, PagedTickCostModel,
                                   TickShape)

REPO = pathlib.Path(__file__).resolve().parents[1]


# ======================================================================
# config space
# ======================================================================

class TestConfigSpace:
    def test_default_is_valid_and_canonical(self):
        space = ConfigSpace(ALL_KNOBS)
        cfg = space.default()
        assert space.is_valid(cfg)
        assert cfg == space.canonicalize(cfg)
        assert set(cfg) == {k.name for k in ALL_KNOBS}

    def test_sample_deterministic_per_seed(self):
        space = engine_space(max_len=256)
        rng1, rng2 = np.random.RandomState(7), np.random.RandomState(7)
        seq1 = [space.sample(rng1) for _ in range(12)]
        seq2 = [space.sample(rng2) for _ in range(12)]
        assert seq1 == seq2

    def test_samples_respect_constraints(self):
        space = ConfigSpace(ALL_KNOBS)
        rng = np.random.RandomState(11)
        for _ in range(40):
            cfg = space.sample(rng)
            assert space.is_valid(cfg), space.errors(cfg)
            # cross-knob constraints can never leak out of sample()
            if cfg["pool_frac"] < 1.0:
                assert cfg["host_pool_mb"] != 0
            if cfg["draft_k"] > 0:
                assert cfg["tick_window"] <= 8

    def test_cross_knob_errors(self):
        space = ConfigSpace(ALL_KNOBS)
        starved = dict(space.default(), pool_frac=0.5, host_pool_mb=0)
        errs = space.errors(starved)
        assert any("host_pool_mb=0" in e for e in errs)
        wide_spec = dict(space.default(), draft_k=4, tick_window=16)
        errs = space.errors(wide_spec)
        assert any("tick_window > 8" in e for e in errs)
        with pytest.raises(ValueError, match="tick_window > 8"):
            space.validate(wide_spec)

    def test_schema_errors(self):
        space = ConfigSpace(ALL_KNOBS)
        cfg = space.default()
        assert any("unknown knob" in e
                   for e in space.errors(dict(cfg, bogus=1)))
        missing = dict(cfg)
        del missing["block_size"]
        assert any("missing knob" in e for e in space.errors(missing))
        assert any("not in" in e
                   for e in space.errors(dict(cfg, block_size=7)))

    def test_canonicalize_collapses_dead_knobs(self):
        space = ConfigSpace(ALL_KNOBS)
        base = space.default()
        # spec gate is dead without speculation -> one fingerprint
        a = dict(base, draft_k=0, spec_gate_low=0.5)
        b = dict(base, draft_k=0, spec_gate_low=4.0)
        assert space.fingerprint(a) == space.fingerprint(b)
        # ...but live once draft_k > 0 (cap the window to stay valid)
        a = dict(base, draft_k=4, tick_window=4, spec_gate_low=0.5)
        b = dict(base, draft_k=4, tick_window=4, spec_gate_low=4.0)
        assert space.fingerprint(a) != space.fingerprint(b)
        # fleet routing knobs are dead at one replica
        a = dict(base, fleet_replicas=1, prefix_weight=0.5)
        b = dict(base, fleet_replicas=1, prefix_weight=2.0)
        assert space.fingerprint(a) == space.fingerprint(b)

    def test_engine_space_pins_fleet_tier(self):
        space = engine_space(max_len=256, pins={"kv_quant": "int8"})
        rng = np.random.RandomState(3)
        for _ in range(10):
            cfg = space.sample(rng)
            assert cfg["fleet_replicas"] == 1
            assert cfg["kv_quant"] == "int8"
        bad = dict(space.default(), kv_quant="none")
        assert any("violates pin" in e for e in space.errors(bad))

    def test_max_len_bounds_block_size(self):
        space = ConfigSpace(ALL_KNOBS, max_len=12)
        assert space.knob("block_size").choices == (8,)
        assert space.default()["block_size"] == 8
        with pytest.raises(ValueError, match="no block_size choice"):
            ConfigSpace(ALL_KNOBS, max_len=4)

    def test_mutate_deterministic_valid_neighbor(self):
        space = engine_space(max_len=256)
        base = space.default()
        m1 = space.mutate(base, np.random.RandomState(5))
        m2 = space.mutate(base, np.random.RandomState(5))
        assert m1 == m2
        assert m1 != base
        assert space.is_valid(m1)


# ======================================================================
# cost model
# ======================================================================

class TestCostModel:
    def test_tick_cost_monotone_in_context(self):
        m = PagedTickCostModel()
        costs = [m.tick_seconds(TickShape(decoding=8, ctx_blocks=cb))
                 for cb in (1.0, 4.0, 16.0, 64.0)]
        assert costs == sorted(costs) and costs[0] < costs[-1]

    def test_trip_amortizes_round_trips(self):
        m = PagedTickCostModel()
        shape = TickShape(decoding=8)
        # one trip of w ticks beats w trips of 1 tick by (w-1) trip costs
        assert m.trip_seconds(shape, 16) < 16 * m.trip_seconds(shape, 1)
        # and the end-to-end model prefers wider tick windows, all else
        # equal (fewer host round trips for the same ticks)
        cm = ServingCostModel(None, max_batch=8)
        wl = WorkloadSpec(requests=16, max_new=32)
        cfg = engine_space(max_len=256).default()
        slow = cm.predict_seconds(dict(cfg, tick_window=1), wl)
        fast = cm.predict_seconds(dict(cfg, tick_window=16), wl)
        assert fast < slow

    def test_starved_pool_costs_more(self):
        cm = ServingCostModel(None, max_batch=8)
        wl = WorkloadSpec(requests=16, max_new=32)
        cfg = engine_space(max_len=256).default()
        parity = cm.predict_seconds(cfg, wl)
        starved = cm.predict_seconds(
            dict(cfg, pool_frac=0.5, host_pool_mb=16), wl)
        assert starved > parity

    def test_spec_break_even_matches_pr3_gate(self):
        """The uncalibrated prior reproduces the PR 3 measurement: the
        speculative break-even at the reference shape is k/2 accepted
        drafts per window — exactly the default dynamic-gate floor."""
        from paddle_tpu.inference.speculative import SpecConfig

        m = PagedTickCostModel()
        shape = TickShape(decoding=REF_DECODING)
        assert m.spec_break_even(4, shape) == pytest.approx(2.0)
        assert m.spec_break_even(4, shape) == pytest.approx(
            SpecConfig().gate_low)
        assert m.spec_break_even(2, shape) == pytest.approx(1.0)
        # ServingCostModel reaches the same number through the workload
        cm = ServingCostModel(None, max_batch=REF_DECODING)
        wl = WorkloadSpec(requests=REF_DECODING, max_new=32,
                          prompt_ladder=(48,))
        assert cm.spec_break_even(4, wl) == pytest.approx(2.0, abs=0.3)

    def test_expected_acceptance_geometric(self):
        assert expected_acceptance(4, 1.0) == pytest.approx(4.0)
        assert expected_acceptance(4, 0.0) == pytest.approx(0.0)
        e = expected_acceptance(4, ACCEPT_P_RANDOM)
        assert 0.0 < e < 1.0

    def test_calibration_reduces_error(self):
        """Ridge calibration from measured trials must beat the prior on
        a held-out config when the truth deviates from the prior."""
        prior = PagedTickCostModel()
        truth = PagedTickCostModel(prior.c_trip * 2.0, prior.c_tick * 0.5,
                                   prior.c_flop * 1.5, prior.c_byte * 0.7)
        cm = ServingCostModel(None, max_batch=8)
        wl = WorkloadSpec(requests=16, max_new=32)
        space = engine_space(max_len=256)
        rng = np.random.RandomState(0)
        configs = [space.default()] + [space.sample(rng) for _ in range(7)]
        held_out = space.sample(rng)
        for cfg in configs:
            a = cm.aggregates(cfg, wl)
            cm.observe(cfg, wl, truth.predict(a["trips"], a["ticks"],
                                              a["flops"], a["bytes"]))
        cm.recalibrate()
        a = cm.aggregates(held_out, wl)
        want = truth.predict(a["trips"], a["ticks"], a["flops"], a["bytes"])
        prior_err = abs(prior.predict(a["trips"], a["ticks"], a["flops"],
                                      a["bytes"]) - want)
        calib_err = abs(cm.tick_model.predict(
            a["trips"], a["ticks"], a["flops"], a["bytes"]) - want)
        assert calib_err < prior_err

    def test_tick_model_round_trip(self):
        m = PagedTickCostModel(1e-3, 2e-4, 3e-9, 4e-11)
        m2 = PagedTickCostModel.from_dict(m.to_dict())
        assert m2.to_dict() == m.to_dict()


# ======================================================================
# workload
# ======================================================================

class TestWorkload:
    def test_draw_deterministic_and_config_free(self):
        spec = WorkloadSpec(requests=8, max_new=8, seed=5)
        t1, t2 = draw_traffic(spec), draw_traffic(spec)
        assert t1.signature() == t2.signature()
        assert t1.requests == t2.requests

    def test_truncated_is_strict_prefix(self):
        spec = WorkloadSpec(requests=8, max_new=8, seed=5)
        full = draw_traffic(spec)
        short = draw_traffic(spec.truncated(3))
        assert short.requests == full.requests[:3]

    def test_warmup_stream_disjoint_from_measured(self):
        spec = WorkloadSpec(requests=4, max_new=8, seed=5)
        measured = draw_traffic(spec).requests
        warm = warmup_traffic(spec, 4)
        assert [w.prompt for w in warm] != \
            [m.prompt for m in measured[:4]]

    def test_repeat_suffix_tiles_shared_motif(self):
        spec = WorkloadSpec(requests=4, max_new=8, repeat_suffix=True,
                            seed=5)
        t = draw_traffic(spec)
        for r in t.requests:
            assert r.prompt[:len(t.motif)] == \
                t.motif[:len(r.prompt)] or len(r.prompt) < len(t.motif)
            assert r.prompt == tuple(
                (list(t.motif) * (len(r.prompt) // len(t.motif) + 1))
                [:len(r.prompt)])

    def test_open_loop_schedule_covers_all_requests(self):
        spec = WorkloadSpec(requests=10, max_new=8, arrival_rate=100.0,
                            burst=4, seed=1)
        t = draw_traffic(spec)
        assert sum(n for _, n in t.schedule) == 10
        times = [at for at, _ in t.schedule]
        assert times == sorted(times)

    def test_spec_round_trip(self):
        spec = WorkloadSpec(requests=8, max_new=8, mixed_priority=True,
                            arrival_rate=50.0, seed=9)
        assert WorkloadSpec.from_dict(spec.to_dict()) == spec


# ======================================================================
# reject gates (stub runner — no model, no jax programs)
# ======================================================================

class _StubRunner:
    """Duck-typed TrialRunner: instant measurements with scripted
    findings/fingerprints, so the gate logic is tested in isolation."""

    def __init__(self, workload, *, findings_for_nondefault=None,
                 wrong_tokens_for_nondefault=False):
        self.workload = workload
        self.max_len = 256
        self.max_batch = 4
        self.model = None
        self.space = engine_space(max_len=self.max_len)
        self._default_fp = self.space.fingerprint(self.space.default())
        self._findings = findings_for_nondefault or []
        self._wrong_tokens = wrong_tokens_for_nondefault

    def traffic_for(self, spec):
        return draw_traffic(spec)

    def run(self, config, workload=None):
        spec = workload if workload is not None else self.workload
        fp_cfg = self.space.fingerprint(config)
        default = fp_cfg == self._default_fp
        tokens = spec.requests * spec.max_new
        # non-default configs measure FASTER — the gates, not the
        # objective, must be what keeps them from winning
        seconds = 1.0 if default else 0.1
        fv = FeatureVector(tokens=tokens, seconds=seconds,
                           tok_s=tokens / seconds)
        tok_fp = "ref0" if (default or not self._wrong_tokens) \
            else f"bad-{fp_cfg}"
        findings = [] if default else list(self._findings)
        return fv, tok_fp, findings


class TestRejectGates:
    def _tune(self, runner, budget=4):
        return autotune(runner, budget=budget, seed=0,
                        space=runner.space,
                        cost=ServingCostModel(None,
                                              max_batch=runner.max_batch))

    def test_watchdog_finding_rejects_fast_config(self):
        wl = WorkloadSpec(requests=8, max_new=8, seed=0)
        runner = _StubRunner(
            wl, findings_for_nondefault=[
                {"kind": "preemption_storm", "detail": "stub"}])
        profile, trials = self._tune(runner)
        rejected = [t for t in trials if not t.accepted]
        assert rejected, "every non-default trial carries a finding"
        assert all(t.reject_reason.startswith("watchdog:preemption_storm")
                   for t in rejected)
        # the 10x-faster pathological configs never become the winner
        assert profile.config == runner.space.default()
        assert profile.search["winner_trial"] == 0
        assert {r["index"] for r in profile.search["rejected"]} == \
            {t.index for t in rejected}

    def test_token_fingerprint_mismatch_rejects(self):
        wl = WorkloadSpec(requests=8, max_new=8, seed=0)
        runner = _StubRunner(wl, wrong_tokens_for_nondefault=True)
        profile, trials = self._tune(runner)
        full_rejects = [t for t in trials
                        if t.rung == "full" and not t.accepted]
        assert full_rejects, "full-rung non-default trials must be gated"
        assert all(t.reject_reason.startswith("token_fingerprint_mismatch")
                   for t in full_rejects)
        # wrong-but-fast never wins; the reference stays the incumbent
        assert profile.config == runner.space.default()

    def test_trial_artifacts_feed_telemetry_dump(self, tmp_path, capsys):
        """TrialResult.to_dict() is the artifact telemetry_dump's trials
        mode consumes — keep the contract wired end to end."""
        sys.path.insert(0, str(REPO / "tools"))
        try:
            import telemetry_dump
        finally:
            sys.path.pop(0)
        wl = WorkloadSpec(requests=8, max_new=8, seed=0)
        runner = _StubRunner(wl, findings_for_nondefault=[
            {"kind": "pool_pressure", "detail": "stub"}])
        _, trials = self._tune(runner)
        paths = []
        for t in trials:
            p = tmp_path / f"trial_{t.index:02d}.json"
            p.write_text(json.dumps(t.to_dict()))
            paths.append(str(p))
        assert telemetry_dump.main(paths) == 0
        out = capsys.readouterr().out
        assert f"autotune trials ({len(trials)})" in out
        assert "REJECT watchdog" in out
        # mixing trials with another artifact kind is refused
        other = tmp_path / "metrics.json"
        other.write_text(json.dumps({"counters": {}}))
        assert telemetry_dump.main(paths + [str(other)]) == 2


# ======================================================================
# tuned profile
# ======================================================================

def _profile_for(space, config, workload):
    return TunedProfile(
        config=space.validate(config),
        config_fingerprint=space.fingerprint(config),
        workload=workload.to_dict(),
        workload_signature=draw_traffic(workload).signature(),
        metrics=FeatureVector().to_dict(),
        baseline=FeatureVector().to_dict(),
        search={"budget": 1, "seed": 0},
        cost_model=PagedTickCostModel().to_dict(),
    )


class TestTunedProfile:
    def test_round_trip(self, tmp_path):
        space = ConfigSpace(ALL_KNOBS)
        wl = WorkloadSpec(requests=4, max_new=8)
        prof = _profile_for(space, dict(space.default(), tick_window=4),
                            wl)
        path = str(tmp_path / "tuned.json")
        prof.save(path, now=123.0)
        back = TunedProfile.load(path)
        assert back.config == prof.config
        assert back.created_unix == 123.0
        assert back.canonical_json() == prof.canonical_json()
        assert back.workload_spec() == wl

    def test_tampered_config_fails_loudly(self, tmp_path):
        space = ConfigSpace(ALL_KNOBS)
        prof = _profile_for(space, space.default(),
                            WorkloadSpec(requests=4, max_new=8))
        d = prof.to_dict()
        d["config"]["tick_window"] = 4          # edited after tuning
        with pytest.raises(ValueError, match="fingerprint mismatch"):
            TunedProfile.from_dict(d)
        d2 = prof.to_dict()
        d2["schema"] = 99
        with pytest.raises(ValueError, match="schema"):
            TunedProfile.from_dict(d2)

    def test_resolve_profile_accepts_all_forms(self, tmp_path):
        space = ConfigSpace(ALL_KNOBS)
        prof = _profile_for(space, space.default(),
                            WorkloadSpec(requests=4, max_new=8))
        assert resolve_profile(None) is None
        assert resolve_profile(prof) is prof
        path = str(tmp_path / "p.json")
        prof.save(path)
        assert resolve_profile(path).config == prof.config
        assert resolve_profile(prof.to_dict()).config == prof.config
        with pytest.raises(ValueError, match="profile must be"):
            resolve_profile(42)

    def test_config_server_kwargs_pool_geometry(self):
        """pool_frac resolves against THIS geometry's fp-parity budget
        and host_pool_mb converts to bytes."""
        space = ConfigSpace(ALL_KNOBS)
        cfg = dict(space.default(), pool_frac=0.5, host_pool_mb=16,
                   kv_quant="int8", draft_k=4, tick_window=4)
        from paddle_tpu.models import LlamaConfig

        mcfg = LlamaConfig(vocab_size=64, hidden_size=32,
                           intermediate_size=64, num_hidden_layers=1,
                           num_attention_heads=2, num_key_value_heads=1,
                           max_position_embeddings=256, dtype="float32",
                           use_flash_attention=False)
        kw = config_server_kwargs(space.validate(cfg), mcfg,
                                  max_batch=4, max_len=64)
        assert kw["cache"] == "paged"
        assert kw["kv_quant"] == "int8"
        assert kw["spec"].k == 4
        assert kw["pool_bytes"] >= 1
        assert kw["host_pool_bytes"] == 16 << 20
        # at parity no pool override is emitted at all
        kw2 = config_server_kwargs(space.default(), mcfg,
                                   max_batch=4, max_len=64)
        assert "pool_bytes" not in kw2 and "host_pool_bytes" not in kw2


# ======================================================================
# end-to-end search on a real (tiny) model
# ======================================================================

class _CountingClock:
    """Deterministic time source: every read advances one quantum, so
    measured durations count clock reads instead of wall time."""

    def __init__(self, quantum: float = 1e-4):
        self.t = 0.0
        self.quantum = quantum

    def __call__(self) -> float:
        self.t += self.quantum
        return self.t


@pytest.fixture(scope="module")
def tiny_model():
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=64,
                      num_hidden_layers=1, num_attention_heads=2,
                      num_key_value_heads=1, max_position_embeddings=256,
                      dtype="float32", use_flash_attention=False)
    paddle.seed(0)
    return LlamaForCausalLM(cfg)


_TUNE_WL = dict(requests=6, max_new=8, prompt_ladder=(8, 12, 16),
                vocab_size=64, seed=0)


def _search(model, budget=3, seed=0):
    wl = WorkloadSpec(**_TUNE_WL)
    runner = TrialRunner(model, wl, max_batch=4, clock=_CountingClock())
    return autotune(runner, budget=budget, seed=seed)


class TestSearchEndToEnd:
    def test_same_seed_same_profile_bytes(self, tiny_model):
        """The determinism contract: two independent searches (fresh
        runner, fresh clock) at one seed produce byte-identical
        profiles and identical trial sequences."""
        p1, t1 = _search(tiny_model)
        p2, t2 = _search(tiny_model)
        assert p1.canonical_json() == p2.canonical_json()
        assert [(t.fingerprint, t.rung, t.accepted) for t in t1] == \
            [(t.fingerprint, t.rung, t.accepted) for t in t2]
        # the reference trial ran the default and was accepted
        assert t1[0].index == 0 and t1[0].rung == "full"
        assert t1[0].accepted
        # profile bookkeeping is consistent
        assert p1.search["trials"] == len(t1)
        win = t1[p1.search["winner_trial"]]
        assert win.accepted and win.rung == "full"
        assert p1.config == win.config
        assert p1.workload_signature == draw_traffic(
            WorkloadSpec(**_TUNE_WL)).signature()

    def test_profile_applies_to_server(self, tiny_model):
        """GenerationServer(profile=) adopts the tuned knobs wherever
        the ctor argument is still at its declared default — and an
        explicit caller argument always wins over the profile."""
        from paddle_tpu.inference.serving import GenerationServer

        space = ConfigSpace(ALL_KNOBS)
        cfg = dict(space.default(), tick_window=4, block_size=8,
                   kv_quant="int8")
        prof = _profile_for(space, cfg, WorkloadSpec(**_TUNE_WL))
        srv = GenerationServer(tiny_model, max_batch=2, max_len=64,
                               profile=prof)
        assert srv.profile is prof
        assert srv.cache_mode == "paged"
        assert srv.tick_window == 4
        assert srv.block_size == 8
        assert srv.kv_quant == "int8"
        # explicit NON-default ctor args beat the profile (an arg left
        # at its declared default is indistinguishable from "not
        # passed", so the profile fills it — kv_quant stays tuned)
        srv2 = GenerationServer(tiny_model, max_batch=2, max_len=64,
                                profile=prof, tick_window=2,
                                block_size=32)
        assert srv2.tick_window == 2
        assert srv2.block_size == 32
        assert srv2.kv_quant == "int8"   # untouched knob still tuned


# ======================================================================
# serving_benchmark traffic decoupling (subprocess regression)
# ======================================================================

def _bench(extra):
    proc = subprocess.run(
        [sys.executable, "tools/serving_benchmark.py", "--paged", "--json",
         "--requests", "6", "--max-new", "8", "--seed", "3"] + extra,
        capture_output=True, text=True, timeout=600, cwd=str(REPO),
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_benchmark_traffic_decoupled_from_config():
    """Two different serving configs at one --seed must see
    byte-identical traffic (traffic_fingerprint) AND — greedy serving
    being config-invariant — produce identical tokens
    (tokens_fingerprint). This is the regression gate for the
    warmup-rng split: before it, warmup consumption shifted the
    measured trace under the config."""
    a = _bench(["--slots", "4"])
    b = _bench(["--slots", "3", "--tick-window", "4", "--block-size", "8"])
    assert a["traffic_fingerprint"] == b["traffic_fingerprint"]
    assert a["tokens_fingerprint"] == b["tokens_fingerprint"]
    assert a["traffic_fingerprint"] != a["tokens_fingerprint"]
