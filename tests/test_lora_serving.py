"""Multi-tenant LoRA serving (inference/lora.py): paged multi-adapter
decode must be TOKEN-EXACT vs the dense model with that adapter's weights
merged in — fp AND int8-KV — while the adapter pool's page lifecycle
(acquire/release, LRU retention, pin, eviction under pressure) mirrors the
KV BlockAllocator's discipline, with zero steady-state recompiles across
adapter churn. Quick tier on CPU."""
import copy

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import (AdapterRegistry, GenerationServer,
                                  LoRAConfig)
from paddle_tpu.inference.lora import (LORA_TARGETS, AdapterPool,
                                       target_dims)
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

_TGT_MODS = {"q": "self_attn.q_proj", "k": "self_attn.k_proj",
             "v": "self_attn.v_proj", "o": "self_attn.o_proj",
             "gate": "mlp.gate_proj", "up": "mlp.up_proj",
             "down": "mlp.down_proj"}


def _model(max_pos=160):
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=max_pos,
                      dtype="float32", use_flash_attention=False)
    paddle.seed(7)
    return LlamaForCausalLM(cfg), cfg


def _adapter_weights(cfg, rank, seed, targets=LORA_TARGETS):
    rng = np.random.RandomState(seed)
    dims = target_dims(cfg)
    w = {}
    for layer in range(cfg.num_hidden_layers):
        for t in targets:
            fi, fo = dims[t]
            w[(layer, t)] = (
                rng.normal(0, 0.02, (fi, rank)).astype(np.float32),
                rng.normal(0, 0.05, (rank, fo)).astype(np.float32))
    return w


def _merged(model, weights, rank, alpha):
    """Dense reference: deep-copy the base model and fold each target's
    ``scale * A @ B`` delta straight into its weight."""
    m = copy.deepcopy(model)
    s = alpha / rank
    for (layer, t), (A, B) in weights.items():
        mod = m.model.layers[layer]
        for part in _TGT_MODS[t].split("."):
            mod = getattr(mod, part)
        W = np.asarray(mod.weight.numpy(), np.float32)
        mod.weight.set_value((W + s * (A @ B)).astype(np.float32))
    return m


@pytest.mark.parametrize("kv_quant", ["none", "int8"])
def test_multi_adapter_paged_matches_merged_dense(kv_quant):
    """Heterogeneous batch — two adapters of DIFFERENT rank plus an
    adapterless row decoding in the same compiled programs — must emit
    exactly the tokens each per-adapter MERGED model emits solo."""
    model, cfg = _model()
    w1 = _adapter_weights(cfg, 4, seed=1)
    w2 = _adapter_weights(cfg, 2, seed=2)
    reg = AdapterRegistry()
    reg.register("a1", w1, rank=4, alpha=8.0)
    reg.register("a2", w2, rank=2, alpha=2.0)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size, (n,)).tolist()
               for n in (6, 4, 9)]

    srv = GenerationServer(model, max_batch=3, max_len=64, cache="paged",
                           block_size=4, prefill_chunk=8, kv_quant=kv_quant,
                           lora=LoRAConfig(reg, max_live_adapters=4,
                                           max_rank=4))
    rids = [srv.submit(prompts[0], max_new_tokens=8, adapter="a1"),
            srv.submit(prompts[1], max_new_tokens=8, adapter="a2"),
            srv.submit(prompts[2], max_new_tokens=8)]
    out = srv.run()

    for rid, w, meta, p in ((rids[0], w1, (4, 8.0), prompts[0]),
                            (rids[1], w2, (2, 2.0), prompts[1]),
                            (rids[2], None, None, prompts[2])):
        ref_model = model if w is None else _merged(model, w, *meta)
        ref = GenerationServer(ref_model, max_batch=1, max_len=64,
                               cache="paged", block_size=4, prefill_chunk=8,
                               kv_quant=kv_quant)
        rr = ref.submit(p, max_new_tokens=8)
        assert out[rid] == ref.run()[rr], (kv_quant, meta)
    # slot release dropped every adapter ref; KV pool fully drained
    assert srv.alloc.blocks_in_use == 0
    assert srv._lora.alloc.blocks_in_use == 0


def test_train_export_serve_roundtrip(tmp_path):
    """Train-side nn.lora checkpoint → registry → paged serving must match
    the same model with merge_lora() folded in: the two halves of the
    subsystem agree on what an adapter means."""
    from paddle_tpu.nn.lora import attach_lora, export_adapter, merge_lora

    model, cfg = _model()
    tuned = copy.deepcopy(model)
    attach_lora(tuned, rank=4, alpha=8.0,
                targets=("q_proj", "v_proj", "up_proj"))
    # stand-in for a training run: kick every B off its zero init
    rng = np.random.RandomState(5)
    for _, layer in tuned.named_sublayers(include_self=True):
        if type(layer).__name__ == "LoRALinear":
            layer.lora_B.set_value(
                rng.normal(0, 0.05, layer.lora_B.shape).astype(np.float32))
    path = str(tmp_path / "adapter.npz")
    export_adapter(tuned, path)

    reg = AdapterRegistry()
    from paddle_tpu.nn.lora import load_adapter

    reg.register("tuned", load_adapter(path))
    srv = GenerationServer(model, max_batch=2, max_len=64, cache="paged",
                           block_size=4, prefill_chunk=8,
                           lora=LoRAConfig(reg, max_live_adapters=2,
                                           max_rank=4,
                                           targets=("q", "v", "up")))
    prompt = [3, 14, 15, 9, 2, 6, 5]
    rid = srv.submit(prompt, max_new_tokens=10, adapter="tuned")
    got = srv.run()[rid]

    merged = merge_lora(tuned, targets=("q_proj", "v_proj", "up_proj"))
    ref = GenerationServer(merged, max_batch=1, max_len=64, cache="paged",
                           block_size=4, prefill_chunk=8)
    rr = ref.submit(prompt, max_new_tokens=10)
    assert got == ref.run()[rr]


def test_submit_adapter_validation():
    """The whole rejection ladder fires at submit() — before the request
    can queue: no lora config, unknown name, rank past the pool's
    max_rank, and shape-incompatible factors."""
    model, cfg = _model()
    plain = GenerationServer(model, max_batch=2, max_len=64, cache="paged",
                             block_size=4)
    with pytest.raises(ValueError, match="lora=LoRAConfig"):
        plain.submit([1, 2, 3], max_new_tokens=4, adapter="a1")

    reg = AdapterRegistry()
    reg.register("ok", _adapter_weights(cfg, 2, seed=1), rank=2, alpha=4.0)
    reg.register("fat", _adapter_weights(cfg, 8, seed=2), rank=8, alpha=8.0)
    bad = _adapter_weights(cfg, 2, seed=3)
    A, B = bad[(0, "q")]
    bad[(0, "q")] = (A[:-1], B)          # wrong in_features
    reg.register("misshapen", bad, rank=2, alpha=4.0)
    srv = GenerationServer(model, max_batch=2, max_len=64, cache="paged",
                           block_size=4,
                           lora=LoRAConfig(reg, max_live_adapters=2,
                                           max_rank=4))
    with pytest.raises(ValueError, match="unknown adapter"):
        srv.submit([1, 2, 3], max_new_tokens=4, adapter="nope")
    with pytest.raises(ValueError, match="exceeds the pool's max_rank"):
        srv.submit([1, 2, 3], max_new_tokens=4, adapter="fat")
    with pytest.raises(ValueError, match="shape"):
        srv.submit([1, 2, 3], max_new_tokens=4, adapter="misshapen")
    # the ladder rejected at the door: nothing queued, nothing resident
    assert len(srv._sched) == 0
    rid = srv.submit([1, 2, 3], max_new_tokens=4, adapter="ok")
    assert len(srv.run()[rid]) == 7

    with pytest.raises(ValueError, match="paged"):
        GenerationServer(model, max_batch=2, max_len=64,
                         lora=LoRAConfig(reg, max_live_adapters=2,
                                         max_rank=4))


@pytest.mark.graftlint
def test_zero_recompiles_across_adapter_churn():
    """6 adapters through a 2-page pool: register/evict/upload churn on
    every refill, plus an adapterless request — all steady-state trips
    must hit the jit cache (the static-shape gather is the whole design).
    Late registration (after warmup) must also not recompile."""
    from paddle_tpu.analysis import jit_cache_guard

    model, cfg = _model()
    reg = AdapterRegistry()
    for i in range(5):
        reg.register(f"a{i}", _adapter_weights(cfg, 2, seed=10 + i),
                     rank=2, alpha=4.0)
    srv = GenerationServer(model, max_batch=2, max_len=64, cache="paged",
                           block_size=4, prefill_chunk=8,
                           lora=LoRAConfig(reg, max_live_adapters=2,
                                           max_rank=2))
    rng = np.random.RandomState(3)
    # warmup: compile prefill + decode with the lora args in place
    for i in range(2):
        srv.submit(rng.randint(1, cfg.vocab_size, (6,)).tolist(),
                   max_new_tokens=6, adapter=f"a{i}")
    srv.run()

    reg.register("late", _adapter_weights(cfg, 2, seed=99), rank=2,
                 alpha=4.0)  # registered AFTER warmup: upload only, no trace
    rids = []
    with jit_cache_guard("lora adapter churn") as g:
        for i, name in enumerate(("a2", "a3", "a4", "late", None, "a0")):
            rids.append(srv.submit(
                rng.randint(1, cfg.vocab_size, (4 + i,)).tolist(),
                max_new_tokens=6, adapter=name))
        out = srv.run()
    assert g.compiles == 0
    assert all(len(out[r]) >= 7 for r in rids)
    st = srv._lora.stats()
    assert st["adapter_evictions"] > 0, st   # churn actually happened
    assert st["adapter_uploads"] >= 6, st


def test_pinned_adapter_page_survives_pool_pressure():
    """AdapterPool page lifecycle under pressure: a PINNED resident
    adapter's page is never reclaimed — eviction takes the unpinned
    cached page; with every page pinned-or-live, acquire refuses."""
    model, cfg = _model()
    reg = AdapterRegistry()
    for i in range(3):
        reg.register(f"a{i}", _adapter_weights(cfg, 2, seed=20 + i),
                     rank=2, alpha=4.0)
    pool = AdapterPool(cfg, LoRAConfig(reg, max_live_adapters=2, max_rank=2))
    p0 = pool.acquire("a0")
    p1 = pool.acquire("a1")
    pool.release(p0)
    pool.release(p1)                  # both cached, a0 is LRU-coldest
    pool.pin("a0")
    p2 = pool.acquire("a2")           # pressure: must evict, but NOT a0
    assert pool.is_resident("a0") and not pool.is_resident("a1")
    assert pool.stats()["adapter_evictions"] == 1
    # a2 live + a0 pinned = no reclaimable page anywhere
    assert not pool.can_acquire("a1")
    with pytest.raises(RuntimeError):
        pool.acquire("a1")
    pool.unpin("a0")
    assert pool.can_acquire("a1")     # unpinned page is fair game again
    pool.release(p2)
    pool.acquire("a1")
    assert pool.is_resident("a1")


def test_adapter_refcount_conserved_across_preempt_swap_resume():
    """A high-priority burst preempts a decoding LoRA request (KV swaps to
    host, adapter ref drops); the victim resumes and finishes token-exact.
    Afterwards BOTH allocators — KV blocks and adapter pages — must show
    zero live refs: nothing leaked through the preempt/resume cycle."""
    model, cfg = _model()
    w = _adapter_weights(cfg, 2, seed=31)
    reg = AdapterRegistry()
    reg.register("a0", w, rank=2, alpha=4.0)
    reg.register("hot", _adapter_weights(cfg, 2, seed=32), rank=2, alpha=4.0)

    srv = GenerationServer(model, max_batch=1, max_len=64, cache="paged",
                           block_size=4, prefill_chunk=8, num_blocks=7,
                           policy="priority",
                           lora=LoRAConfig(reg, max_live_adapters=1,
                                           max_rank=2))
    victim = srv.submit([5, 9, 2, 7, 6, 1], max_new_tokens=12,
                        adapter="a0", priority=2)
    for _ in range(4):               # decode a few ticks, then preempt
        srv.step()
    hot = srv.submit([4, 4, 8], max_new_tokens=6, adapter="hot", priority=0)
    got_victim = srv.run()[victim]
    assert srv._preemptions >= 1     # the single slot WAS displaced
    assert srv._resumes >= 1
    assert srv.alloc.blocks_in_use == 0
    assert srv._lora.alloc.blocks_in_use == 0          # refs conserved
    assert srv._lora.stats()["adapter_evictions"] >= 1  # 1-page pool churned

    # the victim's tokens must be IDENTICAL to an UNINTERRUPTED solo decode
    # (bit-exact swap/resume with the adapter attached)
    ref = GenerationServer(model, max_batch=1, max_len=64, cache="paged",
                           block_size=4, prefill_chunk=8,
                           lora=LoRAConfig(reg, max_live_adapters=1,
                                           max_rank=2))
    rr = ref.submit([5, 9, 2, 7, 6, 1], max_new_tokens=12, adapter="a0")
    assert got_victim == ref.run()[rr]


def test_wfq_demand_governs_adapter_residency():
    """Scheduler.adapter_demand() lists waiting adapters in pop order;
    AdapterPool.warm() replays it so the tenant the policy favors keeps
    its adapter resident — the coldest page belongs to the LAST tenant in
    demand order, and pressure evicts that one."""
    from paddle_tpu.inference.scheduler import Scheduler

    model, cfg = _model()
    reg = AdapterRegistry()
    for i in range(3):
        reg.register(f"a{i}", _adapter_weights(cfg, 2, seed=40 + i),
                     rank=2, alpha=4.0)
    sched = Scheduler(policy="wfq", weights={"gold": 8.0, "bronze": 1.0})
    sched.submit(object(), 0, tenant="bronze", cost=64.0, adapter="a1")
    sched.submit(object(), 1, tenant="gold", cost=64.0, adapter="a0")
    # gold's 8x weight pops first despite submitting second
    assert sched.adapter_demand() == ["a0", "a1"]

    pool = AdapterPool(cfg, LoRAConfig(reg, max_live_adapters=2, max_rank=2))
    pool.release(pool.acquire("a0"))
    pool.release(pool.acquire("a1"))   # LRU order now: a0 coldest
    pool.warm(sched.adapter_demand())  # demand says a0 matters MOST
    pool.acquire("a2")                 # pressure: one cached page must go
    assert pool.is_resident("a0")      # warm() saved the favored tenant
    assert not pool.is_resident("a1")


def test_per_tenant_sched_metrics_and_adapter_stats():
    """sched_metrics() carries the adapter-pool counters and a per-tenant
    TTFT/TPOT p50/p95 breakdown over completed requests."""
    model, cfg = _model()
    reg = AdapterRegistry()
    reg.register("a0", _adapter_weights(cfg, 2, seed=50), rank=2, alpha=4.0)
    srv = GenerationServer(model, max_batch=2, max_len=64, cache="paged",
                           block_size=4, prefill_chunk=8, policy="wfq",
                           lora=LoRAConfig(reg, max_live_adapters=2,
                                           max_rank=2))
    rng = np.random.RandomState(1)
    for i in range(4):
        srv.submit(rng.randint(1, cfg.vocab_size, (5,)).tolist(),
                   max_new_tokens=6, tenant=("t0", "t1")[i % 2],
                   adapter="a0" if i % 2 == 0 else None)
    srv.run()
    m = srv.sched_metrics()
    assert m["adapter_pool_bytes"] > 0
    assert m["adapters_registered"] == 1
    assert m["adapter_hits"] + m["adapter_uploads"] >= 2
    assert 0.0 <= m["adapter_hit_rate"] <= 1.0
    for t in ("t0", "t1"):
        row = m["tenants"][t]
        assert row["completed"] == 2.0
        assert row["ttft_p50_ms"] > 0 and row["ttft_p95_ms"] >= row[
            "ttft_p50_ms"]
        assert "tpot_p50_ms" in row and "tpot_p95_ms" in row
