"""Multi-process ENGINE training with per-rank data shards — loss and final
weights must match the single-process run on values.

This is the reference's strongest distributed correctness pattern
(test_dist_base.py:899: subprocess trainers with per-rank readers compared
against a single-process run), executed for real across OS processes:
launcher rendezvous → init_parallel_env → jax.distributed.initialize →
ParallelEngine with the per-process data path
(jax.make_array_from_process_local_data) → 3 DP train steps → parity.

Every prior multi-device parity claim in this repo was single-process
virtual-mesh; this file is where the framework first trains across a
process boundary (VERDICT r4 item 1)."""
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.optimizer import AdamW
from paddle_tpu.parallel import ParallelEngine

_B, _S, _STEPS = 4, 16, 3

_CHILD = """
import os, sys
sys.path.insert(0, '/root/repo')
os.environ.pop('XLA_FLAGS', None)  # 1 CPU device per process
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_enable_x64', True)
jax.config.update('jax_default_matmul_precision', 'highest')
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from jax.sharding import Mesh
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.optimizer import AdamW
from paddle_tpu.parallel import ParallelEngine

env = dist.init_parallel_env()
assert jax.process_count() == 2, jax.process_count()
rank = env.rank
B, S, STEPS = {B}, {S}, {STEPS}
cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=48,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=32,
                  dtype="float32", use_flash_attention=False,
                  tie_word_embeddings=False, fused_lm_head_ce=False)
paddle.seed(42)  # identical init on every process replaces the broadcast
model = LlamaForCausalLM(cfg)
opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
mesh = Mesh(np.array(jax.devices()), ('data',))
eng = ParallelEngine(model, optimizer=opt, loss_fn=model.loss_fn, mesh=mesh)
rng = np.random.RandomState(0)
losses = []
lo, hi = rank * (B // 2), (rank + 1) * (B // 2)
for _ in range(STEPS):
    x = rng.randint(0, cfg.vocab_size, (B, S)).astype('int32')
    y = rng.randint(0, cfg.vocab_size, (B, S)).astype('int64')
    # per-rank reader: this process only ever holds ITS shard of the batch
    loss = eng.train_batch(x[lo:hi], y[lo:hi])
    losses.append(float(np.asarray(loss.value)))
eng.sync_to_model()
out = {{'loss_' + str(i): np.float64(l) for i, l in enumerate(losses)}}
for k, v in model.state_dict().items():
    out['w_' + k] = np.asarray(v.value)
np.savez({out!r} + str(rank) + '.npz', **out)
print('TRAINED', losses)
"""


def test_two_process_dp_train_parity(tmp_path):
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        master_port = s.getsockname()[1]

    # ---- single-process reference (full global batch, one device) ----
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=48,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=32,
                      dtype="float32", use_flash_attention=False,
                      tie_word_embeddings=False, fused_lm_head_ce=False)
    paddle.seed(42)
    model = LlamaForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
    eng = ParallelEngine(model, optimizer=opt, loss_fn=model.loss_fn)
    rng = np.random.RandomState(0)
    ref_losses = []
    for _ in range(_STEPS):
        x = rng.randint(0, cfg.vocab_size, (_B, _S)).astype("int32")
        y = rng.randint(0, cfg.vocab_size, (_B, _S)).astype("int64")
        ref_losses.append(float(np.asarray(eng.train_batch(x, y).value)))
    eng.sync_to_model()
    ref_w = {k: np.asarray(v.value) for k, v in model.state_dict().items()}

    # ---- 2-process launcher run with per-rank shards ----
    script = tmp_path / "train.py"
    script.write_text(_CHILD.format(B=_B, S=_S, STEPS=_STEPS,
                                    out=str(tmp_path / "rank")))

    def run(rank):
        # launcher output to files, not PIPE: a full 64 KiB pipe buffer
        # would block the child and deadlock wait()
        out = open(tmp_path / f"launcher{rank}.log", "wb")
        return subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", "2", "--rank", str(rank),
             "--master", f"127.0.0.1:{master_port}",
             "--max_restart", "0",
             "--log_dir", str(tmp_path / f"log{rank}"), str(script)],
            cwd="/root/repo", stdout=out, stderr=out)

    p0, p1 = run(0), run(1)
    assert p0.wait(timeout=420) == 0, \
        (tmp_path / "launcher0.log").read_text()[-1500:]
    assert p1.wait(timeout=120) == 0, \
        (tmp_path / "launcher1.log").read_text()[-1500:]

    got = [dict(np.load(tmp_path / f"rank{r}.npz")) for r in (0, 1)]
    for r, g in enumerate(got):
        # per-rank reported loss is the GLOBAL mean (psum over the data
        # axis) — both ranks and the single-process run must agree
        for i, ref in enumerate(ref_losses):
            np.testing.assert_allclose(
                g[f"loss_{i}"], ref, rtol=1e-4, atol=1e-6,
                err_msg=f"rank {r} loss step {i}")
        for k, v in ref_w.items():
            np.testing.assert_allclose(
                g[f"w_{k}"], v, rtol=1e-4, atol=1e-5,
                err_msg=f"rank {r} weight {k}")
    # the two ranks must agree with each other exactly (same replicated
    # global arrays)
    for k in got[0]:
        np.testing.assert_array_equal(got[0][k], got[1][k])


_ELASTIC_CHILD = """
import os, signal, sys
sys.path.insert(0, '/root/repo')
os.environ.pop('XLA_FLAGS', None)
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_enable_x64', True)
jax.config.update('jax_default_matmul_precision', 'highest')
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from jax.sharding import Mesh
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.optimizer import AdamW
from paddle_tpu.parallel import ParallelEngine

WORK = {work!r}
rank = int(os.environ['PADDLE_TRAINER_ID'])
snap = os.path.join(WORK, 'snap' + str(rank) + '.npz')
state, start = None, 0
if os.path.exists(snap):
    state = np.load(snap, allow_pickle=True)['state'].item()
    start = state['step']
if start >= 6:
    # a straggler restart after the job already completed: nothing to do —
    # exit clean WITHOUT joining the (gone) coordinator
    np.savez(os.path.join(WORK, 'final' + str(rank) + '.npz'),
             **{{'w_' + k: v for k, v in state['params'].items()}})
    print('DONE (already complete)')
    sys.exit(0)
env = dist.init_parallel_env()
assert env.rank == rank
cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=48,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=2, max_position_embeddings=32,
                  dtype="float32", use_flash_attention=False,
                  tie_word_embeddings=False, fused_lm_head_ce=False)
paddle.seed(42)
model = LlamaForCausalLM(cfg)
opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
mesh = Mesh(np.array(jax.devices()), ('data',))
eng = ParallelEngine(model, optimizer=opt, loss_fn=model.loss_fn, mesh=mesh)
if state is not None:
    eng.set_engine_state(state)
    open(os.path.join(WORK, 'resumed' + str(rank) + '.log'), 'a').write(
        str(start) + chr(10))
for step in range(start, 6):
    rs = np.random.RandomState(100 + step)
    x = rs.randint(0, cfg.vocab_size, (4, 16)).astype('int32')
    y = rs.randint(0, cfg.vocab_size, (4, 16)).astype('int64')
    loss = eng.train_batch(x[rank * 2:rank * 2 + 2], y[rank * 2:rank * 2 + 2])
    float(np.asarray(loss.value))  # force completion before snapshotting
    state = eng.engine_state_dict()
    tmp = snap + '.tmp.npz'
    np.savez(tmp, state=np.array(state, dtype=object))
    os.replace(tmp, snap)  # atomic: a kill mid-save can't corrupt the snap
    marker = os.path.join(WORK, 'killed_once')
    if rank == 1 and step == 2 and not os.path.exists(marker):
        open(marker, 'w').close()
        os.kill(os.getpid(), signal.SIGKILL)
np.savez(os.path.join(WORK, 'final' + str(rank) + '.npz'),
         **{{'w_' + k: v for k, v in state['params'].items()}})
print('DONE', float(np.asarray(loss.value)))
"""


def test_elastic_kill_training_rank_resumes(tmp_path):
    """A TRAINING child (engine train_batch across 2 processes) is
    SIGKILLed mid-run; failure detection (peer-loss error or heartbeat
    staleness) brings the pod down, the launchers restart both ranks, and
    training resumes from the engine snapshot — final weights match the
    uninterrupted single-process run (ref fleet/elastic manager kill/
    restart drills + test_auto_checkpoint kill-resume, composed with the
    real multi-process engine path for the first time)."""
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        master_port = s.getsockname()[1]

    # uninterrupted single-process reference, same per-step data
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, intermediate_size=48,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=32,
                      dtype="float32", use_flash_attention=False,
                      tie_word_embeddings=False, fused_lm_head_ce=False)
    paddle.seed(42)
    model = LlamaForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
    eng = ParallelEngine(model, optimizer=opt, loss_fn=model.loss_fn)
    for step in range(6):
        rs = np.random.RandomState(100 + step)
        x = rs.randint(0, cfg.vocab_size, (4, 16)).astype("int32")
        y = rs.randint(0, cfg.vocab_size, (4, 16)).astype("int64")
        eng.train_batch(x, y)
    eng.sync_to_model()
    ref_w = {k: np.asarray(v.value) for k, v in model.state_dict().items()}

    script = tmp_path / "train.py"
    script.write_text(_ELASTIC_CHILD.format(work=str(tmp_path)))

    def run(rank):
        out = open(tmp_path / f"launcher{rank}.log", "wb")
        return subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", "2", "--rank", str(rank),
             "--master", f"127.0.0.1:{master_port}",
             "--max_restart", "8", "--elastic_timeout", "6",
             "--log_dir", str(tmp_path / f"log{rank}"), str(script)],
            cwd="/root/repo", stdout=out, stderr=out)

    p0, p1 = run(0), run(1)
    assert p0.wait(timeout=480) == 0, \
        (tmp_path / "launcher0.log").read_text()[-2000:]
    assert p1.wait(timeout=120) == 0, \
        (tmp_path / "launcher1.log").read_text()[-2000:]

    # the kill really happened and at least one rank really resumed >0
    assert (tmp_path / "killed_once").exists()
    resumed = []
    for r in (0, 1):
        log = tmp_path / f"resumed{r}.log"
        if log.exists():
            resumed.extend(int(line) for line in log.read_text().split())
    assert resumed and all(s > 0 for s in resumed), resumed

    for r in (0, 1):
        got = dict(np.load(tmp_path / f"final{r}.npz"))
        for k, v in ref_w.items():
            np.testing.assert_allclose(
                got[f"w_{k}"], v, rtol=1e-4, atol=1e-5,
                err_msg=f"rank {r} weight {k} after kill+resume")


_NPROC_CHILD = """
import os, sys
sys.path.insert(0, '/root/repo')
os.environ.pop('XLA_FLAGS', None)
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
import paddle_tpu.distributed as dist
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

env = dist.init_parallel_env()
assert jax.process_count() == 2, jax.process_count()
mesh = Mesh(np.array(jax.devices()), ('x',))
local = np.full((1,), env.rank + 1.0, np.float32)
garr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P('x')), local)
import jax.numpy as jnp
out = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(garr)
print('PSUM', float(np.asarray(out)))
assert float(np.asarray(out)) == 3.0
"""


def test_single_launcher_nproc_per_node(tmp_path):
    """--nproc_per_node 2 under ONE launcher (the single-host multi-process
    layout): PADDLE_TRAINERS_NUM (nnodes*nproc) must drive
    jax.distributed.initialize, not the per-NODE endpoint count — a
    len(endpoints)=1 fallback would silently initialize a 1-process world
    (r5 fix)."""
    script = tmp_path / "train.py"
    script.write_text(_NPROC_CHILD)
    out = open(tmp_path / "launcher.log", "wb")
    p = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--max_restart", "0",
         "--log_dir", str(tmp_path / "log"), str(script)],
        cwd="/root/repo", stdout=out, stderr=out)
    assert p.wait(timeout=240) == 0, \
        (tmp_path / "launcher.log").read_text()[-1500:]
    for r in (0, 1):
        log = (tmp_path / "log" / f"workerlog.{r}").read_text()
        assert "PSUM 3.0" in log, log[-800:]
