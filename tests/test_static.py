"""paddle.static facade tests: program capture, Executor replay, static
training parity vs dygraph, inference model save/load.

Mirrors the reference's static-graph unittests (ref
python/paddle/fluid/tests/unittests/test_executor_*.py, book/ tests) using
the op-recording Program + jitted replay design."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    static.reset_default_programs()
    yield
    paddle.disable_static()


def test_program_capture_and_run():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        y = paddle.exp(x) + 1.0
    assert len(main.ops) >= 1
    exe = static.Executor()
    exe.run(startup)
    xs = np.random.RandomState(0).randn(3, 4).astype("float32")
    (out,) = exe.run(main, feed={"x": xs}, fetch_list=[y])
    np.testing.assert_allclose(out, np.exp(xs) + 1.0, rtol=1e-5)


def test_fc_forward_matches_layer():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 8], "float32")
        out = static.nn.fc(x, 16, activation="relu")
    exe = static.Executor()
    exe.run(startup)
    xs = np.random.RandomState(1).randn(5, 8).astype("float32")
    (o,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
    assert o.shape == (5, 16)
    assert (o >= 0).all()
    # weight is registered as a program parameter
    assert len(main.params) == 2  # weight + bias


def test_static_training_converges():
    rng = np.random.RandomState(0)
    w_true = rng.randn(4, 1).astype("float32")

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        y = static.data("y", [None, 1], "float32")
        pred = static.nn.fc(x, 1)
        loss = paddle.mean((pred - y) ** 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)

    exe = static.Executor()
    exe.run(startup)
    losses = []
    for i in range(60):
        xs = rng.randn(32, 4).astype("float32")
        ys = xs @ w_true
        (l,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        losses.append(float(l))
    assert losses[-1] < 0.02
    assert losses[-1] < losses[0] * 0.1


def test_static_adam_training():
    rng = np.random.RandomState(0)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        y = static.data("y", [None, 1], "float32")
        h = static.nn.fc(x, 8, activation="tanh")
        pred = static.nn.fc(h, 1)
        loss = paddle.mean((pred - y) ** 2)
        paddle.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    w = rng.randn(4, 1).astype("float32")
    first = last = None
    for i in range(80):
        xs = rng.randn(64, 4).astype("float32")
        ys = np.tanh(xs @ w)
        (l,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        if first is None:
            first = float(l)
        last = float(l)
    assert last < first * 0.2


def test_startup_reinitializes():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        pred = static.nn.fc(x, 1)
        loss = paddle.mean(pred ** 2)
        paddle.optimizer.SGD(learning_rate=0.5).minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    scope = static.global_scope()
    name = next(iter(main.params))
    before = np.asarray(scope.store[name]).copy()
    xs = np.random.RandomState(0).randn(16, 4).astype("float32")
    exe.run(main, feed={"x": xs}, fetch_list=[loss])
    after = np.asarray(scope.store[name])
    assert not np.allclose(before, after)  # step changed weights
    exe.run(startup)  # re-init restores initial values
    np.testing.assert_allclose(np.asarray(scope.store[name]), before)


def test_save_load_inference_model(tmp_path):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 8], "float32")
        out = static.nn.fc(x, 3)
    exe = static.Executor()
    exe.run(startup)
    xs = np.random.RandomState(2).randn(4, 8).astype("float32")
    (ref,) = exe.run(main, feed={"x": xs}, fetch_list=[out])

    path = str(tmp_path / "infer_model")
    static.save_inference_model(path, [x], [out], exe, program=main)
    model, feed_names, fetch_names = static.load_inference_model(path, exe)
    assert feed_names == ["x"]
    (got,) = model.run({"x": xs})
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_program_clone_for_test():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        pred = static.nn.fc(x, 2)
        loss = paddle.mean(pred ** 2)
        paddle.optimizer.SGD(0.1).minimize(loss)
    test_prog = main.clone(for_test=True)
    assert test_prog.optimizer is None
    exe = static.Executor()
    exe.run(startup)
    xs = np.zeros((2, 4), dtype="float32")
    (o,) = exe.run(test_prog, feed={"x": xs}, fetch_list=[pred])
    assert o.shape == (2, 2)


def test_cond_and_while_available():
    # structured control flow re-exported for static users
    assert callable(static.nn.cond)
    assert callable(static.nn.while_loop)


def test_missing_feed_raises():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        y = static.data("y", [None, 4], "float32")
        z = x + y
    exe = static.Executor()
    exe.run(startup)
    with pytest.raises(KeyError, match="was not fed"):
        exe.run(main, feed={"x": np.ones((2, 4), "float32")}, fetch_list=[z])


def test_two_programs_independent_opt_state():
    rng = np.random.RandomState(0)

    def build():
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 4], "float32")
            y = static.data("y", [None, 1], "float32")
            pred = static.nn.fc(x, 1)
            loss = paddle.mean((pred - y) ** 2)
            paddle.optimizer.Adam(learning_rate=0.05).minimize(loss)
        return main, startup, loss

    m1, s1, l1 = build()
    m2, s2, l2 = build()
    exe = static.Executor()
    exe.run(s1)
    xs = rng.randn(16, 4).astype("float32")
    ys = xs[:, :1]
    exe.run(m1, feed={"x": xs, "y": ys}, fetch_list=[l1])
    exe.run(s2)  # must not clobber m1's Adam moments
    exe.run(m2, feed={"x": xs, "y": ys}, fetch_list=[l2])
    # m1 keeps training without KeyError and keeps its own state
    (l,) = exe.run(m1, feed={"x": xs, "y": ys}, fetch_list=[l1])
    assert np.isfinite(l)


def test_non_trainable_param_not_updated():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        const = static.create_global_var([4], 2.0, "float32")
        x = static.data("x", [None, 4], "float32")
        pred = static.nn.fc(x * const, 1)
        loss = paddle.mean(pred ** 2)
        paddle.optimizer.SGD(0.1).minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    xs = np.random.RandomState(0).randn(8, 4).astype("float32")
    exe.run(main, feed={"x": xs}, fetch_list=[loss])
    got = np.asarray(static.global_scope().store[const.name])
    np.testing.assert_allclose(got, np.full(4, 2.0, "float32"))


def test_loaded_model_runs_via_executor(tmp_path):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 8], "float32")
        out = static.nn.fc(x, 3)
    exe = static.Executor()
    exe.run(startup)
    xs = np.random.RandomState(2).randn(4, 8).astype("float32")
    (ref,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
    path = str(tmp_path / "m2")
    static.save_inference_model(path, [x], [out], exe, program=main)
    prog, feeds, fetches = static.load_inference_model(path, exe)
    (got,) = exe.run(prog, feed={"x": xs}, fetch_list=fetches)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_gradients_wrt_input():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 3], "float32")
        y = paddle.sum(x ** 2)
        (gx,) = static.gradients(y, [x])
    exe = static.Executor()
    exe.run(startup)
    xs = np.random.RandomState(0).randn(2, 3).astype("float32")
    (g,) = exe.run(main, feed={"x": xs}, fetch_list=[gx])
    np.testing.assert_allclose(g, 2 * xs, rtol=1e-5)


def test_static_batch_norm_trains_with_batch_stats():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 3, 8, 8], "float32")
        out = static.nn.batch_norm(x)
    exe = static.Executor()
    exe.run(startup)
    xs = (np.random.RandomState(0).randn(4, 3, 8, 8) * 5 + 7).astype("float32")
    (o,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
    # batch-stat normalization -> per-channel mean ~0, std ~1
    assert abs(o.mean()) < 1e-2
    assert abs(o.std() - 1.0) < 5e-2


def test_static_per_param_regularizer_applied():
    """Per-param ParamAttr regularizer must decay weights in the static path
    too (ref append_regularization_ops is execution-mode independent)."""
    import paddle_tpu.nn as nn

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        layer = nn.Linear(
            4, 2,
            weight_attr=paddle.ParamAttr(
                regularizer=paddle.regularizer.L2Decay(0.5)),
            bias_attr=False)
        out = layer(x)
        loss = paddle.mean(out)
        paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    scope = static.global_scope()
    pname = next(iter(main.params))
    w0 = np.asarray(scope.store.get(pname, main.params[pname].value))
    # zero input -> data grad 0; only the regularizer moves the weights
    exe.run(main, feed={"x": np.zeros((2, 4), np.float32)}, fetch_list=[loss])
    w1 = np.asarray(scope.store[pname])
    np.testing.assert_allclose(w1, w0 * (1 - 0.1 * 0.5), rtol=1e-5)


def test_fetch_feed_var_does_not_reset_params():
    """A program with no ops that fetches a feed var must not be mistaken for
    a startup program (which would re-init all params in scope)."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
    exe = static.Executor()
    exe.run(startup)
    scope = static.global_scope()
    scope.store["sentinel"] = 123
    xs = np.random.RandomState(0).randn(2, 4).astype("float32")
    (out,) = exe.run(main, feed={"x": xs}, fetch_list=[x])
    np.testing.assert_allclose(out, xs)
    assert scope.store["sentinel"] == 123


def test_fc_dynamic_tail_dim_raises():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, None, 8], "float32")
        with pytest.raises(ValueError):
            static.nn.fc(x, 16)
