"""Continuous-batching generation server (inference/serving.py): greedy
outputs must match the compiled model.generate() per request, with fewer
slots than requests (slot churn mid-flight)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference import GenerationServer
from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config


def _model():
    cfg = llama_tiny_config(use_flash_attention=False,
                            max_position_embeddings=128)
    paddle.seed(0)
    return LlamaForCausalLM(cfg), cfg


class TestGenerationServer:
    def test_matches_generate_with_slot_churn(self):
        model, cfg = _model()
        rng = np.random.RandomState(0)
        prompts = [rng.randint(1, cfg.vocab_size, (n,)).tolist()
                   for n in (5, 12, 7, 3)]
        refs = []
        for p in prompts:
            out = model.generate(paddle.to_tensor(np.asarray([p], np.int32)),
                                 max_new_tokens=8)
            refs.append(np.asarray(out.value)[0].tolist())

        # 2 slots, 4 requests: finished slots must be refilled mid-flight
        srv = GenerationServer(model, max_batch=2, max_len=64,
                               prompt_buckets=(16,))
        rids = [srv.submit(p, max_new_tokens=8) for p in prompts]
        res = srv.run()
        assert set(res) == set(rids)
        for rid, ref in zip(rids, refs):
            assert res[rid] == ref[:len(res[rid])], rid
            assert len(res[rid]) == len(ref)

    def test_variable_max_new_tokens_and_reuse(self):
        model, cfg = _model()
        rng = np.random.RandomState(1)
        srv = GenerationServer(model, max_batch=2, max_len=64,
                               prompt_buckets=(16,))
        r1 = srv.submit(rng.randint(1, cfg.vocab_size, (4,)).tolist(),
                        max_new_tokens=3)
        r2 = srv.submit(rng.randint(1, cfg.vocab_size, (6,)).tolist(),
                        max_new_tokens=10)
        res = srv.run()
        assert len(res[r1]) == 4 + 3 and len(res[r2]) == 6 + 10
        # server is reusable after drain
        r3 = srv.submit([1, 2, 3], max_new_tokens=2)
        res2 = srv.run()
        assert len(res2[r3]) == 5 and r1 not in res2

    def test_per_slot_temperature_sampling(self):
        """Greedy and sampling requests share one decode tick: the greedy
        slot must still match model.generate; the sampled slot must produce
        valid ids and vary with the server's rng stream."""
        model, cfg = _model()
        rng = np.random.RandomState(2)
        p_greedy = rng.randint(1, cfg.vocab_size, (6,)).tolist()
        p_sample = rng.randint(1, cfg.vocab_size, (6,)).tolist()
        ref = np.asarray(model.generate(
            paddle.to_tensor(np.asarray([p_greedy], np.int32)),
            max_new_tokens=6).value)[0].tolist()

        srv = GenerationServer(model, max_batch=2, max_len=64,
                               prompt_buckets=(16,))
        rg = srv.submit(p_greedy, max_new_tokens=6)
        rs = srv.submit(p_sample, max_new_tokens=6, temperature=1.0)
        res = srv.run()
        assert res[rg] == ref[:len(res[rg])]
        toks = res[rs][len(p_sample):]
        assert all(0 <= t < cfg.vocab_size for t in toks)
        # prefill's first token is argmax either way; the 5 sampled ones
        # coincide with greedy only with probability ~(1/V)^5 on this
        # random-init model (near-uniform logits at temperature 1.0)
        greedy_alt = np.asarray(model.generate(
            paddle.to_tensor(np.asarray([p_sample], np.int32)),
            max_new_tokens=6).value)[0].tolist()
        assert res[rs] != greedy_alt


def test_tick_window_greedy_parity():
    """tick_window batches device ticks per host sync; greedy outputs must
    be IDENTICAL to the exact per-token server (surplus discarded)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import GenerationServer
    from paddle_tpu.models import LlamaForCausalLM, LlamaConfig

    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=160,
                      dtype="float32", use_flash_attention=False)
    paddle.seed(7)
    model = LlamaForCausalLM(cfg)
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, 128, n).tolist() for n in (5, 17, 33)]

    def run(window):
        srv = GenerationServer(model, max_batch=2, max_len=160,
                               prompt_buckets=(32, 64), tick_window=window)
        rids = [srv.submit(p, max_new_tokens=9) for p in prompts]
        out = srv.run()
        return [out[r] for r in rids]

    exact = run(1)
    windowed = run(4)
    assert exact == windowed


def test_tick_window_with_temperature_sampling():
    """Sampling composes with the tick window: a temp>0 request inside a
    windowed scan must produce valid ids that differ from greedy, while a
    greedy slot in the SAME window still matches model.generate."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import GenerationServer
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=96,
                      dtype="float32", use_flash_attention=False)
    paddle.seed(9)
    model = LlamaForCausalLM(cfg)
    rng = np.random.RandomState(4)
    p_greedy = rng.randint(1, 128, (8,)).tolist()
    p_sample = rng.randint(1, 128, (8,)).tolist()
    ref = np.asarray(model.generate(
        paddle.to_tensor(np.asarray([p_greedy], np.int32)),
        max_new_tokens=8).value)[0].tolist()

    srv = GenerationServer(model, max_batch=2, max_len=96,
                           prompt_buckets=(16,), tick_window=8)
    rg = srv.submit(p_greedy, max_new_tokens=8)
    rs = srv.submit(p_sample, max_new_tokens=8, temperature=1.0)
    res = srv.run()
    assert res[rg] == ref[:len(res[rg])]
    toks = res[rs][len(p_sample):]
    assert all(0 <= t < cfg.vocab_size for t in toks)
    greedy_alt = np.asarray(model.generate(
        paddle.to_tensor(np.asarray([p_sample], np.int32)),
        max_new_tokens=8).value)[0].tolist()
    assert res[rs] != greedy_alt
