"""Continuous-batching generation server (inference/serving.py): greedy
outputs must match the compiled model.generate() per request, with fewer
slots than requests (slot churn mid-flight)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.inference import GenerationServer
from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config


def _model():
    cfg = llama_tiny_config(use_flash_attention=False,
                            max_position_embeddings=128)
    paddle.seed(0)
    return LlamaForCausalLM(cfg), cfg


class TestGenerationServer:
    def test_matches_generate_with_slot_churn(self):
        model, cfg = _model()
        rng = np.random.RandomState(0)
        prompts = [rng.randint(1, cfg.vocab_size, (n,)).tolist()
                   for n in (5, 12, 7, 3)]
        refs = []
        for p in prompts:
            out = model.generate(paddle.to_tensor(np.asarray([p], np.int32)),
                                 max_new_tokens=8)
            refs.append(np.asarray(out.value)[0].tolist())

        # 2 slots, 4 requests: finished slots must be refilled mid-flight
        srv = GenerationServer(model, max_batch=2, max_len=64,
                               prompt_buckets=(16,))
        rids = [srv.submit(p, max_new_tokens=8) for p in prompts]
        res = srv.run()
        assert set(res) == set(rids)
        for rid, ref in zip(rids, refs):
            assert res[rid] == ref[:len(res[rid])], rid
            assert len(res[rid]) == len(ref)

    def test_variable_max_new_tokens_and_reuse(self):
        model, cfg = _model()
        rng = np.random.RandomState(1)
        srv = GenerationServer(model, max_batch=2, max_len=64,
                               prompt_buckets=(16,))
        r1 = srv.submit(rng.randint(1, cfg.vocab_size, (4,)).tolist(),
                        max_new_tokens=3)
        r2 = srv.submit(rng.randint(1, cfg.vocab_size, (6,)).tolist(),
                        max_new_tokens=10)
        res = srv.run()
        assert len(res[r1]) == 4 + 3 and len(res[r2]) == 6 + 10
        # server is reusable after drain
        r3 = srv.submit([1, 2, 3], max_new_tokens=2)
        res2 = srv.run()
        assert len(res2[r3]) == 5 and r1 not in res2
