"""dy2static transformer-breadth gate (VERDICT r3 item 9): enumerate the
reference's AST transformer inventory (/root/reference/python/paddle/jit/
dy2static/*_transformer.py + ast_transformer/base_transformer) and assert
every file is either IMPLEMENTED by a named mechanism in
paddle_tpu/jit/dy2static.py or EXEMPT with a reason — the same
zero-unexplained-absences methodology as the tensor-op surface gate
(test_surface_parity.py). Functional tests below exercise each newly
implemented transformer through @to_static.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import to_static

REF_DIR = "/root/reference/python/paddle/jit/dy2static"

# file -> (status, mechanism-or-reason)
STATUS = {
    "ast_transformer.py": (
        "implemented", "_convert_cached orchestrates fold + pre-passes + "
        "_CtrlFlowTransformer (the ProgramTranslator pipeline)"),
    "base_transformer.py": (
        "exempt", "infrastructure base class; ast.NodeTransformer is the "
        "native equivalent"),
    "basic_api_transformer.py": (
        "exempt", "rewrites dygraph API calls (to_variable etc.) to static "
        "ops; JAX has no dygraph/static op split — tracing executes the "
        "eager API directly"),
    "assert_transformer.py": (
        "implemented", "visit_Assert -> convert_assert (concrete enforced; "
        "traced documented no-op, numeric guards via FLAGS_check_nan_inf)"),
    "break_continue_transformer.py": (
        "implemented", "_BreakContinueTransformer guard-flag elimination"),
    "call_transformer.py": (
        "implemented", "visit_Call -> convert_call recursive callee "
        "conversion (cached, with source/closure fallbacks)"),
    "cast_transformer.py": (
        "implemented", "visit_Call -> convert_cast for int/float/bool over "
        "tracers"),
    "create_variable_transformer.py": (
        "implemented", "UNDEF sentinel + globals() fallback in "
        "_make_branch_fn"),
    "decorator_transformer.py": (
        "implemented", "decorator_list stripped at recompile; bound methods "
        "re-bound; decorator-wrapped closures fall back to the original"),
    "early_return_transformer.py": (
        "implemented", "_fold_tail_returns single-exit folding"),
    "ifelse_transformer.py": (
        "implemented", "visit_If -> convert_ifelse (lax.cond)"),
    "logical_transformer.py": (
        "implemented", "visit_BoolOp/visit_UnaryOp -> "
        "convert_logical_and/or/not"),
    "loop_transformer.py": (
        "implemented", "visit_While -> convert_while_loop (lax.while_loop); "
        "_ForRangeTransformer desugars for-range; other iterables unroll at "
        "trace (JAX idiom for concrete containers)"),
    "return_transformer.py": (
        "implemented", "_fold_tail_returns (returns inside loops stay "
        "Python — same restriction class as the reference's RETURN_NO_VALUE "
        "placeholder machinery)"),
    "tensor_shape_transformer.py": (
        "exempt", "rewrites x.shape into shape ops for dynamic static-graph "
        "shapes; XLA shapes are static at trace so x.shape IS a concrete "
        "tuple — nothing to rewrite"),
    "typehint_transformer.py": (
        "exempt", "annotations are inert in the recompiled source; Py3 ast "
        "round-trips them unchanged"),
}


@pytest.mark.skipif(not os.path.isdir(REF_DIR), reason="reference absent")
def test_every_reference_transformer_closed_or_exempt():
    files = sorted(f for f in os.listdir(REF_DIR)
                   if f.endswith("_transformer.py"))
    unexplained = [f for f in files if f not in STATUS]
    assert not unexplained, f"unexplained dy2static transformers: {unexplained}"
    # and the map doesn't rot: no stale entries for removed files
    stale = [f for f in STATUS if f not in files]
    assert not stale, f"stale gate entries: {stale}"
    impl = sum(1 for s, _ in STATUS.values() if s == "implemented")
    assert impl >= 12, "breadth regressed"


# ---------------------------------------------------------------- functional


def _np(t):
    return np.asarray(t.value if hasattr(t, "value") else t)


def test_break_in_tensor_while_compiles():
    @to_static
    def f(x, n):
        i = paddle.to_tensor(0)
        s = x * 0
        while i < n:          # traced predicate -> lax.while_loop
            s = s + x
            i = i + 1
            if i >= 3:
                break
        return s

    x = paddle.to_tensor(2.0)
    out = f(x, paddle.to_tensor(10))
    assert float(_np(out)) == 6.0  # 3 iterations, not 10


def test_continue_in_tensor_while():
    @to_static
    def f(n):
        i = paddle.to_tensor(0)
        s = paddle.to_tensor(0)
        while i < n:
            i = i + 1
            if i % 2 == 0:
                continue
            s = s + i
        return s

    assert float(_np(f(paddle.to_tensor(6)))) == 1 + 3 + 5


def test_for_range_traced_stop():
    @to_static
    def f(x, n):
        s = x * 0
        for i in range(n):    # traced stop: would raise un-desugared
            s = s + x + i
        return s

    out = f(paddle.to_tensor(1.0), paddle.to_tensor(4))
    assert float(_np(out)) == 4 * 1.0 + (0 + 1 + 2 + 3)


def test_for_range_loop_var_after_loop():
    @to_static
    def f(n):
        j = paddle.to_tensor(-1)
        for j in range(n):
            pass
        return j              # Python semantics: last iterate, not stop

    assert int(_np(f(paddle.to_tensor(5)))) == 4


def test_for_range_break():
    @to_static
    def f(n):
        s = paddle.to_tensor(0)
        for i in range(n):
            if i == 2:
                break
            s = s + 10
        return s

    assert int(_np(f(paddle.to_tensor(100)))) == 20


def test_cast_of_traced_value():
    @to_static
    def f(x):
        return float(x) * 2.0 + int(x)

    out = f(paddle.to_tensor(3))
    assert float(_np(out)) == 9.0


def test_assert_concrete_enforced():
    @to_static
    def f(x):
        assert x is not None, "x required"
        return x

    f(paddle.to_tensor(1.0))

    # the raise path, concrete value (under jit even a bool arg is traced,
    # which correctly takes the documented no-op path)
    from paddle_tpu.jit.dy2static import convert_assert

    with pytest.raises(AssertionError, match="boom"):
        convert_assert(False, "boom")


def test_assert_traced_noop():
    @to_static
    def f(x):
        assert x > 100  # traced: documented no-op, must not raise
        return x + 1

    assert float(_np(f(paddle.to_tensor(1.0)))) == 2.0


def _helper_with_tensor_if(x):
    if x > 0:           # module-level helper: converted via convert_call
        y = x * 2
    else:
        y = x - 1
    return y


def test_convert_call_converts_helper():
    @to_static
    def f(x):
        return _helper_with_tensor_if(x) + 1

    # under jit the helper's Tensor-if must lower to lax.cond, which only
    # happens if convert_call rewrote the callee
    assert float(_np(f(paddle.to_tensor(2.0)))) == 5.0
    assert float(_np(f(paddle.to_tensor(-2.0)))) == -2.0


def test_print_traced_routes_to_debug_print(capfd):
    @to_static
    def f(x):
        print("value is", x)
        return x * 2

    out = f(paddle.to_tensor(21.0))
    assert float(_np(out)) == 42.0
    # jax.debug.print flushes through the runtime; just assert no crash and
    # the concrete path still prints
    from paddle_tpu.jit.dy2static import convert_print

    convert_print("plain", 1)
    captured = capfd.readouterr()
    assert "plain 1" in captured.out


def test_shadowed_builtin_not_rewritten():
    @to_static
    def f(x):
        int = 7  # noqa: A001 — deliberate shadow
        return x + int

    assert float(_np(f(paddle.to_tensor(1.0)))) == 8.0


def test_shadowed_range_not_desugared():
    @to_static
    def f(x):
        range = lambda n: [7, 9]  # noqa: A001 — deliberate shadow
        s = x * 0
        for i in range(3):
            s = s + i
        return s

    assert float(_np(f(paddle.to_tensor(0.0)))) == 16.0


def test_for_range_negative_literal_step():
    @to_static
    def f(n):
        s = paddle.to_tensor(0)
        for i in range(n, 0, -1):   # traced stop, reversed
            s = s + i
        return s

    assert int(_np(f(paddle.to_tensor(4)))) == 4 + 3 + 2 + 1


def test_module_global_shadowed_builtins_resolve_to_user_objects():
    """A module-global shadowing int/print is invisible to the AST pass
    (only function-local stores are); the converted callsite must still run
    the USER's object, not the builtin rewrite (ADVICE r4)."""
    import textwrap
    import types

    mod = types.ModuleType("shadow_mod")
    src = textwrap.dedent("""
        calls = []

        def int(x):  # noqa: A001 — deliberate module-global shadow
            calls.append("int")
            return 7

        def print(*a, **k):  # noqa: A001
            calls.append("print")

        def f(x):
            print("hello", 1)
            return int(x)
    """)
    exec(compile(src, "<shadow_mod>", "exec"), mod.__dict__)
    g = to_static(mod.f)
    out = g(3.0)
    assert out == 7
    assert mod.calls == ["print", "int"], mod.calls
