"""Ring flash attention tests: Pallas blockwise kernels (interpreter mode) on
a 4-device 'context' mesh vs the single-device reference composition — both
forward and the hand-written ring VJP (SURVEY §5.7 new-design requirement)."""
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from functools import partial

# check_vma=False: the pallas HLO interpreter's internal dynamic_slice doesn't
# yet propagate varying-mesh-axes types (jax suggests this exact workaround);
# compiled TPU runs keep the default check.
shard_map = partial(jax.shard_map, check_vma=False)

import paddle_tpu.ops  # noqa: F401  (ensure flash module import)
fa = sys.modules["paddle_tpu.ops.flash_attention"]

from paddle_tpu.parallel.ring_flash_attention import ring_flash_attention


@pytest.fixture(autouse=True)
def _interpret_mode():
    os.environ["PT_FLASH_INTERPRET"] = "1"
    yield
    os.environ.pop("PT_FLASH_INTERPRET", None)


def _mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), ("context",))


def _run_ring(q, k, v, causal, n=4):
    mesh = _mesh(n)

    def body(q, k, v):
        return ring_flash_attention(q, k, v, "context", causal, None)

    f = shard_map(body, mesh=mesh,
                  in_specs=(P(None, None, "context"),) * 3,
                  out_specs=P(None, None, "context"))
    return f(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("Hkv", [4, 2])
def test_ring_flash_forward_matches_global(causal, Hkv):
    rng = np.random.RandomState(0)
    B, H, S, D = 1, 4, 4 * 128, 64
    q = jnp.asarray(rng.randn(B, H, S, D).astype("float32"))
    k = jnp.asarray(rng.randn(B, Hkv, S, D).astype("float32"))
    v = jnp.asarray(rng.randn(B, Hkv, S, D).astype("float32"))
    out = _run_ring(q, k, v, causal)
    ref = fa._ref_bhsd(q, k, v, causal, 1.0 / np.sqrt(D))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-5, atol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_grads_match_global(causal):
    rng = np.random.RandomState(1)
    B, H, S, D = 1, 2, 4 * 128, 64
    q = jnp.asarray(rng.randn(B, H, S, D).astype("float32"))
    k = jnp.asarray(rng.randn(B, H, S, D).astype("float32"))
    v = jnp.asarray(rng.randn(B, H, S, D).astype("float32"))
    mesh = _mesh(4)

    def ring_loss(q, k, v):
        def body(q, k, v):
            return ring_flash_attention(q, k, v, "context", causal, None)

        out = shard_map(body, mesh=mesh,
                        in_specs=(P(None, None, "context"),) * 3,
                        out_specs=P(None, None, "context"))(q, k, v)
        return jnp.sum(jnp.sin(out))

    def ref_loss(q, k, v):
        return jnp.sum(jnp.sin(fa._ref_bhsd(q, k, v, causal, 1.0 / np.sqrt(D))))

    np.testing.assert_allclose(float(ring_loss(q, k, v)),
                               float(ref_loss(q, k, v)), rtol=1e-5)
    g = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name} causal={causal}")


def test_ring_flash_gqa_grads():
    rng = np.random.RandomState(2)
    B, H, Hkv, S, D = 1, 4, 2, 4 * 128, 64
    q = jnp.asarray(rng.randn(B, H, S, D).astype("float32"))
    k = jnp.asarray(rng.randn(B, Hkv, S, D).astype("float32"))
    v = jnp.asarray(rng.randn(B, Hkv, S, D).astype("float32"))
    mesh = _mesh(4)

    def ring_loss(q, k, v):
        def body(q, k, v):
            return ring_flash_attention(q, k, v, "context", True, None)

        out = shard_map(body, mesh=mesh,
                        in_specs=(P(None, None, "context"),) * 3,
                        out_specs=P(None, None, "context"))(q, k, v)
        return jnp.sum(out * out)

    def ref_loss(q, k, v):
        return jnp.sum(fa._ref_bhsd(q, k, v, True, 1.0 / np.sqrt(D)) ** 2)

    g = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4, err_msg=f"d{name} GQA")
