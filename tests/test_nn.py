"""nn layer tests (numpy references; ref unittests/test_layers.py pattern)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


def npt(x):
    return np.asarray(x.numpy(), np.float64)


class TestLinear:
    def test_forward(self):
        layer = nn.Linear(4, 3)
        x = paddle.randn([2, 4])
        out = layer(x)
        ref = npt(x) @ npt(layer.weight) + npt(layer.bias)
        np.testing.assert_allclose(npt(out), ref, rtol=1e-5)

    def test_state_dict_roundtrip(self):
        l1 = nn.Linear(4, 3)
        l2 = nn.Linear(4, 3)
        l2.set_state_dict(l1.state_dict())
        np.testing.assert_array_equal(npt(l1.weight), npt(l2.weight))

    def test_param_grads_via_backward(self):
        layer = nn.Linear(4, 2, bias_attr=False)
        x = paddle.ones([3, 4])
        layer(x).sum().backward()
        np.testing.assert_allclose(npt(layer.weight.grad), np.full((4, 2), 3.0))


class TestConv:
    def test_conv2d_matches_manual(self):
        import jax.numpy as jnp

        conv = nn.Conv2D(2, 3, 3, padding=1, bias_attr=False)
        x = paddle.randn([1, 2, 5, 5])
        out = conv(x)
        assert out.shape == [1, 3, 5, 5]
        # compare against scipy-style direct convolution
        from scipy.signal import correlate

        xv = npt(x)[0]
        wv = npt(conv.weight)
        ref = np.zeros((3, 5, 5))
        for o in range(3):
            acc = np.zeros((5, 5))
            for c in range(2):
                acc += correlate(xv[c], wv[o, c], mode="same")
            ref[o] = acc
        np.testing.assert_allclose(npt(out)[0], ref, rtol=1e-4, atol=1e-5)

    def test_conv2d_stride_groups(self):
        conv = nn.Conv2D(4, 4, 3, stride=2, padding=1, groups=2)
        x = paddle.randn([2, 4, 8, 8])
        assert conv(x).shape == [2, 4, 4, 4]

    def test_conv_transpose_shape(self):
        conv = nn.Conv2DTranspose(3, 2, 4, stride=2, padding=1)
        x = paddle.randn([1, 3, 8, 8])
        assert conv(x).shape == [1, 2, 16, 16]

    def test_conv_grad(self):
        conv = nn.Conv2D(1, 1, 2, bias_attr=False)
        x = paddle.ones([1, 1, 3, 3])
        conv(x).sum().backward()
        # each weight position sees 4 ones (2x2 output)
        np.testing.assert_allclose(npt(conv.weight.grad), np.full((1, 1, 2, 2), 4.0))


class TestNorms:
    def test_layer_norm(self):
        ln = nn.LayerNorm(8)
        x = paddle.randn([4, 8])
        out = npt(ln(x))
        xv = npt(x)
        ref = (xv - xv.mean(-1, keepdims=True)) / np.sqrt(xv.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_rms_norm(self):
        rn = nn.RMSNorm(8)
        x = paddle.randn([4, 8])
        xv = npt(x)
        ref = xv / np.sqrt((xv ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(npt(rn(x)), ref, rtol=1e-4, atol=1e-5)

    def test_batch_norm_train_eval(self):
        bn = nn.BatchNorm2D(3)
        x = paddle.randn([4, 3, 2, 2])
        bn.train()
        out = bn(x)
        xv = npt(x)
        mu = xv.mean((0, 2, 3), keepdims=True)
        var = xv.var((0, 2, 3), keepdims=True)
        np.testing.assert_allclose(npt(out), (xv - mu) / np.sqrt(var + 1e-5),
                                   rtol=1e-3, atol=1e-4)
        # running stats updated
        assert not np.allclose(npt(bn._mean), 0)
        bn.eval()
        out2 = bn(x)
        ref2 = (xv - npt(bn._mean).reshape(1, 3, 1, 1)) / np.sqrt(
            npt(bn._variance).reshape(1, 3, 1, 1) + 1e-5)
        np.testing.assert_allclose(npt(out2), ref2, rtol=1e-3, atol=1e-4)

    def test_group_norm(self):
        gn = nn.GroupNorm(2, 4)
        x = paddle.randn([2, 4, 3, 3])
        out = npt(gn(x))
        xv = npt(x).reshape(2, 2, 2, 3, 3)
        mu = xv.mean((2, 3, 4), keepdims=True)
        var = xv.var((2, 3, 4), keepdims=True)
        ref = ((xv - mu) / np.sqrt(var + 1e-5)).reshape(2, 4, 3, 3)
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)


class TestActivationsLoss:
    def test_softmax_ce_matches_manual(self):
        logits = paddle.randn([5, 7])
        labels = paddle.to_tensor(np.array([0, 2, 6, 3, 1]))
        loss = F.cross_entropy(logits, labels)
        lv = npt(logits)
        e = np.exp(lv - lv.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[np.arange(5), npt(labels).astype(int)]).mean()
        np.testing.assert_allclose(float(loss.item()), ref, rtol=1e-4)

    def test_ce_ignore_index(self):
        logits = paddle.randn([4, 3])
        labels = paddle.to_tensor(np.array([0, -100, 2, -100]))
        loss = F.cross_entropy(logits, labels, ignore_index=-100)
        lv = npt(logits)
        e = np.exp(lv - lv.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[[0, 2], [0, 2]]).mean()
        np.testing.assert_allclose(float(loss.item()), ref, rtol=1e-4)

    def test_bce_with_logits(self):
        z = paddle.randn([6])
        t = paddle.to_tensor(np.random.randint(0, 2, 6).astype(np.float32))
        loss = F.binary_cross_entropy_with_logits(z, t)
        zv, tv = npt(z), npt(t)
        ref = np.mean(np.maximum(zv, 0) - zv * tv + np.log1p(np.exp(-np.abs(zv))))
        np.testing.assert_allclose(float(loss.item()), ref, rtol=1e-4)

    def test_activations(self):
        x = paddle.randn([10])
        xv = npt(x)
        np.testing.assert_allclose(npt(F.relu(x)), np.maximum(xv, 0), rtol=1e-5)
        np.testing.assert_allclose(npt(F.sigmoid(x)), 1 / (1 + np.exp(-xv)), rtol=1e-4)
        np.testing.assert_allclose(npt(F.silu(x)), xv / (1 + np.exp(-xv)), rtol=1e-4)
        np.testing.assert_allclose(
            npt(F.gelu(x)), xv * 0.5 * (1 + np.vectorize(np.math.erf if hasattr(
                np, "math") else __import__("math").erf)(xv / np.sqrt(2))), rtol=1e-3,
            atol=1e-5)

    def test_dropout_train_eval(self):
        x = paddle.ones([1000])
        out = F.dropout(x, p=0.5, training=True)
        v = npt(out)
        assert 0.3 < (v == 0).mean() < 0.7
        nz = v[v != 0]
        np.testing.assert_allclose(nz, 2.0, rtol=1e-5)  # upscale_in_train
        np.testing.assert_array_equal(npt(F.dropout(x, 0.5, training=False)), npt(x))


class TestPooling:
    def test_max_avg_pool(self):
        x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
        mp = npt(F.max_pool2d(x, 2))
        np.testing.assert_array_equal(mp[0, 0], [[5, 7], [13, 15]])
        ap = npt(F.avg_pool2d(x, 2))
        np.testing.assert_allclose(ap[0, 0], [[2.5, 4.5], [10.5, 12.5]])

    def test_adaptive_pool(self):
        x = paddle.randn([2, 3, 7, 9])
        out = F.adaptive_avg_pool2d(x, (2, 2))
        assert out.shape == [2, 3, 2, 2]
        np.testing.assert_allclose(
            npt(F.adaptive_avg_pool2d(x, (1, 1)))[..., 0, 0],
            npt(x).mean((2, 3)), rtol=1e-4, atol=1e-6)


class TestTransformer:
    def test_mha_self_attention(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = paddle.randn([2, 5, 16])
        out = mha(x)
        assert out.shape == [2, 5, 16]

    def test_encoder_stack(self):
        layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
        enc = nn.TransformerEncoder(layer, 2)
        x = paddle.randn([2, 5, 16])
        assert enc(x).shape == [2, 5, 16]

    def test_mha_cache_decode_matches_full(self):
        paddle.seed(7)
        mha = nn.MultiHeadAttention(8, 2, dropout=0.0)
        mha.eval()
        x = paddle.randn([1, 4, 8])
        # full causal-free attention over prefix then one more token
        full = mha(x)
        cache = mha.gen_cache(x[:, :0])
        outs = []
        for t in range(4):
            o, cache = mha(x[:, t:t + 1], x[:, t:t + 1], x[:, t:t + 1], None, cache)
            outs.append(o)
        # cached attention is causal; compare last step against manual causal
        # reference for position 3
        q = npt(mha.q_proj(x))[0].reshape(4, 2, 4)
        k = npt(mha.k_proj(x))[0].reshape(4, 2, 4)
        v = npt(mha.v_proj(x))[0].reshape(4, 2, 4)
        ref_heads = []
        for h in range(2):
            s = q[3, h] @ k[:, h].T / 2.0
            p = np.exp(s - s.max())
            p /= p.sum()
            ref_heads.append(p @ v[:, h])
        ref = np.concatenate(ref_heads)
        ref_out = ref @ npt(mha.out_proj.weight) + npt(mha.out_proj.bias)
        np.testing.assert_allclose(npt(outs[-1])[0, 0], ref_out, rtol=1e-3, atol=1e-4)


class TestRNN:
    def test_lstm_shapes(self):
        lstm = nn.LSTM(4, 8, num_layers=2)
        x = paddle.randn([3, 5, 4])
        out, (h, c) = lstm(x)
        assert out.shape == [3, 5, 8]
        assert h.shape == [2, 3, 8]

    def test_gru_bidirectional(self):
        gru = nn.GRU(4, 6, direction="bidirect")
        x = paddle.randn([2, 5, 4])
        out, h = gru(x)
        assert out.shape == [2, 5, 12]

    def test_lstm_cell_manual(self):
        cell = nn.LSTMCell(3, 4)
        x = paddle.randn([2, 3])
        h, (h2, c2) = cell(x)
        assert h.shape == [2, 4]
        np.testing.assert_array_equal(npt(h), npt(h2))


class TestContainers:
    def test_sequential_layerlist(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        x = paddle.randn([3, 4])
        assert m(x).shape == [3, 2]
        assert len(list(m.parameters())) == 4
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        assert len(ll) == 3

    def test_named_parameters_prefixes(self):
        m = nn.Sequential(nn.Linear(2, 2))
        names = [n for n, _ in m.named_parameters()]
        assert "0.weight" in names and "0.bias" in names

    def test_apply_and_dtype(self):
        m = nn.Linear(2, 2)
        m.bfloat16()
        import jax.numpy as jnp

        assert m.weight.dtype == jnp.bfloat16
        m.float()
        assert m.weight.dtype == jnp.float32


class TestEmbedding:
    def test_lookup_and_padding(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        ids = paddle.to_tensor(np.array([[0, 1, 2]]))
        out = npt(emb(ids))
        np.testing.assert_array_equal(out[0, 0], np.zeros(4))
        np.testing.assert_allclose(out[0, 1], npt(emb.weight)[1])

    def test_embedding_grad_scatter(self):
        emb = nn.Embedding(5, 2)
        ids = paddle.to_tensor(np.array([1, 1, 3]))
        emb(ids).sum().backward()
        g = npt(emb.weight.grad)
        np.testing.assert_allclose(g[1], [2.0, 2.0])
        np.testing.assert_allclose(g[3], [1.0, 1.0])
        np.testing.assert_allclose(g[0], [0.0, 0.0])
