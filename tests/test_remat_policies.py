"""Remat policies (engine remat_policy values) and their name-string
contract with the checkpoint_name anchors in models/llama.py — a rename on
either side would silently degrade save_only_these_names to full recompute,
so the coupling is pinned here (VERDICT r3 item 4 infrastructure)."""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
from paddle_tpu.optimizer import AdamW
from paddle_tpu.parallel import ParallelEngine

#: every name an engine policy references must appear in the model jaxpr
ENGINE_POLICY_NAMES = {"attn_out", "qkv", "mlp_out"}


def _engine(policy):
    paddle.seed(0)
    cfg = llama_tiny_config(use_flash_attention=False)
    m = LlamaForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-3, parameters=m.parameters())
    return ParallelEngine(m, optimizer=opt, loss_fn=m.loss_fn, remat=True,
                          remat_policy=policy, donate=False), cfg


def test_checkpoint_names_present_in_model_jaxpr():
    from paddle_tpu.jit import functional_call, state_values
    from paddle_tpu.framework.core import Tensor

    cfg = llama_tiny_config(use_flash_attention=False)
    paddle.seed(0)
    m = LlamaForCausalLM(cfg)
    params = state_values(m)
    ids = np.zeros((1, 8), np.int32)

    def fwd(p, x):
        return functional_call(m, p, Tensor(x)).value

    jaxpr = jax.make_jaxpr(fwd)(params, ids)
    text = str(jaxpr)
    for name in ENGINE_POLICY_NAMES:
        assert f"name={name}" in text or f"'{name}'" in text or \
            name in text, f"checkpoint_name {name!r} missing from model jaxpr"


@pytest.mark.parametrize("policy", ["dots", "nothing", "save_attn",
                                    "save_attn_mlp", "save_qkv_attn"])
def test_policy_trains_one_step(policy):
    eng, cfg = _engine(policy)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (2, 32))
                           .astype("int32"))
    lbl = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (2, 32))
                           .astype("int64"))
    loss = float(np.asarray(eng.train_batch(ids, lbl).value))
    assert np.isfinite(loss), (policy, loss)


def test_unknown_policy_raises():
    eng, cfg = _engine("definitely_not_a_policy")
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (2, 32))
                           .astype("int32"))
    lbl = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (2, 32))
                           .astype("int64"))
    with pytest.raises(ValueError, match="remat_policy"):
        eng.train_batch(ids, lbl)


def test_save_attn_actually_saves_fewer_residuals():
    """The named policy must change what is saved vs nothing_saveable —
    proves the names reach jax.checkpoint (a dead name would make both
    identical)."""
    import io
    from contextlib import redirect_stdout
    from jax.ad_checkpoint import print_saved_residuals

    cfg = llama_tiny_config(use_flash_attention=False)
    paddle.seed(0)
    m = LlamaForCausalLM(cfg)
    from paddle_tpu.jit import functional_call, state_values
    from paddle_tpu.framework.core import Tensor

    params = state_values(m)
    ids = np.zeros((2, 16), np.int32)
    lbl = np.zeros((2, 16), np.int64)

    def loss_of(p):
        out = functional_call(m, p, Tensor(ids))
        return m.loss_fn(out, Tensor(lbl)).value

    def saved(policy):
        f = jax.checkpoint(loss_of, policy=policy)
        buf = io.StringIO()
        with redirect_stdout(buf):
            print_saved_residuals(f, params)
        return buf.getvalue()

    cp = jax.checkpoint_policies
    with_names = saved(cp.save_only_these_names("attn_out", "mlp_out"))
    without = saved(cp.nothing_saveable)
    # the named policy saves the attention/MLP outputs (reported with their
    # llama.py source lines); nothing_saveable saves only arguments
    assert "LlamaAttention" in with_names and "LlamaMLP" in with_names, \
        with_names[-500:]
    assert "LlamaAttention" not in without and "LlamaMLP" not in without


def test_offload_opt_state_requires_pinned_host():
    """The CPU backend has no pinned_host memory (and no placement custom
    call) — the engine must say so clearly instead of failing mid-compile.
    The trains-and-stays-on-host behavior is verified ON CHIP
    (tools/bench_offload.py; BASELINE.md round 4)."""
    paddle.seed(0)
    cfg = llama_tiny_config(use_flash_attention=False)
    m = LlamaForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-3, parameters=m.parameters())
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1), ("data",))
    kinds = {mm.kind for mm in jax.devices()[0].addressable_memories()}
    if "pinned_host" in kinds:
        pytest.skip("TPU backend: covered by the on-chip benchmark")
    with pytest.raises(NotImplementedError, match="pinned_host"):
        ParallelEngine(m, optimizer=opt, loss_fn=m.loss_fn, mesh=mesh,
                       offload_opt_state=True)


def test_offload_multi_device_raises():
    from jax.sharding import Mesh

    cfg = llama_tiny_config(use_flash_attention=False)
    paddle.seed(0)
    m = LlamaForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-3, parameters=m.parameters())
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("data",))
    with pytest.raises(NotImplementedError):
        ParallelEngine(m, optimizer=opt, loss_fn=m.loss_fn, mesh=mesh,
                       offload_opt_state=True)
